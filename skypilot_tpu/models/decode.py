"""KV-cache autoregressive decoding for the Llama family.

The serving-side counterpart of ``models/llama.forward`` (the reference
serves its models through external engines -- vLLM/JetStream YAMLs, e.g.
``examples/tpu/v6e/benchmark-llama2-7b.yaml``; here decode is in-tree and
TPU-first):

* the KV cache is a pair of stacked-layer arrays
  ``[L, B, max_len, kv_heads, head_dim]`` scanned with the same one
  compiled layer body as training (no per-layer Python loop);
* prefill processes the whole (right-padded) prompt batch in one causal
  pass and writes the cache; decode steps are single-token updates with
  per-sequence length masking, so shapes stay static under jit;
* cache insertion is a one-hot scatter over positions (no
  data-dependent dynamic slices -> XLA keeps everything fused);
* sampling: greedy or temperature, jit-compatible.

Right-padding is safe under causal masking: real tokens never attend to
later pads, and decode masks cache positions >= the sequence's length.
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from skypilot_tpu.models import lora as lora_lib
from skypilot_tpu.models.config import ModelConfig
from skypilot_tpu.models.llama import apply_rope, rope_table_for
from skypilot_tpu.models.quant import QTensor, weight_einsum
from skypilot_tpu.ops import rms_norm

Params = Dict[str, Any]


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class KVCache:
    """Stacked-layer KV cache + per-sequence lengths.

    With ``cfg.kv_cache_dtype == 'int8'`` the k/v arrays store int8 and
    ``k_scale``/``v_scale`` hold per-row (position x kv-head) fp32
    scales — half the cache memory, dequantized in-kernel on read.
    """
    k: jax.Array        # [L, B, max_len, kv_heads, head_dim]
    v: jax.Array        # [L, B, max_len, kv_heads, head_dim]
    lengths: jax.Array  # [B] int32: number of valid positions per sequence
    k_scale: Optional[jax.Array] = None   # [L, B, max_len, kv_heads] f32
    v_scale: Optional[jax.Array] = None

    @property
    def max_len(self) -> int:
        return self.k.shape[2]

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None


def quantize_kv(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Per-row symmetric int8 over the trailing head_dim axis."""
    absmax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    scale = jnp.maximum(absmax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale[..., None]),
                 -127, 127).astype(jnp.int8)
    return q, scale


def init_cache(cfg: ModelConfig, batch: int, max_len: int) -> KVCache:
    if cfg.kv_cache_dtype not in ('compute', 'int8'):
        raise ValueError(
            f"kv_cache_dtype must be 'compute' or 'int8', got "
            f'{cfg.kv_cache_dtype!r}')
    shape = (cfg.n_layers, batch, max_len, cfg.n_kv_heads,
             cfg.resolved_head_dim)
    lengths = jnp.zeros((batch,), jnp.int32)
    if cfg.kv_cache_dtype == 'int8':
        return KVCache(k=jnp.zeros(shape, jnp.int8),
                       v=jnp.zeros(shape, jnp.int8),
                       lengths=lengths,
                       k_scale=jnp.zeros(shape[:-1], jnp.float32),
                       v_scale=jnp.zeros(shape[:-1], jnp.float32))
    dt = cfg.compute_dtype
    return KVCache(k=jnp.zeros(shape, dt), v=jnp.zeros(shape, dt),
                   lengths=lengths)


def _embed(params: Params, tokens: jax.Array, cfg: ModelConfig) -> jax.Array:
    dt = cfg.compute_dtype
    table = params['embed']['embedding'].astype(dt)
    if cfg.use_iota_embed:
        one_hot = jax.nn.one_hot(tokens, cfg.vocab_size, dtype=dt)
        return jnp.einsum('bsv,vd->bsd', one_hot, table)
    return table[tokens]


def _lm_head(params: Params, x: jax.Array, cfg: ModelConfig) -> jax.Array:
    x = rms_norm(x, params['final_norm']['scale'], cfg.norm_eps)
    if cfg.tie_embeddings:
        head = params['embed']['embedding'].astype(cfg.compute_dtype).T
    elif isinstance(params['lm_head']['w'], QTensor):
        return weight_einsum('bsd,dv->bsv', x, params['lm_head']['w'],
                             jnp.float32)
    else:
        # fp path: bf16 operands, f32 accumulate (MXU-rate matmul).
        head = params['lm_head']['w'].astype(cfg.compute_dtype)
    return jnp.einsum('bsd,dv->bsv', x, head,
                      preferred_element_type=jnp.float32)


def _prefill_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                       cfg: ModelConfig) -> jax.Array:
    """Prefill attention, TP-aware.

    Under an ambient mesh with a tensor axis (the serving engines enter
    ``set_mesh``), heads are embarrassingly parallel: shard_map splits
    q/k/v on the head axis and each shard runs the normal attention
    (flash kernel on TPU) locally — no collectives, and the kernel
    stays usable where a bare pallas_call would be opaque to GSPMD.
    Falls back to the partitionable XLA reference when head counts
    don't divide the tensor degree.
    """
    from skypilot_tpu.ops import multi_head_attention
    from skypilot_tpu.parallel.sharding import (ambient_tensor_parallelism,
                                                tensor_shard_map)
    mesh, tp = ambient_tensor_parallelism()
    h, kvh = q.shape[2], k.shape[2]
    impl = cfg.attention_impl
    if mesh is None or mesh.size == 1:
        return multi_head_attention(q, k, v, causal=True, impl=impl)
    if tp <= 1 or h % tp or kvh % tp:
        if impl == 'pallas':
            from skypilot_tpu.ops.pallas.common import warn_fallback_once
            warn_fallback_once(
                'prefill attention',
                f'mesh {dict(mesh.shape)} (heads {h}/{kvh} not divisible '
                f'by tensor={tp})')
        from skypilot_tpu.ops.attention import xla_attention
        return xla_attention(q, k, v, causal=True)
    from jax.sharding import PartitionSpec as P

    def shard_fn(q_, k_, v_):
        return multi_head_attention(q_, k_, v_, causal=True, impl=impl)

    return tensor_shard_map(
        shard_fn, mesh,
        in_specs=(P(None, None, 'tensor', None),) * 3,
        out_specs=P(None, None, 'tensor', None),
    )(q, k, v)


def _mlp(x: jax.Array, lp: Params, cfg: ModelConfig) -> jax.Array:
    dt = cfg.compute_dtype
    if cfg.is_moe:
        # Decode reuses the training MoE block (dense or capacity per
        # cfg.moe_dispatch — capacity drops over-capacity tokens during
        # prefill too); the router aux loss is a training-only term.
        from skypilot_tpu.models.llama import _moe_block
        from skypilot_tpu.parallel.sharding import DEFAULT_RULES
        out, _aux = _moe_block(x, lp['moe'], cfg, DEFAULT_RULES)
        return out
    mlp = lp['mlp']
    from skypilot_tpu.models.llama import _activate
    gate = weight_einsum('bsd,df->bsf', x, mlp['wi_gate'], dt)
    up = weight_einsum('bsd,df->bsf', x, mlp['wi_up'], dt)
    return weight_einsum('bsf,fd->bsd', _activate(gate, cfg) * up,
                         mlp['wo'], dt)


# ---------------------------------------------------------------------------
# Prefill
# ---------------------------------------------------------------------------

def prefill(params: Params, tokens: jax.Array, lengths: jax.Array,
            cfg: ModelConfig, max_len: int
            ) -> Tuple[jax.Array, KVCache]:
    """Process right-padded prompts; returns (last-token logits, cache).

    tokens: [B, S] int32 (S <= max_len), lengths: [B] valid counts.
    """
    b, s = tokens.shape
    dt = cfg.compute_dtype
    positions = jnp.arange(s)
    sin, cos = rope_table_for(cfg, positions)
    x = _embed(params, tokens, cfg)

    def layer(carry, lp):
        x = carry
        h = rms_norm(x, lp['ln_attn']['scale'], cfg.norm_eps)
        q = weight_einsum('bsd,dhk->bshk', h, lp['attn']['wq'], dt)
        k = weight_einsum('bsd,dhk->bshk', h, lp['attn']['wk'], dt)
        v = weight_einsum('bsd,dhk->bshk', h, lp['attn']['wv'], dt)
        q = apply_rope(q, sin, cos)
        k = apply_rope(k, sin, cos)
        attn = _prefill_attention(q, k, v, cfg)
        x = x + weight_einsum('bshk,hkd->bsd', attn, lp['attn']['wo'], dt)
        h = rms_norm(x, lp['ln_mlp']['scale'], cfg.norm_eps)
        x = x + _mlp(h, lp, cfg)
        # cache entries for this layer, padded to max_len
        pad = [(0, 0), (0, max_len - s), (0, 0), (0, 0)]
        if cfg.kv_cache_dtype == 'int8':
            k_q, k_s = quantize_kv(k)
            v_q, v_s = quantize_kv(v)
            return x, (jnp.pad(k_q, pad), jnp.pad(v_q, pad),
                       jnp.pad(k_s, pad[:-1]), jnp.pad(v_s, pad[:-1]))
        return x, (jnp.pad(k, pad), jnp.pad(v, pad))

    if cfg.kv_cache_dtype == 'int8':
        x, (k_cache, v_cache, k_scale, v_scale) = jax.lax.scan(
            layer, x, params['layers'])
        cache = KVCache(k=k_cache, v=v_cache, lengths=lengths,
                        k_scale=k_scale, v_scale=v_scale)
    else:
        x, (k_cache, v_cache) = jax.lax.scan(layer, x, params['layers'])
        cache = KVCache(k=k_cache, v=v_cache, lengths=lengths)
    logits = _lm_head(params, x, cfg)               # [B, S, V]
    last = jnp.take_along_axis(
        logits, (lengths - 1)[:, None, None], axis=1)[:, 0]  # [B, V]
    return last, cache


# ---------------------------------------------------------------------------
# Decode step
# ---------------------------------------------------------------------------

def decode_step(params: Params, tokens: jax.Array, cache: KVCache,
                cfg: ModelConfig,
                active: Optional[jax.Array] = None
                ) -> Tuple[jax.Array, KVCache]:
    """One autoregressive step. tokens: [B] int32 (the just-sampled token).

    ``active`` ([B] bool, default all-on) supports continuous batching:
    inactive slots neither write the cache nor advance their length, so
    a finished request's slot stays inert until a new prompt prefills
    over it — the whole batch still runs as ONE static-shape program.

    Returns (logits [B, V], updated cache with lengths+active).
    """
    b = tokens.shape[0]
    if active is None:
        active = jnp.ones((b,), bool)
    dt = cfg.compute_dtype
    positions = cache.lengths[:, None]                       # [B, 1]
    sin, cos = rope_table_for(cfg, positions)
    x = _embed(params, tokens[:, None], cfg)                 # [B, 1, D]

    max_len = cache.max_len
    # one-hot over cache positions for the scatter; valid rows for
    # attention = positions 0..length inclusive (the just-written row).
    pos_iota = jnp.arange(max_len)                           # [T]
    insert = ((pos_iota[None, :] == cache.lengths[:, None]) &
              active[:, None])                               # [B, T]
    n_valid = cache.lengths + 1                              # [B]

    quantized = cache.quantized

    def layer(carry, scanned):
        x = carry
        if quantized:
            lp, k_cache, v_cache, k_scale, v_scale = scanned
        else:
            lp, k_cache, v_cache = scanned
            k_scale = v_scale = None
        h = rms_norm(x, lp['ln_attn']['scale'], cfg.norm_eps)
        q = weight_einsum('bsd,dhk->bshk', h, lp['attn']['wq'], dt)
        k = weight_einsum('bsd,dhk->bshk', h, lp['attn']['wk'], dt)
        v = weight_einsum('bsd,dhk->bshk', h, lp['attn']['wv'], dt)
        q = apply_rope(q, sin, cos)
        k = apply_rope(k, sin, cos)
        # scatter the new K/V row into the cache at position `length`
        ins4 = insert[:, :, None, None]                      # [B,T,1,1]
        if quantized:
            k_q, k_s = quantize_kv(k)
            v_q, v_s = quantize_kv(v)
            k_cache = jnp.where(ins4, k_q, k_cache)
            v_cache = jnp.where(ins4, v_q, v_cache)
            k_scale = jnp.where(insert[:, :, None], k_s, k_scale)
            v_scale = jnp.where(insert[:, :, None], v_s, v_scale)
        else:
            k_cache = jnp.where(ins4, k.astype(k_cache.dtype), k_cache)
            v_cache = jnp.where(ins4, v.astype(v_cache.dtype), v_cache)
        # Grouped-query attention over the cache: the length-aware
        # Pallas kernel reads only ceil(len/block) cache blocks per
        # sequence (ops/pallas/decode_attention.py); the XLA fallback
        # masks the full cache.
        from skypilot_tpu.ops.pallas.decode_attention import (
            decode_attention)
        attn = decode_attention(
            q, k_cache, v_cache, n_valid,
            k_scale=k_scale, v_scale=v_scale,
            impl=cfg.decode_attention_impl or cfg.attention_impl)
        x = x + weight_einsum('bshk,hkd->bsd', attn, lp['attn']['wo'], dt)
        h = rms_norm(x, lp['ln_mlp']['scale'], cfg.norm_eps)
        x = x + _mlp(h, lp, cfg)
        if quantized:
            return x, (k_cache, v_cache, k_scale, v_scale)
        return x, (k_cache, v_cache)

    if quantized:
        x, (k_new, v_new, ks_new, vs_new) = jax.lax.scan(
            layer, x, (params['layers'], cache.k, cache.v,
                       cache.k_scale, cache.v_scale))
    else:
        x, (k_new, v_new) = jax.lax.scan(
            layer, x, (params['layers'], cache.k, cache.v))
        ks_new = vs_new = None
    logits = _lm_head(params, x, cfg)[:, 0]                  # [B, V]
    new_cache = KVCache(k=k_new, v=v_new,
                        lengths=cache.lengths + active.astype(jnp.int32),
                        k_scale=ks_new, v_scale=vs_new)
    return logits, new_cache


# ---------------------------------------------------------------------------
# Paged KV cache: block-granular pool + per-slot block tables
# ---------------------------------------------------------------------------

@jax.tree_util.register_dataclass
@dataclasses.dataclass
class PagedKVCache:
    """Block-granular KV pool + per-slot block tables (PagedAttention).

    Instead of one ``[slots, max_len]`` monolithic cache, KV lives in a
    pool of fixed-size blocks and each slot maps its logical positions
    through a block table — a sequence consumes HBM proportional to its
    actual length, and read-only blocks (shared prompt prefixes) can be
    referenced by many slots at once. All shapes are static: the pool
    has a fixed block count, slots gather/scatter by block index inside
    the jitted step.

    Block id 0 is the reserved null block: unused table entries point
    at it, and masked (inactive / padding) writes land in it.

    ``cfg.kv_cache_dtype == 'int8'`` stores int8 k/v with per-row
    fp32 scales, exactly like the monolithic ``KVCache``.
    """
    k: jax.Array        # [L, num_blocks, block_size, kv_heads, head_dim]
    v: jax.Array        # [L, num_blocks, block_size, kv_heads, head_dim]
    lengths: jax.Array      # [slots] int32 valid positions per slot
    block_tables: jax.Array  # [slots, blocks_per_slot] int32 pool ids
    k_scale: Optional[jax.Array] = None  # [L, num_blocks, block, kvh] f32
    v_scale: Optional[jax.Array] = None

    @property
    def block_size(self) -> int:
        return self.k.shape[2]

    @property
    def num_blocks(self) -> int:
        return self.k.shape[1]

    @property
    def blocks_per_slot(self) -> int:
        return self.block_tables.shape[1]

    @property
    def max_len(self) -> int:
        return self.blocks_per_slot * self.block_size

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None


def init_paged_cache(cfg: ModelConfig, num_blocks: int, block_size: int,
                     slots: int, blocks_per_slot: int) -> PagedKVCache:
    if cfg.kv_cache_dtype not in ('compute', 'int8'):
        raise ValueError(
            f"kv_cache_dtype must be 'compute' or 'int8', got "
            f'{cfg.kv_cache_dtype!r}')
    if num_blocks < 2:
        raise ValueError('num_blocks must be >= 2 (block 0 is reserved)')
    shape = (cfg.n_layers, num_blocks, block_size, cfg.n_kv_heads,
             cfg.resolved_head_dim)
    lengths = jnp.zeros((slots,), jnp.int32)
    tables = jnp.zeros((slots, blocks_per_slot), jnp.int32)
    if cfg.kv_cache_dtype == 'int8':
        return PagedKVCache(k=jnp.zeros(shape, jnp.int8),
                            v=jnp.zeros(shape, jnp.int8),
                            lengths=lengths, block_tables=tables,
                            k_scale=jnp.zeros(shape[:-1], jnp.float32),
                            v_scale=jnp.zeros(shape[:-1], jnp.float32))
    dt = cfg.compute_dtype
    return PagedKVCache(k=jnp.zeros(shape, dt), v=jnp.zeros(shape, dt),
                        lengths=lengths, block_tables=tables)


def _mount_lora_pages(layers: Params, lora_pages) -> Params:
    """Ride the adapter page store through the layer scan: pages are
    ``[L, P, ...]`` (models/lora.init_adapter_pages), so mounting them
    in the scanned pytree hands each layer body its ``[P, ...]``
    slice. ``None`` (no multi-LoRA) leaves the pytree — and therefore
    the traced program — exactly as it was."""
    if lora_pages is None:
        return layers
    out = dict(layers)
    out['lora_pages'] = lora_pages
    return out


def _view_rows(block_tables: jax.Array, block_size: int) -> jax.Array:
    """Block tables [..., BPS] -> flat pool row per view position
    [..., BPS*block_size] (the gather index for a slot's logical
    cache view)."""
    off = jnp.arange(block_size, dtype=block_tables.dtype)
    rows = block_tables[..., :, None] * block_size + off
    return rows.reshape(*block_tables.shape[:-1], -1)


def _chunk_attention(q: jax.Array, k_view: jax.Array, v_view: jax.Array,
                     q_pos: jax.Array,
                     k_scale: Optional[jax.Array] = None,
                     v_scale: Optional[jax.Array] = None) -> jax.Array:
    """Chunked-prefill attention: chunk queries over a gathered cache
    view that already contains the chunk's own rows.

    q: [1, C, H, D] at absolute positions ``q_pos`` [C]; k_view/v_view:
    [1, T, KVH, D] (T = the slot's full logical view; rows at or past a
    query's position+1 are masked). Mirrors ``ops.attention.
    xla_attention`` numerics exactly (fp32 softmax, NEG_INF mask) so a
    single-chunk prefill is bit-compatible with the whole-prompt path.
    """
    from skypilot_tpu.ops.attention import NEG_INF as ATTN_NEG_INF
    from skypilot_tpu.ops.attention import repeat_kv
    _, _, h, d = q.shape
    kvh = k_view.shape[2]
    if k_scale is not None:
        k_view = k_view.astype(jnp.float32) * k_scale[..., None]
        v_view = (v_view.astype(jnp.float32) *
                  v_scale[..., None]).astype(q.dtype)
    k_view = repeat_kv(k_view, h // kvh)
    v_view = repeat_kv(v_view, h // kvh)
    logits = jnp.einsum('bqhd,bkhd->bhqk', q, k_view,
                        preferred_element_type=jnp.float32) * (d ** -0.5)
    t = k_view.shape[1]
    mask = (jnp.arange(t)[None, :] <= q_pos[:, None])[None, None]
    logits = jnp.where(mask, logits, ATTN_NEG_INF)
    weights = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum('bhqk,bkhd->bqhd', weights.astype(v_view.dtype),
                      v_view)


def prefill_chunk(params: Params, tokens: jax.Array, start: jax.Array,
                  n_new: jax.Array, slot: jax.Array, cache: PagedKVCache,
                  cfg: ModelConfig,
                  lora_pages: Optional[Params] = None,
                  adapter_id: Optional[jax.Array] = None
                  ) -> Tuple[jax.Array, PagedKVCache]:
    """Absorb one prompt chunk for one slot into the paged pool.

    tokens: [1, C] int32 right-padded chunk; ``start``: positions
    already cached for the slot (shared-prefix blocks + earlier
    chunks); ``n_new``: valid tokens in this chunk; ``slot``: slot row
    in the block table. Chunk queries attend to the slot's cached rows
    ``[0, start)`` plus causally within the chunk (Sarathi-style
    chunked prefill: one fixed-shape program regardless of prompt
    length). Returns (last-valid-token logits [1, V], updated cache) —
    the logits are meaningful on the final chunk of a prompt.

    ``lora_pages``/``adapter_id`` (multi-LoRA serving): the stacked
    adapter page store and this slot's page index — q/v projection
    deltas gather the page inside the scan (page 0 = base model,
    exact-zero delta). None compiles the exact base program.
    """
    _, c = tokens.shape
    dt = cfg.compute_dtype
    offs = start + jnp.arange(c)                             # [C] abs pos
    sin, cos = rope_table_for(cfg, offs)
    x = _embed(params, tokens, cfg)                          # [1, C, D]

    bs = cache.block_size
    bps = cache.blocks_per_slot
    nb = cache.num_blocks
    bt_slot = jnp.take(cache.block_tables, slot, axis=0)     # [BPS]
    valid_tok = jnp.arange(c) < n_new
    blk = jnp.clip(offs // bs, 0, bps - 1)
    write_rows = jnp.where(valid_tok,
                           jnp.take(bt_slot, blk) * bs + offs % bs,
                           0)                                # [C]
    view_rows = _view_rows(bt_slot, bs)                      # [T]
    quantized = cache.quantized
    layers = _mount_lora_pages(params['layers'], lora_pages)
    adapter_ids = (jnp.reshape(adapter_id, (1,)).astype(jnp.int32)
                   if lora_pages is not None else None)

    def layer(carry, scanned):
        x = carry
        if quantized:
            lp, kp, vp, ksp, vsp = scanned
        else:
            lp, kp, vp = scanned
            ksp = vsp = None
        h = rms_norm(x, lp['ln_attn']['scale'], cfg.norm_eps)
        q = weight_einsum('bsd,dhk->bshk', h, lp['attn']['wq'], dt)
        k = weight_einsum('bsd,dhk->bshk', h, lp['attn']['wk'], dt)
        v = weight_einsum('bsd,dhk->bshk', h, lp['attn']['wv'], dt)
        if lora_pages is not None:
            dq, dv = lora_lib.apply_lora_pages(h, lp['lora_pages'],
                                               adapter_ids)
            q = q + dq
            v = v + dv
        q = apply_rope(q, sin, cos)
        k = apply_rope(k, sin, cos)
        kf = kp.reshape(nb * bs, *kp.shape[2:])
        vf = vp.reshape(nb * bs, *vp.shape[2:])
        if quantized:
            k_q, k_s = quantize_kv(k)
            v_q, v_s = quantize_kv(v)
            kf = kf.at[write_rows].set(k_q[0])
            vf = vf.at[write_rows].set(v_q[0])
            ksf = ksp.reshape(nb * bs, -1).at[write_rows].set(k_s[0])
            vsf = vsp.reshape(nb * bs, -1).at[write_rows].set(v_s[0])
            k_view = kf[view_rows][None]
            v_view = vf[view_rows][None]
            attn = _chunk_attention(q, k_view, v_view, offs,
                                    k_scale=ksf[view_rows][None],
                                    v_scale=vsf[view_rows][None])
        else:
            kf = kf.at[write_rows].set(k[0].astype(kf.dtype))
            vf = vf.at[write_rows].set(v[0].astype(vf.dtype))
            attn = _chunk_attention(q, kf[view_rows][None],
                                    vf[view_rows][None], offs)
        x = x + weight_einsum('bshk,hkd->bsd', attn, lp['attn']['wo'], dt)
        h = rms_norm(x, lp['ln_mlp']['scale'], cfg.norm_eps)
        x = x + _mlp(h, lp, cfg)
        if quantized:
            return x, (kf.reshape(kp.shape), vf.reshape(vp.shape),
                       ksf.reshape(ksp.shape), vsf.reshape(vsp.shape))
        return x, (kf.reshape(kp.shape), vf.reshape(vp.shape))

    if quantized:
        x, (k_new, v_new, ks_new, vs_new) = jax.lax.scan(
            layer, x, (layers, cache.k, cache.v,
                       cache.k_scale, cache.v_scale))
    else:
        x, (k_new, v_new) = jax.lax.scan(
            layer, x, (layers, cache.k, cache.v))
        ks_new = vs_new = None
    logits = _lm_head(params, x, cfg)                        # [1, C, V]
    last = jnp.take(logits[0], jnp.maximum(n_new - 1, 0),
                    axis=0)[None]                            # [1, V]
    new_cache = PagedKVCache(
        k=k_new, v=v_new,
        lengths=cache.lengths.at[slot].set(
            (start + n_new).astype(jnp.int32)),
        block_tables=cache.block_tables,
        k_scale=ks_new, v_scale=vs_new)
    return last, new_cache


def paged_decode_step(params: Params, tokens: jax.Array,
                      cache: PagedKVCache, cfg: ModelConfig,
                      active: Optional[jax.Array] = None,
                      lora_pages: Optional[Params] = None,
                      adapter_ids: Optional[jax.Array] = None
                      ) -> Tuple[jax.Array, PagedKVCache]:
    """One autoregressive step over the paged pool. tokens: [B] int32.

    Same contract as ``decode_step`` (inactive slots neither write nor
    advance), but KV rows scatter into the slot's current tail block
    and attention runs FUSED over the pool: the block table feeds the
    kernel's KV index maps (``ops/pallas/paged_attention.py``) so the
    gather happens inside the attention loop — no materialized
    ``_view_rows`` copy, and HBM reads scale with ``ceil(len/block)``
    per slot instead of the full logical view (the headroom the r10
    ROADMAP named; ``impl='xla'`` keeps the old gathered-view path for
    unsupported shapes). Inactive slots' writes are routed to the null
    block (id 0).
    """
    logits, new_cache = paged_verify_step(
        params, tokens[:, None], cache, cfg, active=active,
        lora_pages=lora_pages, adapter_ids=adapter_ids)
    new_cache = dataclasses.replace(
        new_cache,
        lengths=cache.lengths + (jnp.ones_like(cache.lengths)
                                 if active is None
                                 else active.astype(jnp.int32)))
    return logits[:, 0], new_cache


def paged_verify_step(params: Params, tokens: jax.Array,
                      cache: PagedKVCache, cfg: ModelConfig,
                      active: Optional[jax.Array] = None,
                      n_input: Optional[jax.Array] = None,
                      lora_pages: Optional[Params] = None,
                      adapter_ids: Optional[jax.Array] = None
                      ) -> Tuple[jax.Array, PagedKVCache]:
    """Process a Q-token window per slot in ONE program (speculative
    verify; Q == 1 is plain decode). tokens: [B, Q] int32 — position
    ``lengths[b] + j`` holds ``tokens[b, j]``; ``n_input`` ([B], default
    Q) masks slots with fewer real inputs (their padded rows write to
    the null block and their padded logits are garbage the caller must
    discard). Every row scatters into the slot's tail block(s) — the
    caller must have block-table entries covering ``lengths + n_input``
    rows — and attention runs fused over the pool with causal masking
    inside the window (query j sees rows ``< lengths + j + 1``).

    Returns (logits [B, Q, V], cache with KV written and lengths
    UNCHANGED) — the caller decides how many of the Q rows survive
    (speculative accept/reject) and advances or rolls back lengths
    itself. ``paged_decode_step`` is the Q=1 wrapper that advances by
    one.
    """
    b, q_len = tokens.shape
    if active is None:
        active = jnp.ones((b,), bool)
    if n_input is None:
        n_input = jnp.full((b,), q_len, jnp.int32)
    dt = cfg.compute_dtype
    lens = cache.lengths
    offs = lens[:, None] + jnp.arange(q_len)[None, :]        # [B, Q]
    sin, cos = rope_table_for(cfg, offs)
    x = _embed(params, tokens, cfg)                          # [B, Q, D]

    bs = cache.block_size
    bps = cache.blocks_per_slot
    nb = cache.num_blocks
    valid_q = ((jnp.arange(q_len)[None, :] < n_input[:, None]) &
               active[:, None])                              # [B, Q]
    blk = jnp.clip(offs // bs, 0, bps - 1)
    write_rows = jnp.where(
        valid_q,
        jnp.take_along_axis(cache.block_tables, blk, axis=1) * bs +
        offs % bs,
        0)                                                   # [B, Q]
    # Kernel mask base: rows INCLUDING the whole window. Padded window
    # positions (j >= n_input) would attend stale rows, but their
    # outputs are discarded by contract and the rows they'd see sit in
    # unallocated (null) table entries, never in live blocks.
    n_valid = jnp.where(active, lens + q_len, 1)
    quantized = cache.quantized
    impl = cfg.decode_attention_impl or cfg.attention_impl
    block_k = cfg.paged_block_k or None
    layers = _mount_lora_pages(params['layers'], lora_pages)
    if lora_pages is not None and adapter_ids is None:
        adapter_ids = jnp.zeros((b,), jnp.int32)

    def layer(carry, scanned):
        x = carry
        if quantized:
            lp, kp, vp, ksp, vsp = scanned
        else:
            lp, kp, vp = scanned
            ksp = vsp = None
        h = rms_norm(x, lp['ln_attn']['scale'], cfg.norm_eps)
        q = weight_einsum('bsd,dhk->bshk', h, lp['attn']['wq'], dt)
        k = weight_einsum('bsd,dhk->bshk', h, lp['attn']['wk'], dt)
        v = weight_einsum('bsd,dhk->bshk', h, lp['attn']['wv'], dt)
        if lora_pages is not None:
            dq, dv = lora_lib.apply_lora_pages(
                h, lp['lora_pages'], adapter_ids)
            q = q + dq
            v = v + dv
        q = apply_rope(q, sin, cos)
        k = apply_rope(k, sin, cos)
        kf = kp.reshape(nb * bs, *kp.shape[2:])
        vf = vp.reshape(nb * bs, *vp.shape[2:])
        if quantized:
            k_q, k_s = quantize_kv(k)
            v_q, v_s = quantize_kv(v)
            kf = kf.at[write_rows].set(k_q)
            vf = vf.at[write_rows].set(v_q)
            ksf = ksp.reshape(nb * bs, -1).at[write_rows].set(k_s)
            vsf = vsp.reshape(nb * bs, -1).at[write_rows].set(v_s)
            k_pool_scale = ksf.reshape(nb, bs, -1)
            v_pool_scale = vsf.reshape(nb, bs, -1)
        else:
            kf = kf.at[write_rows].set(k.astype(kf.dtype))
            vf = vf.at[write_rows].set(v.astype(vf.dtype))
            ksf = vsf = None
            k_pool_scale = v_pool_scale = None
        from skypilot_tpu.ops.pallas.paged_attention import paged_attention
        attn = paged_attention(
            q, kf.reshape(kp.shape), vf.reshape(vp.shape),
            cache.block_tables, n_valid,
            k_scale=k_pool_scale, v_scale=v_pool_scale, impl=impl,
            block_k=block_k)
        x = x + weight_einsum('bshk,hkd->bsd', attn, lp['attn']['wo'], dt)
        h = rms_norm(x, lp['ln_mlp']['scale'], cfg.norm_eps)
        x = x + _mlp(h, lp, cfg)
        if quantized:
            return x, (kf.reshape(kp.shape), vf.reshape(vp.shape),
                       ksf.reshape(ksp.shape), vsf.reshape(vsp.shape))
        return x, (kf.reshape(kp.shape), vf.reshape(vp.shape))

    if quantized:
        x, (k_new, v_new, ks_new, vs_new) = jax.lax.scan(
            layer, x, (layers, cache.k, cache.v,
                       cache.k_scale, cache.v_scale))
    else:
        x, (k_new, v_new) = jax.lax.scan(
            layer, x, (layers, cache.k, cache.v))
        ks_new = vs_new = None
    logits = _lm_head(params, x, cfg)                        # [B, Q, V]
    new_cache = PagedKVCache(
        k=k_new, v=v_new, lengths=cache.lengths,
        block_tables=cache.block_tables,
        k_scale=ks_new, v_scale=vs_new)
    return logits, new_cache


# ---------------------------------------------------------------------------
# Sampling + generate loop
# ---------------------------------------------------------------------------

def sample(logits: jax.Array, rng: jax.Array, temperature: float) -> jax.Array:
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return jax.random.categorical(rng, logits / temperature,
                                  axis=-1).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=('cfg', 'max_new_tokens',
                                             'temperature', 'eos_id'))
def generate(params: Params,
             tokens: jax.Array,
             lengths: jax.Array,
             cfg: ModelConfig,
             *,
             max_new_tokens: int,
             temperature: float = 0.0,
             eos_id: Optional[int] = None,
             rng: Optional[jax.Array] = None
             ) -> Tuple[jax.Array, jax.Array]:
    """Batched generation: prompts [B, S] + lengths [B] ->
    (generated [B, max_new_tokens], gen_lengths [B]).

    The decode loop is a lax.scan of the jitted single-token step --
    static shapes throughout, one compiled program per (B, S, N) triple.
    """
    b, s = tokens.shape
    max_len = s + max_new_tokens
    rng = rng if rng is not None else jax.random.key(0)
    eos = -1 if eos_id is None else eos_id

    last_logits, cache = prefill(params, tokens, lengths, cfg, max_len)

    def step(carry, step_rng):
        logits, cache, done = carry
        tok = sample(logits, step_rng, temperature)
        tok = jnp.where(done, eos if eos >= 0 else 0, tok)
        done = done | (tok == eos)
        logits, cache = decode_step(params, tok, cache, cfg)
        return (logits, cache, done), tok

    done0 = jnp.zeros((b,), bool)
    rngs = jax.random.split(rng, max_new_tokens)
    (_, _, done), toks = jax.lax.scan((step), (last_logits, cache, done0),
                                      rngs)
    generated = toks.T                                       # [B, N]
    if eos >= 0:
        gen_lengths = jnp.argmax(generated == eos, axis=1)
        gen_lengths = jnp.where(jnp.any(generated == eos, axis=1),
                                gen_lengths, max_new_tokens)
    else:
        gen_lengths = jnp.full((b,), max_new_tokens, jnp.int32)
    return generated, gen_lengths.astype(jnp.int32)
