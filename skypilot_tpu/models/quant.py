"""Int8 weight quantization for serving (W8A8 on the decode hot path).

Decode is HBM-bandwidth-bound: every generated token re-reads every
weight. Storing weights as int8 with per-output-channel float scales
halves that traffic, and the MXU runs int8 x int8 matmuls natively at
2x the bf16 rate (v5e: 394 vs 197 TOPS), so activations are dynamically
quantized per token too (AQT-style symmetric absmax). The reference
serves through external engines that do the same trick (vLLM/JetStream
int8 checkpoints, ``examples/tpu/v6e/benchmark-llama2-7b.yaml``); here
it is in-tree.

Design:

* ``QTensor`` — a pytree (int8 values + fp32 scale, contraction axes
  reduced) that drops into the existing param dicts. Its ``astype``
  dequantizes, so every code path that does ``w.astype(dt)`` (training
  forward, MoE decode, lm head tying) keeps working unquantized-slow
  but bit-correct; the decode hot path dispatches to the int8 kernel
  via ``weight_einsum``.
* Scales are per-OUTPUT-channel (constant along contraction axes), so
  ``x @ w == (x_q @ q) * (x_scale * w_scale)`` exactly up to rounding.
* Stacked-layer params ([L, ...] scanned weights) quantize with
  per-layer scales; ``lax.scan`` slices the QTensor leaves layerwise.

Quality: per-channel symmetric int8 keeps logits within ~1% cosine
distance on the shipped configs (see tests/test_quant.py).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Sequence, Tuple

import jax
import jax.numpy as jnp

Params = Dict[str, Any]

_EPS = 1e-8


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class QTensor:
    """Symmetric int8 tensor: ``dequant = q.astype(f32) * scale``.

    ``scale`` keeps the quantized tensor's rank with contraction axes
    reduced to 1, so it broadcasts in both the dequant and the
    scale-after-matmul paths.
    """
    q: jax.Array       # int8, original shape
    scale: jax.Array   # float32, shape = q.shape with reduced axes -> 1

    @property
    def shape(self) -> Tuple[int, ...]:
        return self.q.shape

    @property
    def ndim(self) -> int:
        return self.q.ndim

    def astype(self, dt) -> jax.Array:
        """Full dequantization — the drop-in fallback for fp call sites."""
        return (self.q.astype(jnp.float32) * self.scale).astype(dt)


def quantize_tensor(w: jax.Array, reduce_axes: Sequence[int]) -> QTensor:
    """Symmetric absmax int8 over ``reduce_axes`` (the contraction dims)."""
    w = w.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(w), axis=tuple(reduce_axes), keepdims=True)
    scale = jnp.maximum(absmax, _EPS) / 127.0
    q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    return QTensor(q=q, scale=scale)


def _quantize_activations(x: jax.Array,
                          n_contract: int) -> Tuple[jax.Array, jax.Array]:
    """Per-token dynamic int8: reduce over the trailing ``n_contract`` axes."""
    axes = tuple(range(x.ndim - n_contract, x.ndim))
    absmax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=axes,
                     keepdims=True)
    scale = jnp.maximum(absmax, _EPS) / 127.0
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale), -127,
                 127).astype(jnp.int8)
    return q, scale


def weight_einsum(spec: str, x: jax.Array, w: Any, dt) -> jax.Array:
    """``jnp.einsum(spec, x, w)`` that rides the int8 MXU path for QTensors.

    ``spec`` must contract over x's TRAILING axes (true for every
    projection in the model: 'bsd,dhk->bshk', 'bshk,hkd->bsd',
    'bsf,fd->bsd', 'bsd,dv->bsv', ...). Plain arrays fall through to the
    fp einsum unchanged.
    """
    if not isinstance(w, QTensor):
        return jnp.einsum(spec, x, w.astype(dt))
    lhs, out_spec = spec.split('->')
    x_spec, w_spec = lhs.split(',')
    contracted = [a for a in x_spec if a in w_spec]
    # Contraction axes must be trailing in x for the per-token scale, and
    # the weight's output channels must be the SUFFIX of the output so
    # the squeezed weight scale right-aligns (rules out e.g. the MoE
    # 'bsd,edf->ebsf' dispatch, which uses the dequant fallback instead).
    w_out = [a for a in w_spec if a not in contracted]
    assert (x_spec[len(x_spec) - len(contracted):] == ''.join(contracted)
            and out_spec.endswith(''.join(w_out))), (
        f'weight_einsum cannot scale {spec!r}')
    x_q, x_scale = _quantize_activations(x, len(contracted))
    out = jnp.einsum(spec, x_q, w.q,
                     preferred_element_type=jnp.int32).astype(jnp.float32)
    # x_scale: [batch..., 1 x n_contract] -> [batch...] then pad rank.
    x_s = x_scale.reshape(x_scale.shape[:x_scale.ndim - len(contracted)])
    x_s = x_s.reshape(x_s.shape + (1,) * (out.ndim - x_s.ndim))
    # w.scale: contraction axes are size-1; squeeze them so the remaining
    # (output-channel) axes right-align against the einsum output.
    w_axes = [i for i, a in enumerate(w_spec) if a in contracted]
    w_s = jnp.squeeze(w.scale, axis=tuple(w_axes))
    return (out * x_s * w_s).astype(dt)


# ---------------------------------------------------------------------------
# Param-tree quantization
# ---------------------------------------------------------------------------

def maybe_quantize(params: Params, quantize: bool) -> Params:
    """Engine entry point: jitted quantize_params when ``quantize``."""
    if not quantize:
        return params
    return jax.jit(quantize_params)(params)


def quantize_params(params: Params, *, quantize_moe: bool = False) -> Params:
    """Quantize the decoder-layer projections (+ untied lm head).

    Left in fp: embeddings (gather path would dequantize the whole
    table per step), norm scales, MoE router (tiny and
    precision-sensitive). MoE expert FFNs stay fp by default too: the
    decode MoE dispatch ('bsd,edf->ebsf') can't ride the int8 kernel
    yet (weight_einsum's suffix rule), so quantizing them would cost
    quality with no speedup; ``quantize_moe=True`` opts in (per-expert,
    per-channel scales) for memory-bound deployments.
    """

    out: Params = {}
    for name, sub in params.items():
        if name == 'layers':
            out[name] = _quantize_layers(sub, quantize_moe)
        elif name == 'lm_head':
            out[name] = {'w': quantize_tensor(sub['w'], (0,))}   # [d, v]
        else:
            out[name] = sub
    return out


def _quantize_layers(layers: Params, quantize_moe: bool) -> Params:
    out: Params = {}
    for block, sub in layers.items():
        if block == 'attn':
            out[block] = {
                'wq': quantize_tensor(sub['wq'], (1,)),    # [L,d,h,k]
                'wk': quantize_tensor(sub['wk'], (1,)),
                'wv': quantize_tensor(sub['wv'], (1,)),
                'wo': quantize_tensor(sub['wo'], (1, 2)),  # [L,h,k,d]
            }
        elif block == 'mlp':
            out[block] = {
                'wi_gate': quantize_tensor(sub['wi_gate'], (1,)),  # [L,d,f]
                'wi_up': quantize_tensor(sub['wi_up'], (1,)),
                'wo': quantize_tensor(sub['wo'], (1,)),            # [L,f,d]
            }
        elif block == 'moe' and quantize_moe:
            out[block] = {
                # [L,e,d,f] / [L,e,f,d]: contract d / f, scales per
                # (layer, expert, out-channel); router stays fp.
                'router': sub['router'],
                'wi_gate': quantize_tensor(sub['wi_gate'], (2,)),
                'wi_up': quantize_tensor(sub['wi_up'], (2,)),
                'wo': quantize_tensor(sub['wo'], (2,)),
            }
        else:
            out[block] = sub
    return out


def param_bytes(params: Params) -> int:
    """Total on-device bytes (QTensor = int8 payload + fp32 scales)."""
    total = 0
    for leaf in jax.tree.leaves(params):
        total += leaf.size * leaf.dtype.itemsize
    return total
