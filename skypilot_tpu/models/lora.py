"""LoRA adapters for the stacked-layer Llama pytree.

Parity: the reference's finetuning recipes
(``/root/reference/llm/llama-3_1-finetuning/`` runs torchtune
lora_finetune_distributed). TPU-first shape: adapters live INSIDE the
layer pytree (``params['layers']['lora']``) stacked on the leading
layer axis, so the decoder's single ``lax.scan`` body picks them up
with no model-code changes beyond the attention block — one compiled
layer regardless of depth, and the adapter matmuls fuse into the
surrounding einsums.

Standard recipe: adapters on the attention q/v projections
(``W_eff = W + (alpha/r) * A @ B``), A ~ N(0, 1/r), B = 0 — the model
starts exactly at the base checkpoint. ``merge`` folds adapters into
dense weights for export (an HF checkpoint servable anywhere, no
adapter runtime needed).

Multi-adapter serving (Punica's BGMV shape, S-LoRA's paging): the
paged engine keeps a fixed stack of adapter PAGES
(``init_adapter_pages``: ``[L, P, ...]`` arrays, page 0 = base model,
all zeros) and each decode slot carries a page index.
``apply_lora_pages`` gathers each slot's A/B pages by index inside
the jitted step and runs the same two-stage einsum as
``apply_lora_qv`` — one program serves a heterogeneous-adapter batch
at near-base throughput, and a slot on page 0 computes an exact zero
delta (the base model, token-for-token). Ranks are padded to the
stack's ``max_rank`` with zero columns/rows, which add exact zeros.
"""
from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from skypilot_tpu.models.config import ModelConfig

Params = Dict[str, Any]

DEFAULT_ALPHA = 16.0


def init_lora_params(rng: jax.Array, cfg: ModelConfig, rank: int,
                     dtype=jnp.float32) -> Params:
    """Stacked adapter pytree for q/v projections ([L, ...] leading)."""
    d, hd = cfg.d_model, cfg.resolved_head_dim
    h, kv, n = cfg.n_heads, cfg.n_kv_heads, cfg.n_layers
    ks = jax.random.split(rng, 2)
    std = rank ** -0.5

    def a_init(key):
        return std * jax.random.normal(key, (n, d, rank), jnp.float32
                                       ).astype(dtype)

    return {
        'wq_a': a_init(ks[0]),
        'wq_b': jnp.zeros((n, rank, h, hd), dtype),
        'wv_a': a_init(ks[1]),
        'wv_b': jnp.zeros((n, rank, kv, hd), dtype),
    }


def lora_logical_axes() -> Params:
    """Adapter ranks replicate; the head axes shard like their bases."""
    return {
        'wq_a': ('layers', 'embed', None),
        'wq_b': ('layers', None, 'heads', 'head_dim'),
        'wv_a': ('layers', 'embed', None),
        'wv_b': ('layers', None, 'kv_heads', 'head_dim'),
    }


def lora_scale(rank: int, alpha: float = DEFAULT_ALPHA) -> float:
    return alpha / rank


def apply_lora_qv(x: jax.Array, lora: Params):
    """(delta_q, delta_v) for the attention block: [B,S,H,hd] deltas."""
    dt = x.dtype
    rank = lora['wq_a'].shape[-1]
    scale = lora_scale(rank)
    dq = jnp.einsum('bsr,rhk->bshk',
                    jnp.einsum('bsd,dr->bsr', x, lora['wq_a'].astype(dt)),
                    lora['wq_b'].astype(dt)) * scale
    dv = jnp.einsum('bsr,rhk->bshk',
                    jnp.einsum('bsd,dr->bsr', x, lora['wv_a'].astype(dt)),
                    lora['wv_b'].astype(dt)) * scale
    return dq, dv


# ---------------------------------------------------------------------
# Multi-adapter pages (paged serving runtime)
# ---------------------------------------------------------------------


def init_adapter_pages(cfg: ModelConfig, n_pages: int, max_rank: int,
                       dtype=jnp.float32) -> Params:
    """Stacked adapter page store: ``[L, P, ...]`` with
    ``P = n_pages + 1`` (page 0 reserved for the base model — all
    zeros, scale 0 — so an un-adaptered slot gathers an exact-zero
    delta). The leading layer axis scans with ``params['layers']``;
    ``scale`` is replicated per layer so the whole pytree splits
    uniformly under ``lax.scan``."""
    if n_pages < 1:
        raise ValueError('n_pages must be >= 1')
    if max_rank < 1:
        raise ValueError('max_rank must be >= 1')
    d, hd = cfg.d_model, cfg.resolved_head_dim
    h, kv, n = cfg.n_heads, cfg.n_kv_heads, cfg.n_layers
    p = n_pages + 1
    return {
        'wq_a': jnp.zeros((n, p, d, max_rank), dtype),
        'wq_b': jnp.zeros((n, p, max_rank, h, hd), dtype),
        'wv_a': jnp.zeros((n, p, d, max_rank), dtype),
        'wv_b': jnp.zeros((n, p, max_rank, kv, hd), dtype),
        'scale': jnp.zeros((n, p), jnp.float32),
    }


@jax.jit
def _write_page(pages: Params, page, wq_a, wq_b, wv_a, wv_b, scale):
    # One dispatch per admission (page is a traced index -> a single
    # compiled dynamic-update program, not ~10 eager ops per miss —
    # admission cost is on the serving loop's critical path).
    out = dict(pages)
    out['wq_a'] = pages['wq_a'].at[:, page].set(wq_a)
    out['wq_b'] = pages['wq_b'].at[:, page].set(wq_b)
    out['wv_a'] = pages['wv_a'].at[:, page].set(wv_a)
    out['wv_b'] = pages['wv_b'].at[:, page].set(wv_b)
    out['scale'] = pages['scale'].at[:, page].set(scale)
    return out


def write_adapter_page(pages: Params, page: int, lora: Params,
                       alpha: float = DEFAULT_ALPHA) -> Params:
    """Upload one adapter into page slot ``page`` (rank padded to the
    stack's max_rank with zeros — padded terms contribute exact
    zeros). Returns the updated page store."""
    if page < 1:
        raise ValueError('page 0 is reserved for the base model')
    max_rank = pages['wq_a'].shape[-1]
    rank = np.asarray(lora['wq_a']).shape[-1]
    if rank > max_rank:
        raise ValueError(
            f'adapter rank {rank} exceeds the page store max_rank '
            f'{max_rank}')
    dt = pages['wq_a'].dtype
    pad_r = max_rank - rank

    def pad_a(a):     # [L, d, rank] -> [L, d, max_rank]
        return np.pad(np.asarray(a, jnp.dtype(dt)),
                      ((0, 0), (0, 0), (0, pad_r)))

    def pad_b(b):     # [L, rank, heads, hd] -> [L, max_rank, heads, hd]
        return np.pad(np.asarray(b, jnp.dtype(dt)),
                      ((0, 0), (0, pad_r), (0, 0), (0, 0)))

    return _write_page(
        pages, jnp.int32(page),
        pad_a(lora['wq_a']), pad_b(lora['wq_b']),
        pad_a(lora['wv_a']), pad_b(lora['wv_b']),
        jnp.asarray(lora_scale(rank, alpha),
                    pages['scale'].dtype))


def adapter_nbytes(cfg: ModelConfig, rank: int,
                   itemsize: int = 4) -> int:
    """Weight bytes of one rank-``rank`` q/v adapter (the unified-
    paging charge the engine accounts against the KV block pool)."""
    d, hd = cfg.d_model, cfg.resolved_head_dim
    h, kv, n = cfg.n_heads, cfg.n_kv_heads, cfg.n_layers
    per_layer = rank * (d + h * hd) + rank * (d + kv * hd)
    return n * per_layer * itemsize


def apply_lora_pages(x: jax.Array, pages: Params,
                     adapter_ids: jax.Array):
    """Grouped per-slot adapter deltas (Punica BGMV, einsum form).

    ``x``: [B, S, D] attention input; ``pages``: ONE layer's slice of
    the page store ({'wq_a': [P, d, r], ...}); ``adapter_ids``: [B]
    int32 page index per slot (0 = base -> exact-zero delta). Gathers
    each slot's A/B pages and runs the same two-stage einsum as
    :func:`apply_lora_qv`, batched over heterogeneous adapters.
    Returns ``(delta_q, delta_v)`` shaped like the q/v projections."""
    dt = x.dtype
    s = pages['scale'][adapter_ids].astype(dt)   # [B]
    s = s[:, None, None, None]

    def delta(a_pages, b_pages):
        a = a_pages[adapter_ids].astype(dt)      # [B, d, r]
        b = b_pages[adapter_ids].astype(dt)      # [B, r, heads, hd]
        xr = jnp.einsum('bsd,bdr->bsr', x, a)
        return jnp.einsum('bsr,brhk->bshk', xr, b) * s

    return (delta(pages['wq_a'], pages['wq_b']),
            delta(pages['wv_a'], pages['wv_b']))


def attach(params: Params, lora: Params) -> Params:
    """Return params with the adapter subtree mounted for the scan."""
    out = dict(params)
    out['layers'] = dict(params['layers'])
    out['layers']['lora'] = lora
    return out


def detach(params: Params) -> Params:
    out = dict(params)
    out['layers'] = {k: v for k, v in params['layers'].items()
                     if k != 'lora'}
    return out


def merge(params: Params, alpha: float = DEFAULT_ALPHA) -> Params:
    """Fold adapters into the dense weights (export path):
    wq += scale * A_q @ B_q, wv += scale * A_v @ B_v. The rank comes
    from the adapter shapes — a caller-supplied rank could silently
    mis-scale the export relative to the served adapter model."""
    lora = params['layers'].get('lora')
    if lora is None:
        return params
    scale = lora_scale(lora['wq_a'].shape[-1], alpha)
    merged = detach(params)
    attn = dict(merged['layers']['attn'])
    f32 = jnp.float32
    attn['wq'] = (attn['wq'].astype(f32) + scale * jnp.einsum(
        'ldr,lrhk->ldhk', lora['wq_a'].astype(f32),
        lora['wq_b'].astype(f32))).astype(attn['wq'].dtype)
    attn['wv'] = (attn['wv'].astype(f32) + scale * jnp.einsum(
        'ldr,lrhk->ldhk', lora['wv_a'].astype(f32),
        lora['wv_b'].astype(f32))).astype(attn['wv'].dtype)
    merged['layers'] = dict(merged['layers'])
    merged['layers']['attn'] = attn
    return merged
