"""LoRA adapters for the stacked-layer Llama pytree.

Parity: the reference's finetuning recipes
(``/root/reference/llm/llama-3_1-finetuning/`` runs torchtune
lora_finetune_distributed). TPU-first shape: adapters live INSIDE the
layer pytree (``params['layers']['lora']``) stacked on the leading
layer axis, so the decoder's single ``lax.scan`` body picks them up
with no model-code changes beyond the attention block — one compiled
layer regardless of depth, and the adapter matmuls fuse into the
surrounding einsums.

Standard recipe: adapters on the attention q/v projections
(``W_eff = W + (alpha/r) * A @ B``), A ~ N(0, 1/r), B = 0 — the model
starts exactly at the base checkpoint. ``merge`` folds adapters into
dense weights for export (an HF checkpoint servable anywhere, no
adapter runtime needed).
"""
from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp

from skypilot_tpu.models.config import ModelConfig

Params = Dict[str, Any]

DEFAULT_ALPHA = 16.0


def init_lora_params(rng: jax.Array, cfg: ModelConfig, rank: int,
                     dtype=jnp.float32) -> Params:
    """Stacked adapter pytree for q/v projections ([L, ...] leading)."""
    d, hd = cfg.d_model, cfg.resolved_head_dim
    h, kv, n = cfg.n_heads, cfg.n_kv_heads, cfg.n_layers
    ks = jax.random.split(rng, 2)
    std = rank ** -0.5

    def a_init(key):
        return std * jax.random.normal(key, (n, d, rank), jnp.float32
                                       ).astype(dtype)

    return {
        'wq_a': a_init(ks[0]),
        'wq_b': jnp.zeros((n, rank, h, hd), dtype),
        'wv_a': a_init(ks[1]),
        'wv_b': jnp.zeros((n, rank, kv, hd), dtype),
    }


def lora_logical_axes() -> Params:
    """Adapter ranks replicate; the head axes shard like their bases."""
    return {
        'wq_a': ('layers', 'embed', None),
        'wq_b': ('layers', None, 'heads', 'head_dim'),
        'wv_a': ('layers', 'embed', None),
        'wv_b': ('layers', None, 'kv_heads', 'head_dim'),
    }


def lora_scale(rank: int, alpha: float = DEFAULT_ALPHA) -> float:
    return alpha / rank


def apply_lora_qv(x: jax.Array, lora: Params):
    """(delta_q, delta_v) for the attention block: [B,S,H,hd] deltas."""
    dt = x.dtype
    rank = lora['wq_a'].shape[-1]
    scale = lora_scale(rank)
    dq = jnp.einsum('bsr,rhk->bshk',
                    jnp.einsum('bsd,dr->bsr', x, lora['wq_a'].astype(dt)),
                    lora['wq_b'].astype(dt)) * scale
    dv = jnp.einsum('bsr,rhk->bshk',
                    jnp.einsum('bsd,dr->bsr', x, lora['wv_a'].astype(dt)),
                    lora['wv_b'].astype(dt)) * scale
    return dq, dv


def attach(params: Params, lora: Params) -> Params:
    """Return params with the adapter subtree mounted for the scan."""
    out = dict(params)
    out['layers'] = dict(params['layers'])
    out['layers']['lora'] = lora
    return out


def detach(params: Params) -> Params:
    out = dict(params)
    out['layers'] = {k: v for k, v in params['layers'].items()
                     if k != 'lora'}
    return out


def merge(params: Params, alpha: float = DEFAULT_ALPHA) -> Params:
    """Fold adapters into the dense weights (export path):
    wq += scale * A_q @ B_q, wv += scale * A_v @ B_v. The rank comes
    from the adapter shapes — a caller-supplied rank could silently
    mis-scale the export relative to the served adapter model."""
    lora = params['layers'].get('lora')
    if lora is None:
        return params
    scale = lora_scale(lora['wq_a'].shape[-1], alpha)
    merged = detach(params)
    attn = dict(merged['layers']['attn'])
    f32 = jnp.float32
    attn['wq'] = (attn['wq'].astype(f32) + scale * jnp.einsum(
        'ldr,lrhk->ldhk', lora['wq_a'].astype(f32),
        lora['wq_b'].astype(f32))).astype(attn['wq'].dtype)
    attn['wv'] = (attn['wv'].astype(f32) + scale * jnp.einsum(
        'ldr,lrhk->ldhk', lora['wv_a'].astype(f32),
        lora['wv_b'].astype(f32))).astype(attn['wv'].dtype)
    merged['layers'] = dict(merged['layers'])
    merged['layers']['attn'] = attn
    return merged
