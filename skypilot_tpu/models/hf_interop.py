"""HuggingFace checkpoint interop: safetensors <-> stacked JAX pytrees.

The reference's entire ``llm/`` surface exists to launch *real* models
(``/root/reference/llm/llama-3_1/README.md``,
``examples/tpu/v6e/train-llama3-8b.yaml`` trains from
``Meta-Llama-3.1-8B`` safetensors). This module is the TPU-native
equivalent: it maps HF-format Llama/Mistral/Mixtral checkpoints into
the stacked-layer pytree ``models/llama.py`` runs, and back. (Qwen2 and
Gemma are rejected with clear errors — their bias/norm conventions do
not fit this forward pass.)

Design notes (TPU-first):
* The safetensors container is parsed directly (8-byte header length +
  JSON index + raw little-endian tensors) with ``mmap`` — tensors are
  zero-copy views, so an 8B checkpoint streams into the stacked arrays
  without a second resident copy. bf16 goes through ``ml_dtypes``
  (numpy itself has no bfloat16).
* Layer params are **stacked** on a leading axis (one `lax.scan` body —
  see models/llama.py); the stacked destination array is allocated once
  and filled shard-by-shard, so peak memory is the destination + one
  mmap'd shard page set.
* HF stores projections as [out_features, in_features]; the pytree
  keeps [in, heads, head_dim]-style layouts that contract cleanly in
  einsums, so each weight is transposed/reshaped on the way in. HF's
  rotate-half rope convention matches ``models/llama.py:apply_rope``
  (first/second half split), so no head permutation is needed.
"""
from __future__ import annotations

import dataclasses
import json
import mmap
import os
import struct
from typing import Any, Dict, Iterator, List, Optional, Tuple

import numpy as np

from skypilot_tpu.models.config import ModelConfig

Params = Dict[str, Any]

# safetensors dtype tags <-> numpy dtypes (bf16 via ml_dtypes).
_ST_DTYPES = {
    'F64': np.float64, 'F32': np.float32, 'F16': np.float16,
    'I64': np.int64, 'I32': np.int32, 'I16': np.int16, 'I8': np.int8,
    'U8': np.uint8, 'BOOL': np.bool_,
}


def _bf16():
    import ml_dtypes
    return ml_dtypes.bfloat16


def _np_dtype(tag: str):
    if tag == 'BF16':
        return _bf16()
    try:
        return _ST_DTYPES[tag]
    except KeyError:
        raise ValueError(f'unsupported safetensors dtype {tag!r}') from None


def _st_tag(dtype) -> str:
    if dtype == _bf16():
        return 'BF16'
    for tag, dt in _ST_DTYPES.items():
        if np.dtype(dt) == np.dtype(dtype):
            return tag
    raise ValueError(f'unsupported dtype for safetensors export: {dtype}')


class SafetensorsReader:
    """mmap-backed reader for one .safetensors file (zero-copy views)."""

    def __init__(self, path: str) -> None:
        self.path = path
        self._f = open(path, 'rb')
        self._mm = mmap.mmap(self._f.fileno(), 0, access=mmap.ACCESS_READ)
        (header_len,) = struct.unpack('<Q', self._mm[:8])
        header = json.loads(self._mm[8:8 + header_len].decode('utf-8'))
        self.metadata = header.pop('__metadata__', {})
        self._entries = header
        self._data_start = 8 + header_len

    def keys(self) -> List[str]:
        return list(self._entries)

    def shape(self, name: str) -> Tuple[int, ...]:
        return tuple(self._entries[name]['shape'])

    def get(self, name: str) -> np.ndarray:
        ent = self._entries[name]
        start, end = ent['data_offsets']
        dt = _np_dtype(ent['dtype'])
        # frombuffer on the mmap itself: a true zero-copy view (slicing
        # the mmap would copy into a bytes object).
        count = (end - start) // np.dtype(dt).itemsize
        return np.frombuffer(self._mm, dtype=dt, count=count,
                             offset=self._data_start + start
                             ).reshape(ent['shape'])

    def close(self) -> None:
        try:
            self._mm.close()
        except BufferError:
            # Zero-copy views handed out by get() still reference the
            # mmap; the mapping is released when the last view dies.
            pass
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def write_safetensors(path: str, tensors: Dict[str, np.ndarray],
                      metadata: Optional[Dict[str, str]] = None) -> None:
    """Write a .safetensors file (sorted names, contiguous offsets)."""
    header: Dict[str, Any] = {}
    if metadata:
        header['__metadata__'] = metadata
    offset = 0
    arrays = []
    for name in sorted(tensors):
        arr = np.ascontiguousarray(tensors[name])
        n = arr.nbytes
        header[name] = {'dtype': _st_tag(arr.dtype),
                        'shape': list(arr.shape),
                        'data_offsets': [offset, offset + n]}
        arrays.append(arr)
        offset += n
    blob = json.dumps(header, separators=(',', ':')).encode('utf-8')
    # Pad the header to 8 bytes (spec allows trailing spaces).
    if len(blob) % 8:
        blob += b' ' * (8 - len(blob) % 8)
    tmp = path + '.tmp'
    with open(tmp, 'wb') as f:
        f.write(struct.pack('<Q', len(blob)))
        f.write(blob)
        for arr in arrays:
            f.write(arr.tobytes())
    os.replace(tmp, path)


def _iter_checkpoint_files(path: str) -> List[str]:
    """Resolve a checkpoint dir/file to its .safetensors shard list."""
    if os.path.isfile(path):
        return [path]
    index = os.path.join(path, 'model.safetensors.index.json')
    if os.path.exists(index):
        with open(index) as f:
            weight_map = json.load(f)['weight_map']
        return [os.path.join(path, fn) for fn in sorted(set(
            weight_map.values()))]
    single = os.path.join(path, 'model.safetensors')
    if os.path.exists(single):
        return [single]
    shards = sorted(
        os.path.join(path, fn) for fn in os.listdir(path)
        if fn.endswith('.safetensors'))
    if not shards:
        raise FileNotFoundError(f'no .safetensors files under {path}')
    return shards


# ---------------------------------------------------------------------------
# Config mapping
# ---------------------------------------------------------------------------

_SUPPORTED_MODEL_TYPES = ('llama', 'mistral', 'mixtral')


def config_from_hf(hf: Dict[str, Any], *,
                   name: Optional[str] = None) -> ModelConfig:
    """HF config.json dict -> ModelConfig."""
    model_type = hf.get('model_type', 'llama')
    if model_type == 'qwen2':
        # Qwen2 hardcodes q/k/v projection biases (not reflected in its
        # config.json), which the bias-free stacked layout cannot hold.
        raise ValueError(
            "model_type 'qwen2' is not importable: Qwen2 checkpoints "
            'carry QKV biases the stacked pytree has no slot for')
    if model_type == 'gemma':
        # Gemma's (1+weight) RMSNorm and sqrt(d_model) embedding scale
        # differ from the llama forward; importing would produce
        # silently wrong logits.
        raise ValueError(
            "model_type 'gemma' is not importable: its RMSNorm/embed "
            'conventions differ from the llama forward pass')
    if model_type not in _SUPPORTED_MODEL_TYPES:
        raise ValueError(
            f'unsupported HF model_type {model_type!r}; supported: '
            f'{_SUPPORTED_MODEL_TYPES}')
    if hf.get('attention_bias') or hf.get('qkv_bias') or hf.get(
            'mlp_bias'):
        raise ValueError('projection biases are not supported by the '
                         'stacked pytree layout')
    if hf.get('sliding_window') is not None:
        # Mistral-v0.1-style sliding-window attention: the forward pass
        # here attends over the full causal context, which would
        # silently diverge from the published model past the window.
        raise ValueError(
            f"sliding_window={hf['sliding_window']} attention is not "
            'supported; only full-causal-attention checkpoints import '
            '(Mistral v0.2+ exports set sliding_window to null)')
    kwargs: Dict[str, Any] = dict(
        name=name or hf.get('_name_or_path') or model_type,
        vocab_size=hf['vocab_size'],
        d_model=hf['hidden_size'],
        n_layers=hf['num_hidden_layers'],
        n_heads=hf['num_attention_heads'],
        n_kv_heads=hf.get('num_key_value_heads',
                          hf['num_attention_heads']),
        d_ff=hf['intermediate_size'],
        head_dim=hf.get('head_dim'),
        rope_theta=float(hf.get('rope_theta', 10_000.0)),
        norm_eps=float(hf.get('rms_norm_eps', 1e-5)),
        max_seq_len=int(hf.get('max_position_embeddings', 8192)),
        tie_embeddings=bool(hf.get('tie_word_embeddings', False)),
    )
    if model_type == 'mixtral':
        kwargs['num_experts'] = hf['num_local_experts']
        kwargs['experts_per_token'] = hf['num_experts_per_tok']
    scaling = hf.get('rope_scaling')
    if scaling:
        rtype = scaling.get('rope_type', scaling.get('type'))
        if rtype != 'llama3':
            raise ValueError(f'unsupported rope_scaling type {rtype!r} '
                             "(only 'llama3' NTK scaling)")
        kwargs.update(
            rope_scaling_factor=float(scaling['factor']),
            rope_low_freq_factor=float(scaling.get('low_freq_factor', 1.0)),
            rope_high_freq_factor=float(
                scaling.get('high_freq_factor', 4.0)),
            rope_original_max_position=int(
                scaling.get('original_max_position_embeddings', 8192)),
        )
    return ModelConfig(**kwargs)


def config_to_hf(cfg: ModelConfig) -> Dict[str, Any]:
    """ModelConfig -> HF config.json dict (llama/mixtral layout)."""
    hf: Dict[str, Any] = {
        'model_type': 'mixtral' if cfg.is_moe else 'llama',
        'architectures': ['MixtralForCausalLM' if cfg.is_moe
                          else 'LlamaForCausalLM'],
        'vocab_size': cfg.vocab_size,
        'hidden_size': cfg.d_model,
        'num_hidden_layers': cfg.n_layers,
        'num_attention_heads': cfg.n_heads,
        'num_key_value_heads': cfg.n_kv_heads,
        'intermediate_size': cfg.d_ff,
        'head_dim': cfg.resolved_head_dim,
        'rope_theta': cfg.rope_theta,
        'rms_norm_eps': cfg.norm_eps,
        'max_position_embeddings': cfg.max_seq_len,
        'tie_word_embeddings': cfg.tie_embeddings,
        'hidden_act': 'silu',
        'torch_dtype': 'float32',
    }
    if cfg.is_moe:
        hf['num_local_experts'] = cfg.num_experts
        hf['num_experts_per_tok'] = cfg.experts_per_token
    if cfg.rope_scaling:
        factor, low, high, orig = cfg.rope_scaling
        hf['rope_scaling'] = {
            'rope_type': 'llama3', 'factor': factor,
            'low_freq_factor': low, 'high_freq_factor': high,
            'original_max_position_embeddings': orig,
        }
    return hf


def load_config(path: str, *, name: Optional[str] = None,
                **overrides) -> ModelConfig:
    with open(os.path.join(path, 'config.json')) as f:
        cfg = config_from_hf(json.load(f), name=name)
    if overrides:
        cfg = dataclasses.replace(cfg, **overrides)
    return cfg


# ---------------------------------------------------------------------------
# Weight mapping
# ---------------------------------------------------------------------------

def _strip_prefix(key: str) -> str:
    return key[6:] if key.startswith('model.') else key


def load_checkpoint(path: str, *, dtype=None,
                    cfg: Optional[ModelConfig] = None,
                    **config_overrides) -> Tuple[Params, ModelConfig]:
    """HF checkpoint dir (config.json + *.safetensors) -> (params, cfg).

    ``dtype`` overrides the loaded param dtype (e.g. jnp.bfloat16 for
    serving — halves resident memory vs fp32).
    """
    import jax.numpy as jnp
    if cfg is None:
        cfg = load_config(path, **config_overrides)
    dt = (np.dtype(dtype) if dtype is not None else
          np.dtype(_bf16()) if cfg.param_dtype == jnp.bfloat16
          else np.float32)
    if dtype is not None:
        cfg = dataclasses.replace(cfg, param_dtype=dtype)

    d, f, v = cfg.d_model, cfg.d_ff, cfg.vocab_size
    h, kv, hd = cfg.n_heads, cfg.n_kv_heads, cfg.resolved_head_dim
    n = cfg.n_layers

    def alloc(*shape):
        return np.zeros(shape, dt)

    layers: Params = {
        'attn': {'wq': alloc(n, d, h, hd), 'wk': alloc(n, d, kv, hd),
                 'wv': alloc(n, d, kv, hd), 'wo': alloc(n, h, hd, d)},
        'ln_attn': {'scale': alloc(n, d)},
        'ln_mlp': {'scale': alloc(n, d)},
    }
    if cfg.is_moe:
        e = cfg.num_experts
        layers['moe'] = {'router': alloc(n, d, e),
                         'wi_gate': alloc(n, e, d, f),
                         'wi_up': alloc(n, e, d, f),
                         'wo': alloc(n, e, f, d)}
    else:
        layers['mlp'] = {'wi_gate': alloc(n, d, f),
                         'wi_up': alloc(n, d, f),
                         'wo': alloc(n, f, d)}
    params: Params = {
        'embed': {'embedding': alloc(v, d)},
        'layers': layers,
        'final_norm': {'scale': alloc(d,)},
    }
    if not cfg.tie_embeddings:
        params['lm_head'] = {'w': alloc(d, v)}

    seen = set()
    SKIP = 'skip'

    def assign(dest, src):
        np.copyto(dest, src.astype(dt, copy=False))

    def place(key: str, arr: np.ndarray):
        key = _strip_prefix(key)
        # Ignorable extras some exports carry (non-weights).
        if key.endswith('rotary_emb.inv_freq'):
            return SKIP
        if key == 'embed_tokens.weight':
            assign(params['embed']['embedding'], arr)
        elif key == 'norm.weight':
            assign(params['final_norm']['scale'], arr)
        elif key == 'lm_head.weight':
            if cfg.tie_embeddings:
                return SKIP  # redundant tied head in some exports
            assign(params['lm_head']['w'], arr.T)
        elif key.startswith('layers.'):
            parts = key.split('.')
            i = int(parts[1])
            rest = '.'.join(parts[2:])
            at = layers['attn']
            if rest == 'self_attn.q_proj.weight':
                assign(at['wq'][i], arr.T.reshape(d, h, hd))
            elif rest == 'self_attn.k_proj.weight':
                assign(at['wk'][i], arr.T.reshape(d, kv, hd))
            elif rest == 'self_attn.v_proj.weight':
                assign(at['wv'][i], arr.T.reshape(d, kv, hd))
            elif rest == 'self_attn.o_proj.weight':
                assign(at['wo'][i], arr.T.reshape(h, hd, d))
            elif rest == 'input_layernorm.weight':
                assign(layers['ln_attn']['scale'][i], arr)
            elif rest == 'post_attention_layernorm.weight':
                assign(layers['ln_mlp']['scale'][i], arr)
            elif rest == 'mlp.gate_proj.weight':
                assign(layers['mlp']['wi_gate'][i], arr.T)
            elif rest == 'mlp.up_proj.weight':
                assign(layers['mlp']['wi_up'][i], arr.T)
            elif rest == 'mlp.down_proj.weight':
                assign(layers['mlp']['wo'][i], arr.T)
            elif rest == 'block_sparse_moe.gate.weight':
                assign(layers['moe']['router'][i], arr.T)
            elif rest.startswith('block_sparse_moe.experts.'):
                j = int(rest.split('.')[2])
                w = rest.split('.')[3]
                moe = layers['moe']
                if w == 'w1':        # gate
                    assign(moe['wi_gate'][i, j], arr.T)
                elif w == 'w3':      # up
                    assign(moe['wi_up'][i, j], arr.T)
                elif w == 'w2':      # down
                    assign(moe['wo'][i, j], arr.T)
                else:
                    return False
            else:
                return False
        else:
            return False
        return True

    unmapped = []
    for fn in _iter_checkpoint_files(path):
        with SafetensorsReader(fn) as reader:
            for key in reader.keys():
                result = place(key, reader.get(key))
                if result is SKIP:
                    continue
                if result:
                    seen.add(_strip_prefix(key))
                else:
                    unmapped.append(key)
    if unmapped:
        raise ValueError(
            f'unmapped tensors in {path}: {sorted(unmapped)[:8]}'
            f'{"..." if len(unmapped) > 8 else ""}')
    # embed + final norm (+ head), per layer: 4 attn + 2 norms + either
    # 3 dense-MLP tensors or router + 3 per expert.
    per_layer = 6 + (1 + 3 * cfg.num_experts if cfg.is_moe else 3)
    expected = 2 + (0 if cfg.tie_embeddings else 1) + n * per_layer
    if len(seen) != expected:
        raise ValueError(
            f'checkpoint {path} incomplete: {len(seen)} tensors mapped, '
            f'expected {expected}')
    import jax
    params = jax.tree.map(jnp_asarray, params)
    return params, cfg


def jnp_asarray(x: np.ndarray):
    import jax.numpy as jnp
    return jnp.asarray(x)


def resolve_engine_inputs(hf_checkpoint: Optional[str], params, cfg, *,
                          dtype=None):
    """Shared serving-engine constructor path: when ``hf_checkpoint``
    is set, fill missing params/cfg from the HF dir (bf16 by default —
    serving wants half the resident memory of fp32)."""
    if not hf_checkpoint:
        return params, cfg
    import jax.numpy as jnp
    if params is None:
        params, cfg = load_checkpoint(hf_checkpoint,
                                      dtype=dtype or jnp.bfloat16)
    elif cfg is None:
        cfg = load_config(hf_checkpoint)
    return params, cfg


def iter_hf_tensors(params: Params,
                    cfg: ModelConfig) -> Iterator[Tuple[str, np.ndarray]]:
    """Stacked pytree -> (HF tensor name, array) pairs (export side)."""
    d, f = cfg.d_model, cfg.d_ff
    h, kv, hd = cfg.n_heads, cfg.n_kv_heads, cfg.resolved_head_dim

    def np_(x):
        return np.asarray(x)

    yield 'model.embed_tokens.weight', np_(params['embed']['embedding'])
    yield 'model.norm.weight', np_(params['final_norm']['scale'])
    if not cfg.tie_embeddings:
        yield 'lm_head.weight', np_(params['lm_head']['w']).T
    layers = params['layers']
    for i in range(cfg.n_layers):
        p = f'model.layers.{i}.'
        at = layers['attn']
        yield (p + 'self_attn.q_proj.weight',
               np_(at['wq'][i]).reshape(d, h * hd).T)
        yield (p + 'self_attn.k_proj.weight',
               np_(at['wk'][i]).reshape(d, kv * hd).T)
        yield (p + 'self_attn.v_proj.weight',
               np_(at['wv'][i]).reshape(d, kv * hd).T)
        yield (p + 'self_attn.o_proj.weight',
               np_(at['wo'][i]).reshape(h * hd, d).T)
        yield p + 'input_layernorm.weight', np_(layers['ln_attn']['scale'][i])
        yield (p + 'post_attention_layernorm.weight',
               np_(layers['ln_mlp']['scale'][i]))
        if cfg.is_moe:
            moe = layers['moe']
            yield (p + 'block_sparse_moe.gate.weight',
                   np_(moe['router'][i]).T)
            for j in range(cfg.num_experts):
                ep = p + f'block_sparse_moe.experts.{j}.'
                yield ep + 'w1.weight', np_(moe['wi_gate'][i, j]).T
                yield ep + 'w3.weight', np_(moe['wi_up'][i, j]).T
                yield ep + 'w2.weight', np_(moe['wo'][i, j]).T
        else:
            mlp = layers['mlp']
            yield p + 'mlp.gate_proj.weight', np_(mlp['wi_gate'][i]).T
            yield p + 'mlp.up_proj.weight', np_(mlp['wi_up'][i]).T
            yield p + 'mlp.down_proj.weight', np_(mlp['wo'][i]).T


def save_checkpoint(params: Params, cfg: ModelConfig, out_dir: str,
                    *, dtype=None) -> None:
    """Export the pytree as an HF-layout checkpoint (config.json +
    model.safetensors) loadable by ``transformers`` and by
    ``load_checkpoint`` — the finetune-then-publish path."""
    os.makedirs(out_dir, exist_ok=True)
    with open(os.path.join(out_dir, 'config.json'), 'w') as f:
        json.dump(config_to_hf(cfg), f, indent=2)
    tensors = {}
    for name, arr in iter_hf_tensors(params, cfg):
        if dtype is not None:
            arr = arr.astype(dtype)
        tensors[name] = arr
    write_safetensors(
        os.path.join(out_dir, 'model.safetensors'), tensors,
        metadata={'format': 'pt'})
