"""Task -> per-host launch script compilation.

Parity: ``sky/backends/task_codegen.py`` -- but where RayCodeGen emits a Ray
driver with placement groups and GPU-shaped env vars
(``SKYPILOT_NUM_GPUS_PER_NODE``, :626-666), this emits a plain bash script
per host with the **TPU-native distributed contract**:

* ``SKYT_NODE_RANK`` / ``SKYT_NODE_IPS`` / ``SKYT_NUM_NODES`` -- node-level
  (slice-level) topology, the analog of the reference's
  ``SKYPILOT_NODE_RANK/NODE_IPS/NUM_NODES`` (skylet/constants.py:521-526).
* ``TPU_WORKER_ID`` / ``TPU_WORKER_HOSTNAMES`` -- worker identity within a
  slice (what libtpu expects on multi-host slices).
* ``SKYT_COORDINATOR_ADDRESS`` + ``JAX_COORDINATOR_ADDRESS`` /
  ``JAX_NUM_PROCESSES`` / ``JAX_PROCESS_ID`` -- jax.distributed wiring
  across all hosts of all slices/nodes (replaces NCCL/torchrun env blocks;
  see SURVEY.md section 2.9 'distributed communication backend').
* ``MEGASCALE_*`` -- multi-slice (DCN) coordination hints when
  ``num_slices > 1``.
"""
from __future__ import annotations

import shlex
from typing import Dict, List, Optional

from skypilot_tpu.provision.api import ClusterInfo, HostInfo
from skypilot_tpu.spec.resources import Resources
from skypilot_tpu.spec.task import Task

JAX_COORDINATOR_PORT = 8476
RUNTIME_DIR = '~/.skyt_runtime'


def distributed_env(info: ClusterInfo,
                    host: HostInfo,
                    resources: Optional[Resources],
                    num_nodes: int) -> Dict[str, str]:
    """The full rank-env contract for one host."""
    node_hosts = info.hosts_of_node(host.node_index)
    all_hosts = info.hosts
    node_ips = node_ip_list(info)
    coordinator_ip = info.head_host.internal_ip
    process_id = all_hosts.index(host)
    env = {
        'SKYT_NODE_RANK': str(host.node_index),
        'SKYT_NODE_IPS': '\n'.join(node_ips),
        'SKYT_NUM_NODES': str(num_nodes),
        'SKYT_CLUSTER_NAME': info.cluster_name,
        'SKYT_COORDINATOR_ADDRESS':
            f'{coordinator_ip}:{JAX_COORDINATOR_PORT}',
        'JAX_COORDINATOR_ADDRESS':
            f'{coordinator_ip}:{JAX_COORDINATOR_PORT}',
        'JAX_NUM_PROCESSES': str(len(all_hosts)),
        'JAX_PROCESS_ID': str(process_id),
    }
    tpu = resources.tpu if resources is not None and resources.is_tpu else None
    if tpu is not None:
        workers_in_slice = [h for h in node_hosts
                            if _slice_of(h, tpu) == _slice_of(host, tpu)]
        env.update({
            'TPU_WORKER_ID': str(host.worker_index % tpu.hosts_per_slice),
            'TPU_WORKER_HOSTNAMES': ','.join(
                h.internal_ip for h in workers_in_slice),
            'SKYT_TPU_ACCELERATOR': tpu.accelerator_name,
            'SKYT_TPU_TOPOLOGY': tpu.topology_str,
        })
        if tpu.num_slices > 1:
            slice_id = host.worker_index // tpu.hosts_per_slice
            env.update({
                'MEGASCALE_COORDINATOR_ADDRESS': coordinator_ip,
                'MEGASCALE_NUM_SLICES': str(tpu.num_slices),
                'MEGASCALE_SLICE_ID': str(slice_id),
            })
    return env


def _slice_of(host: HostInfo, tpu) -> int:
    return host.worker_index // tpu.hosts_per_slice


def make_job_script(command: str,
                    env: Dict[str, str],
                    *,
                    workdir: Optional[str] = None,
                    secrets: Optional[Dict[str, str]] = None) -> str:
    """A self-contained bash script: env exports + cd + user command."""
    lines = ['#!/usr/bin/env bash', 'set -uo pipefail', '']
    # The shipped runtime (runtime_setup.py REMOTE_PKG_DIR; local-style
    # hosts get a symlink) -- makes `python3 -m skypilot_tpu.*` payloads
    # (the in-tree recipes) importable on every cluster host.
    lines.append('export PYTHONPATH="$HOME/.skyt_runtime/runtime'
                 '${PYTHONPATH:+:$PYTHONPATH}"')
    for key, value in env.items():
        lines.append(f'export {key}={shlex.quote(str(value))}')
    for key, value in (secrets or {}).items():
        lines.append(f'export {key}={shlex.quote(str(value))}')
    if workdir:
        if workdir == '~':
            lines.append('cd "$HOME"')
        elif workdir.startswith('~/'):
            lines.append(f'cd "$HOME/{workdir[2:]}"')  # quoted ~ won't expand
        else:
            lines.append(f'cd {shlex.quote(workdir)}')
    lines += ['', command, '']
    return '\n'.join(lines)


def task_env_for_host(task: Task,
                      info: ClusterInfo,
                      host: HostInfo,
                      resources: Optional[Resources]) -> Dict[str, str]:
    env = dict(task.envs)
    env.update(distributed_env(info, host, resources, task.num_nodes))
    return env


def node_ip_list(info: ClusterInfo) -> List[str]:
    """Head IP of each node, rank-ordered (for CommandGen run functions)."""
    out = []
    for node in range(info.num_nodes):
        hosts = info.hosts_of_node(node)
        if hosts:
            out.append(hosts[0].internal_ip)
    return out
