"""Backend layer: cluster lifecycle engine (parity: ``sky/backends/``)."""
from skypilot_tpu.backend.backend import Backend
from skypilot_tpu.backend.tpu_backend import TpuPodBackend

__all__ = ['Backend', 'TpuPodBackend']
