"""TpuPodBackend: the cluster lifecycle engine.

Parity: ``CloudVmRayBackend`` (cloud_vm_ray_backend.py:3083) minus Ray:
gang semantics come from the provisioner (a TPU slice is created
atomically) plus concurrent per-host rank launch here -- no placement
groups, no vendored Ray patches (SURVEY.md section 7 design stance).
"""
from __future__ import annotations

import sys
from typing import Dict, List, Optional

from skypilot_tpu import exceptions, state
from skypilot_tpu.backend import codegen, runtime_setup
from skypilot_tpu.backend.backend import Backend
from skypilot_tpu.optimizer import Candidate, Optimizer
from skypilot_tpu.provision.api import ClusterInfo, get_provider
from skypilot_tpu.provision.provisioner import provision_with_failover
from skypilot_tpu.runtime.job_client import job_table_for
from skypilot_tpu.spec.task import Task
from skypilot_tpu.utils import locks, log
from skypilot_tpu.utils.command_runner import (CommandRunner,
                                               runners_for_cluster)
from skypilot_tpu.utils.registry import BACKEND_REGISTRY
from skypilot_tpu.utils.subprocess_utils import run_in_parallel

logger = log.init_logger(__name__)

_WORKDIR_REMOTE = '~/skyt_workdir'


@BACKEND_REGISTRY.register('tpu-pod', default=True)
class TpuPodBackend(Backend):
    """Provision TPU pod slices; run tasks with jax.distributed wiring."""

    # ------------------------------------------------------------------
    # Provision
    # ------------------------------------------------------------------

    def provision(self, task: Task, cluster_name: str, *,
                  retry_until_up: bool = False,
                  dryrun: bool = False,
                  blocklist=None) -> Optional[ClusterInfo]:
        candidates = Optimizer.plan_task(task)
        if task.best_resources is not None:
            # An upstream optimize pass (joint DAG placement, or a
            # caller that pinned the choice) already decided: its pick
            # leads, the rest of the ranking stays as failover tail.
            best = task.best_resources

            def _is_best(c) -> bool:
                return (c.resources.cloud == best.cloud and
                        c.resources.region == best.region and
                        (best.zone is None or
                         c.resources.zone == best.zone))

            candidates = ([c for c in candidates if _is_best(c)] +
                          [c for c in candidates if not _is_best(c)])
        if task.volumes:
            # Volume gate: a named volume lives on ONE cloud (a PVC is
            # meaningless on GCE and vice versa), so candidates must be
            # pinned to the volumes' cloud — otherwise a cheaper cloud
            # could win the ranking and the mounts would silently become
            # plain local directories.
            from skypilot_tpu import volumes as volumes_lib
            volume_clouds = {
                volumes_lib.get(name)['cloud']
                for name in task.volumes.values()}
            if len(volume_clouds) > 1:
                raise exceptions.NotSupportedError(
                    f'Task mounts volumes from multiple clouds '
                    f'{sorted(volume_clouds)}; volumes of one task must '
                    f'share a cloud.')
            volume_cloud = volume_clouds.pop()
            supported = [c for c in candidates
                         if c.resources.cloud == volume_cloud]
            if not supported:
                raise exceptions.NotSupportedError(
                    f'Task mounts volumes on {volume_cloud!r} but that '
                    f'cloud is not among the feasible candidates '
                    f'({sorted({c.resources.cloud for c in candidates})}'
                    f'); pin `cloud: {volume_cloud}` or drop the '
                    f'volumes.')
            candidates = supported
        # FUSE-mount storage on k8s needs the fuse-proxy shim wired into
        # the pod manifest (provision/kubernetes.py _needs_fuse); flag it
        # via a label so the request carries the hint to any provider.
        from skypilot_tpu.data.storage import StorageMode
        needs_fuse = any(
            (mount.get('mode') or 'MOUNT').upper() in
            (StorageMode.MOUNT.value, StorageMode.MOUNT_CACHED.value)
            for mount in task.storage_mounts.values())
        if needs_fuse:
            for cand in candidates:
                cand.resources = cand.resources.copy(
                    labels={**cand.resources.labels, 'skyt-fuse': 'true'})
        if dryrun:
            logger.info('Dryrun: would provision %s', candidates[0])
            return None
        with locks.cluster_lock(cluster_name):
            return self._provision_locked(task, cluster_name, candidates,
                                          blocklist=blocklist)

    def _provision_locked(self, task: Task, cluster_name: str,
                          candidates: List[Candidate],
                          blocklist=None) -> ClusterInfo:
        record = state.get_cluster(cluster_name)
        if record is not None:
            # Reusing (or resuming) an existing cluster crosses into its
            # workspace; same guard as core ops (_get_record).
            from skypilot_tpu import workspaces
            workspaces.check_cluster_access(record, op='launch on')
        if record is not None and record.status == state.ClusterStatus.UP:
            info = ClusterInfo.from_dict(record.handle)
            # Reuse only if the existing cluster satisfies the request
            # (parity: Resources.less_demanding_than check in execution).
            from skypilot_tpu.spec.resources import Resources
            existing = Resources.from_yaml_config(record.resources)
            if not any(c.resources.less_demanding_than(existing) or
                       task.resources[0].less_demanding_than(existing)
                       for c in candidates):
                raise exceptions.ResourcesMismatchError(
                    f'Cluster {cluster_name!r} exists with {existing}, '
                    f'which does not satisfy the requested resources. '
                    f'Use a new cluster name or `skyt down {cluster_name}`.')
            state.touch_cluster(cluster_name)
            # The reuse path still mounts task volumes (sync stage), so
            # they must be recorded as attached — otherwise `volumes
            # delete` would pass the in-use check and pull the backing
            # storage out from under the running job.
            if task.volumes:
                from skypilot_tpu import volumes as volumes_lib
                for mount in self._resolve_volumes(task):
                    volumes_lib.note_attached(mount['name'], cluster_name)
            return info
        resume = record is not None and (
            record.status == state.ClusterStatus.STOPPED)
        state.add_or_update_cluster(
            cluster_name, status=state.ClusterStatus.INIT,
            num_nodes=task.num_nodes)
        volume_mounts = self._resolve_volumes(task)
        info, chosen = provision_with_failover(
            cluster_name, candidates, task.num_nodes, resume=resume,
            blocklist=blocklist, volumes=volume_mounts)
        autostop = chosen.resources.autostop
        state.add_or_update_cluster(
            cluster_name,
            status=state.ClusterStatus.UP,
            cloud=chosen.resources.cloud,
            region=chosen.resources.region,
            zone=chosen.resources.zone,
            resources=chosen.resources.to_yaml_config(),
            handle=info.to_dict(),
            num_nodes=task.num_nodes,
            autostop=(autostop.to_yaml_config()
                      if autostop.enabled else {}),
            hourly_cost=chosen.hourly_cost)
        self._start_runtime_daemon(
            info, autostop=(autostop.to_yaml_config()
                            if autostop.enabled else {}))
        if volume_mounts:
            from skypilot_tpu import volumes as volumes_lib
            for mount in volume_mounts:
                volumes_lib.note_attached(mount['name'], cluster_name)
        return info

    @staticmethod
    def _resolve_volumes(task: Task) -> List[Dict]:
        """task.volumes (mount_path -> name) resolved against the volume
        table; every named volume must exist (`skyt volumes apply`)."""
        if not task.volumes:
            return []
        from skypilot_tpu import volumes as volumes_lib
        resolved = []
        for mount_path, volume_name in sorted(task.volumes.items()):
            record = volumes_lib.get(volume_name)  # raises if missing
            resolved.append({
                'name': volume_name,
                'mount_path': mount_path,
                'type': record['type'],
                'config': record['config'],
            })
        return resolved

    def _start_runtime_daemon(self, info: ClusterInfo,
                              autostop=None) -> None:
        """Ship the runtime + start the skylet-equivalent daemon (parity:
        wheel_utils + instance_setup.setup_runtime_on_cluster :301 +
        start_skylet_on_head_node :598). One path for every cluster
        flavor -- local-style daemons run backend-side, SSH clusters get
        the package shipped and the daemon started on the head node."""
        runtime_setup.ensure_runtime(info, autostop=autostop)

    # ------------------------------------------------------------------
    # Sync
    # ------------------------------------------------------------------

    def sync_workdir(self, info: ClusterInfo, task: Task) -> None:
        if not task.workdir:
            return
        runners = runners_for_cluster(info)

        def sync(runner: CommandRunner) -> None:
            runner.rsync(task.workdir, _WORKDIR_REMOTE.replace('~/', '~/'),
                         up=True,
                         excludes=['.git', '__pycache__', '*.pyc'])

        # Every host of every slice gets the workdir (the reference syncs
        # to all pod hosts too, docs/source/reference/tpu.rst:152-196).
        run_in_parallel(sync, runners)

    def sync_file_mounts(self, info: ClusterInfo, task: Task) -> None:
        """file_mounts (rsync or bucket COPY) + storage_mounts (bucket
        MOUNT/COPY/MOUNT_CACHED) onto every host (parity:
        cloud_vm_ray_backend.py:3876 _execute_file_mounts +
        _execute_storage_mounts)."""
        from skypilot_tpu.data.storage import Storage
        runners = runners_for_cluster(info)
        for dst, src in (task.file_mounts or {}).items():
            if '://' in src:
                # Bucket-sourced file mount == COPY-mode storage mount
                # (ref storage.py:781 docstring contract).
                storage = Storage(source=src, mode='COPY')
                storage.ensure_bucket()  # fail client-side on a typo'd bucket
                self._run_mount_command(runners, dst,
                                        storage.cluster_command(dst))
                continue

            def sync(runner: CommandRunner, _src=src, _dst=dst) -> None:
                runner.rsync(_src, _dst, up=True)

            run_in_parallel(sync, runners)
        for dst, config in (task.storage_mounts or {}).items():
            storage = Storage.from_yaml_config(config)
            storage.ensure_bucket()
            self._run_mount_command(runners, dst,
                                    storage.cluster_command(dst))
        # Named volumes. k8s PVCs are already in the pod manifest
        # (provision-time); command-mounted providers (fake/local hostpath,
        # GCE PD) get their mount commands run on every host here.
        if task.volumes:
            from skypilot_tpu import volumes as volumes_lib
            for mount_path, volume_name in sorted(task.volumes.items()):
                record = volumes_lib.get(volume_name)
                if record['type'] == 'k8s-pvc':
                    continue
                for cmd in volumes_lib.mount_commands(volume_name,
                                                      mount_path):
                    self._run_mount_command(runners, mount_path, cmd)

    @staticmethod
    def _run_mount_command(runners, dst: str, cmd: str) -> None:
        def mount(runner: CommandRunner) -> None:
            code, output = runner.run(cmd)
            if code != 0:
                raise exceptions.StorageError(
                    f'Mount of {dst} failed (exit {code}): '
                    f'{output[-800:]}')

        run_in_parallel(mount, runners)

    # ------------------------------------------------------------------
    # Setup / execute
    # ------------------------------------------------------------------

    def setup(self, info: ClusterInfo, task: Task) -> None:
        if not task.setup:
            return
        runners = runners_for_cluster(info)

        def run_setup(pair) -> None:
            runner, host = pair
            env = codegen.task_env_for_host(task, info, host,
                                            _task_resources(task))
            script = codegen.make_job_script(
                task.setup, env,
                workdir=_WORKDIR_REMOTE if task.workdir else None,
                secrets=task.secrets)
            code, output = runner.run(script, log_path='~/.skyt_runtime/setup.log')
            if code != 0:
                raise exceptions.CommandError(
                    code, 'setup', error_msg=output[-2000:])

        run_in_parallel(run_setup, list(zip(runners, info.hosts)))

    @staticmethod
    def _daemon_ready(info: ClusterInfo, job_table,
                      grace: Optional[float] = None) -> bool:
        """daemon_alive with a startup grace: a just-provisioned
        cluster's daemon is nohup'd and needs a beat to write its first
        heartbeat — checking the instant after launch would misread a
        healthy cluster as dead. Local-style clusters skip the grace
        (the in-process pid-verified check is authoritative, and the
        restart path behind this check is a cheap idempotent respawn);
        remote polls pay an SSH exec each, so they poll slowly."""
        import os as os_lib
        import time as time_lib
        if job_table.daemon_alive():
            return True
        if runtime_setup.is_local_style(info):
            return False
        if grace is None:
            from skypilot_tpu.utils import env_registry
            grace = env_registry.get_float('SKYT_DAEMON_START_GRACE')
        deadline = time_lib.monotonic() + grace
        while time_lib.monotonic() < deadline:
            time_lib.sleep(2.0)
            if job_table.daemon_alive():
                return True
        return False

    def execute(self, info: ClusterInfo, task: Task, *,
                detach: bool = True) -> int:
        """Run the task on every host; returns the job id.

        EVERY job flows through the cluster's job queue and is
        gang-started by the runtime daemon — attached runs simply
        follow the rank-0 log until the job is terminal (parity:
        `sky exec` codegens + submits to the job queue and tails,
        never drives ranks from the client). A foreground side-channel
        would bypass the daemon's admission control (TPU exclusivity,
        concurrency caps).
        """
        resources = _task_resources(task)
        node_ips = codegen.node_ip_list(info)
        job_table = job_table_for(info)

        # The submission protocol writes all rank scripts BEFORE the
        # job becomes PENDING: the daemon polls every second and must
        # never observe a partial script set (it would gang-start a
        # partial pod). DirectJobTable does this in-process;
        # RemoteJobTable does it atomically on-head via the job_cli
        # shim (one SSH round trip).
        scripts: Dict[int, str] = {}
        for idx, host in enumerate(info.hosts):
            command = task.get_run_command(host.node_index, node_ips)
            if command is None:
                continue
            env = codegen.task_env_for_host(task, info, host, resources)
            scripts[idx] = codegen.make_job_script(
                command, env,
                workdir=_WORKDIR_REMOTE if task.workdir else None,
                secrets=task.secrets)
        # The daemon's admission control needs the job's resource
        # class: tasks that EXPLICITLY request no accelerator are
        # CPU-only and may share the cluster with a running TPU job.
        # No resources at all (bare `exec`) conservatively counts as
        # TPU — a surprise-concurrent TPU program would crash on busy
        # devices.
        uses_tpu = (resources is None
                    or bool(resources.accelerators))
        if not detach and not self._daemon_ready(info, job_table):
            # Attached runs need a live daemon or the follow would hang
            # on a forever-PENDING job. Local-style daemons can simply
            # be restarted; a dead remote daemon means the runtime needs
            # re-shipping (skyt launch does).
            if runtime_setup.is_local_style(info):
                from skypilot_tpu.runtime import daemon as daemon_lib
                daemon_lib.start_daemon(
                    info.cluster_name, runtime_setup.head_runtime_dir(info))
            else:
                raise exceptions.ClusterNotUpError(
                    f'Runtime daemon on {info.cluster_name!r} is not '
                    'responding; cannot run an attached job. Re-run '
                    '`skyt launch` to restore the cluster runtime.')
        job_id = job_table.submit(task.name, len(info.hosts), scripts,
                                  metadata={'uses_tpu': uses_tpu})
        state.touch_cluster(info.cluster_name)
        if detach:
            return job_id
        job_table.tail(job_id, follow=True, stream=sys.stdout)
        state.touch_cluster(info.cluster_name)
        return job_id

    # ------------------------------------------------------------------
    # Queue / logs / teardown
    # ------------------------------------------------------------------

    def _head_runtime_dir(self, info: ClusterInfo) -> str:
        """Runtime dir of the head host, resolved for local-style clusters."""
        return runtime_setup.head_runtime_dir(info)

    def queue(self, info: ClusterInfo) -> List[Dict]:
        return job_table_for(info).list_jobs()

    def cancel(self, info: ClusterInfo, job_id: int) -> bool:
        return job_table_for(info).cancel(job_id)

    def tail_logs(self, info: ClusterInfo, job_id: Optional[int] = None,
                  stream=None, follow: bool = False) -> str:
        """Return (and optionally follow) the rank-0 log of a job."""
        stream = stream or sys.stdout
        job_table = job_table_for(info)
        if job_id is None:
            jobs = job_table.list_jobs()
            if not jobs:
                raise exceptions.JobNotFoundError('No jobs on cluster')
            job_id = jobs[0]['job_id']
        return job_table.tail(job_id, follow=follow, stream=stream)

    def teardown(self, cluster_name: str, *, terminate: bool = True) -> None:
        with locks.cluster_lock(cluster_name):
            record = state.get_cluster(cluster_name)
            if record is None:
                raise exceptions.ClusterDoesNotExist(
                    f'Cluster {cluster_name!r} not found.')
            if record.handle:
                runtime_setup.local_daemon_teardown(
                    ClusterInfo.from_dict(record.handle))
            provider = get_provider(record.cloud or 'fake')
            if terminate:
                provider.terminate_instances(cluster_name)
                state.remove_cluster(cluster_name)
                state.add_cluster_event(cluster_name, 'TERMINATED', '')
            else:
                provider.stop_instances(cluster_name)
                state.set_cluster_status(cluster_name,
                                         state.ClusterStatus.STOPPED)
                state.add_cluster_event(cluster_name, 'STOPPED', '')


def _task_resources(task: Task):
    return task.best_resources or (task.resources[0] if task.resources
                                   else None)
