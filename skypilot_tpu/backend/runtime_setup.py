"""Runtime self-distribution + on-cluster daemon bring-up.

Parity with three reference pieces:

* ``sky/backends/wheel_utils.py:1-40`` -- build the framework package
  locally (content-hashed tarball, cached) so remote runtime == local
  version;
* ``sky/provision/instance_setup.py:301 setup_runtime_on_cluster`` --
  parallel per-host ship + install;
* ``sky/provision/instance_setup.py:598 start_skylet_on_head_node`` --
  start the runtime daemon on the head.

Local-style clusters (fake/local providers) skip shipping -- every "host"
is a directory on this machine and the daemon runs backend-side -- but go
through the SAME cluster.json spec, so one daemon implementation serves
both paths (runtime/daemon.py).
"""
from __future__ import annotations

import hashlib
import os
import shutil
import subprocess
import tarfile
from typing import List, Optional, Tuple

from skypilot_tpu import exceptions
from skypilot_tpu.provision.api import ClusterInfo
from skypilot_tpu.runtime import cluster_spec as spec_lib
from skypilot_tpu.runtime import daemon as daemon_lib
from skypilot_tpu.runtime.job_client import (REMOTE_PKG_DIR,
                                             REMOTE_RUNTIME_DIR)
from skypilot_tpu.utils import log
from skypilot_tpu.utils.command_runner import (CommandRunner,
                                               runners_for_cluster)
from skypilot_tpu.utils.subprocess_utils import run_in_parallel

logger = log.init_logger(__name__)


def is_local_style(info: ClusterInfo) -> bool:
    """True when the cluster's "hosts" are directories on this machine."""
    return bool(info.custom.get('fake') or info.custom.get('local'))


def head_runtime_dir(info: ClusterInfo) -> str:
    """The head host's runtime dir, resolved for local-style clusters."""
    if is_local_style(info):
        head = runners_for_cluster(info)[0]
        return head._resolve(REMOTE_RUNTIME_DIR)  # pylint: disable=protected-access
    return REMOTE_RUNTIME_DIR


# ---------------------------------------------------------------------------
# Packaging (parity: wheel_utils.build_sky_wheel)
# ---------------------------------------------------------------------------

def _package_root() -> str:
    import skypilot_tpu
    return os.path.dirname(os.path.abspath(skypilot_tpu.__file__))


def _iter_package_files(root: str) -> List[str]:
    out = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != '__pycache__']
        for name in sorted(filenames):
            if name.endswith(('.pyc', '.pyo')):
                continue
            out.append(os.path.join(dirpath, name))
    return sorted(out)


def package_runtime() -> tuple:
    """Build (or reuse) the content-addressed runtime tarball.

    Returns (tarball_path, content_hash). Extracting the tarball yields
    ``skypilot_tpu/...`` so PYTHONPATH=<extract dir> makes it importable.
    """
    root = _package_root()
    files = _iter_package_files(root)
    hasher = hashlib.sha256()
    for path in files:
        hasher.update(os.path.relpath(path, root).encode('utf-8'))
        with open(path, 'rb') as f:
            hasher.update(f.read())
    content_hash = hasher.hexdigest()[:16]

    # Cache location overridable so short-lived state dirs (tests, CI
    # sandboxes) can share one tarball across environments.
    state_dir = os.environ.get('SKYT_STATE_DIR',
                               os.path.expanduser('~/.skyt'))
    cache_dir = os.environ.get(
        'SKYT_RUNTIME_PKG_CACHE', os.path.join(state_dir, 'runtime_pkg'))
    os.makedirs(cache_dir, exist_ok=True)
    tarball = os.path.join(cache_dir, f'skypilot_tpu-{content_hash}.tar.gz')
    if not os.path.exists(tarball):
        # Unique temp name: concurrent builders (two test sessions, two
        # executor children) must not interleave writes into one '.tmp'
        # and os.replace a corrupt archive.
        import tempfile
        fd, tmp = tempfile.mkstemp(dir=cache_dir, suffix='.tmp')
        try:
            os.close(fd)
            with tarfile.open(tmp, 'w:gz') as tar:
                for path in files:
                    arcname = os.path.join(
                        'skypilot_tpu', os.path.relpath(path, root))
                    tar.add(path, arcname=arcname)
            os.replace(tmp, tarball)
        except BaseException:
            # A failed build must not leak half-written .tmp archives
            # into the long-lived cache dir.
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        logger.info('Packaged runtime %s (%d files)', content_hash,
                    len(files))
    return tarball, content_hash


# ---------------------------------------------------------------------------
# Cluster-internal SSH key (head -> worker fan-out)
# ---------------------------------------------------------------------------

REMOTE_CLUSTER_KEY = f'{REMOTE_RUNTIME_DIR}/cluster_key'


def _ensure_cluster_key(cluster_name: str,
                        fallback_key: Optional[str]
                        ) -> Tuple[Optional[str], Optional[str]]:
    """A dedicated keypair for intra-cluster SSH (head daemon -> ranks).

    Returns (private_key_path, public_key_text) on the CLIENT. Generated
    once per cluster with ssh-keygen; when ssh-keygen is unavailable the
    provisioning key is reused (parity: the reference generates a cluster
    key in backend_utils and distributes it via cloud metadata /
    authorized_keys).
    """
    state_dir = os.environ.get('SKYT_STATE_DIR',
                               os.path.expanduser('~/.skyt'))
    key_dir = os.path.join(state_dir, 'keys', cluster_name)
    key_path = os.path.join(key_dir, 'cluster_key')
    pub_path = key_path + '.pub'
    if not os.path.exists(key_path):
        os.makedirs(key_dir, exist_ok=True)
        if shutil.which('ssh-keygen'):
            subprocess.run(
                ['ssh-keygen', '-t', 'ed25519', '-N', '', '-q',
                 '-C', f'skyt-{cluster_name}', '-f', key_path],
                check=True)
        elif fallback_key and os.path.exists(
                os.path.expanduser(fallback_key)):
            shutil.copy2(os.path.expanduser(fallback_key), key_path)
            os.chmod(key_path, 0o600)
            fallback_pub = os.path.expanduser(fallback_key) + '.pub'
            if os.path.exists(fallback_pub):
                shutil.copy2(fallback_pub, pub_path)
        else:
            return None, None
    pub_text = None
    if os.path.exists(pub_path):
        with open(pub_path, encoding='utf-8') as f:
            pub_text = f.read().strip()
    return key_path, pub_text


def _install_cluster_key(runners: List[CommandRunner], key_path: str,
                         pub_text: Optional[str]) -> None:
    """Private key to the head; pubkey into every host's authorized_keys."""
    head = runners[0]
    head.run(f'mkdir -p {REMOTE_RUNTIME_DIR}', check=True)
    head.rsync(key_path, f'{REMOTE_RUNTIME_DIR}/', up=True)
    head.run(f'chmod 600 {REMOTE_CLUSTER_KEY}', check=True)
    if not pub_text:
        return

    def authorize(runner: CommandRunner) -> None:
        quoted = pub_text.replace("'", "'\\''")
        runner.run(
            f'mkdir -p ~/.ssh && chmod 700 ~/.ssh && '
            f"grep -qF '{quoted}' ~/.ssh/authorized_keys 2>/dev/null || "
            f"echo '{quoted}' >> ~/.ssh/authorized_keys && "
            f'chmod 600 ~/.ssh/authorized_keys', check=True)

    run_in_parallel(authorize, runners)


# ---------------------------------------------------------------------------
# Cluster spec construction
# ---------------------------------------------------------------------------

def build_cluster_spec(info: ClusterInfo,
                       autostop: Optional[dict] = None,
                       ssh_key: Optional[str] = None
                       ) -> spec_lib.ClusterSpec:
    hosts: List[spec_lib.HostSpec] = []
    if is_local_style(info):
        runners = runners_for_cluster(info)
        for rank, (runner, host) in enumerate(zip(runners, info.hosts)):
            hosts.append(spec_lib.HostSpec(
                rank=rank, kind='local',
                root=getattr(runner, 'host_root', '~'),
                node_index=host.node_index,
                worker_index=host.worker_index))
    else:
        for rank, host in enumerate(info.hosts):
            if rank == 0:
                # The daemon runs ON the head node itself.
                hosts.append(spec_lib.HostSpec(
                    rank=0, kind='local', root='~',
                    node_index=host.node_index,
                    worker_index=host.worker_index))
            else:
                hosts.append(spec_lib.HostSpec(
                    rank=rank, kind='ssh',
                    address=host.internal_ip,
                    ssh_port=host.ssh_port,
                    node_index=host.node_index,
                    worker_index=host.worker_index))
    return spec_lib.ClusterSpec(
        cluster_name=info.cluster_name,
        cloud=info.provider,
        hosts=hosts,
        ssh_user=info.ssh_user,
        ssh_key=ssh_key,
        autostop=autostop or {})


# ---------------------------------------------------------------------------
# Bring-up
# ---------------------------------------------------------------------------

def _ship_runtime_to_host(runner: CommandRunner, tarball: str,
                          content_hash: str) -> None:
    code, out = runner.run(
        f'cat {REMOTE_RUNTIME_DIR}/runtime_hash 2>/dev/null || true')
    if code == 0 and out.strip() == content_hash:
        return  # up to date
    # Ship into a DIRECTORY, not a file path: rsync-over-ssh and the
    # kubectl tar-pipe transport both place the file inside a target dir
    # under its basename, so this is the one dst shape that behaves the
    # same on every runner.
    pkg_dir = f'{REMOTE_RUNTIME_DIR}/pkg'
    remote_tar = f'{pkg_dir}/{os.path.basename(tarball)}'
    runner.run(f'mkdir -p {pkg_dir}', check=True)
    runner.rsync(tarball, pkg_dir + '/', up=True)
    # The import probe catches broken installs on real clusters but
    # costs a ~2s python start per host; test harnesses (which install
    # the very package they run from) may skip it.
    from skypilot_tpu.utils import env_registry
    skip_verify = env_registry.get_bool('SKYT_RUNTIME_SKIP_IMPORT_CHECK')
    verify = ('true' if skip_verify
              else f'PYTHONPATH={REMOTE_PKG_DIR} python3 -c '
                   f'"import skypilot_tpu"')
    code, out = runner.run(
        f'mkdir -p {REMOTE_PKG_DIR} && '
        f'tar -xzf {remote_tar} -C {REMOTE_PKG_DIR} && '
        f'rm -rf {pkg_dir} && '
        f'echo {content_hash} > {REMOTE_RUNTIME_DIR}/runtime_hash && '
        f'{verify} && '
        f'echo SKYT_RUNTIME_OK')
    if code != 0 or 'SKYT_RUNTIME_OK' not in out:
        raise exceptions.CommandError(
            code or 1, 'runtime install', error_msg=out[-2000:])


def _start_remote_daemon(head_runner: CommandRunner) -> None:
    probe = (f'PYTHONPATH={REMOTE_PKG_DIR}:$PYTHONPATH python3 -m '
             f'skypilot_tpu.runtime.job_cli --runtime-dir '
             f'{REMOTE_RUNTIME_DIR} daemon-status')
    code, out = head_runner.run(probe)
    if code == 0 and '"alive": true' in out:
        return
    # NOTE: assignment-prefix (not `env VAR=~/..`) so the shell
    # tilde-expands REMOTE_PKG_DIR; nohup inherits the environment.
    start = (f'PYTHONPATH={REMOTE_PKG_DIR}:$PYTHONPATH '
             f'nohup python3 -um skypilot_tpu.runtime.daemon '
             f'--runtime-dir {REMOTE_RUNTIME_DIR} '
             f'>> {REMOTE_RUNTIME_DIR}/daemon.log 2>&1 < /dev/null & '
             f'echo SKYT_DAEMON_STARTED $!')
    code, out = head_runner.run(start)
    if code != 0 or 'SKYT_DAEMON_STARTED' not in out:
        raise exceptions.CommandError(code or 1, 'daemon start',
                                      error_msg=out[-2000:])


def stop_remote_daemon(head_runner: CommandRunner) -> None:
    """Best-effort daemon kill on the head node (teardown path)."""
    # Heartbeat/pid files are scrubbed with the kill: a re-provision of
    # the same host minutes later must not read the dead daemon's fresh
    # heartbeat as "alive" and skip starting its own daemon.
    cmd = (f'pid=$(cat {REMOTE_RUNTIME_DIR}/daemon.pid 2>/dev/null); '
           f'if [ -n "$pid" ]; then kill $pid 2>/dev/null; fi; '
           f'rm -f {REMOTE_RUNTIME_DIR}/daemon.pid '
           f'{REMOTE_RUNTIME_DIR}/daemon_heartbeat; true')
    try:
        head_runner.run(cmd, timeout=60)
    except Exception as e:  # pylint: disable=broad-except
        logger.warning('Remote daemon stop failed: %s', e)


def ensure_runtime(info: ClusterInfo,
                   autostop: Optional[dict] = None) -> None:
    """Ship the runtime, write the cluster spec, start the daemon.

    Idempotent: re-running on an up cluster re-ships only when the
    package content changed and never double-starts the daemon.
    """
    if is_local_style(info):
        spec = build_cluster_spec(info, autostop=autostop)
        runtime_dir = head_runtime_dir(info)
        os.makedirs(runtime_dir, exist_ok=True)
        spec_lib.write_spec(runtime_dir, spec)
        # "Ship" the runtime to each local host root as a symlink so job
        # scripts find it at the uniform $HOME/.skyt_runtime/runtime
        # location (same contract as _ship_runtime_to_host over SSH).
        pkg_root = _package_root()
        for host in spec.hosts:
            root = os.path.expanduser(host.root or '~')
            link_dir = os.path.join(root, '.skyt_runtime', 'runtime')
            os.makedirs(link_dir, exist_ok=True)
            link = os.path.join(link_dir, 'skypilot_tpu')
            if os.path.lexists(link) and not os.path.exists(link):
                os.remove(link)  # dangling symlink from a moved install
            if not os.path.lexists(link):
                os.symlink(pkg_root, link)
        daemon_lib.start_daemon(info.cluster_name, runtime_dir)
        return

    runners = runners_for_cluster(info)
    tarball, content_hash = package_runtime()

    def setup_host(runner: CommandRunner) -> None:
        _ship_runtime_to_host(runner, tarball, content_hash)

    # Parallel ship to every host (parity: instance_setup.py:301).
    run_in_parallel(setup_host, runners)

    head = runners[0]
    # Multi-host: the head daemon fans ranks out over SSH, so it needs a
    # key that works cluster-internally -- generate + install one.
    remote_key: Optional[str] = None
    if len(info.hosts) > 1:
        key_path, pub_text = _ensure_cluster_key(info.cluster_name,
                                                 info.ssh_key_path)
        if key_path:
            _install_cluster_key(runners, key_path, pub_text)
            remote_key = REMOTE_CLUSTER_KEY
        else:
            logger.warning(
                'No cluster-internal SSH key available (ssh-keygen '
                'missing and no provisioning key); multi-host gang '
                'start from the head daemon may fail auth.')
    spec = build_cluster_spec(info, autostop=autostop, ssh_key=remote_key)
    spec_json = spec.to_json()
    import base64
    b64 = base64.b64encode(spec_json.encode('utf-8')).decode('ascii')
    head.run(
        f'mkdir -p {REMOTE_RUNTIME_DIR} && echo {b64} | base64 -d > '
        f'{REMOTE_RUNTIME_DIR}/{spec_lib.CLUSTER_SPEC_FILENAME}',
        check=True)
    _start_remote_daemon(head)


def local_daemon_teardown(info: ClusterInfo) -> None:
    """Stop whichever daemon flavor this cluster has."""
    if is_local_style(info):
        daemon_lib.stop_daemon(info.cluster_name)
        return
    try:
        stop_remote_daemon(runners_for_cluster(info)[0])
    except Exception as e:  # pylint: disable=broad-except
        logger.warning('Daemon teardown failed: %s', e)
