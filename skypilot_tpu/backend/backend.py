"""Backend ABC (parity: ``sky/backends/backend.py:30``)."""
from __future__ import annotations

import abc
from typing import Any, Dict, Optional

from skypilot_tpu.optimizer import Candidate
from skypilot_tpu.provision.api import ClusterInfo
from skypilot_tpu.spec.task import Task


class Backend(abc.ABC):
    """provision / sync / setup / execute / teardown contract."""

    @abc.abstractmethod
    def provision(self, task: Task, cluster_name: str, *,
                  retry_until_up: bool = False,
                  dryrun: bool = False) -> Optional[ClusterInfo]:
        ...

    @abc.abstractmethod
    def sync_workdir(self, info: ClusterInfo, task: Task) -> None:
        ...

    @abc.abstractmethod
    def sync_file_mounts(self, info: ClusterInfo, task: Task) -> None:
        ...

    @abc.abstractmethod
    def setup(self, info: ClusterInfo, task: Task) -> None:
        ...

    @abc.abstractmethod
    def execute(self, info: ClusterInfo, task: Task, *,
                detach: bool = True) -> int:
        """Run the task; returns the job id."""

    @abc.abstractmethod
    def teardown(self, cluster_name: str, *, terminate: bool = True) -> None:
        ...

    def register_info(self, **kwargs: Dict[str, Any]) -> None:
        del kwargs
