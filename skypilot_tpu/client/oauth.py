"""Browser-flow login: localhost callback server + server login page.

Parity: ``sky/client/oauth.py`` — the CLI starts a loopback HTTP
listener, opens the server's ``/auth/login`` page with
``redirect_uri=http://127.0.0.1:<port>/callback``, and the server
redirects the browser back with a freshly-minted token; the CLI
captures it without the user pasting anything. No IdP dependency: the
server's login page authenticates whatever credential the deployment
uses (static operator token or a user token), which is what the
reference's OAuth2-proxy indirection ultimately does too.
"""
from __future__ import annotations

import threading
import urllib.parse
import webbrowser
from http.server import BaseHTTPRequestHandler, HTTPServer
from typing import Optional, Tuple

DEFAULT_TIMEOUT_SECONDS = 300.0


class _Callback(BaseHTTPRequestHandler):
    token: Optional[str] = None
    user: Optional[str] = None

    def log_message(self, fmt, *args):  # quiet
        pass

    def do_GET(self):  # noqa: N802
        query = urllib.parse.parse_qs(
            urllib.parse.urlparse(self.path).query)
        type(self).token = (query.get('token') or [None])[0]
        type(self).user = (query.get('user') or [None])[0]
        ok = type(self).token is not None
        body = (b'<html><body><h3>Login complete - return to your '
                b'terminal.</h3></body></html>' if ok else
                b'<html><body><h3>Login failed: no token in '
                b'callback.</h3></body></html>')
        self.send_response(200 if ok else 400)
        self.send_header('Content-Type', 'text/html')
        self.send_header('Content-Length', str(len(body)))
        self.end_headers()
        self.wfile.write(body)


def login_url(endpoint: str, callback_port: int) -> str:
    redirect = f'http://127.0.0.1:{callback_port}/callback'
    return (f'{endpoint}/auth/login?' +
            urllib.parse.urlencode({'redirect_uri': redirect}))


def browser_login(endpoint: str,
                  timeout: float = DEFAULT_TIMEOUT_SECONDS,
                  open_browser: bool = True) -> Tuple[str, str]:
    """(token, user_name) once the browser round-trip completes."""

    class Handler(_Callback):
        token = None
        user = None

    server = HTTPServer(('127.0.0.1', 0), Handler)
    server.timeout = 1.0
    port = server.server_address[1]
    done = threading.Event()

    def serve_one():
        # Keep serving until the TOKEN callback lands: browsers open
        # speculative/preconnect requests (favicon, prefetch) that must
        # not consume the listener.
        while not done.is_set() and Handler.token is None:
            server.handle_request()
        done.set()

    thread = threading.Thread(target=serve_one, daemon=True)
    thread.start()
    url = login_url(endpoint, port)
    print(f'Opening {url}\n(continue in the browser; waiting for the '
          'callback...)')
    if open_browser:
        try:
            webbrowser.open(url)
        except Exception:  # pylint: disable=broad-except
            pass
    try:
        if not done.wait(timeout):
            raise TimeoutError(
                f'no login callback within {timeout:.0f}s; open {url} '
                'manually or use --token')
        if Handler.token is None:
            raise RuntimeError('login callback carried no token')
        return Handler.token, Handler.user or 'unknown'
    finally:
        done.set()  # stop the serve loop before closing the socket
        server.server_close()
