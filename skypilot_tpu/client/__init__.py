"""Client layer: Python SDK + CLI over the REST API server.

Parity: ``sky/client/`` (sdk.py, cli/command.py).
"""
