"""Python SDK: every call POSTs to the API server and returns a
``request_id``; ``get()`` blocks on it, ``stream_and_get()`` also tails
logs (parity: ``sky/client/sdk.py`` launch :668, get :2313,
stream_and_get :2368 — all-async contract per sky/__init__.py:104-131).

If no server is running, one is auto-started locally (the reference does
the same for the local API server case).
"""
from __future__ import annotations

import io
import json
import os
import sys
import tarfile
import time
import urllib.parse
from typing import Any, Dict, List, Optional, Union

import requests as requests_lib

from skypilot_tpu import exceptions
from skypilot_tpu.server import requests_db
from skypilot_tpu.server.app import DEFAULT_PORT
from skypilot_tpu.spec.dag import Dag
from skypilot_tpu.spec.task import Task
from skypilot_tpu.utils import env_registry, log, subprocess_utils
from skypilot_tpu.utils import tracing

logger = log.init_logger(__name__)


class RequestId(str):
    """A server-side request handle (prefix-resolvable, like git SHAs)."""


def api_server_url() -> str:
    env = os.environ.get('SKYT_API_SERVER_URL')
    if env:
        return env.rstrip('/')
    from skypilot_tpu import config
    configured = config.get_nested(('api_server', 'endpoint'), None)
    if configured:  # `skyt api login` wrote it
        return str(configured).rstrip('/')
    info_path = os.path.join(requests_db.server_dir(), 'server.json')
    if os.path.exists(info_path):
        with open(info_path, encoding='utf-8') as f:
            info = json.load(f)
        return f'http://{info["host"]}:{info["port"]}'
    return f'http://127.0.0.1:{DEFAULT_PORT}'


def _auth_headers() -> Dict[str, str]:
    """Bearer token from env/config (parity: the reference reads service
    account tokens from SKYPILOT_SERVICE_ACCOUNT_TOKEN / ~/.sky config).
    Every request also declares the client's API protocol version so
    the server can refuse below-floor clients."""
    from skypilot_tpu.server import versions
    headers = {versions.API_VERSION_HEADER: str(versions.API_VERSION)}
    token = os.environ.get('SKYT_API_TOKEN')
    if not token:
        from skypilot_tpu import config
        token = config.get_nested(('api_server', 'token'), None)
    if token:
        headers['Authorization'] = f'Bearer {token}'
    return headers


_version_checked: set = set()


def api_is_healthy(url: Optional[str] = None) -> bool:
    url = url or api_server_url()
    try:
        resp = requests_lib.get(f'{url}/api/health', timeout=2)
        if resp.status_code != 200:
            return False
        _check_server_version(url, resp)
        return True
    except requests_lib.exceptions.RequestException:
        return False


def _check_server_version(url: str, resp) -> None:
    """Client/server version negotiation (parity: sky/server/versions.py
    — the reference refuses mismatched majors; we warn loudly once per
    server: mismatched wheels are the classic source of protocol bugs)."""
    if url in _version_checked:
        return
    _version_checked.add(url)
    try:
        payload = resp.json()
    except ValueError:
        return  # a proxy answering 200 with junk is still "healthy"
    if not isinstance(payload, dict):
        return
    # HARD floor on the protocol version (ref: sky/server/versions.py
    # refuses incompatible versions; unparsable values count as 0 and
    # are refused too — versions.check_compatibility never raises) ...
    from skypilot_tpu.server import versions
    message = versions.check_compatibility(
        payload.get('api_version'), peer='server')
    if message is not None:
        _version_checked.discard(url)  # re-check after an upgrade
        raise exceptions.ApiServerError(message)
    # ... and a WARNING on mixed package versions (usually harmless).
    server_version = payload.get('version')
    if server_version and server_version != _client_version():
        logger.warning(
            'API server at %s runs skypilot-tpu %s but this client '
            'is %s — upgrade the older side if requests misbehave.',
            url, server_version, _client_version())


def _client_version() -> str:
    import skypilot_tpu
    return skypilot_tpu.__version__


def _endpoint_is_configured() -> bool:
    """True when the endpoint came from env or `skyt api login` config —
    i.e. the user points at a specific (usually remote) server and we
    must never auto-start a local one in its place."""
    if os.environ.get('SKYT_API_SERVER_URL'):
        return True
    from skypilot_tpu import config
    return bool(config.get_nested(('api_server', 'endpoint'), None))


def ensure_api_server() -> str:
    """Return a healthy server URL, auto-starting a local one if needed."""
    url = api_server_url()
    try:
        if api_is_healthy(url):
            return url
    except exceptions.ApiServerError:
        # Below the protocol floor. A remote server isn't ours to fix;
        # a LOCAL daemon left over from an older wheel is — replace it
        # (otherwise every command fails until a manual `skyt api stop`).
        if _endpoint_is_configured():
            raise
        logger.warning('Local API server at %s speaks an incompatible '
                       'protocol (older wheel?); restarting it.', url)
        api_stop()
        _version_checked.discard(url)
    if _endpoint_is_configured():
        # Configured (remote) server: transient unreachability (restart,
        # flaky network) is retried before giving up.
        for _ in range(max(0, _retries() - 1)):
            time.sleep(0.2)
            if api_is_healthy(url):
                return url
        raise exceptions.ApiServerError(
            f'API server at {url} is unreachable.')
    logger.info('Starting local API server at %s', url)
    port = int(url.rsplit(':', 1)[1])
    subprocess_utils.daemonize_and_run(
        [sys.executable, '-m', 'skypilot_tpu.server.app', '--port',
         str(port)],
        log_path=os.path.join(requests_db.server_dir(), 'server.log'))
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if api_is_healthy(url):
            return url
        time.sleep(0.2)
    raise exceptions.ApiServerError(
        f'Local API server failed to start at {url}; see '
        f'{os.path.join(requests_db.server_dir(), "server.log")}')


def api_stop() -> bool:
    """Stop the local API server (parity: `sky api stop`)."""
    info_path = os.path.join(requests_db.server_dir(), 'server.json')
    if not os.path.exists(info_path):
        return False
    with open(info_path, encoding='utf-8') as f:
        pid = json.load(f).get('pid')
    os.remove(info_path)
    if pid:
        import signal
        subprocess_utils.kill_process_tree(pid, signal.SIGTERM)
        return True
    return False


# Transient transport failures worth retrying: connection refused/reset,
# and a response cut mid-body. Server-side HTTP errors are NOT retried.
_RETRYABLE = (requests_lib.exceptions.ConnectionError,
              requests_lib.exceptions.ChunkedEncodingError,
              requests_lib.exceptions.Timeout)


def _retries() -> int:
    return env_registry.get_int('SKYT_CLIENT_RETRIES')


def _retry_after_seconds(resp, payload) -> Optional[float]:
    """The server's backoff directive: prefer the precise float the
    overload/quota rejections carry in their JSON body (``payload``,
    parsed once by the caller), fall back to the integer Retry-After
    header. None = no directive."""
    if isinstance(payload, dict) and \
            payload.get('retry_after') is not None:
        try:
            return float(payload['retry_after'])
        except (TypeError, ValueError):
            pass
    header = resp.headers.get('Retry-After')
    if header is None:
        return None
    try:
        return float(header)
    except ValueError:
        return None


def _request_with_retries(method: str, url: str, **kwargs: Any):
    """requests.request with jittered backoff on transient transport
    errors AND server overload signals.

    Safe for POSTs because every submission carries an idempotency key the
    server dedupes on (parity target: the reference's chaos-proxy suite,
    tests/chaos/chaos_proxy.py, exercises exactly this client behavior).
    A 200 whose body fails to parse as JSON is also transient: a response
    truncated mid-headers can surface as a 'successful' garbage response
    rather than a transport error.

    A 429/503 carrying Retry-After (admission control: per-tenant quota
    or the overload gate shedding — docs/control_plane_scale.md) is
    retried after max(server's Retry-After, the jittered backoff
    schedule): the server's directive is a FLOOR, and the decorrelated
    jitter (resilience.backoff_delays) keeps a shed client herd from
    re-arriving in lockstep. A 429/503 with NO Retry-After is a plain
    server error and is raised to the caller as before.
    """
    from skypilot_tpu.utils import resilience
    attempts = _retries()
    delays = resilience.backoff_delays(base=0.2, cap=5.0)
    for attempt in range(attempts):
        try:
            resp = requests_lib.request(method, url, **kwargs)
            if not kwargs.get('stream'):
                try:
                    resp.json()
                except ValueError as e:
                    raise requests_lib.exceptions.ChunkedEncodingError(
                        f'malformed response body: {e}')
            if resp.status_code in (429, 503) and attempt < attempts - 1:
                try:
                    payload = resp.json()
                except ValueError:
                    payload = None
                retry_after = _retry_after_seconds(resp, payload)
                if retry_after is not None:
                    delay = max(retry_after, next(delays))
                    hint = ''
                    if isinstance(payload, dict) and \
                            payload.get('queue_position') is not None:
                        hint = (' (queue position '
                                f'{payload["queue_position"]})')
                    logger.info(
                        'Server overloaded (HTTP %d)%s; honoring '
                        'Retry-After: retrying in %.1fs',
                        resp.status_code, hint, delay)
                    time.sleep(delay)
                    continue
            return resp
        except _RETRYABLE:
            if attempt == attempts - 1:
                raise
            delay = next(delays)
            logger.debug('Transient %s %s failure; retry %d/%d in '
                         '%.1fs', method, url, attempt + 1,
                         attempts - 1, delay)
            time.sleep(delay)
    raise AssertionError('unreachable')


def _post(route: str, body: Dict[str, Any]) -> RequestId:
    url = ensure_api_server()
    headers = _auth_headers()
    headers['X-Skyt-Idempotency-Key'] = os.urandom(16).hex()
    from skypilot_tpu import workspaces
    headers['X-Skyt-Workspace'] = workspaces.active_workspace()
    # Distributed tracing: every submission carries a W3C traceparent
    # so the server's submit span (and everything under it) joins the
    # CLIENT's trace — the client is where the request truly begins.
    with tracing.span(f'client.{route}', service='client') as sp:
        traceparent = sp.traceparent()
        if traceparent is not None:
            headers[tracing.TRACEPARENT_HEADER] = traceparent
        resp = _request_with_retries('POST', f'{url}/{route}', json=body,
                                     timeout=30, headers=headers)
        payload = resp.json()
        if resp.status_code != 200:
            raise exceptions.ApiServerError(
                payload.get('error', f'HTTP {resp.status_code}'))
        sp.annotate(request_id=payload['request_id'])
    return RequestId(payload['request_id'])


# -- async request lifecycle ------------------------------------------

# Server-side long-poll window per /api/get round trip (tests shrink it
# to observe PENDING polls quickly).
_GET_POLL_S = 15.0


def get(request_id: str, timeout: Optional[float] = None,
        on_pending: Optional[Any] = None) -> Any:
    """Block until the request finishes; return its value or raise.

    ``on_pending`` (callable taking the poll payload dict) fires each
    time a poll window expires with the request still PENDING — the
    payload carries ``queue_position``, the server's queue-position
    hint, which CLI waits echo so a queued-under-load user sees
    progress instead of silence. Parity: sdk.get :2313."""
    url = ensure_api_server()
    deadline = None if timeout is None else time.monotonic() + timeout
    while True:
        resp = _request_with_retries(
            'GET', f'{url}/api/get',
            params={'request_id': request_id, 'timeout': _GET_POLL_S},
            timeout=60, headers=_auth_headers())
        if resp.status_code == 404:
            raise exceptions.RequestDoesNotExist(
                f'No request {request_id!r}.')
        payload = resp.json()
        if resp.status_code != 200:
            raise exceptions.ApiServerError(
                payload.get('error', f'HTTP {resp.status_code}'))
        status = requests_db.RequestStatus(payload['status'])
        if status == requests_db.RequestStatus.PENDING and \
                on_pending is not None:
            try:
                on_pending(payload)
            except Exception:  # pylint: disable=broad-except
                pass  # a hint printer must never kill the wait
        if status == requests_db.RequestStatus.SUCCEEDED:
            return payload['return_value']
        if status == requests_db.RequestStatus.FAILED:
            raise exceptions.RequestFailedError(
                payload.get('error') or 'request failed',
                request_id=request_id)
        if status == requests_db.RequestStatus.CANCELLED:
            raise exceptions.RequestCancelledError(
                f'Request {request_id} was cancelled.')
        if deadline is not None and time.monotonic() > deadline:
            raise TimeoutError(
                f'Request {request_id} still {status.value} after '
                f'{timeout}s.')


def stream_and_get(request_id: str,
                   output: Any = None) -> Any:
    """Tail the request's log to ``output`` (default stdout), then get().

    A stream cut mid-flight resumes from the byte offset already received
    (``tail_from``) — no replayed or lost log lines across connection
    drops. Parity: sdk.stream_and_get :2368."""
    url = ensure_api_server()
    output = output or sys.stdout
    received = 0
    attempts_left = _retries()
    while True:
        try:
            with requests_lib.get(f'{url}/api/stream',
                                  params={'request_id': request_id,
                                          'tail_from': received},
                                  stream=True, timeout=None,
                                  headers=_auth_headers()) as resp:
                if resp.status_code != 200:
                    raise exceptions.ApiServerError(
                        f'stream failed: HTTP {resp.status_code}: '
                        f'{resp.text[:500]}')
                for chunk in resp.iter_content(chunk_size=None):
                    output.write(chunk.decode('utf-8', errors='replace'))
                    received += len(chunk)
                    if hasattr(output, 'flush'):
                        output.flush()
            break
        except _RETRYABLE:
            attempts_left -= 1
            if attempts_left <= 0:
                raise
            time.sleep(0.2)
    return get(request_id)


def ssh_info(cluster_name: str) -> RequestId:
    return _post('ssh_info', {'cluster_name': cluster_name})


def open_tunnel(cluster_name: str, port: Optional[int] = None):
    """Raw duplex socket to the cluster head's SSH port, THROUGH the API
    server (parity: sky/templates/websocket_proxy.py). Returns a
    connected socket plus any bytes the server already sent past the
    HTTP headers."""
    import socket as socket_lib
    import urllib.parse
    url = ensure_api_server()
    parsed = urllib.parse.urlparse(url)
    sock = socket_lib.create_connection(
        (parsed.hostname, parsed.port or 80), timeout=30)
    from skypilot_tpu import workspaces
    lines = [f'POST /api/tunnel HTTP/1.1',
             f'Host: {parsed.netloc}',
             f'X-Skyt-Cluster: {cluster_name}',
             f'X-Skyt-Workspace: {workspaces.active_workspace()}',
             'Content-Length: 0']
    if port is not None:
        lines.append(f'X-Skyt-Port: {port}')
    for key, value in _auth_headers().items():
        lines.append(f'{key}: {value}')
    sock.sendall(('\r\n'.join(lines) + '\r\n\r\n').encode())
    buf = b''
    while b'\r\n\r\n' not in buf:
        chunk = sock.recv(4096)
        if not chunk:
            raise exceptions.ApiServerError(
                'tunnel: server closed during handshake')
        buf += chunk
    headers, leftover = buf.split(b'\r\n\r\n', 1)
    status_line = headers.split(b'\r\n', 1)[0].decode()
    if ' 200 ' not in status_line + ' ':
        sock.close()
        raise exceptions.ApiServerError(
            f'tunnel: {status_line} {leftover[:300]!r}')
    return sock, leftover


def tunnel_stdio(cluster_name: str, port: Optional[int] = None) -> int:
    """Pump stdin/stdout through the tunnel (ssh ProxyCommand mode)."""
    import threading
    sock, leftover = open_tunnel(cluster_name, port)
    stdout = os.fdopen(1, 'wb', buffering=0)
    stdin = os.fdopen(0, 'rb', buffering=0)
    if leftover:
        stdout.write(leftover)

    def downstream() -> None:
        try:
            while True:
                data = sock.recv(65536)
                if not data:
                    break
                stdout.write(data)
        except OSError:
            pass
        finally:
            os._exit(0)  # ssh closed on us; end the proxy process

    thread = threading.Thread(target=downstream, daemon=True)
    thread.start()
    try:
        while True:
            data = stdin.read(65536)
            if not data:
                break
            sock.sendall(data)
    except OSError:
        pass
    try:
        sock.shutdown(1)  # SHUT_WR: stdin closed, drain the rest
    except OSError:
        pass
    thread.join(timeout=30)
    return 0


def volumes_apply(volume_config: Dict[str, Any]) -> RequestId:
    return _post('volumes/apply', {'volume_config': volume_config})


def volumes_ls() -> RequestId:
    return _post('volumes/ls', {})


def volumes_delete(name: str) -> RequestId:
    return _post('volumes/delete', {'name': name})


def api_cancel(request_id: str) -> bool:
    url = ensure_api_server()
    resp = requests_lib.post(f'{url}/api/cancel',
                             json={'request_id': request_id}, timeout=30,
                             headers=_auth_headers())
    payload = resp.json()
    if resp.status_code != 200:
        raise exceptions.ApiServerError(
            payload.get('error', f'HTTP {resp.status_code}'))
    return bool(payload.get('cancelled'))


def api_trace(request_id: str) -> Dict[str, Any]:
    """The collected trace of a request (or a raw trace_id): span tree
    + critical path, straight from GET /api/trace/<id>."""
    url = ensure_api_server()
    resp = _request_with_retries(
        'GET', f'{url}/api/trace/{urllib.parse.quote(request_id)}',
        timeout=30, headers=_auth_headers())
    payload = resp.json()
    if resp.status_code == 404:
        raise exceptions.RequestDoesNotExist(
            payload.get('error', f'no trace for {request_id!r}'))
    if resp.status_code != 200:
        raise exceptions.ApiServerError(
            payload.get('error', f'HTTP {resp.status_code}'))
    return payload


def api_alerts(wait: float = 0.0) -> Dict[str, Any]:
    """The SLO burn-rate alert table from GET /api/alerts.
    ``wait`` long-polls on the server's ALERTS topic (bounded)."""
    url = ensure_api_server()
    params = {'wait': wait} if wait > 0 else {}
    resp = _request_with_retries('GET', f'{url}/api/alerts',
                                 params=params,
                                 timeout=max(35.0, wait + 10.0),
                                 headers=_auth_headers())
    payload = resp.json()
    if resp.status_code != 200:
        raise exceptions.ApiServerError(
            payload.get('error', f'HTTP {resp.status_code}'))
    return payload


def api_metrics_query(name: str,
                      start: Optional[float] = None,
                      end: Optional[float] = None,
                      step: Optional[float] = None,
                      labels: Optional[Dict[str, str]] = None,
                      agg: str = 'mean') -> Dict[str, Any]:
    """Range query over the server's durable telemetry store
    (GET /api/metrics/query)."""
    url = ensure_api_server()
    params: Dict[str, Any] = {'name': name, 'agg': agg}
    if start is not None:
        params['start'] = start
    if end is not None:
        params['end'] = end
    if step is not None:
        params['step'] = step
    for key, value in (labels or {}).items():
        params[f'label.{key}'] = value
    resp = _request_with_retries('GET', f'{url}/api/metrics/query',
                                 params=params, timeout=30,
                                 headers=_auth_headers())
    payload = resp.json()
    if resp.status_code != 200:
        raise exceptions.ApiServerError(
            payload.get('error', f'HTTP {resp.status_code}'))
    return payload


def api_status(status: Optional[str] = None) -> List[Dict[str, Any]]:
    url = ensure_api_server()
    params = {'status': status} if status else {}
    resp = _request_with_retries('GET', f'{url}/api/requests',
                                 params=params,
                                 timeout=30, headers=_auth_headers())
    payload = resp.json()
    if resp.status_code != 200:
        raise exceptions.ApiServerError(
            payload.get('error', f'HTTP {resp.status_code}'))
    return payload


# -- user administration (server-side, auth/RBAC enforced) -------------


def _users_request(method: str, route: str,
                   body: Optional[Dict[str, Any]] = None) -> Any:
    """Users routes go through the SERVER so rbac gates apply (a local
    sqlite write would bypass auth and target the wrong DB on remote
    deployments)."""
    url = ensure_api_server()
    if method == 'GET':
        resp = requests_lib.get(f'{url}{route}', timeout=30,
                                headers=_auth_headers())
    else:
        resp = requests_lib.post(f'{url}{route}', json=body or {},
                                 timeout=30, headers=_auth_headers())
    payload = resp.json()
    if resp.status_code != 200:
        raise exceptions.ApiServerError(
            payload.get('error', f'HTTP {resp.status_code}'))
    return payload


def users_list() -> List[Dict[str, Any]]:
    return _users_request('GET', '/api/users')


def users_create(name: str, role: str = 'user') -> Dict[str, Any]:
    return _users_request('POST', '/api/users/create',
                          {'name': name, 'role': role})


def users_delete(name: str) -> Dict[str, Any]:
    return _users_request('POST', '/api/users/delete', {'name': name})


def users_set_role(name: str, role: str) -> Dict[str, Any]:
    return _users_request('POST', '/api/users/set-role',
                          {'name': name, 'role': role})


def users_token(name: Optional[str] = None, label: str = '') -> str:
    body: Dict[str, Any] = {'label': label}
    if name:
        body['name'] = name
    return _users_request('POST', '/api/users/token', body)['token']


def users_service_account(name: str, label: str = '',
                          expires_seconds: Optional[float] = None
                          ) -> Dict[str, Any]:
    body: Dict[str, Any] = {'name': name, 'label': label}
    if expires_seconds is not None:
        body['expires_seconds'] = expires_seconds
    return _users_request('POST', '/api/users/service-account', body)


def workspace_set_role(workspace: str, name: str,
                       role: Optional[str]) -> Dict[str, Any]:
    """Bind (role) or unbind (role=None) a user in a workspace."""
    return _users_request('POST', '/api/workspaces/set-role',
                          {'workspace': workspace, 'name': name,
                           'role': role})


def workspace_roles(workspace: Optional[str] = None
                    ) -> List[Dict[str, Any]]:
    route = '/api/workspaces/roles'
    if workspace:
        route += '?' + urllib.parse.urlencode({'workspace': workspace})
    return _users_request('GET', route)


# -- workdir upload ----------------------------------------------------


def _upload_workdir(task_config: Dict[str, Any]) -> Dict[str, Any]:
    """Tar the local workdir and upload it; rewrite the task's workdir to
    the server-side extracted path (parity: POST /upload, chunked
    server.py:1564).

    The tarball is spooled to disk (never held in RAM) and hashed; a
    GET /upload/<digest> probe skips the transfer entirely when the
    server already holds this content (resume-by-digest), else the POST
    streams the file so a multi-GB workdir costs O(chunk) memory on
    both ends.
    """
    import hashlib
    import tempfile
    workdir = task_config.get('workdir')
    if not workdir or not os.path.isdir(os.path.expanduser(workdir)):
        return task_config
    src = os.path.expanduser(workdir)

    def _exclude_git_dir(ti: tarfile.TarInfo) -> Optional[tarfile.TarInfo]:
        # Exact '.git' path components only: .gitignore/.github must ship.
        parts = ti.name.split('/')
        return None if '.git' in parts else ti

    url = ensure_api_server()
    import gzip
    with tempfile.NamedTemporaryFile(prefix='skyt-workdir-',
                                     suffix='.tgz') as spool:
        # gzip mtime pinned to 0 and FNAME to '': `w:gz` stamps the
        # compression time AND the spool's random temp filename into
        # the header, which would give identical content a different
        # digest on every call and defeat resume-by-digest.
        with gzip.GzipFile(filename='', fileobj=spool, mode='wb',
                           mtime=0) as gz:
            with tarfile.open(fileobj=gz, mode='w') as tar:
                tar.add(src, arcname='.', filter=_exclude_git_dir)
        spool.flush()
        hasher = hashlib.sha256()
        spool.seek(0)
        for chunk in iter(lambda: spool.read(1 << 20), b''):
            hasher.update(chunk)
        digest = hasher.hexdigest()
        probe = requests_lib.get(f'{url}/upload/{digest}', timeout=10,
                                 headers=_auth_headers())
        if not (probe.status_code == 200 and probe.json().get('exists')):
            # Pre-full-sha256 server: it stored (and will re-mint) the
            # legacy 16-char address — probe that too before paying a
            # full re-upload of content it already holds.
            probe = requests_lib.get(f'{url}/upload/{digest[:16]}',
                                     timeout=10, headers=_auth_headers())
        if probe.status_code == 200 and probe.json().get('exists'):
            task_config = dict(task_config)
            task_config['workdir'] = probe.json()['path']
            return task_config
        spool.seek(0)
        resp = requests_lib.post(
            f'{url}/upload', data=spool, timeout=600,
            headers={**_auth_headers(), 'X-Skyt-Digest': digest})
        if (resp.status_code == 400 and
                'digest mismatch' in resp.text):
            # Pre-full-sha256 server: it hashes to the legacy 16-char
            # truncation and rejects our full-length claim. Retry once
            # with the short form it expects (forward compat for the
            # client-upgrades-first skew).
            spool.seek(0)
            resp = requests_lib.post(
                f'{url}/upload', data=spool, timeout=600,
                headers={**_auth_headers(),
                         'X-Skyt-Digest': digest[:16]})
    if resp.status_code != 200:
        raise exceptions.ApiServerError(
            f'workdir upload failed: {resp.text}')
    task_config = dict(task_config)
    task_config['workdir'] = resp.json()['path']
    return task_config


# -- public verbs ------------------------------------------------------


def _task_configs(task_or_dag: Union[Task, Dag]) -> List[Dict[str, Any]]:
    tasks = task_or_dag.tasks if isinstance(task_or_dag, Dag) else [
        task_or_dag]
    return [_upload_workdir(t.to_yaml_config()) for t in tasks]


def launch(task: Union[Task, Dag],
           cluster_name: Optional[str] = None,
           *,
           dryrun: bool = False,
           down: bool = False) -> RequestId:
    configs = _task_configs(task)
    if len(configs) == 1:
        return _post('launch', {
            'task_config': configs[0],
            'cluster_name': cluster_name,
            'dryrun': dryrun,
            'down': down,
        })
    # Chain DAG: the SERVER runs the stages in order with WAIT_SUCCESS
    # gating (one request, one log stream — server/payloads._launch).
    return _post('launch', {
        'task_configs': configs,
        'cluster_name': cluster_name,
        'dryrun': dryrun,
        'down': down,
    })


def exec(task: Union[Task, Dag],  # pylint: disable=redefined-builtin
         cluster_name: str) -> RequestId:
    configs = _task_configs(task)
    return _post('exec', {
        'task_config': configs[0],
        'cluster_name': cluster_name,
    })


def status(cluster_names: Optional[List[str]] = None,
           refresh: bool = False,
           all_workspaces: bool = False) -> RequestId:
    return _post('status', {'cluster_names': cluster_names,
                            'refresh': refresh,
                            'all_workspaces': all_workspaces})


def stop(cluster_name: str) -> RequestId:
    return _post('stop', {'cluster_name': cluster_name})


def start(cluster_name: str) -> RequestId:
    return _post('start', {'cluster_name': cluster_name})


def down(cluster_name: str) -> RequestId:
    return _post('down', {'cluster_name': cluster_name})


def queue(cluster_name: str) -> RequestId:
    return _post('queue', {'cluster_name': cluster_name})


def cancel(cluster_name: str, job_id: int) -> RequestId:
    return _post('cancel', {'cluster_name': cluster_name,
                            'job_id': job_id})


def tail_logs(cluster_name: str,
              job_id: Optional[int] = None,
              follow: bool = False) -> RequestId:
    return _post('logs', {'cluster_name': cluster_name, 'job_id': job_id,
                          'follow': follow})


def autostop(cluster_name: str, idle_minutes: float,
             down_on_idle: bool = False) -> RequestId:
    return _post('autostop', {'cluster_name': cluster_name,
                              'idle_minutes': idle_minutes,
                              'down_on_idle': down_on_idle})


def cost_report() -> RequestId:
    return _post('cost_report', {})


def check() -> RequestId:
    return _post('check', {})


# -- managed jobs ------------------------------------------------------


def jobs_launch(task: Union[Task, Dag],
                name: Optional[str] = None) -> RequestId:
    configs = _task_configs(task)
    assert len(configs) == 1, 'chain DAGs: launch tasks individually'
    return _post('jobs/launch', {'task_config': configs[0], 'name': name})


def jobs_launch_group(tasks: List[Task], group_name: str) -> RequestId:
    return _post('jobs/launch-group', {
        'task_configs': [t.to_yaml_config() for t in tasks],
        'group_name': group_name,
    })


def jobs_queue(skip_finished: bool = False) -> RequestId:
    return _post('jobs/queue', {'skip_finished': skip_finished})


def jobs_cancel(job_id: int) -> RequestId:
    return _post('jobs/cancel', {'job_id': job_id})


def jobs_logs(job_id: int, controller: bool = False) -> RequestId:
    return _post('jobs/logs', {'job_id': job_id, 'controller': controller})


def pool_apply(task: Union[Task, Dag], pool_name: str,
               workers: Optional[int] = None) -> RequestId:
    configs = _task_configs(task)
    return _post('jobs/pool/apply', {'task_config': configs[0],
                                     'pool_name': pool_name,
                                     'workers': workers})


def pool_status(pool_name: Optional[str] = None) -> RequestId:
    return _post('jobs/pool/status', {'pool_name': pool_name})


def pool_down(pool_name: str, purge: bool = False) -> RequestId:
    return _post('jobs/pool/down', {'pool_name': pool_name,
                                    'purge': purge})


# -- serving -----------------------------------------------------------


def serve_up(task: Union[Task, Dag],
             service_name: Optional[str] = None) -> RequestId:
    configs = _task_configs(task)
    assert len(configs) == 1, 'a service is a single task'
    return _post('serve/up', {'task_config': configs[0],
                              'service_name': service_name})


def serve_down(service_name: str, purge: bool = False) -> RequestId:
    return _post('serve/down', {'service_name': service_name,
                                'purge': purge})


def serve_status(service_name: Optional[str] = None) -> RequestId:
    return _post('serve/status', {'service_name': service_name})


def serve_logs(service_name: str,
               replica_id: Optional[int] = None) -> RequestId:
    return _post('serve/logs', {'service_name': service_name,
                                'replica_id': replica_id})
