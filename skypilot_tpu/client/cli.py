"""The ``skyt`` CLI (parity: ``sky/client/cli/command.py`` — launch :1317,
exec :1541, status :2068, queue :2612, logs :2728, cancel :2929, stop
:3056, autostop :3137, start :3270, down :3480, check :3997, show-gpus
:4075 → here `show-tpus`, api group :7717).

Every verb goes through the SDK: submit → request_id → stream/get.
"""
from __future__ import annotations

import json
import os
import sys
from typing import List, Optional

import click

from skypilot_tpu import exceptions
from skypilot_tpu.client import sdk
from skypilot_tpu.spec.task import Task
from skypilot_tpu.utils import common_utils


def _echo_table(rows: List[dict], columns: List[str]) -> None:
    if not rows:
        click.echo('(none)')
        return
    widths = {c: max(len(c), *(len(str(r.get(c, ''))) for r in rows))
              for c in columns}
    click.echo('  '.join(c.upper().ljust(widths[c]) for c in columns))
    for r in rows:
        click.echo('  '.join(str(r.get(c, '')).ljust(widths[c])
                             for c in columns))


def _run(request_id: str, async_: bool, stream: bool = True):
    if async_:
        click.echo(f'request: {request_id}')
        return None
    try:
        if stream:
            return sdk.stream_and_get(request_id)
        # Non-streamed waits (status-style verbs) echo the server's
        # queue-position hint while the request is still queued, so a
        # user behind a backlog sees movement instead of silence.
        last_pos = [None]

        def _pending_hint(payload) -> None:
            pos = payload.get('queue_position')
            if pos is not None and pos != last_pos[0]:
                last_pos[0] = pos
                click.echo(f'queued: position {pos} in the '
                           f'{payload.get("name", "request")} queue',
                           err=True)

        return sdk.get(request_id, on_pending=_pending_hint)
    except exceptions.SkytError as e:
        raise click.ClickException(str(e)) from e


@click.group()
def cli() -> None:
    """skypilot-tpu: launch and manage TPU workloads on the cloud."""


# -- cluster lifecycle -------------------------------------------------


@cli.command()
@click.argument('entrypoint', required=True)
@click.option('--cluster', '-c', default=None, help='Cluster name.')
@click.option('--dryrun', is_flag=True, default=False)
@click.option('--down', is_flag=True, default=False,
              help='Tear down after the job finishes.')
@click.option('--async', 'async_', is_flag=True, default=False,
              help='Submit and return the request id immediately.')
@click.option('--env', multiple=True, help='KEY=VALUE env overrides.')
def launch(entrypoint: str, cluster: Optional[str], dryrun: bool,
           down: bool, async_: bool, env) -> None:
    """Launch a task YAML (provision + sync + setup + run).

    Multi-document ('---'-separated) pipeline YAMLs launch stage by
    stage in order, each stage on its own cluster sized by its own
    resources (parity: the reference's pipeline handling).
    """
    from skypilot_tpu.spec.dag import Dag
    dag = Dag.from_yaml(entrypoint)
    env_overrides = dict(e.split('=', 1) for e in env) if env else {}
    if env_overrides:
        for task in dag.tasks:
            task.update_envs(env_overrides)
    if len(dag.tasks) > 1:
        cluster = cluster or dag.name or 'pipeline'
        click.echo(f'pipeline {cluster}: {len(dag.tasks)} stages '
                   '(server runs them in order; a failed stage aborts '
                   'the rest)')
    request_id = sdk.launch(dag if len(dag.tasks) > 1 else dag.tasks[0],
                            cluster, dryrun=dryrun, down=down)
    result = _run(request_id, async_)
    if result:
        for name, job_id in result:
            click.echo(f'cluster: {name}  job: {job_id}')


@cli.command('exec')
@click.argument('entrypoint', required=True)
@click.option('--cluster', '-c', required=True)
@click.option('--async', 'async_', is_flag=True, default=False)
def exec_cmd(entrypoint: str, cluster: str, async_: bool) -> None:
    """Run a task on an existing cluster (skips provision/setup)."""
    task = Task.from_yaml(entrypoint)
    result = _run(sdk.exec(task, cluster), async_)
    if result:
        for name, job_id in result:
            click.echo(f'cluster: {name}  job: {job_id}')


@cli.command()
@click.argument('clusters', nargs=-1)
@click.option('--refresh', '-r', is_flag=True, default=False)
@click.option('--all-workspaces', '-u', is_flag=True, default=False,
              help='Show clusters from every workspace.')
def status(clusters, refresh: bool, all_workspaces: bool) -> None:
    """Show clusters (scoped to the active workspace)."""
    records = _run(sdk.status(list(clusters) or None, refresh=refresh,
                              all_workspaces=all_workspaces),
                   False, stream=False)
    for r in records or []:
        res = r.get('resources') or {}
        r['resources'] = (res.get('accelerators') or
                          res.get('instance_type') or 'cpu')
        if r.get('launched_at'):
            import time
            r['age'] = common_utils.readable_duration(
                time.time() - r['launched_at'])
    _echo_table(records or [],
                ['name', 'status', 'resources', 'region', 'age'])


@cli.command()
@click.argument('cluster')
def stop(cluster: str) -> None:
    """Stop a cluster (keeps its disk; restart with `skyt start`)."""
    _run(sdk.stop(cluster), False, stream=False)
    click.echo(f'Cluster {cluster} stopped.')


@cli.command()
@click.argument('cluster')
def start(cluster: str) -> None:
    """Restart a stopped cluster."""
    _run(sdk.start(cluster), False)
    click.echo(f'Cluster {cluster} started.')


@cli.command()
@click.argument('cluster')
@click.option('--yes', '-y', is_flag=True, default=False)
def down(cluster: str, yes: bool) -> None:
    """Terminate a cluster."""
    if not yes:
        click.confirm(f'Tear down cluster {cluster!r}?', abort=True)
    _run(sdk.down(cluster), False, stream=False)
    click.echo(f'Cluster {cluster} terminated.')


@cli.command()
@click.argument('cluster')
@click.option('--idle-minutes', '-i', type=float, required=True,
              help='Idle minutes before stopping; -1 disables.')
@click.option('--down', 'down_on_idle', is_flag=True, default=False,
              help='Tear down instead of stop.')
def autostop(cluster: str, idle_minutes: float, down_on_idle: bool) -> None:
    """Schedule stop/teardown after idleness (runtime-daemon enforced)."""
    _run(sdk.autostop(cluster, idle_minutes, down_on_idle), False,
         stream=False)
    click.echo(f'Autostop set on {cluster}: {idle_minutes} min '
               f'({"down" if down_on_idle else "stop"}).')


# -- jobs on a cluster -------------------------------------------------


@cli.command()
@click.argument('cluster')
def queue(cluster: str) -> None:
    """Show a cluster's job queue."""
    jobs = _run(sdk.queue(cluster), False, stream=False)
    _echo_table(jobs or [],
                ['job_id', 'name', 'status', 'submitted_at'])


@cli.command()
@click.argument('cluster')
@click.option('--job-id', '-j', type=int, default=None)
@click.option('--follow/--no-follow', default=True)
def logs(cluster: str, job_id: Optional[int], follow: bool) -> None:
    """Tail a job's logs."""
    _run(sdk.tail_logs(cluster, job_id, follow=follow), False)


@cli.command()
@click.argument('cluster')
@click.argument('job_id', type=int)
def cancel(cluster: str, job_id: int) -> None:
    """Cancel a job."""
    ok = _run(sdk.cancel(cluster, job_id), False, stream=False)
    click.echo('Cancelled.' if ok else 'Job already finished.')


# -- info --------------------------------------------------------------


@cli.command()
@click.option('--verbose', '-v', is_flag=True, default=False,
              help='Also show per-cloud capability limits.')
def check(verbose: bool) -> None:
    """Probe cloud credentials and show enabled clouds."""
    result = _run(sdk.check(), False, stream=False) or {}
    caps = {}
    if verbose:
        from skypilot_tpu import check as check_lib
        caps = check_lib.capabilities()
    for cloud, (ok, reason) in result.items():
        mark = 'enabled' if ok else f'disabled ({reason})'
        click.echo(f'  {cloud}: {mark}')
        for cap, why in sorted(caps.get(cloud, {}).items()):
            click.echo(f'      no {cap}: {why}')
    from skypilot_tpu.catalog import refresh as catalog_refresh
    warning = catalog_refresh.staleness_warning()
    if warning:
        click.echo(f'  WARNING: {warning}')


@cli.command('show-tpus')
@click.option('--name-filter', '-n', default=None)
@click.option('--tpus-only', is_flag=True, default=False)
def show_tpus(name_filter: Optional[str], tpus_only: bool) -> None:
    """List TPU/accelerator offerings and pricing from the catalog."""
    from skypilot_tpu.catalog import common as catalog
    rows = []
    for name, regions in catalog.list_accelerators(name_filter,
                                                   tpus_only=tpus_only
                                                   ).items():
        rows.append({
            'accelerator': name,
            'regions': ','.join(regions[:4]) + (
                f' (+{len(regions)-4})' if len(regions) > 4 else ''),
            'price_hr': f'${catalog.get_hourly_cost(name):.2f}',
            'spot_hr':
                f'${catalog.get_hourly_cost(name, use_spot=True):.2f}',
        })
    _echo_table(rows, ['accelerator', 'regions', 'price_hr', 'spot_hr'])


@cli.command('cost-report')
def cost_report() -> None:
    """Accumulated cost per cluster."""
    rows = _run(sdk.cost_report(), False, stream=False)
    _echo_table(rows or [],
                ['name', 'status', 'hourly_cost', 'accumulated_cost'])


# -- managed jobs ------------------------------------------------------


@cli.group()
def jobs() -> None:
    """Managed jobs with automatic preemption recovery."""


@jobs.command('launch')
@click.argument('entrypoint', required=True)
@click.option('--name', '-n', default=None)
def jobs_launch(entrypoint: str, name: Optional[str]) -> None:
    """Submit a managed job (launch-and-forget with recovery)."""
    task = Task.from_yaml(entrypoint)
    job_id = _run(sdk.jobs_launch(task, name), False, stream=False)
    click.echo(f'Managed job {job_id} submitted. '
               f'`skyt jobs logs {job_id}` to tail.')


@jobs.command('queue')
@click.option('--skip-finished', '-s', is_flag=True, default=False)
def jobs_queue(skip_finished: bool) -> None:
    """List managed jobs."""
    rows = _run(sdk.jobs_queue(skip_finished), False, stream=False)
    _echo_table(rows or [],
                ['job_id', 'name', 'status', 'cluster_name',
                 'recovery_count', 'strategy'])


@jobs.command('cancel')
@click.argument('job_id', type=int)
def jobs_cancel(job_id: int) -> None:
    """Cancel a managed job (tears its cluster down)."""
    ok = _run(sdk.jobs_cancel(job_id), False, stream=False)
    click.echo('Cancellation requested.' if ok else 'Already finished.')


@jobs.command('logs')
@click.argument('job_id', type=int)
@click.option('--controller', is_flag=True, default=False,
              help="Show the controller's log instead of the job's.")
def jobs_logs(job_id: int, controller: bool) -> None:
    """Show a managed job's logs."""
    _run(sdk.jobs_logs(job_id, controller=controller), False)


@jobs.command('launch-group')
@click.argument('entrypoints', nargs=-1, required=True)
@click.option('--name', '-n', 'group_name', required=True)
def jobs_launch_group(entrypoints, group_name: str) -> None:
    """Gang-schedule several task YAMLs as one group (all provision
    before any runs; one failure cancels the rest)."""
    tasks = [Task.from_yaml(e) for e in entrypoints]
    job_ids = _run(sdk.jobs_launch_group(tasks, group_name), False,
                   stream=False)
    click.echo(f'group {group_name}: jobs {job_ids}')


@jobs.group('pool')
def jobs_pool() -> None:
    """Pre-provisioned worker pools for jobs/batch."""


@jobs_pool.command('apply')
@click.argument('entrypoint')
@click.option('--pool', '-p', 'pool_name', required=True)
@click.option('--workers', '-n', type=int, default=None)
def jobs_pool_apply(entrypoint: str, pool_name: str,
                    workers: Optional[int]) -> None:
    """Create or resize a worker pool from a task YAML."""
    task = Task.from_yaml(entrypoint)
    result = _run(sdk.pool_apply(task, pool_name, workers), False,
                  stream=False)
    click.echo(f"pool {result['name']} applying")


@jobs_pool.command('status')
@click.argument('pool_name', required=False, default=None)
def jobs_pool_status(pool_name: Optional[str]) -> None:
    rows = _run(sdk.pool_status(pool_name), False, stream=False)
    flat = []
    for r in rows or []:
        ready = sum(1 for rep in r.get('replicas', [])
                    if rep.get('status') == 'READY')
        flat.append({'name': r['name'], 'status': r['status'],
                     'workers': f"{ready}/{len(r.get('replicas', []))}"})
    _echo_table(flat, ['name', 'status', 'workers'])


@jobs_pool.command('down')
@click.argument('pool_name')
@click.option('--purge', is_flag=True, default=False)
def jobs_pool_down(pool_name: str, purge: bool) -> None:
    _run(sdk.pool_down(pool_name, purge=purge), False, stream=False)
    click.echo(f'pool {pool_name} shutting down')


# -- serving -----------------------------------------------------------


@cli.group()
def serve() -> None:
    """Serve behind a load balancer with autoscaling."""


@serve.command('up')
@click.argument('entrypoint', required=True)
@click.option('--service-name', '-n', default=None)
def serve_up(entrypoint: str, service_name: Optional[str]) -> None:
    """Bring up a service from a task YAML with a `service:` section."""
    task = Task.from_yaml(entrypoint)
    result = _run(sdk.serve_up(task, service_name), False, stream=False)
    click.echo(f"Service {result['name']} starting; endpoint "
               f"{result['endpoint']}. `skyt serve status` to watch.")


@serve.command('down')
@click.argument('service_name')
@click.option('--purge', '-p', is_flag=True, default=False,
              help='Clean up even if the controller is unreachable.')
def serve_down(service_name: str, purge: bool) -> None:
    """Tear down a service and all its replicas."""
    _run(sdk.serve_down(service_name, purge=purge), False, stream=False)
    click.echo(f'Service {service_name} shutdown requested.')


@serve.command('status')
@click.argument('service_name', required=False, default=None)
def serve_status(service_name: Optional[str]) -> None:
    """Show services and their replica fleets."""
    rows = _run(sdk.serve_status(service_name), False, stream=False)
    for row in rows or []:
        # Fleet latency + warm pool (r11 autoscaling subsystem): the
        # p99 over per-replica EWMA TTFB the controller persists each
        # tick, and how many replicas are parked WARM for fast resume.
        p99 = row.get('fleet_p99_ms')
        row['fleet_p99_ms'] = f'{p99:.1f}' if p99 is not None else '-'
    _echo_table(rows or [], ['name', 'status', 'endpoint',
                             'fleet_p99_ms', 'warm_replicas',
                             'controller_cluster', 'failure_reason'])
    for row in rows or []:
        for replica in row.get('replicas', []):
            domain = '/'.join(
                p for p in (replica.get('cloud'), replica.get('region'),
                            replica.get('zone')) if p) or '-'
            ewma = replica.get('lb_ewma_ms')
            ewma_s = f'{ewma:.1f}ms' if ewma else '-'
            click.echo(
                f"  replica {replica['replica_id']:>3} "
                f"{replica['status']:<22} {replica['endpoint'] or '-':<28}"
                f"{'spot' if replica['is_spot'] else 'on-demand':<10}"
                f"{domain:<28}{ewma_s}")
        demand = row.get('adapter_demand') or {}
        if demand:
            # Multi-LoRA demand the controller persists each tick:
            # which fine-tunes are hot and where their traffic sticks
            # (docs/multi_lora_serving.md).
            click.echo('  adapters:')
            by_qps = sorted(demand.items(),
                            key=lambda kv: -(kv[1].get('qps') or 0))
            for adapter, info in by_qps:
                replica = info.get('replica')
                click.echo(
                    f"    {adapter:<32}"
                    f"{info.get('qps', 0):>8.2f} req/s   "
                    f"replica {replica if replica is not None else '-'}")


@serve.command('logs')
@click.argument('service_name')
@click.option('--replica-id', '-r', type=int, default=None,
              help="A replica's logs instead of the controller's.")
def serve_logs(service_name: str, replica_id: Optional[int]) -> None:
    """Show a service's controller (or replica) logs."""
    _run(sdk.serve_logs(service_name, replica_id), False)


# -- api server control ------------------------------------------------


@cli.group()
def api() -> None:
    """Manage the API server and async requests."""


@api.command('start')
def api_start() -> None:
    url = sdk.ensure_api_server()
    click.echo(f'API server healthy at {url}')


@api.command('stop')
def api_stop() -> None:
    stopped = sdk.api_stop()
    click.echo('API server stopped.' if stopped else 'No server running.')


@api.command('login')
@click.option('--endpoint', '-e', required=True,
              help='Remote API server URL, e.g. http://skyt.corp:46590')
@click.option('--token', '-t', default=None,
              help='Bearer token (prompted for if omitted and required).')
@click.option('--sso', is_flag=True, default=False,
              help='Browser flow: sign in on the server login page; '
                   'the minted token lands here via a localhost '
                   'callback (parity: sky/client/oauth.py).')
def api_login(endpoint: str, token: Optional[str], sso: bool) -> None:
    """Point this client at a (remote) API server and store credentials
    (parity: `sky api login`; --sso is the browser flow, or mint a
    token with `skyt users token`)."""
    endpoint = endpoint.rstrip('/')
    if not sdk.api_is_healthy(endpoint):
        raise click.ClickException(f'No healthy API server at {endpoint}')
    if sso:
        from skypilot_tpu.client.oauth import browser_login
        token, user = browser_login(endpoint)
        from skypilot_tpu import config
        config.set_nested(('api_server', 'endpoint'), endpoint)
        config.set_nested(('api_server', 'token'), token)
        click.echo(f'Logged in to {endpoint} as {user} (token stored).')
        return
    from skypilot_tpu import config
    import requests as requests_lib
    headers = {'Authorization': f'Bearer {token}'} if token else {}
    resp = requests_lib.get(f'{endpoint}/api/requests', headers=headers,
                            timeout=10)
    if resp.status_code == 401:
        if token is None:
            token = click.prompt('Bearer token', hide_input=True)
            headers = {'Authorization': f'Bearer {token}'}
            resp = requests_lib.get(f'{endpoint}/api/requests',
                                    headers=headers, timeout=10)
        if resp.status_code == 401:
            raise click.ClickException('Token rejected (401).')
    config.set_nested(('api_server', 'endpoint'), endpoint)
    if token:
        config.set_nested(('api_server', 'token'), token)
    click.echo(f'Logged in to {endpoint}'
               f'{" (token stored)" if token else ""}.')


@api.command('status')
@click.option('--all', 'show_all', is_flag=True, default=False)
def api_status(show_all: bool) -> None:
    reqs = sdk.api_status()
    if not show_all:
        reqs = [r for r in reqs
                if r['status'] in ('PENDING', 'RUNNING')] or reqs[:10]
    rows = [{
        'request': r['request_id'][:8],
        'name': r['name'],
        'status': r['status'],
        'user': r['user'],
    } for r in reqs]
    _echo_table(rows, ['request', 'name', 'status', 'user'])


@api.command('get')
@click.argument('request_id')
def api_get(request_id: str) -> None:
    result = sdk.get(request_id)
    click.echo(json.dumps(result, indent=2, default=str))


@api.command('logs')
@click.argument('request_id')
def api_logs(request_id: str) -> None:
    sdk.stream_and_get(request_id)


@api.command('cancel')
@click.argument('request_id')
def api_cancel(request_id: str) -> None:
    ok = sdk.api_cancel(request_id)
    click.echo('Cancelled.' if ok else 'Not cancellable.')


@cli.command('trace')
@click.argument('request_id')
@click.option('--json', 'as_json', is_flag=True, default=False,
              help='Raw /api/trace payload instead of the waterfall.')
@click.option('--width', default=48, help='Waterfall bar width (cols).')
def trace_cmd(request_id: str, as_json: bool, width: int) -> None:
    """Show the distributed trace of a request: span waterfall +
    critical-path breakdown (requires SKYT_TRACE_SAMPLE at submit, or
    a tail-kept errored/slow request — docs/observability.md)."""
    try:
        view = sdk.api_trace(request_id)
    except exceptions.SkytError as e:
        raise click.ClickException(str(e)) from e
    if as_json:
        click.echo(json.dumps(view, indent=2, default=str))
        return
    _render_waterfall(view, max(16, width))


def _render_waterfall(view: dict, width: int) -> None:
    spans = view.get('spans') or []
    total_ms = max(float(view.get('total_ms') or 0.0), 0.001)
    crit = set(view.get('critical_span_ids') or [])
    click.echo(f"trace {view.get('trace_id')}  "
               f"request {view.get('request_id') or '-'}  "
               f"{len(spans)} spans / "
               f"{len(view.get('processes') or [])} processes  "
               f"total {total_ms:.1f}ms")
    # Depth via parent links; children render under their parent in
    # start order (the classic trace-viewer waterfall, in a terminal).
    by_id = {s['span_id']: s for s in spans}
    children: dict = {}
    roots = []
    for s in spans:
        parent = s.get('parent_span_id')
        if parent in by_id:
            children.setdefault(parent, []).append(s)
        else:
            roots.append(s)
    name_col = min(36, max((len(s.get('name', '')) for s in spans),
                           default=8) + 8)

    def emit(span: dict, depth: int) -> None:
        start_ms = float(span.get('start_ms') or 0.0)
        dur_ms = float(span.get('dur_ms') or 0.0)
        lead = int(width * start_ms / total_ms)
        bar = max(1, int(width * dur_ms / total_ms))
        bar = min(bar, width - min(lead, width - 1))
        mark = '*' if span['span_id'] in crit else ' '
        flag = ' !' if span.get('status') == 'error' else ''
        label = ('  ' * depth + span.get('name', '?'))[:name_col]
        click.echo(f'{label:<{name_col}} '
                   f'{" " * min(lead, width - 1)}{"█" * bar}'
                   f'{" " * max(0, width - lead - bar)} '
                   f'{dur_ms:9.1f}ms {mark} '
                   f'[{span.get("service", "?")}/{span.get("pid")}]'
                   f'{flag}')
        for child in sorted(children.get(span['span_id'], []),
                            key=lambda c: c.get('start_ms', 0.0)):
            emit(child, depth + 1)

    for root in sorted(roots, key=lambda s: s.get('start_ms', 0.0)):
        emit(root, 0)
    path = view.get('critical_path') or []
    if path:
        click.echo('\ncritical path (self-time per hop, * above):')
        for seg in path:
            pct = 100.0 * float(seg.get('self_ms', 0.0)) / total_ms
            click.echo(f"  {seg.get('name', '?'):<{name_col}} "
                       f"{float(seg.get('self_ms', 0.0)):9.1f}ms "
                       f"{pct:5.1f}%  [{seg.get('service', '?')}]")


@cli.command('alerts')
@click.option('--json', 'as_json', is_flag=True, default=False,
              help='Raw /api/alerts payload.')
def alerts_cmd(as_json: bool) -> None:
    """Show SLO burn-rate alerts (pending/firing/resolved) from the
    server's telemetry plane (docs/observability.md)."""
    try:
        payload = sdk.api_alerts()
    except exceptions.SkytError as e:
        raise click.ClickException(str(e)) from e
    if as_json:
        click.echo(json.dumps(payload, indent=2, default=str))
        return
    alerts = payload.get('alerts') or []
    if not alerts:
        click.echo('(no alerts — every SLO inside budget)')
        return
    import time as time_lib
    rows = []
    for a in alerts:
        since = a.get('firing_since') or a.get('pending_since')
        rows.append({
            'slo': a['slo'],
            'severity': a['severity'],
            'state': a['state'].upper(),
            'burn': (f"{a.get('burn_short', 0):g}x/"
                     f"{a.get('burn_long', 0):g}x "
                     f"(>{a.get('burn_threshold', 0):g}x)"),
            'windows': '/'.join(
                common_utils.readable_duration(w)
                for w in a.get('windows_seconds', [])),
            'since': (common_utils.readable_duration(
                max(0.0, time_lib.time() - since)) + ' ago'
                if since else '-'),
        })
    _echo_table(rows, ['slo', 'severity', 'state', 'burn', 'windows',
                       'since'])


_SPARK_BLOCKS = '▁▂▃▄▅▆▇█'


def _sparkline(values: List[float], width: int) -> str:
    if not values:
        return ''
    if len(values) > width:
        # Bucket-mean compress onto the terminal width.
        out = []
        for i in range(width):
            lo = i * len(values) // width
            hi = max(lo + 1, (i + 1) * len(values) // width)
            window = values[lo:hi]
            out.append(sum(window) / len(window))
        values = out
    lo, hi = min(values), max(values)
    span = (hi - lo) or 1.0
    return ''.join(
        _SPARK_BLOCKS[min(len(_SPARK_BLOCKS) - 1,
                          int((v - lo) / span * len(_SPARK_BLOCKS)))]
        for v in values)


def _parse_duration(text: str) -> float:
    units = {'s': 1.0, 'm': 60.0, 'h': 3600.0, 'd': 86400.0}
    text = text.strip().lower()
    if text and text[-1] in units:
        return float(text[:-1]) * units[text[-1]]
    return float(text)


@cli.group('metrics')
def metrics_group() -> None:
    """Query the server's durable telemetry history."""


@metrics_group.command('query')
@click.argument('name')
@click.option('--since', default='1h',
              help='Trailing window (e.g. 30m, 1h, 2d).')
@click.option('--step', default=None,
              help='Resample step (e.g. 60s); default raw points.')
@click.option('--label', 'label_opts', multiple=True,
              help='KEY=VALUE series filter (repeatable).')
@click.option('--agg', default='mean',
              type=click.Choice(['mean', 'max']),
              help='Rollup column for windows older than raw '
                   'retention.')
@click.option('--json', 'as_json', is_flag=True, default=False,
              help='Raw /api/metrics/query payload.')
@click.option('--width', default=60, help='Sparkline width (cols).')
def metrics_query(name: str, since: str, step: Optional[str],
                  label_opts, agg: str, as_json: bool,
                  width: int) -> None:
    """Range-query one metric and render a terminal sparkline per
    series (`skyt metrics query skyt_request_exec_seconds_count
    --since 2h`)."""
    try:
        labels = dict(l.split('=', 1) for l in label_opts)
    except ValueError:
        raise click.ClickException('--label takes KEY=VALUE')
    import time as time_lib
    end = time_lib.time()
    try:
        payload = sdk.api_metrics_query(
            name, start=end - _parse_duration(since), end=end,
            step=_parse_duration(step) if step else None,
            labels=labels or None, agg=agg)
    except exceptions.SkytError as e:
        raise click.ClickException(str(e)) from e
    except ValueError as e:
        raise click.ClickException(f'bad duration: {e}')
    if as_json:
        click.echo(json.dumps(payload, indent=2, default=str))
        return
    series = payload.get('series') or []
    if not series:
        click.echo(f'(no data for {name} in the last {since})')
        return
    width = max(8, width)
    for entry in series:
        labels_str = ','.join(f'{k}={v}' for k, v in
                              sorted((entry.get('labels') or {}).items())
                              if k not in ('instance',))
        points = entry.get('points') or []
        values = [v for _, v in points]
        if not values:
            continue
        click.echo(f'{name}{{{labels_str}}}  ({len(points)} pts)')
        click.echo(f'  {_sparkline(values, width)}')
        click.echo(f'  min {min(values):g}  max {max(values):g}  '
                   f'last {values[-1]:g}')


@cli.group()
def recipes() -> None:
    """Curated launchable recipes (`skyt launch recipe://NAME`)."""


@recipes.command('list')
def recipes_list() -> None:
    from skypilot_tpu import recipes as recipes_lib
    _echo_table(recipes_lib.list_recipes(), ['name', 'description'])


@recipes.command('show')
@click.argument('name')
def recipes_show(name: str) -> None:
    from skypilot_tpu import recipes as recipes_lib
    with open(recipes_lib.resolve(name), encoding='utf-8') as f:
        click.echo(f.read())


@cli.group()
def users() -> None:
    """User + token administration (parity: the reference's users/RBAC
    surface, sky/users/). Goes through the API server so auth/RBAC
    apply; bootstrap the first admin with the operator's static
    SKYT_API_SERVER_TOKEN, or --local on the server host itself."""


_LOCAL_HELP = 'Operate on the local users DB directly (server-host bootstrap).'


@users.command('list')
def users_list() -> None:
    from skypilot_tpu.client import sdk
    _echo_table(sdk.users_list(), ['name', 'role', 'created_at'])


@users.command('create')
@click.argument('name')
@click.option('--role', default='user', type=click.Choice(['admin', 'user']))
@click.option('--local', is_flag=True, default=False, help=_LOCAL_HELP)
def users_create(name: str, role: str, local: bool) -> None:
    if local:
        from skypilot_tpu.users import users_db
        record = users_db.create_user(name, role).to_dict()
    else:
        from skypilot_tpu.client import sdk
        record = sdk.users_create(name, role)
    click.echo(f"created user {record['name']} (role {record['role']})")


@users.command('delete')
@click.argument('name')
def users_delete(name: str) -> None:
    from skypilot_tpu.client import sdk
    sdk.users_delete(name)
    click.echo(f'deleted user {name}')


@users.command('set-role')
@click.argument('name')
@click.argument('role', type=click.Choice(['admin', 'user']))
def users_set_role(name: str, role: str) -> None:
    from skypilot_tpu.client import sdk
    sdk.users_set_role(name, role)
    click.echo(f'user {name} role -> {role}')


@users.command('service-account')
@click.argument('name')
@click.option('--label', default='')
@click.option('--expires-hours', type=float, default=None,
              help='Token lifetime; omitted = no expiry.')
def users_service_account(name: str, label: str,
                          expires_hours: Optional[float]) -> None:
    """Create a machine principal + its bearer token (printed once).

    Service accounts never hold admin or workspace-admin rights; their
    tokens can expire (parity: sky/users/token_service.py SA tokens).
    """
    from skypilot_tpu.client import sdk
    result = sdk.users_service_account(
        name, label,
        expires_seconds=(expires_hours * 3600
                         if expires_hours is not None else None))
    click.echo(f"service account {result['name']}: {result['token']}")


@users.command('set-workspace-role')
@click.argument('workspace')
@click.argument('name')
@click.argument('role', type=click.Choice(['admin', 'editor', 'viewer',
                                           'none']))
def users_set_workspace_role(workspace: str, name: str,
                             role: str) -> None:
    """Bind (or with 'none', unbind) a user's role in a workspace.

    The first binding CLOSES the workspace to non-members: submission
    needs 'use' (editor+), request/log visibility needs 'view'.
    """
    from skypilot_tpu.client import sdk
    sdk.workspace_set_role(workspace, name,
                           None if role == 'none' else role)
    click.echo(f'{workspace}: {name} -> {role}')


@users.command('workspace-roles')
@click.option('--workspace', '-w', default=None)
def users_workspace_roles(workspace: Optional[str]) -> None:
    """List per-workspace role bindings."""
    from skypilot_tpu.client import sdk
    _echo_table(sdk.workspace_roles(workspace),
                ['workspace', 'user_name', 'role'])


@users.command('token')
@click.argument('name', required=False, default=None)
@click.option('--label', default='')
@click.option('--local', is_flag=True, default=False, help=_LOCAL_HELP)
def users_token(name: Optional[str], label: str, local: bool) -> None:
    """Mint a bearer token (printed once; store it securely)."""
    if local:
        from skypilot_tpu.users import users_db
        if name is None:
            raise click.UsageError('NAME is required with --local')
        click.echo(users_db.create_token(name, label))
    else:
        from skypilot_tpu.client import sdk
        click.echo(sdk.users_token(name, label))


# -- ssh (parity: command.py ssh :8212 + websocket proxy) --------------


@cli.command('ssh')
@click.argument('cluster')
@click.argument('command', nargs=-1)
def ssh_cmd(cluster: str, command) -> None:
    """Open an SSH session to a cluster's head host (tunneled through
    the API server, so it works without a direct route to cluster IPs)."""
    info = _run(sdk.ssh_info(cluster), False, stream=False)
    proxy = (f'{sys.executable} -m skypilot_tpu.client.cli api '
             f'tunnel-stdio {cluster}')
    args = ['ssh',
            '-o', f'ProxyCommand={proxy}',
            '-o', 'StrictHostKeyChecking=no',
            '-o', 'UserKnownHostsFile=/dev/null',
            '-o', 'LogLevel=ERROR']
    key = info.get('key_path')
    if key and os.path.exists(os.path.expanduser(key)):
        args += ['-i', os.path.expanduser(key)]
    args += [f'{info["user"]}@skyt.{cluster}'] + list(command)
    os.execvp('ssh', args)


@api.command('tunnel-stdio', hidden=True)
@click.argument('cluster')
@click.option('--port', type=int, default=None)
def api_tunnel_stdio(cluster: str, port: Optional[int]) -> None:
    """ProxyCommand mode: pump stdin/stdout through /api/tunnel."""
    sys.exit(sdk.tunnel_stdio(cluster, port))


# -- volumes (parity: command.py volumes group :5435) ------------------


@cli.group()
def volumes() -> None:
    """Manage persistent volumes."""


@volumes.command('apply')
@click.argument('name')
@click.option('--type', 'type_', required=True,
              type=click.Choice(['k8s-pvc', 'hostpath', 'gce-pd']))
@click.option('--size', default='10', help='Size in GiB.')
@click.option('--zone', default=None)
@click.option('--use-existing', is_flag=True, default=False)
def volumes_apply(name: str, type_: str, size: str, zone: Optional[str],
                  use_existing: bool) -> None:
    """Create (or adopt) a volume."""
    record = _run(sdk.volumes_apply({
        'name': name, 'type': type_, 'size': size, 'zone': zone,
        'use_existing': use_existing}), False, stream=False)
    click.echo(f"volume {record['name']} ({record['type']}, "
               f"{record['size_gb']}GiB): {record['status']}")


@volumes.command('ls')
def volumes_ls() -> None:
    rows = _run(sdk.volumes_ls(), False, stream=False)
    for r in rows or []:
        r['attached_to'] = ','.join(r.get('attached_to') or []) or '-'
    _echo_table(rows or [],
                ['name', 'type', 'size_gb', 'status', 'attached_to'])


@volumes.command('delete')
@click.argument('name')
def volumes_delete(name: str) -> None:
    _run(sdk.volumes_delete(name), False, stream=False)
    click.echo(f'volume {name} deleted')


# -- workspaces (parity: command.py workspace group :8110) -------------


@cli.group()
def workspace() -> None:
    """Manage workspaces (multi-tenant resource isolation)."""


@workspace.command('list')
def workspace_list() -> None:
    from skypilot_tpu import workspaces
    active = workspaces.active_workspace()
    rows = []
    for name, spec in sorted(workspaces.list_workspaces().items()):
        rows.append({
            'name': ('* ' if name == active else '  ') + name,
            'allowed_clouds': ','.join(spec.get('allowed_clouds') or [])
                              or '(any)',
            'description': spec.get('description', ''),
        })
    _echo_table(rows, ['name', 'allowed_clouds', 'description'])


@workspace.command('create')
@click.argument('name')
@click.option('--allowed-cloud', 'allowed', multiple=True,
              help='Restrict the workspace to these clouds (repeatable).')
@click.option('--description', default='')
def workspace_create(name: str, allowed, description: str) -> None:
    from skypilot_tpu import workspaces
    workspaces.create_workspace(name, list(allowed) or None, description)
    click.echo(f'workspace {name} created')


@workspace.command('delete')
@click.argument('name')
def workspace_delete(name: str) -> None:
    from skypilot_tpu import workspaces
    try:
        workspaces.delete_workspace(name)
    except exceptions.SkytError as e:
        raise click.ClickException(str(e)) from e
    click.echo(f'workspace {name} deleted')


@workspace.command('switch')
@click.argument('name')
def workspace_switch(name: str) -> None:
    """Make NAME the active workspace for subsequent commands."""
    from skypilot_tpu import workspaces
    try:
        workspaces.set_active(name)
    except exceptions.SkytError as e:
        raise click.ClickException(str(e)) from e
    click.echo(f'active workspace: {name}')


# Command groups whose SECOND argv token is a subcommand name (safe to
# record); for plain commands argv[2] is user content (cluster names,
# YAML paths) and must never reach telemetry.
_TELEMETRY_GROUPS = frozenset({
    'jobs', 'serve', 'api', 'volumes', 'workspace', 'users', 'recipes'})


def _telemetry_verb(argv: List[str]) -> str:
    if len(argv) < 1 or argv[0].startswith('-'):
        return 'help'
    verb = argv[0]
    if verb not in cli.commands:
        # A typo'd/omitted command puts USER CONTENT at argv[0] (e.g.
        # `skyt my-cluster status`); never record it.
        return 'unknown'
    if (verb in _TELEMETRY_GROUPS and len(argv) > 1 and
            not argv[1].startswith('-')):
        group = cli.commands[verb]
        sub = argv[1]
        if hasattr(group, 'commands') and sub in group.commands:
            verb += '.' + sub
    return verb[:48]


def main() -> None:
    import time
    from skypilot_tpu import plugins
    from skypilot_tpu.utils import usage
    plugins.load_plugins()
    verb = _telemetry_verb(sys.argv[1:])
    start = time.monotonic()
    try:
        cli()
        # Unreachable in practice: click's standalone mode exits via
        # SystemExit even on success (handled below).
    except KeyboardInterrupt:
        usage.record(f'cli.{verb}', outcome='interrupted',
                     duration_s=time.monotonic() - start)
        sys.exit(130)
    except SystemExit as e:
        code = e.code if isinstance(e.code, int) else (0 if e.code is None
                                                       else 1)
        usage.record(f'cli.{verb}',
                     outcome='ok' if code == 0 else f'exit_{code}',
                     duration_s=time.monotonic() - start)
        raise


if __name__ == '__main__':
    main()
