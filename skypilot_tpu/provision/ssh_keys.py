"""Per-cloud SSH keypair management (shared by cloud drivers).

Each cloud gets its own keypair under ``$SKYT_STATE_DIR/keys/<cloud>/``
so drivers don't couple to a sibling cloud's module or mislabel keys.
"""
from __future__ import annotations

import os
import shutil
import subprocess
from typing import Tuple

from skypilot_tpu import exceptions


def key_path(cloud: str) -> str:
    state_dir = os.environ.get('SKYT_STATE_DIR',
                               os.path.expanduser('~/.skyt'))
    return os.path.join(state_dir, 'keys', cloud, f'skyt-{cloud}-key')


def ensure_keypair(cloud: str) -> Tuple[str, str]:
    """(private key path, public key line); generates ed25519 once."""
    path = key_path(cloud)
    pub_path = path + '.pub'
    if not os.path.exists(path):
        os.makedirs(os.path.dirname(path), exist_ok=True)
        if not shutil.which('ssh-keygen'):
            raise exceptions.ProvisionError(
                f'ssh-keygen not available; cannot generate the '
                f'{cloud} cluster SSH keypair')
        subprocess.run(
            ['ssh-keygen', '-t', 'ed25519', '-N', '', '-q',
             '-C', f'skyt-{cloud}', '-f', path], check=True)
    with open(pub_path, encoding='utf-8') as f:
        return path, f.read().strip()
