"""GCP TPU-VM provider: queued-resource gang provisioning over the TPU REST
API.

Parity targets: ``sky/provision/gcp/instance_utils.py:1258 GCPTPUVMInstance``
(TPU-VM create/stop/terminate), :1491 (queued-resource create+wait),
``sky/clouds/gcp.py:600`` (queued resources opt-in -- here they are the
*default* multi-host path, closing the SURVEY.md section 2.10 gap).

Network calls go through `_request` so tests can stub the transport; the
image is zero-egress, so live use requires a GCP environment (credentials
via metadata server or GOOGLE_APPLICATION_CREDENTIALS).
"""
from __future__ import annotations

import json
import os
import subprocess
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.provision.api import (ClusterInfo, HostInfo,
                                        ProvisionRequest, Provider)
from skypilot_tpu.utils import log
from skypilot_tpu.utils.registry import CLOUD_REGISTRY

logger = log.init_logger(__name__)

TPU_API = 'https://tpu.googleapis.com/v2'

# Error substrings -> typed exceptions (parity: FailoverCloudErrorHandlerV2
# _gcp_handler, cloud_vm_ray_backend.py:554).
_CAPACITY_MARKERS = (
    'does not have enough resources available',
    'no more capacity in the zone',
    'resource_exhausted',
    'stockout',
)
_QUOTA_MARKERS = (
    'quota exceeded',
    'quota limit',
    'exceeds quota',
)


def classify_gcp_error(message: str) -> exceptions.ProvisionError:
    low = message.lower()
    if any(m in low for m in _QUOTA_MARKERS):
        return exceptions.QuotaExceededError(message)
    if any(m in low for m in _CAPACITY_MARKERS):
        return exceptions.CapacityError(message)
    return exceptions.ProvisionError(message)


def _default_project() -> Optional[str]:
    proj = os.environ.get('GOOGLE_CLOUD_PROJECT')
    if proj:
        return proj
    try:
        out = subprocess.run(
            ['gcloud', 'config', 'get-value', 'project'],
            capture_output=True, text=True, timeout=10, check=False)
        if out.returncode == 0 and out.stdout.strip():
            return out.stdout.strip()
    except (FileNotFoundError, subprocess.TimeoutExpired):
        pass
    return None


def _access_token() -> str:
    out = subprocess.run(
        ['gcloud', 'auth', 'print-access-token'],
        capture_output=True, text=True, timeout=30, check=False)
    if out.returncode != 0:
        raise exceptions.NoCloudAccessError(
            f'gcloud auth failed: {out.stderr.strip()[:200]}')
    return out.stdout.strip()


@CLOUD_REGISTRY.register('gcp')
class GcpTpuProvider(Provider):
    """TPU-VM slices via queued resources; one node == one slice."""

    name = 'gcp'

    def __init__(self, project: Optional[str] = None) -> None:
        self._project = project or _default_project()

    # -- transport (stubbed in tests) ------------------------------------

    def _request(self, method: str, url: str,
                 body: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        import urllib.request
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(url, data=data, method=method)
        req.add_header('Authorization', f'Bearer {_access_token()}')
        req.add_header('Content-Type', 'application/json')
        try:
            with urllib.request.urlopen(req, timeout=60) as resp:
                return json.loads(resp.read().decode() or '{}')
        except Exception as e:  # noqa: BLE001 -- classified below
            raise classify_gcp_error(str(e)) from e

    def _parent(self, zone: str) -> str:
        return f'projects/{self._project}/locations/{zone}'

    # -- provider interface ----------------------------------------------

    def run_instances(self, request: ProvisionRequest) -> ClusterInfo:
        if self._project is None:
            raise exceptions.NoCloudAccessError(
                'No GCP project configured (GOOGLE_CLOUD_PROJECT or '
                'gcloud config).')
        res = request.resources
        if not res.is_tpu:
            raise exceptions.NotSupportedError(
                'The GCP provider currently targets TPU-VM slices; use '
                'accelerators: tpu-... (GPU/CPU instances: future work).')
        zone = request.zone or f'{request.region}-a'
        tpu = res.tpu
        for node in range(request.num_nodes):
            for slice_idx in range(tpu.num_slices):
                self._create_queued_resource(request, zone, node, slice_idx)
        self._wait_queued_resources(request, zone, timeout=1800)
        info = self.get_cluster_info(request.cluster_name)
        if info is None:
            raise exceptions.ProvisionError(
                f'{request.cluster_name}: queued resources active but no '
                'nodes found')
        return info

    def _qr_name(self, cluster_name: str, node: int, slice_idx: int) -> str:
        return f'{cluster_name}-n{node}-s{slice_idx}'

    def _create_queued_resource(self, request: ProvisionRequest, zone: str,
                                node: int, slice_idx: int) -> None:
        res = request.resources
        tpu = res.tpu
        qr_id = self._qr_name(request.cluster_name, node, slice_idx)
        node_spec = {
            'acceleratorType': tpu.accelerator_type,
            'runtimeVersion': res.tpu_runtime_version,
            'networkConfig': {'enableExternalIps': True},
            'metadata': {
                'skyt-cluster': request.cluster_name,
                'skyt-node': str(node),
                'skyt-slice': str(slice_idx),
            },
            'labels': {**request.labels, 'skyt-cluster': request.cluster_name},
        }
        body: Dict[str, Any] = {
            'tpu': {'nodeSpec': [{
                'parent': self._parent(zone),
                'nodeId': qr_id,
                'node': node_spec,
            }]},
        }
        if res.use_spot:
            body['spot'] = {}
        self._request(
            'POST',
            f'{TPU_API}/{self._parent(zone)}/queuedResources'
            f'?queuedResourceId={qr_id}', body)
        logger.info('Queued resource %s requested in %s', qr_id, zone)

    def _wait_queued_resources(self, request: ProvisionRequest, zone: str,
                               timeout: float) -> None:
        """Poll until every slice is ACTIVE (parity: queued-resource wait,
        instance_utils.py:1491)."""
        deadline = time.time() + timeout
        tpu = request.resources.tpu
        names = [
            self._qr_name(request.cluster_name, n, s)
            for n in range(request.num_nodes)
            for s in range(tpu.num_slices)
        ]
        while time.time() < deadline:
            states = {}
            for name in names:
                resp = self._request(
                    'GET',
                    f'{TPU_API}/{self._parent(zone)}/queuedResources/{name}')
                states[name] = resp.get('state', {}).get('state', 'UNKNOWN')
            if all(s == 'ACTIVE' for s in states.values()):
                return
            failed = {n: s for n, s in states.items()
                      if s in ('FAILED', 'SUSPENDED')}
            if failed:
                raise classify_gcp_error(
                    f'Queued resources failed: {failed}')
            time.sleep(10)
        raise exceptions.CapacityError(
            f'{request.cluster_name}: queued resources not ACTIVE within '
            f'{timeout}s (treating as capacity shortage for failover)')

    def _list_cluster_nodes(self, cluster_name: str,
                            zone: str) -> List[Dict[str, Any]]:
        resp = self._request('GET', f'{TPU_API}/{self._parent(zone)}/nodes')
        nodes = resp.get('nodes', [])
        return [n for n in nodes
                if n.get('labels', {}).get('skyt-cluster') == cluster_name]

    def _zone_of(self, cluster_name: str) -> Optional[str]:
        from skypilot_tpu import state as state_lib
        record = state_lib.get_cluster(cluster_name)
        return record.zone if record else None

    def stop_instances(self, cluster_name: str) -> None:
        zone = self._zone_of(cluster_name)
        for node in self._list_cluster_nodes(cluster_name, zone):
            self._request('POST', f'{TPU_API}/{node["name"]}:stop', {})

    def terminate_instances(self, cluster_name: str) -> None:
        zone = self._zone_of(cluster_name)
        if zone is None:
            return
        resp = self._request(
            'GET', f'{TPU_API}/{self._parent(zone)}/queuedResources')
        for qr in resp.get('queuedResources', []):
            if qr['name'].split('/')[-1].startswith(cluster_name + '-n'):
                self._request('DELETE', f'{TPU_API}/{qr["name"]}?force=true')

    def query_instances(self, cluster_name: str) -> Dict[str, str]:
        zone = self._zone_of(cluster_name)
        if zone is None:
            return {}
        out = {}
        state_map = {'READY': 'running', 'STOPPED': 'stopped',
                     'PREEMPTED': 'preempted', 'TERMINATED': 'terminated'}
        for node in self._list_cluster_nodes(cluster_name, zone):
            out[node['name'].split('/')[-1]] = state_map.get(
                node.get('state', ''), node.get('state', 'unknown').lower())
        return out

    def get_cluster_info(self, cluster_name: str) -> Optional[ClusterInfo]:
        zone = self._zone_of(cluster_name)
        if zone is None:
            return None
        nodes = self._list_cluster_nodes(cluster_name, zone)
        if not nodes:
            return None
        hosts: List[HostInfo] = []
        for tpu_node in nodes:
            meta = tpu_node.get('metadata', {})
            node_index = int(meta.get('skyt-node', 0))
            endpoints = tpu_node.get('networkEndpoints', [])
            for worker_index, ep in enumerate(endpoints):
                hosts.append(
                    HostInfo(
                        instance_id=(f'{tpu_node["name"].split("/")[-1]}'
                                     f'-w{worker_index}'),
                        internal_ip=ep.get('ipAddress', ''),
                        external_ip=ep.get('accessConfig', {}).get(
                            'externalIp'),
                        node_index=node_index,
                        worker_index=worker_index,
                    ))
        hosts.sort(key=lambda h: (h.node_index, h.worker_index))
        region = zone.rsplit('-', 1)[0]
        return ClusterInfo(
            cluster_name=cluster_name, provider='gcp', region=region,
            zone=zone, hosts=hosts, ssh_user='skyt',
            ssh_key_path=os.path.expanduser('~/.ssh/skyt-key'))
