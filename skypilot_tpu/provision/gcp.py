"""GCP provider: TPU-VM slices (queued resources) + GCE CPU instances.

Parity targets: ``sky/provision/gcp/instance_utils.py:1258 GCPTPUVMInstance``
(TPU-VM create/stop/terminate), :1491 (queued-resource create+wait),
``sky/clouds/gcp.py:600`` (queued resources opt-in -- here they are the
*default* multi-host path, closing the SURVEY.md section 2.10 gap),
``sky/provision/gcp/config.py`` (network/firewall/key bootstrap, compacted:
default-VPC probe + skyt-managed firewall rules + generated SSH keypair
injected via instance metadata instead of the reference's 1178-LoC
IAM/VPC state machine), GCE CPU instances for cheap controller VMs
(``instance_utils.py GCPComputeInstance``).

Network calls go through `_request` so tests can stub the transport; the
image is zero-egress, so live use requires a GCP environment (credentials
via metadata server or GOOGLE_APPLICATION_CREDENTIALS).
"""
from __future__ import annotations

import json
import os
import random
import shutil
import subprocess
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.provision.api import (ClusterInfo, HostInfo,
                                        ProvisionRequest, Provider)
from skypilot_tpu.utils import log
from skypilot_tpu.utils.registry import CLOUD_REGISTRY

logger = log.init_logger(__name__)

TPU_API = 'https://tpu.googleapis.com/v2'
COMPUTE_API = 'https://compute.googleapis.com/compute/v1'

SSH_USER = 'skyt'
_NOT_FOUND_MARKERS = ('404', 'not found', 'notfound')

# Error substrings -> typed exceptions (parity: FailoverCloudErrorHandlerV2
# _gcp_handler, cloud_vm_ray_backend.py:554).
_CAPACITY_MARKERS = (
    'does not have enough resources available',
    'no more capacity in the zone',
    'resource_exhausted',
    'stockout',
)
_QUOTA_MARKERS = (
    'quota exceeded',
    'quota limit',
    'exceeds quota',
)


def classify_gcp_error(message: str) -> exceptions.ProvisionError:
    low = message.lower()
    if any(m in low for m in _QUOTA_MARKERS):
        return exceptions.QuotaExceededError(message)
    if any(m in low for m in _CAPACITY_MARKERS):
        return exceptions.CapacityError(message)
    return exceptions.ProvisionError(message)


def _default_project() -> Optional[str]:
    proj = os.environ.get('GOOGLE_CLOUD_PROJECT')
    if proj:
        return proj
    try:
        out = subprocess.run(
            ['gcloud', 'config', 'get-value', 'project'],
            capture_output=True, text=True, timeout=10, check=False)
        if out.returncode == 0 and out.stdout.strip():
            return out.stdout.strip()
    except (FileNotFoundError, subprocess.TimeoutExpired):
        pass
    return None


def _access_token() -> str:
    out = subprocess.run(
        ['gcloud', 'auth', 'print-access-token'],
        capture_output=True, text=True, timeout=30, check=False)
    if out.returncode != 0:
        raise exceptions.NoCloudAccessError(
            f'gcloud auth failed: {out.stderr.strip()[:200]}')
    return out.stdout.strip()


# ---------------------------------------------------------------------------
# SSH keypair management (parity: the reference wires OS Login / metadata
# keys through gcp-ray.yml.j2; here a skyt-managed keypair is generated
# once and its public half is injected into node metadata at create time)
# ---------------------------------------------------------------------------

def ssh_key_path() -> str:
    state_dir = os.environ.get('SKYT_STATE_DIR',
                               os.path.expanduser('~/.skyt'))
    return os.path.join(state_dir, 'keys', 'gcp', 'skyt-gcp-key')


def ensure_ssh_keypair() -> tuple:
    """(private_key_path, public_key_text); generated once per install."""
    key_path = ssh_key_path()
    pub_path = key_path + '.pub'
    if not os.path.exists(key_path):
        os.makedirs(os.path.dirname(key_path), exist_ok=True)
        if not shutil.which('ssh-keygen'):
            raise exceptions.ProvisionError(
                'ssh-keygen not available; cannot generate the GCP '
                'cluster SSH keypair')
        subprocess.run(
            ['ssh-keygen', '-t', 'ed25519', '-N', '', '-q',
             '-C', 'skyt-gcp', '-f', key_path], check=True)
    with open(pub_path, encoding='utf-8') as f:
        return key_path, f.read().strip()


@CLOUD_REGISTRY.register('gcp')
class GcpTpuProvider(Provider):
    """TPU-VM slices via queued resources; one node == one slice."""

    name = 'gcp'

    def __init__(self, project: Optional[str] = None) -> None:
        self._project = project or _default_project()

    # -- transport (stubbed in tests) ------------------------------------

    def _request(self, method: str, url: str,
                 body: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        import urllib.request
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(url, data=data, method=method)
        req.add_header('Authorization', f'Bearer {_access_token()}')
        req.add_header('Content-Type', 'application/json')
        try:
            with urllib.request.urlopen(req, timeout=60) as resp:
                return json.loads(resp.read().decode() or '{}')
        except Exception as e:  # noqa: BLE001 -- classified below
            raise classify_gcp_error(str(e)) from e

    def _parent(self, zone: str) -> str:
        return f'projects/{self._project}/locations/{zone}'

    def _get_optional(self, url: str) -> Optional[Dict[str, Any]]:
        """GET that returns None on 404 (probe-style calls)."""
        try:
            return self._request('GET', url)
        except exceptions.ProvisionError as e:
            low = str(e).lower()
            if any(m in low for m in _NOT_FOUND_MARKERS):
                return None
            raise

    # -- network/firewall bootstrap (parity: provision/gcp/config.py,
    #    compacted) ------------------------------------------------------

    # project -> chosen network; CLASS-level so the result (including
    # which network to use) survives across provider instances -- every
    # provision goes through a fresh get_provider() object.
    _bootstrapped_projects: dict = {}

    def bootstrap(self) -> str:
        """Ensure a usable VPC + SSH ingress; returns the network name.

        The default VPC is used when present (the common case); otherwise
        a ``skyt-net`` auto-subnet VPC is created. A ``skyt-allow-ssh``
        firewall rule opens tcp:22 to the managed instances.
        """
        key = self._project
        if key in self._bootstrapped_projects:
            self._network = self._bootstrapped_projects[key]
            return self._network
        base = f'{COMPUTE_API}/projects/{self._project}/global'
        network = 'default'
        if self._get_optional(f'{base}/networks/default') is None:
            if self._get_optional(f'{base}/networks/skyt-net') is None:
                self._request('POST', f'{base}/networks', {
                    'name': 'skyt-net',
                    'autoCreateSubnetworks': True,
                })
            network = 'skyt-net'
        if self._get_optional(f'{base}/firewalls/skyt-allow-ssh') is None:
            self._request('POST', f'{base}/firewalls', {
                'name': 'skyt-allow-ssh',
                'network': f'global/networks/{network}',
                'direction': 'INGRESS',
                'allowed': [{'IPProtocol': 'tcp', 'ports': ['22']}],
                'sourceRanges': ['0.0.0.0/0'],
                'targetTags': ['skyt'],
            })
        self._network = network
        self._bootstrapped_projects[key] = network
        return network

    def open_ports(self, cluster_name: str, ports: List[str]) -> None:
        """Per-cluster ingress rule (parity: provision API open_ports)."""
        if not ports:
            return
        base = f'{COMPUTE_API}/projects/{self._project}/global'
        rule = f'skyt-{cluster_name}-ports'
        if self._get_optional(f'{base}/firewalls/{rule}') is not None:
            return
        network = getattr(self, '_network', 'default')
        self._request('POST', f'{base}/firewalls', {
            'name': rule,
            'network': f'global/networks/{network}',
            'direction': 'INGRESS',
            'allowed': [{'IPProtocol': 'tcp', 'ports': list(ports)}],
            'sourceRanges': ['0.0.0.0/0'],
            'targetTags': ['skyt'],
        })

    # -- volumes (GCE persistent disks for controller VMs; parity:
    #    sky/provision/gcp/volume_utils.py) -------------------------------

    def create_volume(self, volume) -> Dict[str, Any]:
        zone = volume.zone or volume.config.get('zone')
        if not zone:
            raise exceptions.InvalidSpecError(
                'gce-pd volumes need an explicit zone')
        base = f'{COMPUTE_API}/projects/{self._project}/zones/{zone}'
        if not volume.use_existing:
            self._request('POST', f'{base}/disks', {
                'name': volume.name,
                'sizeGb': str(volume.size_gb),
                'type': f'zones/{zone}/diskTypes/'
                        f'{volume.config.get("disk_type", "pd-balanced")}',
                'labels': volume.labels,
            })
        return {'disk': volume.name, 'zone': zone}

    def delete_volume(self, record: Dict[str, Any]) -> None:
        zone = record['config']['zone']
        base = f'{COMPUTE_API}/projects/{self._project}/zones/{zone}'
        self._request('DELETE',
                      f'{base}/disks/{record["config"]["disk"]}')

    def volume_mount_commands(self, record: Dict[str, Any],
                              mount_path: str) -> List[str]:
        """Attached PDs surface as /dev/disk/by-id/google-<name>; format
        on first use, then mount (the standard GCE recipe)."""
        dev = f'/dev/disk/by-id/google-{record["config"]["disk"]}'
        return [
            f'sudo blkid {dev} >/dev/null 2>&1 || '
            f'sudo mkfs.ext4 -q {dev}',
            f'sudo mkdir -p {mount_path} && '
            f'sudo mount -o discard,defaults {dev} {mount_path} && '
            f'sudo chmod a+w {mount_path}',
        ]

    # -- provider interface ----------------------------------------------

    def run_instances(self, request: ProvisionRequest) -> ClusterInfo:
        if self._project is None:
            raise exceptions.NoCloudAccessError(
                'No GCP project configured (GOOGLE_CLOUD_PROJECT or '
                'gcloud config).')
        res = request.resources
        zone = request.zone or f'{request.region}-a'
        self.bootstrap()
        if request.ports:
            self.open_ports(request.cluster_name, request.ports)
        if request.resume and self.query_instances(request.cluster_name):
            # Only wait when instances actually exist: a fully-reclaimed
            # cluster (spot DELETE) would otherwise hang wait_instances
            # for the whole timeout instead of creating fresh.
            self._start_stopped(request.cluster_name, zone)
            self.wait_instances(request.cluster_name, 'running',
                                timeout=600)
            info = self.get_cluster_info(request.cluster_name)
            if info is not None:
                return info
            # fall through: nothing to resume, create fresh
        if res.is_tpu:
            tpu = res.tpu
            for node in range(request.num_nodes):
                for slice_idx in range(tpu.num_slices):
                    self._create_queued_resource(request, zone, node,
                                                 slice_idx)
            self._wait_queued_resources(request, zone, timeout=1800)
        else:
            # GCE CPU instances: the controller-VM path (parity:
            # instance_utils.py GCPComputeInstance) -- jobs/serve
            # controllers live on cheap VMs, not TPU hosts.
            for node in range(request.num_nodes):
                self._create_compute_instance(request, zone, node)
            self.wait_instances(request.cluster_name, 'running',
                                timeout=600)
        info = self.get_cluster_info(request.cluster_name)
        if info is None:
            raise exceptions.ProvisionError(
                f'{request.cluster_name}: instances created but none '
                'found on list')
        return info

    def _start_stopped(self, cluster_name: str, zone: str) -> None:
        for node in self._list_cluster_nodes(cluster_name, zone):
            if node.get('state') == 'STOPPED':
                self._request('POST', f'{TPU_API}/{node["name"]}:start', {})
        for inst in self._list_compute_instances(cluster_name, zone):
            if inst.get('status') == 'TERMINATED':  # GCE 'stopped' status
                self._request(
                    'POST',
                    f'{self._zone_base(zone)}/instances/{inst["name"]}'
                    f'/start', {})

    def _qr_name(self, cluster_name: str, node: int, slice_idx: int) -> str:
        return f'{cluster_name}-n{node}-s{slice_idx}'

    def _create_queued_resource(self, request: ProvisionRequest, zone: str,
                                node: int, slice_idx: int) -> None:
        res = request.resources
        tpu = res.tpu
        qr_id = self._qr_name(request.cluster_name, node, slice_idx)
        _, pub_key = ensure_ssh_keypair()
        network = getattr(self, '_network', 'default')
        node_spec = {
            'acceleratorType': tpu.accelerator_type,
            'runtimeVersion': res.tpu_runtime_version,
            'networkConfig': {'enableExternalIps': True,
                              'network': f'global/networks/{network}'},
            'tags': ['skyt'],
            'metadata': {
                'skyt-cluster': request.cluster_name,
                'skyt-node': str(node),
                'skyt-slice': str(slice_idx),
                # The key that makes wait_for_ssh/runtime-ship possible:
                # same metadata contract as GCE (guest agent installs it
                # into ~skyt/.ssh/authorized_keys on every worker).
                'ssh-keys': f'{SSH_USER}:{pub_key}',
            },
            'labels': {**request.labels, 'skyt-cluster': request.cluster_name},
        }
        body: Dict[str, Any] = {
            'tpu': {'nodeSpec': [{
                'parent': self._parent(zone),
                'nodeId': qr_id,
                'node': node_spec,
            }]},
        }
        if res.use_spot:
            body['spot'] = {}
        self._request(
            'POST',
            f'{TPU_API}/{self._parent(zone)}/queuedResources'
            f'?queuedResourceId={qr_id}', body)
        logger.info('Queued resource %s requested in %s', qr_id, zone)

    def _wait_queued_resources(self, request: ProvisionRequest, zone: str,
                               timeout: float) -> None:
        """Poll until every slice is ACTIVE (parity: queued-resource wait,
        instance_utils.py:1491)."""
        deadline = time.monotonic() + timeout
        tpu = request.resources.tpu
        names = [
            self._qr_name(request.cluster_name, n, s)
            for n in range(request.num_nodes)
            for s in range(tpu.num_slices)
        ]
        interval = 5.0
        while time.monotonic() < deadline:
            states = {}
            for name in names:
                resp = self._request(
                    'GET',
                    f'{TPU_API}/{self._parent(zone)}/queuedResources/{name}')
                states[name] = resp.get('state', {}).get('state', 'UNKNOWN')
            if all(s == 'ACTIVE' for s in states.values()):
                return
            failed = {n: s for n, s in states.items()
                      if s in ('FAILED', 'SUSPENDED')}
            if failed:
                raise classify_gcp_error(
                    f'Queued resources failed: {failed}')
            # Exponential backoff to 30s with +/-25% jitter: queued
            # resources take minutes-to-hours and synchronized polls from
            # many provisioners hammer the regional endpoint.
            time.sleep(interval * random.uniform(0.75, 1.25))
            interval = min(interval * 1.5, 30.0)
        raise exceptions.CapacityError(
            f'{request.cluster_name}: queued resources not ACTIVE within '
            f'{timeout}s (treating as capacity shortage for failover)')

    # -- GCE CPU instances (controller VMs) ------------------------------

    def _zone_base(self, zone: str) -> str:
        return f'{COMPUTE_API}/projects/{self._project}/zones/{zone}'

    def _machine_type(self, res) -> str:
        if res.instance_type:
            return res.instance_type
        cpus = int(res.cpus[0]) if res.cpus else 4
        # e2-standard-N (N a power of two >= 2): the cheap controller-VM
        # family; round the request up to the next available size.
        n = max(2, 1 << (max(1, cpus) - 1).bit_length())
        return f'e2-standard-{min(n, 32)}'

    def _create_compute_instance(self, request: ProvisionRequest, zone: str,
                                 node: int) -> None:
        res = request.resources
        _, pub_key = ensure_ssh_keypair()
        network = getattr(self, '_network', 'default')
        name = f'{request.cluster_name}-n{node}'
        body = {
            'name': name,
            'machineType': (f'zones/{zone}/machineTypes/'
                            f'{self._machine_type(res)}'),
            'tags': {'items': ['skyt']},
            'disks': [{
                'boot': True,
                'autoDelete': True,
                'initializeParams': {
                    'sourceImage': ('projects/debian-cloud/global/images/'
                                    'family/debian-12'),
                    'diskSizeGb': str(res.disk_size),
                },
            }],
            'networkInterfaces': [{
                'network': f'global/networks/{network}',
                'accessConfigs': [{'type': 'ONE_TO_ONE_NAT',
                                   'name': 'External NAT'}],
            }],
            'metadata': {'items': [
                {'key': 'ssh-keys', 'value': f'{SSH_USER}:{pub_key}'},
                {'key': 'skyt-cluster', 'value': request.cluster_name},
                {'key': 'skyt-node', 'value': str(node)},
            ]},
            'labels': {**request.labels,
                       'skyt-cluster': request.cluster_name},
        }
        if res.use_spot:
            body['scheduling'] = {'provisioningModel': 'SPOT',
                                  'instanceTerminationAction': 'DELETE'}
        for vol in request.volumes:
            # Named gce-pd volumes attach at create; they surface as
            # /dev/disk/by-id/google-<name> (volume_mount_commands).
            if vol.get('type') != 'gce-pd':
                continue
            body['disks'].append({
                'boot': False,
                'autoDelete': False,
                'deviceName': vol['config']['disk'],
                'source': (f'projects/{self._project}/zones/'
                           f'{vol["config"]["zone"]}/disks/'
                           f'{vol["config"]["disk"]}'),
            })
        self._request('POST', f'{self._zone_base(zone)}/instances', body)
        logger.info('GCE instance %s requested in %s', name, zone)

    def _list_compute_instances(self, cluster_name: str,
                                zone: str) -> List[Dict[str, Any]]:
        import urllib.parse
        flt = urllib.parse.quote(f'labels.skyt-cluster={cluster_name}')
        resp = self._request(
            'GET', f'{self._zone_base(zone)}/instances?filter={flt}')
        return resp.get('items', [])

    def _list_cluster_nodes(self, cluster_name: str,
                            zone: str) -> List[Dict[str, Any]]:
        resp = self._request('GET', f'{TPU_API}/{self._parent(zone)}/nodes')
        nodes = resp.get('nodes', [])
        return [n for n in nodes
                if n.get('labels', {}).get('skyt-cluster') == cluster_name]

    def _zone_of(self, cluster_name: str) -> Optional[str]:
        from skypilot_tpu import state as state_lib
        record = state_lib.get_cluster(cluster_name)
        return record.zone if record else None

    def stop_instances(self, cluster_name: str) -> None:
        zone = self._zone_of(cluster_name)
        if zone is None:
            # No cluster record -> nothing addressable to stop (VERDICT
            # weak #4: previously built a locations/None URL).
            logger.warning('stop_instances(%s): no zone on record, '
                           'skipping', cluster_name)
            return
        for node in self._list_cluster_nodes(cluster_name, zone):
            self._request('POST', f'{TPU_API}/{node["name"]}:stop', {})
        for inst in self._list_compute_instances(cluster_name, zone):
            self._request(
                'POST',
                f'{self._zone_base(zone)}/instances/{inst["name"]}/stop', {})

    def terminate_instances(self, cluster_name: str) -> None:
        zone = self._zone_of(cluster_name)
        if zone is None:
            return
        resp = self._request(
            'GET', f'{TPU_API}/{self._parent(zone)}/queuedResources')
        for qr in resp.get('queuedResources', []):
            # Match by the skyt-cluster label on the QR's node spec, like
            # every other listing path. A name-prefix match is ambiguous:
            # cluster 'a' would capture 'a-n1''s QR 'a-n1-n0-s0'.
            specs = qr.get('tpu', {}).get('nodeSpec', [])
            owner = {ns.get('node', {}).get('labels', {})
                     .get('skyt-cluster') for ns in specs}
            if cluster_name in owner:
                self._request('DELETE', f'{TPU_API}/{qr["name"]}?force=true')
        for inst in self._list_compute_instances(cluster_name, zone):
            self._request(
                'DELETE',
                f'{self._zone_base(zone)}/instances/{inst["name"]}')
        # Per-cluster firewall rule cleanup (created by open_ports).
        base = f'{COMPUTE_API}/projects/{self._project}/global'
        rule = f'skyt-{cluster_name}-ports'
        if self._get_optional(f'{base}/firewalls/{rule}') is not None:
            self._request('DELETE', f'{base}/firewalls/{rule}')

    _TPU_STATE_MAP = {'READY': 'running', 'STOPPED': 'stopped',
                      'PREEMPTED': 'preempted', 'TERMINATED': 'terminated'}
    _GCE_STATE_MAP = {'RUNNING': 'running', 'TERMINATED': 'stopped',
                      'STOPPING': 'stopping', 'PROVISIONING': 'starting',
                      'STAGING': 'starting'}

    def query_instances(self, cluster_name: str) -> Dict[str, str]:
        zone = self._zone_of(cluster_name)
        if zone is None:
            return {}
        out = {}
        for node in self._list_cluster_nodes(cluster_name, zone):
            out[node['name'].split('/')[-1]] = self._TPU_STATE_MAP.get(
                node.get('state', ''), node.get('state', 'unknown').lower())
        for inst in self._list_compute_instances(cluster_name, zone):
            out[inst['name']] = self._GCE_STATE_MAP.get(
                inst.get('status', ''),
                inst.get('status', 'unknown').lower())
        return out

    def get_cluster_info(self, cluster_name: str) -> Optional[ClusterInfo]:
        zone = self._zone_of(cluster_name)
        if zone is None:
            return None
        hosts: List[HostInfo] = []
        for tpu_node in self._list_cluster_nodes(cluster_name, zone):
            meta = tpu_node.get('metadata', {})
            node_index = int(meta.get('skyt-node', 0))
            endpoints = tpu_node.get('networkEndpoints', [])
            for worker_index, ep in enumerate(endpoints):
                hosts.append(
                    HostInfo(
                        instance_id=(f'{tpu_node["name"].split("/")[-1]}'
                                     f'-w{worker_index}'),
                        internal_ip=ep.get('ipAddress', ''),
                        external_ip=ep.get('accessConfig', {}).get(
                            'externalIp'),
                        node_index=node_index,
                        worker_index=worker_index,
                    ))
        for inst in self._list_compute_instances(cluster_name, zone):
            meta_items = {i['key']: i['value']
                          for i in inst.get('metadata', {}).get('items', [])}
            nic = (inst.get('networkInterfaces') or [{}])[0]
            access = (nic.get('accessConfigs') or [{}])[0]
            hosts.append(
                HostInfo(
                    instance_id=inst['name'],
                    internal_ip=nic.get('networkIP', ''),
                    external_ip=access.get('natIP'),
                    node_index=int(meta_items.get('skyt-node', 0)),
                    worker_index=0,
                ))
        if not hosts:
            return None
        hosts.sort(key=lambda h: (h.node_index, h.worker_index))
        region = zone.rsplit('-', 1)[0]
        return ClusterInfo(
            cluster_name=cluster_name, provider='gcp', region=region,
            zone=zone, hosts=hosts, ssh_user=SSH_USER,
            ssh_key_path=ssh_key_path())
