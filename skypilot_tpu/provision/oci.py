"""OCI compute provider: Core Services REST with HTTP-Signature auth.

Parity: ``sky/provision/oci/instance.py`` + ``sky/clouds/oci.py`` — the
reference builds on the ``oci`` SDK; it isn't in this image, so the
wire protocol is implemented directly (same stance as the GCP REST /
AWS SigV4 / Azure ARM drivers): draft-cavage HTTP Signatures with the
tenancy API key (RSA-SHA256 over ``(request-target) date host`` plus
the content headers on writes) against
``iaas.<region>.oraclecloud.com``.

Deployment model (deliberately simpler than the reference's VCN
bootstrap): networking is BYO — ``oci.subnet_id``, ``oci.compartment_id``
and ``oci.image_id`` come from config (how OCI tenancies typically pin
networking/images centrally); the driver owns instance lifecycle only.
Cluster identity rides ``skyt-cluster``/``skyt-node`` freeform tags.
Network calls go through ``_request`` so tests stub the transport
(tests/test_oci_provider.py, mirroring the Azure/GCP/AWS fakes).
"""
from __future__ import annotations

import base64
import hashlib
import json
import urllib.error
import urllib.parse
import urllib.request
from email.utils import formatdate
from typing import Any, Dict, List, Optional, Tuple

from skypilot_tpu import exceptions
from skypilot_tpu.provision.api import (ClusterInfo, CloudCapability,
                                        HostInfo, Provider,
                                        ProvisionRequest)
from skypilot_tpu.utils import log
from skypilot_tpu.utils.registry import CLOUD_REGISTRY

logger = log.init_logger(__name__)

CORE_API = '20160918'
SSH_USER = 'skyt'

# OCI service error codes -> typed exceptions (parity: the reference's
# failover handler mapping for OCI).
_CAPACITY_CODES = ('OutOfHostCapacity', 'OutOfCapacity',
                   'InternalServerError')
_QUOTA_CODES = ('LimitExceeded', 'QuotaExceeded', 'TooManyRequests')
_AUTH_CODES = ('NotAuthenticated', 'NotAuthorizedOrNotFound',
               'SignatureInvalid')


def classify_oci_error(code: str, message: str) -> exceptions.ProvisionError:
    if code in _QUOTA_CODES:
        return exceptions.QuotaExceededError(f'{code}: {message}')
    if code in _CAPACITY_CODES:
        return exceptions.CapacityError(f'{code}: {message}')
    if code in _AUTH_CODES:
        return exceptions.NoCloudAccessError(f'{code}: {message}')
    return exceptions.ProvisionError(f'{code}: {message}')


def _setting(env: str, config_key: str) -> Optional[str]:
    import os
    value = os.environ.get(env)
    if value:
        return value
    from skypilot_tpu import config as config_lib
    return config_lib.get_nested(('oci', config_key), None)


def credentials() -> Dict[str, str]:
    creds = {
        'tenancy': _setting('OCI_TENANCY_OCID', 'tenancy_ocid'),
        'user': _setting('OCI_USER_OCID', 'user_ocid'),
        'fingerprint': _setting('OCI_FINGERPRINT', 'fingerprint'),
        'key_file': _setting('OCI_KEY_FILE', 'key_file'),
    }
    missing = [k for k, v in creds.items() if not v]
    if missing:
        raise exceptions.NoCloudAccessError(
            f'OCI credentials incomplete (missing {missing}): set '
            'OCI_TENANCY_OCID/OCI_USER_OCID/OCI_FINGERPRINT/'
            'OCI_KEY_FILE or oci.* in config')
    return creds


def placement() -> Dict[str, str]:
    """BYO networking/image settings every lifecycle call needs."""
    settings = {
        'compartment': _setting('OCI_COMPARTMENT_OCID',
                                'compartment_id'),
        'subnet': _setting('OCI_SUBNET_OCID', 'subnet_id'),
        'image': _setting('OCI_IMAGE_OCID', 'image_id'),
    }
    missing = [k for k, v in settings.items() if not v]
    if missing:
        raise exceptions.ProvisionError(
            f'OCI placement incomplete (missing {missing}): set '
            'oci.compartment_id / oci.subnet_id / oci.image_id in '
            'config (BYO-network model)')
    return settings


def signed_headers(method: str, url: str,
                   body: Optional[bytes],
                   *,
                   key_id: str,
                   private_key_pem: bytes,
                   date: Optional[str] = None) -> Dict[str, str]:
    """draft-cavage HTTP-Signature headers for one OCI request.

    Pure function (key + date injected) so the signature itself is
    unit-testable against the public half of a generated key.
    """
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import padding
    parsed = urllib.parse.urlparse(url)
    target = parsed.path + (f'?{parsed.query}' if parsed.query else '')
    date = date or formatdate(usegmt=True)
    headers = {'date': date, 'host': parsed.netloc}
    signed = ['(request-target)', 'date', 'host']
    lines = [f'(request-target): {method.lower()} {target}',
             f'date: {date}', f'host: {parsed.netloc}']
    if method.upper() in ('POST', 'PUT', 'PATCH') and body is None:
        # OCI signs the content headers on EVERY write, including
        # body-less instance actions (the SDK hashes the empty body).
        body = b''
    if body is not None:
        sha = base64.b64encode(hashlib.sha256(body).digest()).decode()
        headers.update({'x-content-sha256': sha,
                        'content-type': 'application/json',
                        'content-length': str(len(body))})
        signed += ['x-content-sha256', 'content-type', 'content-length']
        lines += [f'x-content-sha256: {sha}',
                  'content-type: application/json',
                  f'content-length: {len(body)}']
    key = serialization.load_pem_private_key(private_key_pem,
                                             password=None)
    signature = base64.b64encode(
        key.sign('\n'.join(lines).encode(), padding.PKCS1v15(),
                 hashes.SHA256())).decode()
    headers['authorization'] = (
        'Signature version="1",keyId="{kid}",algorithm="rsa-sha256",'
        'headers="{hdrs}",signature="{sig}"').format(
            kid=key_id, hdrs=' '.join(signed), sig=signature)
    return headers


@CLOUD_REGISTRY.register('oci')
class OciProvider(Provider):
    """Instance lifecycle on BYO OCI networking (see module doc)."""

    name = 'oci'
    # cluster -> region, remembered at launch: the provisioner calls
    # wait/terminate before the state record carries a region, and
    # guessing DEFAULT_REGION would poll (and leak instances in) the
    # wrong region for any non-default launch. Class-level: providers
    # are constructed per call.
    _region_memo: Dict[str, str] = {}
    _key_pem_cache: Dict[str, bytes] = {}

    @classmethod
    def unsupported_features(cls) -> Dict[CloudCapability, str]:
        return {
            CloudCapability.VOLUMES:
                'block-volume provisioning is not wired up yet',
        }

    # -- transport (stubbed in tests) ----------------------------------

    def _endpoint(self, region: str) -> str:
        return f'https://iaas.{region}.oraclecloud.com/{CORE_API}'

    def _request(self, method: str, region: str, path: str,
                 body: Optional[Dict[str, Any]] = None,
                 params: Optional[Dict[str, str]] = None
                 ) -> Any:
        creds = credentials()
        url = self._endpoint(region) + path
        if params:
            url += '?' + urllib.parse.urlencode(params)
        data = json.dumps(body).encode() if body is not None else None
        key_pem = self._key_pem_cache.get(creds['key_file'])
        if key_pem is None:
            try:
                with open(creds['key_file'], 'rb') as f:
                    key_pem = f.read()
            except OSError as e:
                raise exceptions.NoCloudAccessError(
                    f'OCI key file unreadable: {e}') from None
            self._key_pem_cache[creds['key_file']] = key_pem
        key_id = (f'{creds["tenancy"]}/{creds["user"]}/'
                  f'{creds["fingerprint"]}')
        headers = signed_headers(method, url, data, key_id=key_id,
                                 private_key_pem=key_pem)
        req = urllib.request.Request(url, data=data, method=method,
                                     headers=headers)
        try:
            with urllib.request.urlopen(req, timeout=60) as resp:
                raw = resp.read()
                parsed = json.loads(raw) if raw else {}
                # OCI list pagination rides a response HEADER; fold it
                # into the payload so list callers can follow it
                # without reaching into the transport.
                next_page = resp.headers.get('opc-next-page')
                if next_page:
                    if isinstance(parsed, list):
                        parsed = {'items': parsed,
                                  'opc-next-page': next_page}
                    elif isinstance(parsed, dict):
                        parsed.setdefault('opc-next-page', next_page)
                return parsed
        except urllib.error.HTTPError as e:
            text = e.read().decode('utf-8', errors='replace')
            try:
                err = json.loads(text)
                code = err.get('code', str(e.code))
                msg = err.get('message', text[:300])
            except ValueError:
                code, msg = str(e.code), text[:300]
            if e.code == 404 and method == 'GET':
                raise exceptions.ProvisionError(
                    f'NotFound: {msg}') from None
            raise classify_oci_error(code, msg) from None
        except exceptions.ProvisionError:
            raise
        except Exception as e:  # pylint: disable=broad-except
            raise exceptions.ProvisionError(
                f'OCI {method} {path} failed: {e}') from e

    # -- instance selection --------------------------------------------

    @staticmethod
    def _shape(resources) -> Tuple[str, Optional[Dict[str, float]]]:
        """(shape name, shapeConfig or None for fixed shapes)."""
        from skypilot_tpu.catalog import oci_data
        if resources.instance_type:
            name = resources.instance_type
            if name.startswith('VM.Standard') and name.count('-') >= 2:
                base, ocpus, mem = name.rsplit('-', 2)
                return base, {'ocpus': float(ocpus) / 2,
                              'memoryInGBs': float(mem)}
            if name.endswith('.Flex'):
                # Flex shapes REQUIRE a size; a bare name gets the
                # smallest preset instead of an opaque API 400. Use
                # the '<shape>-<vcpus>-<memGB>' form to size it.
                return name, {'ocpus': 1.0, 'memoryInGBs': 16.0}
            return name, None
        accels = resources.accelerators
        if accels:
            (name, count), = accels.items()
            picked = oci_data.instance_type_for(name, count)
            if picked is None:
                raise exceptions.ProvisionError(
                    f'no OCI shape for {count}x {name}; known: '
                    f'{sorted(oci_data.GPU_INSTANCE_TYPES)}')
            return picked[0], None
        from skypilot_tpu.catalog.common import pick_cpu_instance_type
        cpus = resources.cpus[0] if resources.cpus else None
        mem = resources.memory[0] if resources.memory else None
        preset = pick_cpu_instance_type(cpus, mem, cloud='oci')
        base, ocpus, mem_gb = preset.rsplit('-', 2)
        return base, {'ocpus': float(ocpus) / 2,
                      'memoryInGBs': float(mem_gb)}

    # -- queries -------------------------------------------------------

    def _list_instances(self, cluster: str,
                        region: str) -> List[Dict[str, Any]]:
        """Non-terminated instances carrying this cluster's tag.

        Follows ``opc-next-page`` pagination (ADVICE r5 low): in a
        large compartment a single page can hide this cluster's
        instances from stop/terminate, silently leaking them."""
        rows: List[Dict[str, Any]] = []
        params = {'compartmentId': placement()['compartment']}
        for _ in range(100):  # bounded: 100 pages ≈ 100k instances
            out = self._request('GET', region, '/instances/',
                                params=dict(params))
            page = out if isinstance(out, list) else out.get('items', [])
            rows.extend(page)
            token = (out.get('opc-next-page')
                     if isinstance(out, dict) else None)
            if not token:
                break
            params['page'] = token
        else:
            logger.warning(
                'OCI instance listing for %s did not drain in 100 '
                'pages; lifecycle ops may miss instances.', cluster)
        return [r for r in rows
                if (r.get('freeformTags') or {}).get('skyt-cluster')
                == cluster and r.get('lifecycleState') not in
                ('TERMINATED', 'TERMINATING')]

    def _vnic_ips(self, region: str, instance_id: str
                  ) -> Tuple[Optional[str], Optional[str]]:
        attachments = self._request(
            'GET', region, '/vnicAttachments/',
            params={'compartmentId': placement()['compartment'],
                    'instanceId': instance_id})
        rows = (attachments if isinstance(attachments, list)
                else attachments.get('items', []))
        for att in rows:
            vnic_id = att.get('vnicId')
            if not vnic_id or att.get('lifecycleState') == 'DETACHED':
                continue
            vnic = self._request('GET', region, f'/vnics/{vnic_id}')
            return vnic.get('privateIp'), vnic.get('publicIp')
        return None, None

    # -- Provider API --------------------------------------------------

    def run_instances(self, request: ProvisionRequest) -> ClusterInfo:
        cluster, region = request.cluster_name, request.region
        self._region_memo[cluster] = region
        where = placement()
        existing = self._list_instances(cluster, region)
        if request.resume and existing:
            for inst in existing:
                if inst['lifecycleState'] == 'STOPPED':
                    self._request('POST', region,
                                  f'/instances/{inst["id"]}',
                                  params={'action': 'START'})
            return self._cluster_info_from(cluster, region, existing)
        if existing:
            raise exceptions.ProvisionError(
                f'cluster {cluster} already has instances; use resume '
                'or terminate first')
        from skypilot_tpu.provision.ssh_keys import ensure_keypair
        _, pub_key = ensure_keypair('oci')
        shape, shape_config = self._shape(request.resources)
        availability_domain = (request.zone or
                               f'{region}-AD-1')
        for node in range(request.num_nodes):
            body: Dict[str, Any] = {
                'availabilityDomain': availability_domain,
                'compartmentId': where['compartment'],
                'displayName': f'{cluster}-n{node}',
                'shape': shape,
                'createVnicDetails': {
                    'subnetId': where['subnet'],
                    'assignPublicIp': True,
                },
                'sourceDetails': {
                    'sourceType': 'image',
                    'imageId': where['image'],
                },
                'metadata': {
                    'ssh_authorized_keys': f'{SSH_USER}:{pub_key}',
                },
                'freeformTags': {'skyt-cluster': cluster,
                                 'skyt-node': str(node),
                                 **request.labels},
            }
            if shape_config:
                body['shapeConfig'] = shape_config
            if request.resources.use_spot:
                body['preemptibleInstanceConfig'] = {
                    'preemptionAction': {'type': 'TERMINATE',
                                         'preserveBootVolume': False}}
            self._request('POST', region, '/instances/', body)
        self.wait_instances(cluster, 'running',
                            region_hint=region,
                            expected=request.num_nodes)
        return self._cluster_info_from(
            cluster, region, self._list_instances(cluster, region))

    def _region_of(self, cluster_name: str) -> str:
        memo = self._region_memo.get(cluster_name)
        if memo:
            return memo
        from skypilot_tpu import state
        record = state.get_cluster(cluster_name)
        if record is not None and record.region:
            return record.region
        from skypilot_tpu.catalog import oci_data
        logger.warning(
            'OCI cluster %s has no recorded region; defaulting to %s',
            cluster_name, oci_data.DEFAULT_REGION)
        return oci_data.DEFAULT_REGION

    def stop_instances(self, cluster_name: str) -> None:
        region = self._region_of(cluster_name)
        for inst in self._list_instances(cluster_name, region):
            self._request('POST', region, f'/instances/{inst["id"]}',
                          params={'action': 'SOFTSTOP'})

    def terminate_instances(self, cluster_name: str) -> None:
        region = self._region_of(cluster_name)
        for inst in self._list_instances(cluster_name, region):
            self._request('DELETE', region,
                          f'/instances/{inst["id"]}',
                          params={'preserveBootVolume': 'false'})

    _STATE_MAP = {
        'PROVISIONING': 'starting', 'STARTING': 'starting',
        'RUNNING': 'running', 'STOPPING': 'stopping',
        'STOPPED': 'stopped', 'TERMINATING': 'terminated',
        'TERMINATED': 'terminated',
    }

    def query_instances(self, cluster_name: str) -> Dict[str, str]:
        region = self._region_of(cluster_name)
        return {
            inst['id']: self._STATE_MAP.get(inst['lifecycleState'],
                                            inst['lifecycleState'].lower())
            for inst in self._list_instances(cluster_name, region)
        }

    def wait_instances(self, cluster_name: str, state: str = 'running',
                       timeout: float = 600,
                       region_hint: Optional[str] = None,
                       expected: Optional[int] = None) -> None:
        """``expected`` guards against list eventual-consistency and a
        partially-failed multi-node launch loop (ADVICE r5 low): the
        wait only succeeds once at least that many instances are
        visible AND in the target state — never on a subset."""
        import time
        deadline = time.monotonic() + timeout
        region = region_hint or self._region_of(cluster_name)
        states: Dict[str, str] = {}
        while time.monotonic() < deadline:
            states = {
                inst['id']: self._STATE_MAP.get(
                    inst['lifecycleState'],
                    inst['lifecycleState'].lower())
                for inst in self._list_instances(cluster_name, region)}
            if (states and
                    (expected is None or len(states) >= expected) and
                    all(s == state for s in states.values())):
                return
            time.sleep(min(2, max(0.01, deadline - time.monotonic())))
        raise TimeoutError(
            f'{cluster_name}: OCI instances did not reach {state!r} '
            f'in {timeout}s'
            + (f' (saw {len(states)}/{expected} instances)'
               if expected is not None else ''))

    def _cluster_info_from(self, cluster: str, region: str,
                           instances: List[Dict[str, Any]]
                           ) -> ClusterInfo:
        from skypilot_tpu.provision.ssh_keys import key_path
        hosts = []
        for inst in sorted(
                instances,
                key=lambda r: int((r.get('freeformTags') or {})
                                  .get('skyt-node', 0))):
            private_ip, public_ip = self._vnic_ips(region, inst['id'])
            node = int((inst.get('freeformTags') or {})
                       .get('skyt-node', 0))
            hosts.append(HostInfo(
                instance_id=inst['id'],
                internal_ip=private_ip or '',
                external_ip=public_ip,
                node_index=node,
                worker_index=0))
        return ClusterInfo(
            cluster_name=cluster, provider='oci', region=region,
            zone=instances[0].get('availabilityDomain')
            if instances else None,
            hosts=hosts, ssh_user=SSH_USER,
            ssh_key_path=key_path('oci'))

    def get_cluster_info(self, cluster_name: str) -> Optional[ClusterInfo]:
        region = self._region_of(cluster_name)
        instances = self._list_instances(cluster_name, region)
        if not instances:
            return None
        return self._cluster_info_from(cluster_name, region, instances)
