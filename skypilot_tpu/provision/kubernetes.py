"""Kubernetes (GKE TPU) provider: pods as hosts, TPU slices first-class.

Parity targets: ``sky/provision/kubernetes/`` (11k LoC) — GKE TPU name
normalization (`utils.py:310` tpu-v6e-8 -> tpu-v6e-slice), generation
map :243, topology map :632, `is_tpu_on_gke` :4705 — with the big
difference that **multi-host TPU slices are supported** (the reference
rejects them, `utils.py:1299-1301`; closing that gap is a SURVEY.md
§2.10 deliverable). One pod per TPU host; the pods of a slice share a
`job-name`-style label and a headless Service for stable DNS, and GKE's
TPU webhook injects `TPU_WORKER_ID`/`TPU_WORKER_HOSTNAMES` for pods
with the right selectors — our backend additionally injects its own
rank envs at exec time, so both the webhook and non-GKE clusters work.

The API transport is pluggable: `RestKubernetesApi` talks to a real
apiserver with kubeconfig auth (bearer token or client certs — the k8s
Python SDK is intentionally not a dependency, matching the reference's
lazy-adaptor stance); `FakeKubernetesApi` is a file-backed in-process
cluster for tests (the moto-style fixture of SURVEY.md §4), with fault
injection for unschedulable pods.
"""
from __future__ import annotations

import base64
import json
import os
import ssl
import tempfile
import time
import urllib.error
import urllib.parse
import urllib.request
import uuid
from typing import Any, Dict, List, Optional

import filelock
import yaml

from skypilot_tpu import exceptions
from skypilot_tpu.provision.api import (ClusterInfo, HostInfo,
                                        ProvisionRequest, Provider)
from skypilot_tpu.utils import env_registry, log
from skypilot_tpu.utils.registry import CLOUD_REGISTRY

logger = log.init_logger(__name__)

# GKE accelerator label values per TPU generation (ref kubernetes/
# utils.py:243 GKE_TPU_ACCELERATOR_TO_GENERATION inverted).
GKE_TPU_ACCELERATOR = {
    'v4': 'tpu-v4-podslice',
    'v5e': 'tpu-v5-lite-podslice',
    'v5p': 'tpu-v5p-slice',
    'v6e': 'tpu-v6e-slice',
}

LABEL_CLUSTER = 'skyt/cluster'
LABEL_NODE = 'skyt/node-index'
LABEL_WORKER = 'skyt/worker-index'

DEFAULT_IMAGE = os.environ.get(
    'SKYT_K8S_IMAGE', 'python:3.11-slim')


def _provision_timeout() -> float:
    return env_registry.get_float('SKYT_K8S_PROVISION_TIMEOUT')


def gke_tpu_selectors(resources) -> Dict[str, str]:
    """nodeSelector labels for a TPU slice request (ref utils.py:310/632:
    name normalization + topology map, derived here from TpuTopology
    instead of lookup tables)."""
    tpu = resources.tpu
    accel = GKE_TPU_ACCELERATOR.get(tpu.generation)
    if accel is None:
        raise exceptions.NotSupportedError(
            f'TPU generation {tpu.generation} has no GKE node pools '
            f'(available: {sorted(GKE_TPU_ACCELERATOR)})')
    return {
        'cloud.google.com/gke-tpu-accelerator': accel,
        'cloud.google.com/gke-tpu-topology': tpu.topology_str,
    }


def build_pod_manifest(request: ProvisionRequest, node: int, worker: int,
                       namespace: str) -> Dict[str, Any]:
    """One pod = one TPU host of one slice (pure; unit-testable)."""
    res = request.resources
    name = f'{request.cluster_name}-{node}-{worker}'
    labels = {
        LABEL_CLUSTER: request.cluster_name,
        LABEL_NODE: str(node),
        LABEL_WORKER: str(worker),
        **request.labels,
    }
    spec: Dict[str, Any] = {
        'restartPolicy': 'Never',
        'containers': [{
            'name': 'skyt',
            'image': DEFAULT_IMAGE,
            'command': ['/bin/sh', '-c', 'sleep infinity'],
            'resources': {},
        }],
        'hostname': name,
        'subdomain': request.cluster_name,   # headless-service DNS
    }
    if res.is_tpu:
        tpu = res.tpu
        spec['nodeSelector'] = gke_tpu_selectors(res)
        chips = tpu.chips_per_host
        spec['containers'][0]['resources'] = {
            'requests': {'google.com/tpu': str(chips)},
            'limits': {'google.com/tpu': str(chips)},
        }
    if res.use_spot:
        spec.setdefault('nodeSelector', {})[
            'cloud.google.com/gke-spot'] = 'true'
        spec['tolerations'] = [{
            'key': 'cloud.google.com/gke-spot',
            'operator': 'Equal',
            'value': 'true',
            'effect': 'NoSchedule',
        }]
    if _needs_fuse(request):
        _add_fuse_proxy_mount(spec)
    for i, vol in enumerate(request.volumes):
        # PVC volumes ride the pod manifest (parity: the reference mounts
        # k8s volumes via pod spec, sky/provision/kubernetes/volume.py).
        if vol.get('type') != 'k8s-pvc':
            continue
        vol_name = f'skyt-vol-{i}'
        spec.setdefault('volumes', []).append({
            'name': vol_name,
            'persistentVolumeClaim': {
                'claimName': vol['config'].get('pvc', vol['name'])},
        })
        spec['containers'][0].setdefault('volumeMounts', []).append({
            'name': vol_name,
            'mountPath': vol['mount_path'],
        })
    return {
        'apiVersion': 'v1',
        'kind': 'Pod',
        'metadata': {'name': name, 'namespace': namespace,
                     'labels': labels},
        'spec': spec,
    }


def _needs_fuse(request: ProvisionRequest) -> bool:
    """MOUNT/MOUNT_CACHED storage on an unprivileged pod needs the
    fuse-proxy shim (labels carry the hint from the backend)."""
    return request.labels.get('skyt-fuse') == 'true'


FUSE_PROXY_SOCKET_DIR = '/run/skyt-fuse-proxy'


def _add_fuse_proxy_mount(spec: Dict[str, Any]) -> None:
    """Wire the pod to the node's fuse-proxy DaemonSet (addons/fuse_proxy
    C++ rebuild of the reference's Go addons/fuse-proxy): the shim binary
    + server socket arrive via hostPath, the shim is prepended to PATH so
    gcsfuse/rclone transparently exec it instead of real fusermount --
    NO privileged: true on the workload pod."""
    spec.setdefault('volumes', []).append({
        'name': 'skyt-fuse-proxy',
        'hostPath': {'path': FUSE_PROXY_SOCKET_DIR,
                     'type': 'DirectoryOrCreate'},
    })
    container = spec['containers'][0]
    container.setdefault('volumeMounts', []).append({
        'name': 'skyt-fuse-proxy',
        'mountPath': FUSE_PROXY_SOCKET_DIR,
    })
    container.setdefault('env', []).append(
        {'name': 'FUSE_PROXY_SOCKET',
         'value': f'{FUSE_PROXY_SOCKET_DIR}/fuse-proxy.sock'})
    # NOTE: the shim dir is prepended to PATH at mount-command run time
    # (mounting_utils.fuse_proxy_path_prefix), in-shell -- setting a
    # PATH env here would clobber whatever PATH the image bakes in.


def build_fuse_proxy_daemonset(namespace: str) -> Dict[str, Any]:
    """The privileged per-node fuse-proxy server (parity: the reference's
    fuse-proxy DaemonSet manifest, addons/fuse-proxy README)."""
    return {
        'apiVersion': 'apps/v1',
        'kind': 'DaemonSet',
        'metadata': {'name': 'skyt-fuse-proxy', 'namespace': namespace},
        'spec': {
            'selector': {'matchLabels': {'app': 'skyt-fuse-proxy'}},
            'template': {
                'metadata': {'labels': {'app': 'skyt-fuse-proxy'}},
                'spec': {
                    'hostPID': True,
                    'containers': [{
                        'name': 'server',
                        'image': DEFAULT_IMAGE,
                        'command': [
                            '/bin/sh', '-c',
                            # Install shim for pods, then serve.
                            f'mkdir -p {FUSE_PROXY_SOCKET_DIR}/bin && '
                            f'cp /opt/skyt/fusermount-shim '
                            f'{FUSE_PROXY_SOCKET_DIR}/bin/fusermount && '
                            f'cp /opt/skyt/fusermount-shim '
                            f'{FUSE_PROXY_SOCKET_DIR}/bin/fusermount3 && '
                            f'exec /opt/skyt/fuse-proxy-server '
                            f'{FUSE_PROXY_SOCKET_DIR}/fuse-proxy.sock',
                        ],
                        'securityContext': {'privileged': True},
                        'volumeMounts': [
                            {'name': 'proxy-dir',
                             'mountPath': FUSE_PROXY_SOCKET_DIR},
                            {'name': 'dev-fuse', 'mountPath': '/dev/fuse'},
                        ],
                    }],
                    'volumes': [
                        {'name': 'proxy-dir',
                         'hostPath': {'path': FUSE_PROXY_SOCKET_DIR,
                                      'type': 'DirectoryOrCreate'}},
                        {'name': 'dev-fuse',
                         'hostPath': {'path': '/dev/fuse'}},
                    ],
                },
            },
        },
    }


def build_headless_service(cluster_name: str,
                           namespace: str) -> Dict[str, Any]:
    """Stable per-pod DNS (<hostname>.<cluster>.<ns>.svc) for the gang
    — what TPU_WORKER_HOSTNAMES points at on GKE."""
    return {
        'apiVersion': 'v1',
        'kind': 'Service',
        'metadata': {'name': cluster_name, 'namespace': namespace,
                     'labels': {LABEL_CLUSTER: cluster_name}},
        'spec': {
            'clusterIP': 'None',
            'selector': {LABEL_CLUSTER: cluster_name},
        },
    }


# ---------------------------------------------------------------------------
# API transports
# ---------------------------------------------------------------------------


class KubernetesApi:
    """The handful of apiserver operations the provider needs."""

    def create_pod(self, namespace: str, manifest: Dict[str, Any]) -> None:
        raise NotImplementedError

    def create_service(self, namespace: str,
                       manifest: Dict[str, Any]) -> None:
        raise NotImplementedError

    def list_pods(self, namespace: str,
                  label_selector: str) -> List[Dict[str, Any]]:
        raise NotImplementedError

    def delete_pod(self, namespace: str, name: str) -> None:
        raise NotImplementedError

    def delete_service(self, namespace: str, name: str) -> None:
        raise NotImplementedError

    def create_pvc(self, namespace: str, manifest: Dict[str, Any]) -> None:
        raise NotImplementedError

    def delete_pvc(self, namespace: str, name: str) -> None:
        raise NotImplementedError


def find_kubeconfig() -> Optional[str]:
    """First existing file of $KUBECONFIG (colon-separated list, per the
    k8s convention) or ~/.kube/config."""
    env = os.environ.get('KUBECONFIG')
    candidates = (env.split(os.pathsep) if env
                  else [os.path.expanduser('~/.kube/config')])
    for path in candidates:
        if path and os.path.exists(path):
            return path
    return None


class RestKubernetesApi(KubernetesApi):
    """Thin kubeconfig-authenticated REST client (no k8s SDK dep).

    Auth: static bearer token, embedded client certs, or an ``exec:``
    credential plugin (the GKE default — gke-gcloud-auth-plugin emits an
    ExecCredential JSON whose token we use)."""

    def __init__(self, kubeconfig: Optional[str] = None,
                 context: Optional[str] = None) -> None:
        path = kubeconfig or find_kubeconfig()
        if path is None or not os.path.exists(path):
            raise exceptions.NoCloudAccessError(
                f'No kubeconfig found (KUBECONFIG='
                f'{os.environ.get("KUBECONFIG")!r}).')
        with open(path, encoding='utf-8') as f:
            cfg = yaml.safe_load(f)
        ctx_name = context or cfg.get('current-context')
        ctx = next(c['context'] for c in cfg['contexts']
                   if c['name'] == ctx_name)
        cluster = next(c['cluster'] for c in cfg['clusters']
                       if c['name'] == ctx['cluster'])
        user = next(u['user'] for u in cfg['users']
                    if u['name'] == ctx['user'])
        self.server = cluster['server']
        self._ssl = self._ssl_context(cluster, user)
        self._token = user.get('token') or self._exec_plugin_token(user)

    @staticmethod
    def _exec_plugin_token(user: Dict[str, Any]) -> Optional[str]:
        """Run the kubeconfig `exec:` credential plugin (client.authn
        ExecCredential protocol — how GKE kubeconfigs authenticate)."""
        exec_cfg = user.get('exec')
        if not exec_cfg:
            return None
        import subprocess
        cmd = [exec_cfg['command']] + list(exec_cfg.get('args') or [])
        env = dict(os.environ)
        for item in exec_cfg.get('env') or []:
            env[item['name']] = item['value']
        try:
            out = subprocess.run(cmd, capture_output=True, text=True,
                                 env=env, timeout=60, check=False)
        except FileNotFoundError as e:
            raise exceptions.NoCloudAccessError(
                f'kubeconfig exec plugin {cmd[0]!r} not installed: {e}'
            ) from e
        if out.returncode != 0:
            raise exceptions.NoCloudAccessError(
                f'kubeconfig exec plugin failed: {out.stderr[-500:]}')
        try:
            cred = json.loads(out.stdout)
            return cred['status']['token']
        except (json.JSONDecodeError, KeyError) as e:
            raise exceptions.NoCloudAccessError(
                f'Malformed ExecCredential from {cmd[0]!r}: {e}') from e

    @staticmethod
    def _ssl_context(cluster: Dict[str, Any],
                     user: Dict[str, Any]) -> ssl.SSLContext:
        ctx = ssl.create_default_context()
        ca = cluster.get('certificate-authority-data')
        if ca:
            ctx.load_verify_locations(
                cadata=base64.b64decode(ca).decode())
        elif cluster.get('certificate-authority'):
            ctx.load_verify_locations(cluster['certificate-authority'])
        cert = user.get('client-certificate-data')
        key = user.get('client-key-data')
        if cert and key:
            # load_cert_chain needs files; write the decoded pair to a
            # private tmp file and unlink as soon as it is loaded (key
            # material must not persist in /tmp).
            cert_file = tempfile.NamedTemporaryFile(delete=False,
                                                    suffix='.pem')
            try:
                os.chmod(cert_file.name, 0o600)
                cert_file.write(base64.b64decode(cert))
                cert_file.write(b'\n')
                cert_file.write(base64.b64decode(key))
                cert_file.close()
                ctx.load_cert_chain(cert_file.name)
            finally:
                os.unlink(cert_file.name)
        return ctx

    def _request(self, method: str, path: str,
                 body: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        url = f'{self.server}{path}'
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(url, data=data, method=method)
        req.add_header('Content-Type', 'application/json')
        req.add_header('Accept', 'application/json')
        if self._token:
            req.add_header('Authorization', f'Bearer {self._token}')
        try:
            with urllib.request.urlopen(req, context=self._ssl,
                                        timeout=30) as resp:
                return json.loads(resp.read() or b'{}')
        except urllib.error.HTTPError as e:
            detail = e.read().decode(errors='replace')[:800]
            raise exceptions.ProvisionError(
                f'k8s API {method} {path}: HTTP {e.code}: {detail}') from e
        except (urllib.error.URLError, OSError) as e:
            # Connection refused / DNS / TLS / timeout: wrap so the
            # failover provisioner classifies it, not a raw traceback.
            raise exceptions.ProvisionError(
                f'k8s API {method} {self.server}{path}: {e}') from e

    def create_pod(self, namespace, manifest):
        self._request('POST', f'/api/v1/namespaces/{namespace}/pods',
                      manifest)

    def create_service(self, namespace, manifest):
        self._request('POST', f'/api/v1/namespaces/{namespace}/services',
                      manifest)

    def list_pods(self, namespace, label_selector):
        out = self._request(
            'GET', f'/api/v1/namespaces/{namespace}/pods'
            f'?labelSelector={urllib.parse.quote(label_selector)}')
        return out.get('items', [])

    def delete_pod(self, namespace, name):
        try:
            self._request('DELETE',
                          f'/api/v1/namespaces/{namespace}/pods/{name}')
        except exceptions.ProvisionError as e:
            if 'HTTP 404' not in str(e):
                raise

    def delete_service(self, namespace, name):
        try:
            self._request(
                'DELETE', f'/api/v1/namespaces/{namespace}/services/{name}')
        except exceptions.ProvisionError as e:
            if 'HTTP 404' not in str(e):
                raise

    def create_pvc(self, namespace, manifest):
        self._request(
            'POST',
            f'/api/v1/namespaces/{namespace}/persistentvolumeclaims',
            manifest)

    def delete_pvc(self, namespace, name):
        try:
            self._request(
                'DELETE', f'/api/v1/namespaces/{namespace}/'
                f'persistentvolumeclaims/{name}')
        except exceptions.ProvisionError as e:
            if 'HTTP 404' not in str(e):
                raise


def _fake_store_path() -> str:
    state_dir = os.environ.get('SKYT_STATE_DIR',
                               os.path.expanduser('~/.skyt'))
    return os.path.join(state_dir, 'fake_k8s.json')


class _FakeStore:
    def __init__(self) -> None:
        self._path = _fake_store_path()
        os.makedirs(os.path.dirname(self._path), exist_ok=True)
        self._lock = filelock.FileLock(self._path + '.lock')

    def __enter__(self) -> Dict[str, Any]:
        self._lock.acquire()
        if os.path.exists(self._path):
            with open(self._path, encoding='utf-8') as f:
                self._data = json.load(f)
        else:
            self._data = {'pods': {}, 'services': {}, 'faults': {}}
        return self._data

    def __exit__(self, exc_type, *args) -> None:
        # release() in a finally: a failed flush must not keep the
        # file lock held forever for every other process.
        try:
            if exc_type is None:
                tmp = self._path + '.tmp'
                with open(tmp, 'w', encoding='utf-8') as f:
                    json.dump(self._data, f)
                os.replace(tmp, self._path)
        finally:
            self._lock.release()


def fake_inject_unschedulable(selector_value: str, count: int = -1) -> None:
    """Pods whose gke-tpu-accelerator selector equals `selector_value`
    stay Pending/Unschedulable (capacity fault; -1 = always)."""
    with _FakeStore() as data:
        data['faults'].setdefault('unschedulable', {})[selector_value] = count


def fake_reset() -> None:
    path = _fake_store_path()
    if os.path.exists(path):
        os.remove(path)


class FakeKubernetesApi(KubernetesApi):
    """In-process apiserver: pods schedule instantly (or fault)."""

    def create_pod(self, namespace, manifest):
        with _FakeStore() as data:
            name = manifest['metadata']['name']
            key = f'{namespace}/{name}'
            if key in data['pods']:
                raise exceptions.ProvisionError(
                    f'k8s API POST pods: HTTP 409: pod {name} exists')
            accel = manifest['spec'].get('nodeSelector', {}).get(
                'cloud.google.com/gke-tpu-accelerator', '')
            faults = data['faults'].get('unschedulable', {})
            unschedulable = False
            if accel in faults and faults[accel] != 0:
                if faults[accel] > 0:
                    faults[accel] -= 1
                unschedulable = True
            pod = dict(manifest)
            pod['status'] = (
                {'phase': 'Pending',
                 'conditions': [{'type': 'PodScheduled',
                                 'status': 'False',
                                 'reason': 'Unschedulable'}]}
                if unschedulable else
                {'phase': 'Running',
                 'podIP': f'10.42.{len(data["pods"]) % 250}.'
                          f'{uuid.uuid4().int % 250 + 2}'})
            data['pods'][key] = pod

    def create_service(self, namespace, manifest):
        with _FakeStore() as data:
            key = f'{namespace}/{manifest["metadata"]["name"]}'
            data['services'][key] = manifest

    def list_pods(self, namespace, label_selector):
        want = dict(part.split('=', 1)
                    for part in label_selector.split(',') if part)
        with _FakeStore() as data:
            out = []
            for key, pod in data['pods'].items():
                if not key.startswith(f'{namespace}/'):
                    continue
                labels = pod['metadata'].get('labels', {})
                if all(labels.get(k) == v for k, v in want.items()):
                    out.append(pod)
            return out

    def delete_pod(self, namespace, name):
        with _FakeStore() as data:
            data['pods'].pop(f'{namespace}/{name}', None)

    def delete_service(self, namespace, name):
        with _FakeStore() as data:
            data['services'].pop(f'{namespace}/{name}', None)

    def create_pvc(self, namespace, manifest):
        with _FakeStore() as data:
            key = f'{namespace}/{manifest["metadata"]["name"]}'
            pvc = dict(manifest)
            pvc['status'] = {'phase': 'Bound'}
            data.setdefault('pvcs', {})[key] = pvc

    def delete_pvc(self, namespace, name):
        with _FakeStore() as data:
            data.setdefault('pvcs', {}).pop(f'{namespace}/{name}', None)


def fake_preempt_pod(namespace: str, name: str) -> None:
    """Spot reclaim: the pod vanishes (GKE deletes preempted pods)."""
    with _FakeStore() as data:
        data['pods'].pop(f'{namespace}/{name}', None)


# ---------------------------------------------------------------------------
# Provider
# ---------------------------------------------------------------------------


@CLOUD_REGISTRY.register('kubernetes', aliases=['k8s'])
class KubernetesProvider(Provider):
    """Pods-as-hosts provider over a pluggable apiserver transport."""

    name = 'kubernetes'

    @classmethod
    def unsupported_features(cls):
        from skypilot_tpu.provision.api import CloudCapability
        return {
            CloudCapability.STOP:
                'Kubernetes pods cannot be stopped; use down (terminate). '
                '(Same stance as the reference: no k8s stop support.)',
        }

    def __init__(self, api: Optional[KubernetesApi] = None,
                 namespace: Optional[str] = None) -> None:
        if api is not None:
            self.api: KubernetesApi = api
        elif env_registry.get_bool('SKYT_K8S_FAKE'):
            self.api = FakeKubernetesApi()
        else:
            self.api = RestKubernetesApi()
        from skypilot_tpu import config
        self.namespace = (namespace or
                          config.get_nested(('kubernetes', 'namespace'),
                                            'default'))

    def _selector(self, cluster_name: str) -> str:
        return f'{LABEL_CLUSTER}={cluster_name}'

    # -- volumes (PVCs; parity: sky/provision/kubernetes/volume.py) ----

    def create_volume(self, volume) -> Dict[str, Any]:
        manifest = {
            'apiVersion': 'v1',
            'kind': 'PersistentVolumeClaim',
            'metadata': {'name': volume.name, 'namespace': self.namespace,
                         'labels': {'skyt-volume': volume.name,
                                    **volume.labels}},
            'spec': {
                'accessModes': [volume.config.get('access_mode',
                                                  'ReadWriteOnce')],
                'resources': {
                    'requests': {'storage': f'{volume.size_gb}Gi'}},
                **({'storageClassName': volume.config['storage_class']}
                   if volume.config.get('storage_class') else {}),
            },
        }
        if not volume.use_existing:
            self.api.create_pvc(self.namespace, manifest)
        return {'pvc': volume.name, 'namespace': self.namespace}

    def delete_volume(self, record: Dict[str, Any]) -> None:
        self.api.delete_pvc(record['config'].get('namespace',
                                                 self.namespace),
                            record['config'].get('pvc', record['name']))

    def run_instances(self, request: ProvisionRequest) -> ClusterInfo:
        res = request.resources
        if res.is_tpu:
            hosts_per_node = res.tpu.hosts_per_slice * res.tpu.num_slices
        else:
            hosts_per_node = 1
        self.api.create_service(
            self.namespace,
            build_headless_service(request.cluster_name, self.namespace))
        created = []
        try:
            for node in range(request.num_nodes):
                for worker in range(hosts_per_node):
                    manifest = build_pod_manifest(request, node, worker,
                                                  self.namespace)
                    self.api.create_pod(self.namespace, manifest)
                    created.append(manifest['metadata']['name'])
            return self._wait_pods_running(request)
        except exceptions.ProvisionError:
            # All-or-nothing gang semantics: roll back partial pods so
            # failover retries cleanly elsewhere.
            self._cleanup(request.cluster_name)
            raise

    def _wait_pods_running(self,
                           request: ProvisionRequest) -> ClusterInfo:
        timeout = _provision_timeout()
        deadline = time.monotonic() + timeout
        selector = self._selector(request.cluster_name)
        while True:
            pods = self.api.list_pods(self.namespace, selector)
            phases = [p.get('status', {}).get('phase') for p in pods]
            if pods and all(ph == 'Running' for ph in phases):
                return self._to_cluster_info(request.cluster_name, pods)
            for pod in pods:
                for cond in pod.get('status', {}).get('conditions', []):
                    if cond.get('reason') == 'Unschedulable':
                        if time.monotonic() > deadline:
                            self._cleanup(request.cluster_name)
                            raise exceptions.CapacityError(
                                f'{request.cluster_name}: TPU pods '
                                'unschedulable (no node pool capacity '
                                f'for {pod["spec"].get("nodeSelector")})')
            if time.monotonic() > deadline:
                self._cleanup(request.cluster_name)
                raise exceptions.ProvisionError(
                    f'{request.cluster_name}: pods not Running after '
                    f'{timeout:.0f}s (phases: {phases})')
            time.sleep(min(2.0, timeout / 10))

    def _to_cluster_info(self, cluster_name: str,
                         pods: List[Dict[str, Any]]) -> ClusterInfo:
        hosts = []
        for pod in pods:
            labels = pod['metadata']['labels']
            hosts.append(HostInfo(
                instance_id=pod['metadata']['name'],
                internal_ip=pod.get('status', {}).get('podIP', ''),
                external_ip=None,
                node_index=int(labels.get(LABEL_NODE, 0)),
                worker_index=int(labels.get(LABEL_WORKER, 0)),
            ))
        hosts.sort(key=lambda h: (h.node_index, h.worker_index))
        return ClusterInfo(
            cluster_name=cluster_name, provider='kubernetes',
            region=self.namespace, zone=None, hosts=hosts,
            ssh_user='root',
            custom={'kubernetes': True, 'namespace': self.namespace,
                    'fake': isinstance(self.api, FakeKubernetesApi)})

    def _cleanup(self, cluster_name: str) -> None:
        for pod in self.api.list_pods(self.namespace,
                                      self._selector(cluster_name)):
            self.api.delete_pod(self.namespace, pod['metadata']['name'])
        self.api.delete_service(self.namespace, cluster_name)

    def stop_instances(self, cluster_name: str) -> None:
        raise exceptions.NotSupportedError(
            'Kubernetes pods cannot be stopped; use down (terminate). '
            '(Same stance as the reference: no k8s stop support.)')

    def terminate_instances(self, cluster_name: str) -> None:
        self._cleanup(cluster_name)

    def query_instances(self, cluster_name: str) -> Dict[str, str]:
        phase_map = {'Running': 'running', 'Pending': 'pending',
                     'Succeeded': 'terminated', 'Failed': 'terminated',
                     'Unknown': 'unknown'}
        out = {}
        for pod in self.api.list_pods(self.namespace,
                                      self._selector(cluster_name)):
            phase = pod.get('status', {}).get('phase', 'Unknown')
            out[pod['metadata']['name']] = phase_map.get(phase, 'unknown')
        return out

    def get_cluster_info(self, cluster_name: str) -> Optional[ClusterInfo]:
        pods = self.api.list_pods(self.namespace,
                                  self._selector(cluster_name))
        running = [p for p in pods
                   if p.get('status', {}).get('phase') == 'Running']
        if not running:
            return None
        return self._to_cluster_info(cluster_name, running)

    def open_ports(self, cluster_name: str, ports: List[str]) -> None:
        # Pod-network reachability is cluster-internal; LoadBalancer/
        # Ingress wiring is the serve layer's concern.
        pass
