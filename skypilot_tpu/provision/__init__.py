"""Provision layer: uniform per-cloud driver interface.

Parity: ``sky/provision/__init__.py:147`` (name-routed dispatch; ops at
:193-457). Providers register in CLOUD_REGISTRY; the failover provisioner
(`provisioner.py`) sits above and implements zone->region retry with error
classification, the TPU flavor of ``RetryingVmProvisioner``
(cloud_vm_ray_backend.py:789).
"""
from skypilot_tpu.provision.api import (ClusterInfo, HostInfo,
                                        ProvisionRequest, Provider,
                                        get_provider)

# Import for registry side effects.
from skypilot_tpu.provision import fake as _fake  # noqa: F401
from skypilot_tpu.provision import local as _local  # noqa: F401
from skypilot_tpu.provision import gcp as _gcp  # noqa: F401
from skypilot_tpu.provision import aws as _aws  # noqa: F401
from skypilot_tpu.provision import azure as _azure  # noqa: F401
from skypilot_tpu.provision import oci as _oci  # noqa: F401
from skypilot_tpu.provision import kubernetes as _kubernetes  # noqa: F401
from skypilot_tpu.provision import ssh_pool as _ssh_pool  # noqa: F401
from skypilot_tpu.provision import slurm as _slurm  # noqa: F401

__all__ = ['ClusterInfo', 'HostInfo', 'ProvisionRequest', 'Provider',
           'get_provider']
