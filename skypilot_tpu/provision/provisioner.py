"""Failover provisioner: ordered candidates -> zone/region retry loop.

Parity: ``RetryingVmProvisioner`` (cloud_vm_ray_backend.py:789;
`_yield_zones` :842, `_retry_zones` :1003 -- the HOT RETRY LOOP in
SURVEY.md section 3.1) + the error classify-and-blocklist handlers
(:395/:522). TPU flavor: the unit of atomicity is a whole pod slice, and
queued-resource timeouts count as capacity errors.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional, Set, Tuple

from skypilot_tpu import exceptions, state
from skypilot_tpu.optimizer import Candidate
from skypilot_tpu.provision.api import (ClusterInfo, ProvisionRequest,
                                        get_provider)
from skypilot_tpu.utils import log

logger = log.init_logger(__name__)


@dataclasses.dataclass
class Blocklist:
    """Locations proven infeasible during this provisioning round."""
    zones: Set[Tuple[str, str]] = dataclasses.field(default_factory=set)
    regions: Set[Tuple[str, str]] = dataclasses.field(default_factory=set)
    clouds: Set[str] = dataclasses.field(default_factory=set)

    def blocks(self, candidate: Candidate) -> bool:
        res = candidate.resources
        if res.cloud in self.clouds:
            return True
        if (res.cloud, res.region) in self.regions:
            return True
        if res.zone is not None and (res.cloud, res.zone) in self.zones:
            return True
        return False

    def add_for(self, candidate: Candidate,
                error: exceptions.ProvisionError) -> None:
        res = candidate.resources
        if isinstance(error, exceptions.QuotaExceededError):
            self.regions.add((res.cloud, res.region))
        elif isinstance(error, exceptions.CapacityError):
            if res.zone is not None:
                self.zones.add((res.cloud, res.zone))
            else:
                self.regions.add((res.cloud, res.region))
        elif isinstance(error, exceptions.NoCloudAccessError):
            self.clouds.add(res.cloud)
        else:
            # Unclassified: be conservative, skip the zone only.
            if res.zone is not None:
                self.zones.add((res.cloud, res.zone))
            else:
                self.regions.add((res.cloud, res.region))


def provision_with_failover(
        cluster_name: str,
        candidates: List[Candidate],
        num_nodes: int,
        *,
        resume: bool = False,
        blocklist: Optional[Blocklist] = None,
        volumes: Optional[List[Dict]] = None,
) -> Tuple[ClusterInfo, Candidate]:
    """Try candidates in (cost) order until one provisions.

    Returns (cluster info, the candidate that succeeded). Raises
    ResourcesUnavailableError with per-location history when all fail.
    """
    blocklist = blocklist or Blocklist()
    history: List[Exception] = []
    attempted = 0
    for candidate in candidates:
        if blocklist.blocks(candidate):
            continue
        res = candidate.resources
        res.assert_launchable()
        provider = get_provider(res.cloud)
        request = ProvisionRequest(
            cluster_name=cluster_name,
            resources=res,
            num_nodes=num_nodes,
            region=res.region,
            zone=res.zone,
            resume=resume,
            ports=res.ports,
            labels=res.labels,
            volumes=list(volumes or []),
        )
        attempted += 1
        where = f'{res.cloud}/{res.region}' + (f'/{res.zone}' if res.zone
                                               else '')
        logger.info('Provisioning %s on %s (%s)...', cluster_name, where,
                    res)
        state.add_cluster_event(cluster_name, 'PROVISION_ATTEMPT', where)
        attempt_start = time.monotonic()
        try:
            info = provider.run_instances(request)
            provider.wait_instances(cluster_name, 'running')
            state.add_cluster_event(cluster_name, 'PROVISION_OK', where)
            # Durable latency sample: /api/metrics builds the
            # skyt_provision_seconds histogram (the BASELINE p50
            # orchestration metric) from these events.
            state.add_cluster_event(cluster_name, 'PROVISION_DONE',
                                    f'{time.monotonic() - attempt_start:.3f}')
            return info, candidate
        except exceptions.ProvisionError as e:
            logger.warning('Provision failed on %s: %s', where, e)
            state.add_cluster_event(cluster_name, 'PROVISION_FAIL',
                                    f'{where}: {e}')
            history.append(e)
            blocklist.add_for(candidate, e)
            # Best-effort cleanup of partial creations.
            try:
                provider.terminate_instances(cluster_name)
            except Exception:  # pylint: disable=broad-except
                pass
        except exceptions.NoCloudAccessError as e:
            history.append(e)
            blocklist.clouds.add(res.cloud)
    tried = f'{attempted} locations tried' if attempted else (
        'all candidate locations blocklisted')
    raise exceptions.ResourcesUnavailableError(
        f'Failed to provision {cluster_name!r}: {tried}. '
        f'History: {[str(e) for e in history]}',
        failover_history=history)
