"""Local provider: 'provisions' this machine.

The end-to-end execution path (sync -> setup -> rank launch -> logs ->
queue) runs for real against localhost processes -- no cloud, no SSH. This
is the rebuild's always-available provider for dev and integration tests
(the reference gets the same effect from kind/existing clusters).
"""
from __future__ import annotations

import json
import os
import time
from typing import Dict, Optional

from skypilot_tpu.provision.api import (ClusterInfo, HostInfo,
                                        ProvisionRequest, Provider)
from skypilot_tpu.utils.registry import CLOUD_REGISTRY


def _store_path() -> str:
    state_dir = os.environ.get('SKYT_STATE_DIR',
                               os.path.expanduser('~/.skyt'))
    return os.path.join(state_dir, 'local_clusters.json')


def _load() -> Dict:
    path = _store_path()
    if os.path.exists(path):
        with open(path, encoding='utf-8') as f:
            return json.load(f)
    return {}


def _save(data: Dict) -> None:
    path = _store_path()
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = path + '.tmp'
    with open(tmp, 'w', encoding='utf-8') as f:
        json.dump(data, f)
    os.replace(tmp, path)


@CLOUD_REGISTRY.register('local')
class LocalProvider(Provider):
    """One 'host' per node, all localhost; commands run as subprocesses."""

    name = 'local'

    @classmethod
    def unsupported_features(cls):
        from skypilot_tpu.provision.api import CloudCapability
        return {
            CloudCapability.SPOT: 'localhost is never preempted',
            CloudCapability.VOLUMES: 'no disk API on localhost; use '
                                     'plain paths',
        }

    def run_instances(self, request: ProvisionRequest) -> ClusterInfo:
        data = _load()
        hosts = []
        for node in range(request.num_nodes):
            hosts.append({
                'instance_id': f'local-{request.cluster_name}-{node}',
                'internal_ip': '127.0.0.1',
                'external_ip': '127.0.0.1',
                'node_index': node,
                'worker_index': 0,
                'state': 'running',
            })
        data[request.cluster_name] = {
            'state': 'running',
            'hosts': hosts,
            'created_at': time.time(),
            'resources': request.resources.to_yaml_config(),
        }
        _save(data)
        return self.get_cluster_info(request.cluster_name)

    def stop_instances(self, cluster_name: str) -> None:
        data = _load()
        if cluster_name in data:
            data[cluster_name]['state'] = 'stopped'
            for h in data[cluster_name]['hosts']:
                h['state'] = 'stopped'
            _save(data)

    def terminate_instances(self, cluster_name: str) -> None:
        data = _load()
        data.pop(cluster_name, None)
        _save(data)

    def query_instances(self, cluster_name: str) -> Dict[str, str]:
        data = _load()
        if cluster_name not in data:
            return {}
        return {h['instance_id']: h['state']
                for h in data[cluster_name]['hosts']}

    def get_cluster_info(self, cluster_name: str) -> Optional[ClusterInfo]:
        data = _load()
        record = data.get(cluster_name)
        if record is None or record['state'] != 'running':
            return None
        hosts = [
            HostInfo(instance_id=h['instance_id'],
                     internal_ip=h['internal_ip'],
                     external_ip=h['external_ip'],
                     node_index=h['node_index'],
                     worker_index=h['worker_index'])
            for h in record['hosts']
        ]
        return ClusterInfo(cluster_name=cluster_name, provider='local',
                           region='local', zone=None, hosts=hosts,
                           ssh_user=os.environ.get('USER', 'root'),
                           custom={'local': True})
