"""AWS EC2 provider: SigV4-signed Query API over stdlib HTTP.

Parity: ``sky/provision/aws/instance.py`` + ``sky/clouds/aws.py`` — the
reference's second-biggest driver, built there on boto3. Neither boto3
nor aws-cli is in this image, so the wire protocol is implemented
directly (same stance as the GCP driver's urllib REST and the S3
client's SigV4): the EC2 Query API is form-encoded POST + XML, and
SigV4 is the same ~40 lines of hmac the S3 client uses.

Cluster identity rides tags (``skyt-cluster``), instances are plain EC2
VMs (GPU shapes from ``catalog/aws_data.py``), the SSH keypair is
imported once per account, and a ``skyt-<cluster>`` security group
opens 22 (+ task ports via ``open_ports``). Network calls go through
``_request`` so tests stub the transport (tests/test_aws_provider.py,
mirroring the GCP fake).
"""
from __future__ import annotations

import datetime
import hashlib
import hmac
import os
import shutil
import subprocess
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Dict, List, Optional
from xml.etree import ElementTree

from skypilot_tpu import exceptions
from skypilot_tpu.provision.api import (ClusterInfo, CloudCapability,
                                        HostInfo, Provider,
                                        ProvisionRequest)
from skypilot_tpu.utils import log
from skypilot_tpu.utils.registry import CLOUD_REGISTRY

logger = log.init_logger(__name__)

EC2_API_VERSION = '2016-11-15'

# Error codes -> typed exceptions (parity: FailoverCloudErrorHandlerV2
# _aws_handler, cloud_vm_ray_backend.py).
_CAPACITY_CODES = ('InsufficientInstanceCapacity', 'InsufficientCapacity',
                   'SpotMaxPriceTooLow', 'InsufficientHostCapacity')
_QUOTA_CODES = ('InstanceLimitExceeded', 'VcpuLimitExceeded',
                'MaxSpotInstanceCountExceeded', 'RequestLimitExceeded')
_AUTH_CODES = ('AuthFailure', 'UnauthorizedOperation',
               'InvalidClientTokenId', 'SignatureDoesNotMatch')


def classify_aws_error(code: str, message: str) -> exceptions.ProvisionError:
    if code in _QUOTA_CODES:
        return exceptions.QuotaExceededError(f'{code}: {message}')
    if code in _CAPACITY_CODES:
        return exceptions.CapacityError(f'{code}: {message}')
    if code in _AUTH_CODES:
        return exceptions.NoCloudAccessError(f'{code}: {message}')
    return exceptions.ProvisionError(f'{code}: {message}')


def _credentials() -> tuple:
    key = os.environ.get('AWS_ACCESS_KEY_ID')
    secret = os.environ.get('AWS_SECRET_ACCESS_KEY')
    if not key or not secret:
        from skypilot_tpu import config as config_lib
        key = key or config_lib.get_nested(('aws', 'access_key_id'), None)
        secret = secret or config_lib.get_nested(
            ('aws', 'secret_access_key'), None)
    if not key or not secret:
        raise exceptions.NoCloudAccessError(
            'AWS credentials not found: set AWS_ACCESS_KEY_ID/'
            'AWS_SECRET_ACCESS_KEY or aws.access_key_id/'
            'secret_access_key in config')
    return key, secret


def ssh_key_path() -> str:
    state_dir = os.environ.get('SKYT_STATE_DIR',
                               os.path.expanduser('~/.skyt'))
    return os.path.join(state_dir, 'keys', 'aws', 'skyt-aws-key')


def ensure_ssh_keypair() -> tuple:
    """(private_key_path, public_key_text); generated once per install."""
    key_path = ssh_key_path()
    pub_path = key_path + '.pub'
    if not os.path.exists(key_path):
        os.makedirs(os.path.dirname(key_path), exist_ok=True)
        if not shutil.which('ssh-keygen'):
            raise exceptions.ProvisionError(
                'ssh-keygen not available; cannot generate the AWS '
                'cluster SSH keypair')
        subprocess.run(
            ['ssh-keygen', '-t', 'ed25519', '-N', '', '-q',
             '-C', 'skyt-aws', '-f', key_path], check=True)
    with open(pub_path, encoding='utf-8') as f:
        return key_path, f.read().strip()


def _sign(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def _flatten_params(params: Dict[str, Any]) -> Dict[str, str]:
    """Nested dicts/lists -> the Query API's dotted/indexed flat keys."""
    flat: Dict[str, str] = {}

    def put(prefix: str, value: Any) -> None:
        if isinstance(value, dict):
            for k, v in value.items():
                put(f'{prefix}.{k}' if prefix else k, v)
        elif isinstance(value, (list, tuple)):
            for i, v in enumerate(value, start=1):
                put(f'{prefix}.{i}', v)
        elif isinstance(value, bool):
            flat[prefix] = 'true' if value else 'false'
        else:
            flat[prefix] = str(value)

    put('', params)
    return flat


class _Xml:
    """Namespace-insensitive helpers over the EC2 response XML."""

    @staticmethod
    def strip(tag: str) -> str:
        return tag.split('}', 1)[1] if '}' in tag else tag

    @classmethod
    def find_all(cls, node, name: str) -> List[Any]:
        return [c for c in node.iter() if cls.strip(c.tag) == name]

    @classmethod
    def child_text(cls, node, name: str) -> Optional[str]:
        for child in node:
            if cls.strip(child.tag) == name:
                return child.text
        return None


@CLOUD_REGISTRY.register('aws')
class AwsProvider(Provider):
    """Plain-EC2 clusters; every host is one instance."""

    name = 'aws'

    @classmethod
    def unsupported_features(cls) -> Dict[CloudCapability, str]:
        return {
            CloudCapability.VOLUMES:
                'EBS volume provisioning is not wired up yet',
        }

    # -- transport (stubbed in tests) ----------------------------------

    def _request(self, action: str, params: Dict[str, Any],
                 region: str) -> ElementTree.Element:
        """One signed EC2 Query API call; returns the parsed XML root."""
        key_id, secret = _credentials()
        host = f'ec2.{region}.amazonaws.com'
        flat = dict(_flatten_params(params))
        flat['Action'] = action
        flat['Version'] = EC2_API_VERSION
        body = urllib.parse.urlencode(sorted(flat.items())).encode()
        now = datetime.datetime.now(datetime.timezone.utc)
        amz_date = now.strftime('%Y%m%dT%H%M%SZ')
        datestamp = now.strftime('%Y%m%d')
        payload_hash = hashlib.sha256(body).hexdigest()
        headers = {
            'content-type': 'application/x-www-form-urlencoded',
            'host': host,
            'x-amz-date': amz_date,
        }
        signed_headers = ';'.join(sorted(headers))
        canonical_headers = ''.join(
            f'{k}:{headers[k]}\n' for k in sorted(headers))
        canonical_request = '\n'.join(
            ['POST', '/', '', canonical_headers, signed_headers,
             payload_hash])
        scope = f'{datestamp}/{region}/ec2/aws4_request'
        string_to_sign = '\n'.join([
            'AWS4-HMAC-SHA256', amz_date, scope,
            hashlib.sha256(canonical_request.encode()).hexdigest()])
        k_date = _sign(f'AWS4{secret}'.encode(), datestamp)
        k_region = _sign(k_date, region)
        k_service = _sign(k_region, 'ec2')
        k_signing = _sign(k_service, 'aws4_request')
        signature = hmac.new(k_signing, string_to_sign.encode(),
                             hashlib.sha256).hexdigest()
        headers['Authorization'] = (
            f'AWS4-HMAC-SHA256 Credential={key_id}/{scope}, '
            f'SignedHeaders={signed_headers}, Signature={signature}')
        req = urllib.request.Request(f'https://{host}/', data=body,
                                     headers=headers, method='POST')
        try:
            with urllib.request.urlopen(req, timeout=60) as resp:
                return ElementTree.fromstring(resp.read())
        except urllib.error.HTTPError as e:
            text = e.read().decode('utf-8', errors='replace')
            try:
                root = ElementTree.fromstring(text)
                err = _Xml_first_error(root)
            except ElementTree.ParseError:
                err = (str(e.code), text[:300])
            raise classify_aws_error(*err) from None
        except exceptions.ProvisionError:
            raise
        except Exception as e:  # pylint: disable=broad-except
            # URLError / socket timeouts / parse failures: typed, so
            # failover and cleanup paths keyed on ProvisionError work.
            raise exceptions.ProvisionError(
                f'EC2 {action} in {region} failed: {e}') from e

    # -- identity tags -------------------------------------------------

    @staticmethod
    def _cluster_filter(cluster_name: str) -> Dict[str, Any]:
        return {'Filter': [
            {'Name': 'tag:skyt-cluster', 'Value': [cluster_name]},
            {'Name': 'instance-state-name',
             'Value': ['pending', 'running', 'stopping', 'stopped']},
        ]}

    def _describe(self, cluster_name: str, region: str,
                  include_terminated: bool = False) -> List[Dict[str, Any]]:
        params = self._cluster_filter(cluster_name)
        if include_terminated:
            params['Filter'][1]['Value'].extend(
                ['shutting-down', 'terminated'])
        root = self._request('DescribeInstances', params, region)
        out = []
        for inst in _Xml.find_all(root, 'instancesSet'):
            for item in inst:
                if _Xml.strip(item.tag) != 'item':
                    continue
                tags = {}
                for tag_item in _Xml.find_all(item, 'tagSet'):
                    for t in tag_item:
                        k = _Xml.child_text(t, 'key')
                        v = _Xml.child_text(t, 'value')
                        if k:
                            tags[k] = v or ''
                state_el = next(iter(_Xml.find_all(item, 'instanceState')),
                                None)
                out.append({
                    'instance_id': _Xml.child_text(item, 'instanceId'),
                    'state': (_Xml.child_text(state_el, 'name')
                              if state_el is not None else 'unknown'),
                    'private_ip': _Xml.child_text(item,
                                                  'privateIpAddress'),
                    'public_ip': _Xml.child_text(item, 'ipAddress'),
                    'zone': next(
                        (_Xml.child_text(p, 'availabilityZone')
                         for p in _Xml.find_all(item, 'placement')), None),
                    'tags': tags,
                })
        out.sort(key=lambda i: int(i['tags'].get('skyt-node', 0)))
        return out

    def _region_of(self, cluster_name: str) -> Optional[str]:
        from skypilot_tpu import state
        record = state.get_cluster(cluster_name)
        if record and record.handle.get('provider') == 'aws':
            return record.handle.get('region')
        return None

    # -- security group / keypair --------------------------------------

    def _ensure_keypair(self, region: str) -> str:
        _, pub = ensure_ssh_keypair()
        # Key NAME embeds the pubkey digest: a regenerated local key
        # gets a fresh EC2 keypair instead of silently diverging from
        # an old upload with the same name (unreachable instances).
        digest = hashlib.sha256(pub.encode()).hexdigest()[:12]
        name = f'skyt-aws-key-{digest}'
        root = self._request('DescribeKeyPairs', {}, region)
        existing = {_Xml.child_text(i, 'keyName')
                    for i in _Xml.find_all(root, 'item')}
        if name not in existing:
            import base64
            self._request('ImportKeyPair', {
                'KeyName': name,
                'PublicKeyMaterial':
                    base64.b64encode(pub.encode()).decode(),
            }, region)
        return name

    def _ensure_security_group(self, cluster_name: str,
                               region: str) -> str:
        name = f'skyt-{cluster_name}'
        root = self._request('DescribeSecurityGroups', {'Filter': [
            {'Name': 'group-name', 'Value': [name]}]}, region)
        for item in _Xml.find_all(root, 'item'):
            gid = _Xml.child_text(item, 'groupId')
            if gid and _Xml.child_text(item, 'groupName') == name:
                return gid
        created = self._request('CreateSecurityGroup', {
            'GroupName': name,
            'GroupDescription': f'skyt cluster {cluster_name}',
        }, region)
        gid = next((e.text for e in created.iter()
                    if _Xml.strip(e.tag) == 'groupId'), name)
        self._authorize_ingress(gid, ['22'], region)
        return gid

    def _authorize_ingress(self, group_id: str, ports: List[str],
                           region: str) -> None:
        perms = []
        for port in ports:
            lo, _, hi = str(port).partition('-')
            perms.append({
                'IpProtocol': 'tcp',
                'FromPort': int(lo),
                'ToPort': int(hi or lo),
                'IpRanges': [{'CidrIp': '0.0.0.0/0'}],
            })
        try:
            self._request('AuthorizeSecurityGroupIngress', {
                'GroupId': group_id, 'IpPermissions': perms}, region)
        except exceptions.ProvisionError as e:
            if 'InvalidPermission.Duplicate' not in str(e):
                raise

    # -- instance selection --------------------------------------------

    @staticmethod
    def _instance_type(resources) -> str:
        from skypilot_tpu.catalog import aws_data
        if resources.instance_type:
            return resources.instance_type
        accels = resources.accelerators
        if accels:
            (name, count), = accels.items()
            picked = aws_data.instance_type_for(name, count)
            if picked is None:
                raise exceptions.ProvisionError(
                    f'no AWS instance shape for {count}x {name}; known: '
                    f'{sorted(aws_data.GPU_INSTANCE_TYPES)}')
            return picked[0]
        from skypilot_tpu.catalog.common import pick_cpu_instance_type
        cpus = resources.cpus[0] if resources.cpus else None
        mem = resources.memory[0] if resources.memory else None
        # Raises ResourcesUnavailableError when nothing satisfies the
        # request — never silently under-provisions.
        return pick_cpu_instance_type(cpus, mem, cloud='aws')

    @staticmethod
    def _image_id(resources) -> str:
        from skypilot_tpu import config as config_lib
        from skypilot_tpu.catalog import aws_data
        return (resources.image_id or
                config_lib.get_nested(('aws', 'ami_id'), None) or
                aws_data.DEFAULT_AMI_SSM)

    # -- Provider API --------------------------------------------------

    def run_instances(self, request: ProvisionRequest) -> ClusterInfo:
        region = request.region
        existing = self._describe(request.cluster_name, region)
        if request.resume and existing:
            stopped = [i['instance_id'] for i in existing
                       if i['state'] == 'stopped']
            if stopped:
                self._request('StartInstances',
                              {'InstanceId': stopped}, region)
            return self._cluster_info(request.cluster_name, region)
        if existing:
            raise exceptions.ProvisionError(
                f'cluster {request.cluster_name} already has instances '
                f'in {region}; use resume or terminate first')
        key_name = self._ensure_keypair(region)
        group_id = self._ensure_security_group(request.cluster_name,
                                               region)
        if request.ports:
            self._authorize_ingress(group_id, request.ports, region)
        params: Dict[str, Any] = {
            'ImageId': self._image_id(request.resources),
            'InstanceType': self._instance_type(request.resources),
            'MinCount': request.num_nodes,
            'MaxCount': request.num_nodes,
            'KeyName': key_name,
            'SecurityGroupId': [group_id],
            'TagSpecification': [{
                'ResourceType': 'instance',
                'Tag': [{'Key': 'skyt-cluster',
                         'Value': request.cluster_name},
                        {'Key': 'Name',
                         'Value': request.cluster_name}] +
                       [{'Key': k, 'Value': v}
                        for k, v in request.labels.items()],
            }],
        }
        if request.zone:
            params['Placement'] = {'AvailabilityZone': request.zone}
        if request.resources.use_spot:
            params['InstanceMarketOptions'] = {'MarketType': 'spot'}
        root = self._request('RunInstances', params, region)
        ids = [_Xml.child_text(i, 'instanceId')
               for i in _Xml.find_all(root, 'item')
               if _Xml.child_text(i, 'instanceId')]
        # Per-node rank tags (instance order within the reservation is
        # the node order).
        for idx, iid in enumerate(ids):
            self._request('CreateTags', {
                'ResourceId': [iid],
                'Tag': [{'Key': 'skyt-node', 'Value': str(idx)}],
            }, region)
        logger.info('AWS: launched %d x %s in %s for %s', len(ids),
                    params['InstanceType'], region, request.cluster_name)
        return self._cluster_info(request.cluster_name, region)

    def _cluster_info(self, cluster_name: str, region: str) -> ClusterInfo:
        instances = self._describe(cluster_name, region)
        hosts = [
            HostInfo(
                instance_id=i['instance_id'],
                internal_ip=i['private_ip'] or '',
                external_ip=i['public_ip'],
                node_index=int(i['tags'].get('skyt-node', idx)),
                worker_index=0,
                tags=i['tags'],
            ) for idx, i in enumerate(instances)
        ]
        from skypilot_tpu import config as config_lib
        return ClusterInfo(
            cluster_name=cluster_name,
            provider='aws',
            region=region,
            zone=instances[0]['zone'] if instances else None,
            hosts=hosts,
            ssh_user=config_lib.get_nested(('aws', 'ssh_user'), 'ubuntu'),
            ssh_key_path=ssh_key_path(),
        )

    def get_cluster_info(self, cluster_name: str) -> Optional[ClusterInfo]:
        region = self._region_of(cluster_name)
        if region is None:
            return None
        info = self._cluster_info(cluster_name, region)
        return info if info.hosts else None

    def query_instances(self, cluster_name: str) -> Dict[str, str]:
        region = self._region_of(cluster_name)
        if region is None:
            return {}
        state_map = {
            'pending': 'starting', 'running': 'running',
            'stopping': 'stopping', 'stopped': 'stopped',
            'shutting-down': 'terminating', 'terminated': 'terminated',
        }
        return {
            i['instance_id']: state_map.get(i['state'], i['state'])
            for i in self._describe(cluster_name, region,
                                    include_terminated=True)
        }

    def _instance_ids(self, cluster_name: str, region: str) -> List[str]:
        return [i['instance_id']
                for i in self._describe(cluster_name, region)]

    def stop_instances(self, cluster_name: str) -> None:
        region = self._region_of(cluster_name)
        if region is None:
            return
        ids = self._instance_ids(cluster_name, region)
        if ids:
            self._request('StopInstances', {'InstanceId': ids}, region)

    def terminate_instances(self, cluster_name: str) -> None:
        region = self._region_of(cluster_name)
        if region is None:
            return
        ids = self._instance_ids(cluster_name, region)
        if ids:
            self._request('TerminateInstances', {'InstanceId': ids},
                          region)
        try:
            root = self._request('DescribeSecurityGroups', {'Filter': [
                {'Name': 'group-name',
                 'Value': [f'skyt-{cluster_name}']}]}, region)
            for item in _Xml.find_all(root, 'item'):
                gid = _Xml.child_text(item, 'groupId')
                if gid:
                    self._request('DeleteSecurityGroup',
                                  {'GroupId': gid}, region)
        except exceptions.ProvisionError as e:
            # Group deletion races instance shutdown; leave it for the
            # next terminate (parity: the reference retries SG cleanup).
            logger.debug('SG cleanup deferred: %s', e)

    def open_ports(self, cluster_name: str, ports: List[str]) -> None:
        region = self._region_of(cluster_name)
        if region is None:
            return
        gid = self._ensure_security_group(cluster_name, region)
        self._authorize_ingress(gid, ports, region)


def _Xml_first_error(root) -> tuple:
    code = msg = None
    for el in root.iter():
        tag = _Xml.strip(el.tag)
        if tag == 'Code' and code is None:
            code = el.text
        elif tag == 'Message' and msg is None:
            msg = el.text
    return code or 'Unknown', msg or 'unknown AWS error'
