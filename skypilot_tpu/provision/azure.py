"""Azure compute provider: ARM REST (JSON) over stdlib HTTP.

Parity: ``sky/provision/azure/instance.py`` + ``sky/clouds/azure.py`` —
the reference's third compute cloud, built there on the azure-sdk
adaptors. The SDK isn't in this image, so the ARM wire protocol is
implemented directly (same stance as the GCP urllib REST and AWS Query
API drivers): OAuth2 client-credentials tokens against
login.microsoftonline.com, then JSON PUT/GET/POST/DELETE against
``management.azure.com``.

Deployment model (deliberately simpler than the reference's per-cluster
ARM template): ONE resource group per cluster (``skyt-<cluster>``)
holding the vnet/NSG/NICs/public-IPs/VMs — terminate is a single RG
delete, the idiomatic-Azure equivalent of label-filtered teardown.
Cluster identity additionally rides ``skyt-cluster``/``skyt-node`` tags
on each VM. Network calls go through ``_request`` so tests stub the
transport (tests/test_azure_provider.py, mirroring the GCP/AWS fakes).
"""
from __future__ import annotations

import json
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.provision.api import (ClusterInfo, CloudCapability,
                                        HostInfo, Provider,
                                        ProvisionRequest)
from skypilot_tpu.utils import log
from skypilot_tpu.utils.registry import CLOUD_REGISTRY

logger = log.init_logger(__name__)

ARM = 'https://management.azure.com'
COMPUTE_API = '2024-07-01'
NETWORK_API = '2024-05-01'
RESOURCE_API = '2022-09-01'

SSH_USER = 'skyt'

# ARM error codes -> typed exceptions (parity:
# FailoverCloudErrorHandlerV2._azure_handler).
_CAPACITY_CODES = ('SkuNotAvailable', 'AllocationFailed',
                   'ZonalAllocationFailed', 'OverconstrainedAllocationRequest',
                   'SpotAllocationFailed')
_QUOTA_CODES = ('QuotaExceeded', 'OperationNotAllowed')
_AUTH_CODES = ('AuthorizationFailed', 'InvalidAuthenticationToken',
               'AuthenticationFailed', 'InvalidClientSecret')


def classify_azure_error(code: str, message: str) -> exceptions.ProvisionError:
    if code in _QUOTA_CODES:
        return exceptions.QuotaExceededError(f'{code}: {message}')
    if code in _CAPACITY_CODES:
        return exceptions.CapacityError(f'{code}: {message}')
    if code in _AUTH_CODES:
        return exceptions.NoCloudAccessError(f'{code}: {message}')
    return exceptions.ProvisionError(f'{code}: {message}')


def _setting(env: str, config_key: str) -> Optional[str]:
    import os
    value = os.environ.get(env)
    if value:
        return value
    from skypilot_tpu import config as config_lib
    return config_lib.get_nested(('azure', config_key), None)


def credentials() -> Dict[str, str]:
    creds = {
        'subscription': _setting('AZURE_SUBSCRIPTION_ID',
                                 'subscription_id'),
        'tenant': _setting('AZURE_TENANT_ID', 'tenant_id'),
        'client': _setting('AZURE_CLIENT_ID', 'client_id'),
        'secret': _setting('AZURE_CLIENT_SECRET', 'client_secret'),
    }
    missing = [k for k, v in creds.items() if not v]
    if missing:
        raise exceptions.NoCloudAccessError(
            f'Azure credentials incomplete (missing {missing}): set '
            'AZURE_SUBSCRIPTION_ID/AZURE_TENANT_ID/AZURE_CLIENT_ID/'
            'AZURE_CLIENT_SECRET or azure.* in config')
    return creds


def ssh_key_path() -> str:
    import os
    state_dir = os.environ.get('SKYT_STATE_DIR',
                               os.path.expanduser('~/.skyt'))
    return os.path.join(state_dir, 'keys', 'azure', 'skyt-azure-key')


def ensure_ssh_keypair() -> tuple:
    import os
    import shutil
    import subprocess
    key_path = ssh_key_path()
    pub_path = key_path + '.pub'
    if not os.path.exists(key_path):
        os.makedirs(os.path.dirname(key_path), exist_ok=True)
        if not shutil.which('ssh-keygen'):
            raise exceptions.ProvisionError(
                'ssh-keygen not available; cannot generate the Azure '
                'cluster SSH keypair')
        subprocess.run(
            ['ssh-keygen', '-t', 'ed25519', '-N', '', '-q',
             '-C', 'skyt-azure', '-f', key_path], check=True)
    with open(pub_path, encoding='utf-8') as f:
        return key_path, f.read().strip()


@CLOUD_REGISTRY.register('azure')
class AzureProvider(Provider):
    """One resource group per cluster; every host is one VM."""

    name = 'azure'
    _token_cache: Dict[str, tuple] = {}

    @classmethod
    def unsupported_features(cls) -> Dict[CloudCapability, str]:
        return {
            CloudCapability.VOLUMES:
                'managed-disk volume provisioning is not wired up yet',
        }

    # -- transport (stubbed in tests) ----------------------------------

    def _token(self) -> str:
        creds = credentials()
        cache_key = f'{creds["tenant"]}/{creds["client"]}'
        cached = self._token_cache.get(cache_key)
        if cached and cached[1] - 60 > time.time():
            return cached[0]
        body = urllib.parse.urlencode({
            'grant_type': 'client_credentials',
            'client_id': creds['client'],
            'client_secret': creds['secret'],
            'scope': f'{ARM}/.default',
        }).encode()
        url = (f'https://login.microsoftonline.com/{creds["tenant"]}'
               f'/oauth2/v2.0/token')
        req = urllib.request.Request(url, data=body, method='POST')
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                payload = json.loads(resp.read())
        except urllib.error.HTTPError as e:
            raise exceptions.NoCloudAccessError(
                f'Azure token request failed: '
                f'{e.read().decode(errors="replace")[:300]}') from None
        except urllib.error.URLError as e:
            # Typed so provision_with_failover moves to the next cloud
            # instead of crashing on a raw socket error.
            raise exceptions.ProvisionError(
                f'Azure token endpoint unreachable: {e}') from None
        token = payload['access_token']
        self._token_cache[cache_key] = (
            token, time.time() + float(payload.get('expires_in', 3600)))
        return token

    def _request(self, method: str, path: str,
                 body: Optional[Dict[str, Any]] = None,
                 api_version: str = COMPUTE_API) -> Dict[str, Any]:
        """One ARM call; path is subscription-relative or absolute."""
        creds = credentials()
        if not path.startswith('/subscriptions'):
            path = f'/subscriptions/{creds["subscription"]}{path}'
        sep = '&' if '?' in path else '?'
        url = f'{ARM}{path}{sep}api-version={api_version}'
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            url, data=data, method=method,
            headers={'Authorization': f'Bearer {self._token()}',
                     'Content-Type': 'application/json'})
        try:
            with urllib.request.urlopen(req, timeout=60) as resp:
                raw = resp.read()
                return json.loads(raw) if raw else {}
        except urllib.error.HTTPError as e:
            text = e.read().decode('utf-8', errors='replace')
            try:
                err = json.loads(text).get('error', {})
                code, msg = err.get('code', str(e.code)), err.get(
                    'message', text[:300])
            except (ValueError, AttributeError):
                code, msg = str(e.code), text[:300]
            if e.code == 404 and method == 'GET':
                raise exceptions.ProvisionError(
                    f'NotFound: {msg}') from None
            raise classify_azure_error(code, msg) from None
        except exceptions.ProvisionError:
            raise
        except Exception as e:  # pylint: disable=broad-except
            raise exceptions.ProvisionError(
                f'ARM {method} {path} failed: {e}') from e

    def _get_optional(self, path: str,
                      api_version: str) -> Optional[Dict[str, Any]]:
        try:
            return self._request('GET', path, api_version=api_version)
        except exceptions.ProvisionError as e:
            if 'NotFound' in str(e) or 'ResourceGroupNotFound' in str(e):
                return None
            raise

    # -- naming --------------------------------------------------------

    @staticmethod
    def _rg(cluster_name: str) -> str:
        return f'skyt-{cluster_name}'

    def _rg_path(self, cluster_name: str) -> str:
        return f'/resourceGroups/{self._rg(cluster_name)}'

    def _net_path(self, cluster_name: str, kind: str, name: str) -> str:
        return (f'{self._rg_path(cluster_name)}/providers/'
                f'Microsoft.Network/{kind}/{name}')

    def _vm_path(self, cluster_name: str, vm: str) -> str:
        return (f'{self._rg_path(cluster_name)}/providers/'
                f'Microsoft.Compute/virtualMachines/{vm}')

    # -- network scaffolding -------------------------------------------

    def _ensure_network(self, request: ProvisionRequest,
                        region: str) -> str:
        """RG + vnet + NSG; returns the subnet resource id."""
        cluster = request.cluster_name
        self._request('PUT', self._rg_path(cluster),
                      {'location': region,
                       'tags': {'skyt-cluster': cluster}},
                      api_version=RESOURCE_API)
        nsg_rules = [{
            'name': 'skyt-allow-ssh',
            'properties': {
                'priority': 1000, 'direction': 'Inbound',
                'access': 'Allow', 'protocol': 'Tcp',
                'sourceAddressPrefix': '*', 'sourcePortRange': '*',
                'destinationAddressPrefix': '*',
                'destinationPortRange': '22',
            },
        }]
        for i, port in enumerate(request.ports or []):
            nsg_rules.append({
                'name': f'skyt-port-{port}',
                'properties': {
                    'priority': 1100 + i, 'direction': 'Inbound',
                    'access': 'Allow', 'protocol': 'Tcp',
                    'sourceAddressPrefix': '*', 'sourcePortRange': '*',
                    'destinationAddressPrefix': '*',
                    'destinationPortRange': str(port),
                },
            })
        nsg = self._request(
            'PUT', self._net_path(cluster, 'networkSecurityGroups',
                                  'skyt-nsg'),
            {'location': region,
             'properties': {'securityRules': nsg_rules}},
            api_version=NETWORK_API)
        vnet = self._request(
            'PUT', self._net_path(cluster, 'virtualNetworks', 'skyt-vnet'),
            {'location': region,
             'properties': {
                 'addressSpace': {'addressPrefixes': ['10.20.0.0/16']},
                 'subnets': [{
                     'name': 'default',
                     'properties': {
                         'addressPrefix': '10.20.0.0/24',
                         'networkSecurityGroup': {'id': nsg['id']},
                     },
                 }],
             }},
            api_version=NETWORK_API)
        return vnet['properties']['subnets'][0]['id']

    def _create_nic(self, cluster: str, region: str, node: int,
                    subnet_id: str) -> str:
        ip = self._request(
            'PUT', self._net_path(cluster, 'publicIPAddresses',
                                  f'{cluster}-n{node}-ip'),
            {'location': region,
             'sku': {'name': 'Standard'},
             'properties': {'publicIPAllocationMethod': 'Static'}},
            api_version=NETWORK_API)
        nic = self._request(
            'PUT', self._net_path(cluster, 'networkInterfaces',
                                  f'{cluster}-n{node}-nic'),
            {'location': region,
             'properties': {'ipConfigurations': [{
                 'name': 'primary',
                 'properties': {
                     'subnet': {'id': subnet_id},
                     'publicIPAddress': {'id': ip['id']},
                 },
             }]}},
            api_version=NETWORK_API)
        return nic['id']

    # -- instance selection --------------------------------------------

    @staticmethod
    def _vm_size(resources) -> str:
        from skypilot_tpu.catalog import azure_data
        if resources.instance_type:
            return resources.instance_type
        accels = resources.accelerators
        if accels:
            (name, count), = accels.items()
            picked = azure_data.instance_type_for(name, count)
            if picked is None:
                raise exceptions.ProvisionError(
                    f'no Azure VM size for {count}x {name}; known: '
                    f'{sorted(azure_data.GPU_INSTANCE_TYPES)}')
            return picked[0]
        from skypilot_tpu.catalog.common import pick_cpu_instance_type
        cpus = resources.cpus[0] if resources.cpus else None
        mem = resources.memory[0] if resources.memory else None
        return pick_cpu_instance_type(cpus, mem, cloud='azure')

    # -- Provider API --------------------------------------------------

    def run_instances(self, request: ProvisionRequest) -> ClusterInfo:
        from skypilot_tpu.catalog import azure_data
        cluster, region = request.cluster_name, request.region
        existing = self._list_vms(cluster)
        if request.resume and existing:
            for vm in existing:
                if self._power_state(cluster, vm['name']) == 'deallocated':
                    self._request(
                        'POST', self._vm_path(cluster, vm['name']) +
                        '/start', {})
            return self._cluster_info(cluster, region)
        if existing:
            raise exceptions.ProvisionError(
                f'cluster {cluster} already has VMs; use resume or '
                'terminate first')
        _, pub_key = ensure_ssh_keypair()
        subnet_id = self._ensure_network(request, region)
        size = self._vm_size(request.resources)
        for node in range(request.num_nodes):
            nic_id = self._create_nic(cluster, region, node, subnet_id)
            body: Dict[str, Any] = {
                'location': region,
                'tags': {'skyt-cluster': cluster, 'skyt-node': str(node),
                         **request.labels},
                'properties': {
                    'hardwareProfile': {'vmSize': size},
                    'storageProfile': {
                        'imageReference': dict(azure_data.DEFAULT_IMAGE),
                        'osDisk': {
                            'createOption': 'FromImage',
                            'deleteOption': 'Delete',
                            'diskSizeGB': request.resources.disk_size,
                        },
                    },
                    'osProfile': {
                        'computerName': f'{cluster}-n{node}',
                        'adminUsername': SSH_USER,
                        'linuxConfiguration': {
                            'disablePasswordAuthentication': True,
                            'ssh': {'publicKeys': [{
                                'path': (f'/home/{SSH_USER}/.ssh/'
                                         'authorized_keys'),
                                'keyData': pub_key,
                            }]},
                        },
                    },
                    'networkProfile': {'networkInterfaces': [{
                        'id': nic_id,
                        'properties': {'deleteOption': 'Delete'},
                    }]},
                },
            }
            if request.zone:
                body['zones'] = [str(request.zone)]
            if request.resources.use_spot:
                body['properties']['priority'] = 'Spot'
                body['properties']['evictionPolicy'] = 'Deallocate'
                body['properties']['billingProfile'] = {'maxPrice': -1}
            self._request('PUT', self._vm_path(cluster,
                                               f'{cluster}-n{node}'),
                          body)
        self._wait_provisioned(cluster, request.num_nodes)
        logger.info('Azure: launched %d x %s in %s for %s',
                    request.num_nodes, size, region, cluster)
        return self._cluster_info(cluster, region)

    def _wait_provisioned(self, cluster: str, num_nodes: int,
                          timeout: float = 900.0) -> None:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            vms = self._list_vms(cluster)
            states = [vm.get('properties', {}).get('provisioningState')
                      for vm in vms]
            if len(vms) >= num_nodes and all(
                    s == 'Succeeded' for s in states):
                return
            failed = [vm['name'] for vm, s in zip(vms, states)
                      if s == 'Failed']
            if failed:
                raise exceptions.CapacityError(
                    f'Azure VM provisioning failed for {failed} '
                    '(treating as capacity for failover)')
            time.sleep(5.0)
        raise exceptions.CapacityError(
            f'{cluster}: VMs not provisioned within {timeout}s')

    # -- inventory -----------------------------------------------------

    def _list_vms(self, cluster: str) -> List[Dict[str, Any]]:
        resp = self._get_optional(
            f'{self._rg_path(cluster)}/providers/Microsoft.Compute'
            '/virtualMachines', COMPUTE_API)
        if resp is None:
            return []
        vms = [vm for vm in resp.get('value', [])
               if vm.get('tags', {}).get('skyt-cluster') == cluster]
        vms.sort(key=lambda vm: int(vm.get('tags', {}).get('skyt-node',
                                                           0)))
        return vms

    def _power_state(self, cluster: str, vm_name: str) -> str:
        view = self._get_optional(
            self._vm_path(cluster, vm_name) + '/instanceView',
            COMPUTE_API) or {}
        for status in view.get('statuses', []):
            code = status.get('code', '')
            if code.startswith('PowerState/'):
                return code.split('/', 1)[1]
        return 'unknown'

    def _ip_of(self, cluster: str, node: int) -> tuple:
        nic = self._get_optional(
            self._net_path(cluster, 'networkInterfaces',
                           f'{cluster}-n{node}-nic'), NETWORK_API) or {}
        configs = nic.get('properties', {}).get('ipConfigurations', [])
        private = public = None
        for cfg in configs:
            props = cfg.get('properties', {})
            private = private or props.get('privateIPAddress')
            ip_ref = props.get('publicIPAddress')
            if ip_ref:
                ip = self._get_optional(
                    self._net_path(cluster, 'publicIPAddresses',
                                   f'{cluster}-n{node}-ip'),
                    NETWORK_API) or {}
                public = ip.get('properties', {}).get('ipAddress')
        return private, public

    def _cluster_info(self, cluster: str, region: str) -> ClusterInfo:
        hosts = []
        for vm in self._list_vms(cluster):
            node = int(vm.get('tags', {}).get('skyt-node', 0))
            private, public = self._ip_of(cluster, node)
            hosts.append(HostInfo(
                instance_id=vm['name'],
                internal_ip=private or '',
                external_ip=public,
                node_index=node,
                worker_index=0,
                tags=vm.get('tags', {}),
            ))
        return ClusterInfo(
            cluster_name=cluster, provider='azure', region=region,
            zone=None, hosts=hosts, ssh_user=SSH_USER,
            ssh_key_path=ssh_key_path())

    def _region_of(self, cluster: str) -> Optional[str]:
        from skypilot_tpu import state
        record = state.get_cluster(cluster)
        if record and record.handle.get('provider') == 'azure':
            return record.handle.get('region')
        return None

    def get_cluster_info(self, cluster_name: str) -> Optional[ClusterInfo]:
        region = self._region_of(cluster_name)
        if region is None:
            return None
        info = self._cluster_info(cluster_name, region)
        return info if info.hosts else None

    def query_instances(self, cluster_name: str) -> Dict[str, str]:
        state_map = {
            'running': 'running', 'starting': 'starting',
            'deallocated': 'stopped', 'deallocating': 'stopping',
            'stopped': 'stopped', 'stopping': 'stopping',
        }
        out = {}
        for vm in self._list_vms(cluster_name):
            power = self._power_state(cluster_name, vm['name'])
            out[vm['name']] = state_map.get(power, power)
        return out

    def stop_instances(self, cluster_name: str) -> None:
        for vm in self._list_vms(cluster_name):
            # Deallocate (not powerOff): releases compute billing, the
            # semantic `skyt stop` promises.
            self._request(
                'POST',
                self._vm_path(cluster_name, vm['name']) + '/deallocate',
                {})

    def terminate_instances(self, cluster_name: str) -> None:
        # The RG owns every cluster resource: one delete, no orphan
        # NIC/IP/disk sweep (deleteOption=Delete covers the VM-attached
        # ones; the RG covers the rest).
        if self._get_optional(self._rg_path(cluster_name),
                              RESOURCE_API) is None:
            return
        self._request('DELETE', self._rg_path(cluster_name),
                      api_version=RESOURCE_API)

    def open_ports(self, cluster_name: str, ports: List[str]) -> None:
        region = self._region_of(cluster_name)
        if region is None:
            return
        for i, port in enumerate(ports):
            self._request(
                'PUT',
                self._net_path(cluster_name, 'networkSecurityGroups',
                               'skyt-nsg') +
                f'/securityRules/skyt-open-{port}',
                {'properties': {
                    'priority': 1200 + i, 'direction': 'Inbound',
                    'access': 'Allow', 'protocol': 'Tcp',
                    'sourceAddressPrefix': '*', 'sourcePortRange': '*',
                    'destinationAddressPrefix': '*',
                    'destinationPortRange': str(port),
                }},
                api_version=NETWORK_API)
