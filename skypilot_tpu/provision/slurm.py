"""Slurm as a provision target: a cluster is a long-lived allocation.

Parity: ``sky/clouds/slurm.py`` + ``sky/provision/slurm/`` +
``sky/skylet/executor/slurm.py``. The model mirrors the reference's:
"provisioning" submits a placeholder batch job that holds N nodes
(``sleep infinity``), the allocated nodes become the cluster's hosts,
and the normal SSH runtime path (runtime shipping, head daemon,
detached job queue) runs on them — Slurm hands out nodes; skyt runs the
workload. Terminate = ``scancel``.

Slurm access is via the local binaries (login node) or a configurable
SSH prefix (``slurm.command_prefix`` config, e.g. ``ssh login01``).
Partitions map to the ``region`` field.
"""
from __future__ import annotations

import shlex
import subprocess
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import config, exceptions
from skypilot_tpu.provision.api import (ClusterInfo, HostInfo,
                                        ProvisionRequest, Provider)
from skypilot_tpu.utils import env_registry, log
from skypilot_tpu.utils.registry import CLOUD_REGISTRY

logger = log.init_logger(__name__)

_JOB_PREFIX = 'skyt-'


def _run_slurm(args: List[str], timeout: float = 30) -> str:
    prefix = config.get_nested(('slurm', 'command_prefix'), None)
    cmd = (shlex.split(prefix) if prefix else []) + args
    proc = subprocess.run(cmd, capture_output=True, text=True,
                          timeout=timeout)
    if proc.returncode != 0:
        raise exceptions.ProvisionError(
            f'slurm: {" ".join(args)} failed (rc={proc.returncode}): '
            f'{(proc.stderr or proc.stdout)[-500:]}')
    return proc.stdout


def slurm_available() -> bool:
    try:
        _run_slurm(['sinfo', '--version'], timeout=10)
        return True
    except (exceptions.ProvisionError, FileNotFoundError, OSError,
            subprocess.TimeoutExpired):
        return False


@CLOUD_REGISTRY.register('slurm')
class SlurmProvider(Provider):
    """Hold nodes with a placeholder allocation; run via SSH on them."""

    name = 'slurm'

    @classmethod
    def unsupported_features(cls):
        from skypilot_tpu.provision.api import CloudCapability
        return {
            CloudCapability.SPOT:
                'slurm allocations have no preemptible tier (use '
                'preemptible partitions via region instead)',
            CloudCapability.VOLUMES:
                'no network-disk API under slurm; use the shared '
                'filesystem',
            CloudCapability.OPEN_PORTS:
                'cluster firewalls are admin-managed',
        }

    # -- helpers -------------------------------------------------------

    @staticmethod
    def _job_name(cluster_name: str) -> str:
        return f'{_JOB_PREFIX}{cluster_name}'

    _ACTIVE_STATES = ('RUNNING', 'PENDING', 'CONFIGURING', 'COMPLETING',
                      'SUSPENDED')

    def _squeue(self, cluster_name: str) -> Optional[Dict[str, str]]:
        """{job_id, state, nodelist} of the live placeholder job, or
        None. squeue can briefly list just-cancelled jobs; those stale
        terminal lines must not shadow a fresh submission, so only
        ACTIVE states count (newest job wins on ties)."""
        out = _run_slurm([
            'squeue', '--noheader', '-o', '%i|%T|%N',
            '--name', self._job_name(cluster_name)])
        newest = None
        for line in out.strip().splitlines():
            job_id, job_state, nodelist = line.split('|', 2)
            if job_state not in self._ACTIVE_STATES:
                continue
            if newest is None or int(job_id) > int(newest['job_id']):
                newest = {'job_id': job_id, 'state': job_state,
                          'nodelist': nodelist}
        return newest

    @staticmethod
    def _expand_nodelist(nodelist: str) -> List[str]:
        """Expand Slurm's compressed hostlist form, including multiple
        groups: 'cpu[01-02],gpu[03,05],login1' -> [cpu01, cpu02, gpu03,
        gpu05, login1]. (scontrol does this on a real cluster, but the
        grammar is small enough to not shell out for.)"""
        nodes: List[str] = []
        i = 0
        n = len(nodelist)
        while i < n:
            # One group: <base>[<ranges>] or a bare name, ','-separated
            # at bracket depth 0.
            j = i
            depth = 0
            while j < n and (nodelist[j] != ',' or depth > 0):
                if nodelist[j] == '[':
                    depth += 1
                elif nodelist[j] == ']':
                    depth -= 1
                j += 1
            group = nodelist[i:j]
            i = j + 1
            if not group:
                continue
            if '[' not in group:
                nodes.append(group)
                continue
            base, rest = group.split('[', 1)
            for part in rest.rstrip(']').split(','):
                if '-' in part:
                    lo, hi = part.split('-')
                    width = len(lo)
                    for k in range(int(lo), int(hi) + 1):
                        nodes.append(f'{base}{k:0{width}d}')
                else:
                    nodes.append(f'{base}{part}')
        return nodes

    # -- provider interface --------------------------------------------

    def run_instances(self, request: ProvisionRequest) -> ClusterInfo:
        existing = self._squeue(request.cluster_name)
        if existing is None:
            partition = request.region
            args = ['sbatch', '--parsable',
                    '--job-name', self._job_name(request.cluster_name),
                    '-N', str(request.num_nodes)]
            if partition and partition != 'slurm':
                args += ['-p', partition]
            cpus = request.resources.cpus
            if cpus:
                args += ['--cpus-per-task', str(int(float(cpus[0])))]
            args += ['--wrap', 'sleep infinity']
            out = _run_slurm(args).strip()
            logger.info('Slurm: submitted placeholder job %s for %s',
                        out, request.cluster_name)
        info = self._wait_allocation(request)
        return info

    def _wait_allocation(self, request: ProvisionRequest,
                         timeout: float = 600) -> ClusterInfo:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            job = self._squeue(request.cluster_name)
            if job is None:
                # _squeue only reports ACTIVE jobs: gone means rejected,
                # cancelled, or failed at allocation.
                raise exceptions.CapacityError(
                    f'slurm: placeholder job for {request.cluster_name} '
                    f'left the queue (rejected/cancelled/failed)')
            if job['state'] == 'RUNNING' and job['nodelist']:
                nodes = self._expand_nodelist(job['nodelist'])
                if len(nodes) < request.num_nodes:
                    raise exceptions.ProvisionError(
                        f'slurm: got {len(nodes)} nodes, wanted '
                        f'{request.num_nodes}')
                return self._info(request.cluster_name,
                                  request.region or 'slurm', nodes,
                                  job['job_id'])
            time.sleep(env_registry.get_float('SKYT_SLURM_POLL_SECONDS'))
        raise exceptions.CapacityError(
            f'slurm: allocation for {request.cluster_name} still pending '
            f'after {timeout}s (queue full?)')

    @staticmethod
    def _info(cluster_name: str, partition: str, nodes: List[str],
              job_id: str) -> ClusterInfo:
        user = config.get_nested(('slurm', 'ssh_user'), None)
        key = config.get_nested(('slurm', 'ssh_key'), None)
        import getpass
        hosts = [HostInfo(instance_id=f'slurm/{job_id}/{n}',
                          internal_ip=n, node_index=i, worker_index=0)
                 for i, n in enumerate(nodes)]
        return ClusterInfo(
            cluster_name=cluster_name, provider='slurm',
            region=partition, zone=None, hosts=hosts,
            ssh_user=user or getpass.getuser(),
            ssh_key_path=key,
            custom={'slurm_job_id': job_id})

    def stop_instances(self, cluster_name: str) -> None:
        # A held allocation burns queue time; stop releases it (restart
        # re-queues — same semantics as spot-style reclaim).
        self.terminate_instances(cluster_name)

    def terminate_instances(self, cluster_name: str) -> None:
        try:
            _run_slurm(['scancel', '--name',
                        self._job_name(cluster_name)])
        except exceptions.ProvisionError as e:
            logger.warning('scancel %s: %s', cluster_name, e)

    def query_instances(self, cluster_name: str) -> Dict[str, str]:
        job = self._squeue(cluster_name)
        if job is None or job['state'] not in ('RUNNING',):
            return {}
        return {n: 'running'
                for n in self._expand_nodelist(job['nodelist'])}

    def get_cluster_info(self, cluster_name: str) -> Optional[ClusterInfo]:
        job = self._squeue(cluster_name)
        if job is None or job['state'] != 'RUNNING':
            return None
        return self._info(cluster_name,
                          config.get_nested(('slurm', 'partition'),
                                            'slurm'),
                          self._expand_nodelist(job['nodelist']),
                          job['job_id'])
