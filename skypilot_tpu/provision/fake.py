"""Fake GCP-TPU provider: an in-process cloud for tests and dev.

Plays the role of the reference's moto-backed ``mock_aws_backend`` +
``enable_all_clouds`` fixtures (tests/common_test_fixtures.py:195,494): the
full provision/failover/recovery machinery runs against it with zero
credentials. State is a JSON file under the state dir so separate CLI
processes share the same fake cloud. Fault injection (stockouts, quota,
preemption, slow creation) is configured through the same file.
"""
from __future__ import annotations

import json
import os
import time
import uuid
from typing import Any, Dict, List, Optional

import filelock

from skypilot_tpu import exceptions
from skypilot_tpu.provision.api import (ClusterInfo, HostInfo,
                                        ProvisionRequest, Provider)
from skypilot_tpu.utils.registry import CLOUD_REGISTRY


def _store_path() -> str:
    state_dir = os.environ.get('SKYT_STATE_DIR',
                               os.path.expanduser('~/.skyt'))
    return os.path.join(state_dir, 'fake_cloud.json')


class _Store:
    """File-backed dict with an exclusive lock."""

    def __init__(self) -> None:
        self._path = _store_path()
        os.makedirs(os.path.dirname(self._path), exist_ok=True)
        self._lock = filelock.FileLock(self._path + '.lock')

    def __enter__(self) -> Dict[str, Any]:
        self._lock.acquire()
        if os.path.exists(self._path):
            with open(self._path, encoding='utf-8') as f:
                self._data = json.load(f)
        else:
            self._data = {'clusters': {}, 'faults': {}}
        return self._data

    def __exit__(self, exc_type, *args) -> None:
        # release() in a finally: a failed flush must not keep the
        # file lock held forever for every other process.
        try:
            if exc_type is None:
                tmp = self._path + '.tmp'
                with open(tmp, 'w', encoding='utf-8') as f:
                    json.dump(self._data, f)
                os.replace(tmp, self._path)
        finally:
            self._lock.release()


# ---------------------------------------------------------------------------
# Fault injection API (used by tests and the chaos harness)
# ---------------------------------------------------------------------------

def inject_stockout(zone: str, count: int = -1) -> None:
    """Next `count` creations in `zone` fail with CapacityError (-1=always)."""
    with _Store() as data:
        data['faults'].setdefault('stockout', {})[zone] = count


def inject_quota_exceeded(region: str, count: int = -1) -> None:
    with _Store() as data:
        data['faults'].setdefault('quota', {})[region] = count


def inject_slow_create(seconds: float) -> None:
    """Every creation sleeps `seconds` (queued-resource provisioning is
    slow in reality; lets tests exercise pending/cancel paths)."""
    with _Store() as data:
        data['faults']['slow_create_seconds'] = seconds


def clear_faults() -> None:
    with _Store() as data:
        had_faults = bool(data['faults'])
        names = list(data['clusters']) if had_faults else []
        data['faults'] = {}
    # Capacity returning IS a health change: shrunken elastic gangs
    # waiting on the CLUSTERS topic should retry their grow-back now,
    # not at the next poll tick. Signalled per live cluster (the gangs
    # that could grow), and only when faults were actually cleared —
    # hygiene calls from test setup must not pollute the durable
    # cluster_events table or broadcast-wake every controller.
    for name in names:
        _signal_cluster_change(name, 'CAPACITY_CHANGED', '')


def _signal_cluster_change(cluster_name: str, event: str,
                           detail: str) -> None:
    """Ripple a fake-cloud mutation into the shared cluster-state DB so
    out-of-process watchers (job controllers) wake on the CLUSTERS
    topic's external signal instead of their poll fallback. Best-effort:
    the fake store stays authoritative either way."""
    try:
        from skypilot_tpu import state
        state.add_cluster_event(cluster_name, event, detail)
    except Exception:  # pylint: disable=broad-except
        pass


def preempt_cluster(cluster_name: str) -> None:
    """Simulate spot preemption: all hosts -> terminated."""
    with _Store() as data:
        cluster = data['clusters'].get(cluster_name)
        if cluster:
            for host in cluster['hosts']:
                host['state'] = 'preempted'
            cluster['state'] = 'preempted'
    _signal_cluster_change(cluster_name, 'PREEMPTED', 'all slices')


def preempt_slice(cluster_name: str, slice_index: int,
                  hosts_per_slice: int = 1) -> List[str]:
    """Preempt ONE pod slice of a multi-slice cluster (TPU slices vanish
    as a unit, but independent slices of a gang die independently).
    Returns the instance ids taken. Hosts are slice-blocked by
    worker_index, mirroring codegen._slice_of."""
    taken: List[str] = []
    with _Store() as data:
        cluster = data['clusters'].get(cluster_name)
        if cluster:
            for host in cluster['hosts']:
                if host['worker_index'] // hosts_per_slice == slice_index:
                    host['state'] = 'preempted'
                    taken.append(host['instance_id'])
    _signal_cluster_change(cluster_name, 'PREEMPTED',
                           f'slice {slice_index}')
    return taken


def reset() -> None:
    path = _store_path()
    if os.path.exists(path):
        os.remove(path)


def _consume_fault(data: Dict[str, Any], kind: str, key: str) -> bool:
    faults = data.get('faults', {}).get(kind, {})
    if key not in faults:
        return False
    remaining = faults[key]
    if remaining == 0:
        return False
    if remaining > 0:
        faults[key] = remaining - 1
    return True


# ---------------------------------------------------------------------------
# Provider
# ---------------------------------------------------------------------------

@CLOUD_REGISTRY.register('fake')
class FakeProvider(Provider):
    """Simulates the GCP TPU queued-resource API (instance_utils.py:1258)."""

    name = 'fake'

    # -- volumes (hostpath: a shared dir stands in for a network disk) --

    @staticmethod
    def _volumes_root() -> str:
        return os.path.join(os.path.dirname(_store_path()), 'fake_volumes')

    def create_volume(self, volume) -> Dict[str, Any]:
        backing = os.path.join(self._volumes_root(), volume.name)
        os.makedirs(backing, exist_ok=True)
        return {'backing_path': backing}

    def delete_volume(self, record: Dict[str, Any]) -> None:
        import shutil
        backing = record['config'].get('backing_path')
        if backing:
            shutil.rmtree(backing, ignore_errors=True)

    def volume_mount_commands(self, record: Dict[str, Any],
                              mount_path: str) -> List[str]:
        backing = record['config']['backing_path']
        return [f'mkdir -p "$(dirname {mount_path})" && '
                f'ln -sfn {backing} {mount_path}']

    def run_instances(self, request: ProvisionRequest) -> ClusterInfo:
        res = request.resources
        zone = request.zone or f'{request.region}-a'
        # Consume faults in their own transaction: raising inside the store
        # context would roll back the decrement of one-shot faults.
        with _Store() as data:
            quota_hit = _consume_fault(data, 'quota', request.region)
            stockout_hit = (not quota_hit and
                            _consume_fault(data, 'stockout', zone))
            slow = data.get('faults', {}).get('slow_create_seconds', 0)
            existing_state = (data['clusters']
                              .get(request.cluster_name) or {}).get('state')
        # Resuming a STOPPED cluster is not a create: the injected
        # create latency models slice provisioning, which a warm resume
        # exactly exists to skip (bench_serve_autoscale measures the
        # difference).
        resuming = request.resume and existing_state == 'stopped'
        if slow and not resuming:
            time.sleep(slow)
        if quota_hit:
            raise exceptions.QuotaExceededError(
                f'Quota exceeded for {res.accelerators} in region '
                f'{request.region} (fake)')
        if stockout_hit:
            raise exceptions.CapacityError(
                f'The zone {zone} does not have enough resources '
                f'available to fulfill the request (fake stockout)')
        with _Store() as data:
            existing = data['clusters'].get(request.cluster_name)
            if existing and existing['state'] == 'stopped' and request.resume:
                for host in existing['hosts']:
                    host['state'] = 'running'
                existing['state'] = 'running'
                return self._to_cluster_info(request.cluster_name, existing)

            if res.is_tpu:
                hosts_per_node = res.tpu.hosts_per_slice * res.tpu.num_slices
            else:
                hosts_per_node = 1
            hosts = []
            for node in range(request.num_nodes):
                for worker in range(hosts_per_node):
                    idx = node * hosts_per_node + worker
                    hosts.append({
                        'instance_id': f'fake-{uuid.uuid4().hex[:8]}',
                        'internal_ip': f'10.0.{node}.{worker + 2}',
                        'external_ip': f'34.0.{node}.{worker + 2}',
                        'node_index': node,
                        'worker_index': worker,
                        'state': 'running',
                        'index': idx,
                    })
            data['clusters'][request.cluster_name] = {
                'state': 'running',
                'region': request.region,
                'zone': zone,
                'resources': res.to_yaml_config(),
                'hosts': hosts,
                'created_at': time.time(),
                'spot': res.use_spot,
            }
            return self._to_cluster_info(request.cluster_name,
                                         data['clusters'][request.cluster_name])

    def _to_cluster_info(self, name: str,
                         record: Dict[str, Any]) -> ClusterInfo:
        hosts = [
            HostInfo(
                instance_id=h['instance_id'],
                internal_ip=h['internal_ip'],
                external_ip=h.get('external_ip'),
                node_index=h['node_index'],
                worker_index=h['worker_index'],
            ) for h in record['hosts'] if h['state'] == 'running'
        ]
        from skypilot_tpu.utils import env_registry
        if env_registry.get_bool('SKYT_FAKE_SSH_MODE'):
            # SSH mode: the backend sees a *real* (non-local-style)
            # cluster and goes down the SSHCommandRunner + runtime-ship +
            # remote-daemon path; the `ssh`/`rsync` binaries are the
            # tests/fake_bin shims, which map each fake IP to a private
            # host root via the map file written here.
            self._write_ssh_map(name, hosts)
            return ClusterInfo(cluster_name=name, provider='fake',
                               region=record['region'], zone=record['zone'],
                               hosts=hosts, ssh_user='skyt',
                               custom={'fake_ssh': True})
        return ClusterInfo(cluster_name=name, provider='fake',
                           region=record['region'], zone=record['zone'],
                           hosts=hosts, ssh_user='skyt',
                           custom={'fake': True})

    @staticmethod
    def _write_ssh_map(cluster_name: str, hosts: List[HostInfo]) -> None:
        state_dir = os.environ.get('SKYT_STATE_DIR',
                                   os.path.expanduser('~/.skyt'))
        map_path = os.environ.get(
            'SKYT_FAKE_SSH_MAP', os.path.join(state_dir,
                                              'fake_ssh_map.json'))
        existing: Dict[str, str] = {}
        if os.path.exists(map_path):
            try:
                with open(map_path, encoding='utf-8') as f:
                    existing = json.load(f)
            except (OSError, ValueError):
                existing = {}
        for h in hosts:
            root = os.path.join(state_dir, 'hosts', cluster_name,
                                f'{h.node_index}-{h.worker_index}')
            existing[h.internal_ip] = root
            if h.external_ip:
                existing[h.external_ip] = root
        os.makedirs(os.path.dirname(map_path), exist_ok=True)
        tmp = map_path + '.tmp'
        with open(tmp, 'w', encoding='utf-8') as f:
            json.dump(existing, f)
        os.replace(tmp, map_path)

    # -- elastic gang resize -------------------------------------------

    def trim_instances(self, cluster_name: str,
                       keep_instance_ids: List[str]) -> None:
        """Drop the dead slice's hosts; survivors get contiguous worker
        indices (slice ids re-derive as worker_index // hosts_per_slice,
        so a surviving slice 1 becomes slice 0 of the shrunken gang)."""
        keep = set(keep_instance_ids)
        with _Store() as data:
            cluster = data['clusters'].get(cluster_name)
            if cluster is None:
                raise exceptions.ClusterDoesNotExist(cluster_name)
            survivors = [h for h in cluster['hosts']
                         if h['instance_id'] in keep]
            if not survivors:
                raise exceptions.ProvisionError(
                    f'trim of {cluster_name} would leave zero hosts')
            for idx, host in enumerate(survivors):
                host['worker_index'] = idx
                host['index'] = idx
                host['state'] = 'running'
            cluster['hosts'] = survivors
            cluster['state'] = 'running'
        _signal_cluster_change(cluster_name, 'SHRUNK',
                               f'{len(survivors)} hosts kept')

    def grow_instances(self, request: ProvisionRequest) -> ClusterInfo:
        """Append hosts until the cluster matches the request again;
        capacity faults apply exactly as on a fresh create (a grow-back
        races real provisioning demand)."""
        res = request.resources
        with _Store() as data:
            cluster = data['clusters'].get(request.cluster_name)
            if cluster is None:
                raise exceptions.ClusterDoesNotExist(request.cluster_name)
            zone = cluster.get('zone') or f"{cluster['region']}-a"
            quota_hit = _consume_fault(data, 'quota', cluster['region'])
            stockout_hit = (not quota_hit and
                            _consume_fault(data, 'stockout', zone))
        if quota_hit:
            raise exceptions.QuotaExceededError(
                f'Quota exceeded for {res.accelerators} in region '
                f'{request.region} (fake)')
        if stockout_hit:
            raise exceptions.CapacityError(
                f'The zone {zone} does not have enough resources '
                f'available to grow the gang (fake stockout)')
        if res.is_tpu:
            target = res.tpu.hosts_per_slice * res.tpu.num_slices
        else:
            target = request.num_nodes
        with _Store() as data:
            cluster = data['clusters'][request.cluster_name]
            hosts = cluster['hosts']
            node = hosts[0]['node_index'] if hosts else 0
            used_ips = {h['internal_ip'] for h in hosts}
            octet = 2
            while len(hosts) < target:
                worker = len(hosts)
                # Survivors kept their original IPs through the trim's
                # renumbering, so fresh hosts probe for a free octet.
                while f'10.0.{node}.{octet}' in used_ips:
                    octet += 1
                used_ips.add(f'10.0.{node}.{octet}')
                hosts.append({
                    'instance_id': f'fake-{uuid.uuid4().hex[:8]}',
                    'internal_ip': f'10.0.{node}.{octet}',
                    'external_ip': f'34.0.{node}.{octet}',
                    'node_index': node,
                    'worker_index': worker,
                    'state': 'running',
                    'index': worker,
                })
            cluster['resources'] = res.to_yaml_config()
            cluster['state'] = 'running'
            info = self._to_cluster_info(request.cluster_name, cluster)
        _signal_cluster_change(request.cluster_name, 'GROWN',
                               f'{target} hosts')
        return info

    def stop_instances(self, cluster_name: str) -> None:
        with _Store() as data:
            cluster = data['clusters'].get(cluster_name)
            if cluster is None:
                return
            for host in cluster['hosts']:
                if host['state'] == 'running':
                    host['state'] = 'stopped'
            cluster['state'] = 'stopped'

    def terminate_instances(self, cluster_name: str) -> None:
        with _Store() as data:
            data['clusters'].pop(cluster_name, None)

    def query_instances(self, cluster_name: str) -> Dict[str, str]:
        with _Store() as data:
            cluster = data['clusters'].get(cluster_name)
            if cluster is None:
                return {}
            return {h['instance_id']: h['state'] for h in cluster['hosts']}

    def get_cluster_info(self, cluster_name: str) -> Optional[ClusterInfo]:
        with _Store() as data:
            cluster = data['clusters'].get(cluster_name)
            if cluster is None or cluster['state'] != 'running':
                return None
            return self._to_cluster_info(cluster_name, cluster)



def list_fake_clusters() -> List[str]:
    with _Store() as data:
        return sorted(data['clusters'])
