"""Provider interface + data model.

The reference models a TPU pod as ONE node with many IPs
(``num_ips_per_node``, cloud_vm_ray_backend.py:2613) -- SURVEY.md calls this
an impedance mismatch to avoid. Here hosts are explicit: a cluster is
``num_nodes`` *nodes* (for TPU, one node = one pod slice), each node has a
list of ``HostInfo`` (slice workers). Rank math lives in one place
(`all_hosts` ordering).
"""
from __future__ import annotations

import abc
import dataclasses
import enum
from typing import Any, Dict, List, Optional


class CloudCapability(enum.Enum):
    """Feature flags a provider may declare unsupported (parity:
    sky/clouds/cloud.py:714 CloudImplementationFeatures — the per-cloud
    capability surface the planner consults BEFORE provisioning, so a
    spot request never reaches a cloud with no spot tier and `skyt
    stop` fails at submit time on clouds that cannot stop)."""
    STOP = 'stop'
    SPOT = 'spot'
    AUTOSTOP = 'autostop'
    OPEN_PORTS = 'open_ports'
    VOLUMES = 'volumes'
    MULTI_NODE = 'multi_node'

from skypilot_tpu.spec.resources import Resources
from skypilot_tpu.utils.registry import CLOUD_REGISTRY


@dataclasses.dataclass
class HostInfo:
    """One reachable VM (a TPU slice worker or a plain instance)."""
    instance_id: str
    internal_ip: str
    external_ip: Optional[str] = None
    ssh_port: int = 22
    node_index: int = 0        # which cluster node (slice) this host belongs to
    worker_index: int = 0      # worker id within the node (TPU_WORKER_ID)
    tags: Dict[str, str] = dataclasses.field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> 'HostInfo':
        return cls(**d)


@dataclasses.dataclass
class ClusterInfo:
    """Everything the backend needs to reach and drive a cluster."""
    cluster_name: str
    provider: str                       # cloud name
    region: str
    zone: Optional[str]
    hosts: List[HostInfo]               # ordered by (node_index, worker_index)
    ssh_user: str = 'skyt'
    ssh_key_path: Optional[str] = None
    custom: Dict[str, Any] = dataclasses.field(default_factory=dict)

    @property
    def head_host(self) -> HostInfo:
        return self.hosts[0]

    def hosts_of_node(self, node_index: int) -> List[HostInfo]:
        return [h for h in self.hosts if h.node_index == node_index]

    @property
    def num_nodes(self) -> int:
        return max(h.node_index for h in self.hosts) + 1 if self.hosts else 0

    def to_dict(self) -> Dict[str, Any]:
        return {
            'cluster_name': self.cluster_name,
            'provider': self.provider,
            'region': self.region,
            'zone': self.zone,
            'hosts': [h.to_dict() for h in self.hosts],
            'ssh_user': self.ssh_user,
            'ssh_key_path': self.ssh_key_path,
            'custom': self.custom,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> 'ClusterInfo':
        d = dict(d)
        d['hosts'] = [HostInfo.from_dict(h) for h in d['hosts']]
        return cls(**d)


@dataclasses.dataclass
class ProvisionRequest:
    """One provisioning attempt at a concrete (cloud, region, zone)."""
    cluster_name: str
    resources: Resources                # launchable: cloud/region decided
    num_nodes: int
    region: str
    zone: Optional[str]
    # resume: restart existing stopped instances instead of creating
    resume: bool = False
    ports: List[str] = dataclasses.field(default_factory=list)
    labels: Dict[str, str] = dataclasses.field(default_factory=dict)
    # Volumes to attach at create time (k8s PVCs ride the pod manifest);
    # each: {'name', 'mount_path', 'type', 'config'} from volumes.get().
    volumes: List[Dict[str, Any]] = dataclasses.field(default_factory=list)


class Provider(abc.ABC):
    """Per-cloud driver (parity: sky/provision per-cloud modules)."""

    name: str = 'abstract'

    @classmethod
    def unsupported_features(cls) -> Dict[CloudCapability, str]:
        """capability -> human reason; absent = supported."""
        return {}

    @classmethod
    def supports(cls, capability: CloudCapability) -> bool:
        return capability not in cls.unsupported_features()

    @abc.abstractmethod
    def run_instances(self, request: ProvisionRequest) -> ClusterInfo:
        """Create (or restart) all hosts; atomic per TPU slice.

        Raises CapacityError / QuotaExceededError / ProvisionError.
        """

    @abc.abstractmethod
    def stop_instances(self, cluster_name: str) -> None:
        ...

    @abc.abstractmethod
    def terminate_instances(self, cluster_name: str) -> None:
        ...

    @abc.abstractmethod
    def query_instances(self, cluster_name: str) -> Dict[str, str]:
        """instance_id -> state ('running'|'stopped'|'terminated'|...)."""

    @abc.abstractmethod
    def get_cluster_info(self, cluster_name: str) -> Optional[ClusterInfo]:
        ...

    def wait_instances(self, cluster_name: str, state: str = 'running',
                       timeout: float = 600) -> None:
        """Default: poll query_instances until all hosts reach `state`."""
        import time
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            states = self.query_instances(cluster_name)
            if states and all(s == state for s in states.values()):
                return
            time.sleep(min(2, max(0.01, deadline - time.monotonic())))
        raise TimeoutError(
            f'{cluster_name}: instances did not reach {state!r} in '
            f'{timeout}s: {self.query_instances(cluster_name)}')

    def open_ports(self, cluster_name: str, ports: List[str]) -> None:
        del cluster_name, ports  # default: no-op

    # -- elastic gang resize (optional capability) ---------------------
    #
    # Providers that can tear down / re-add individual pod slices of a
    # multi-slice cluster implement these; the default NotImplementedError
    # makes ElasticStrategy fall back to a full relaunch (the rigid
    # legacy path) on clouds without the capability.

    def trim_instances(self, cluster_name: str,
                       keep_instance_ids: List[str]) -> None:
        """Terminate every host NOT in ``keep_instance_ids`` (the dead
        slice) and renumber the survivors' worker indices contiguously,
        keeping the cluster itself alive."""
        raise NotImplementedError(
            f'{self.name} cannot trim individual slices')

    def grow_instances(self, request: 'ProvisionRequest') -> ClusterInfo:
        """Add hosts to an existing (shrunken) cluster until it matches
        ``request.resources`` again. Raises CapacityError when the cloud
        still has no capacity (the grow-back watcher retries later)."""
        raise NotImplementedError(
            f'{self.name} cannot grow an existing gang')


def get_provider(cloud: str) -> Provider:
    provider_cls = CLOUD_REGISTRY.get(cloud)
    return provider_cls()
