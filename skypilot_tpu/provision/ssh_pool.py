"""SSH node pools: bring-your-own machines as a provision target.

Parity: ``sky/ssh_node_pools/`` + ``sky/provision/ssh/`` — an inventory
of SSH-reachable hosts (lab boxes, on-prem TPU VMs, reserved capacity)
declared in ``~/.skyt/ssh_node_pools.yaml``::

    my-lab:
      user: ubuntu
      identity_file: ~/.ssh/lab_key
      hosts:
        - 10.0.0.11
        - 10.0.0.12
    tpu-reserved:
      user: tpuadmin
      hosts:
        - ip: 10.1.0.5
        - ip: 10.1.0.6

Each pool is addressable as ``cloud: ssh`` with ``region: <pool name>``
(or any pool when no region is pinned). "Provisioning" allocates free
hosts from the pool (persisted, so concurrent clusters never share a
host); terminate releases them. stop/restart are no-ops — BYO machines
stay up. The backend then treats the cluster exactly like any SSH
cluster: runtime tarball shipped, daemon started on the head host,
detached jobs/queue/logs via the remote job table.
"""
from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional

import filelock
import yaml

from skypilot_tpu import exceptions
from skypilot_tpu.provision.api import (ClusterInfo, HostInfo,
                                        ProvisionRequest, Provider)
from skypilot_tpu.utils import log
from skypilot_tpu.utils.registry import CLOUD_REGISTRY

logger = log.init_logger(__name__)


def inventory_path() -> str:
    return os.environ.get(
        'SKYT_SSH_NODE_POOLS',
        os.path.join(os.environ.get('SKYT_STATE_DIR',
                                    os.path.expanduser('~/.skyt')),
                     'ssh_node_pools.yaml'))


def _allocations_path() -> str:
    return inventory_path() + '.alloc.json'


def load_inventory() -> Dict[str, Dict[str, Any]]:
    path = inventory_path()
    if not os.path.exists(path):
        return {}
    with open(path, encoding='utf-8') as f:
        data = yaml.safe_load(f) or {}
    pools: Dict[str, Dict[str, Any]] = {}
    for pool_name, spec in data.items():
        hosts = []
        for h in spec.get('hosts', []):
            hosts.append({'ip': h} if isinstance(h, str) else dict(h))
        pools[pool_name] = {
            'user': spec.get('user', 'root'),
            'identity_file': spec.get('identity_file'),
            'hosts': hosts,
        }
    return pools


class _Allocations:
    """host ip -> cluster name, persisted with an exclusive lock."""

    def __init__(self) -> None:
        self._path = _allocations_path()
        os.makedirs(os.path.dirname(self._path), exist_ok=True)
        self._lock = filelock.FileLock(self._path + '.lock')

    def __enter__(self) -> Dict[str, str]:
        self._lock.acquire()
        if os.path.exists(self._path):
            with open(self._path, encoding='utf-8') as f:
                self._data = json.load(f)
        else:
            self._data = {}
        return self._data

    def __exit__(self, exc_type, *args) -> None:
        # release() in a finally: a failed flush must not keep the
        # file lock held forever for every other process.
        try:
            if exc_type is None:
                tmp = self._path + '.tmp'
                with open(tmp, 'w', encoding='utf-8') as f:
                    json.dump(self._data, f)
                os.replace(tmp, self._path)
        finally:
            self._lock.release()


@CLOUD_REGISTRY.register('ssh')
class SshNodePoolProvider(Provider):
    """Allocate cluster hosts from the static SSH inventory."""

    name = 'ssh'

    @classmethod
    def unsupported_features(cls):
        from skypilot_tpu.provision.api import CloudCapability
        return {
            CloudCapability.SPOT:
                'BYO machines have no preemptible pricing tier',
            CloudCapability.VOLUMES:
                'no network-disk API on inventory hosts',
            CloudCapability.OPEN_PORTS:
                'inventory host firewalls are admin-managed',
        }

    def run_instances(self, request: ProvisionRequest) -> ClusterInfo:
        pools = load_inventory()
        if not pools:
            raise exceptions.ProvisionError(
                f'No SSH node pools defined ({inventory_path()}).')
        pool_name = request.region
        if pool_name in (None, 'ssh', 'default'):
            pool_name = next(iter(pools))
        if pool_name not in pools:
            raise exceptions.ProvisionError(
                f'No SSH node pool {pool_name!r}; defined: '
                f'{sorted(pools)}')
        pool = pools[pool_name]
        want = request.num_nodes
        with _Allocations() as alloc:
            mine = [h for h in pool['hosts']
                    if alloc.get(h['ip']) == request.cluster_name]
            if len(mine) >= want:
                chosen = mine[:want]  # resume / idempotent re-provision
            else:
                free = [h for h in pool['hosts']
                        if h['ip'] not in alloc]
                if len(mine) + len(free) < want:
                    raise exceptions.CapacityError(
                        f'SSH pool {pool_name!r}: need {want} hosts, '
                        f'{len(free)} free of {len(pool["hosts"])}.')
                chosen = mine + free[:want - len(mine)]
                for h in chosen:
                    alloc[h['ip']] = request.cluster_name
        hosts = [
            HostInfo(instance_id=f'{pool_name}/{h["ip"]}',
                     internal_ip=h['ip'],
                     external_ip=h.get('external_ip'),
                     ssh_port=int(h.get('port', 22)),
                     node_index=i, worker_index=0)
            for i, h in enumerate(chosen)
        ]
        logger.info('SSH pool %s: allocated %s to %s', pool_name,
                    [h.internal_ip for h in hosts], request.cluster_name)
        return self._info(request.cluster_name, pool_name, pool, hosts)

    @staticmethod
    def _info(cluster_name: str, pool_name: str, pool: Dict[str, Any],
              hosts: List[HostInfo]) -> ClusterInfo:
        identity = pool.get('identity_file')
        return ClusterInfo(
            cluster_name=cluster_name,
            provider='ssh',
            region=pool_name,
            zone=None,
            hosts=hosts,
            ssh_user=pool.get('user', 'root'),
            ssh_key_path=(os.path.expanduser(identity) if identity
                          else None),
            custom={'ssh_pool': pool_name},
        )

    def stop_instances(self, cluster_name: str) -> None:
        # BYO machines are never powered off by us; stopping a cluster
        # just keeps the allocation (restart is instant).
        logger.info('SSH pool: stop is a no-op for %s (BYO hosts)',
                    cluster_name)

    def terminate_instances(self, cluster_name: str) -> None:
        with _Allocations() as alloc:
            for ip in [ip for ip, c in alloc.items()
                       if c == cluster_name]:
                del alloc[ip]

    def query_instances(self, cluster_name: str) -> Dict[str, str]:
        with _Allocations() as alloc:
            ips = [ip for ip, c in alloc.items() if c == cluster_name]
        return {ip: 'running' for ip in ips}

    def get_cluster_info(self, cluster_name: str) -> Optional[ClusterInfo]:
        pools = load_inventory()
        with _Allocations() as alloc:
            ips = {ip for ip, c in alloc.items() if c == cluster_name}
        if not ips:
            return None
        for pool_name, pool in pools.items():
            chosen = [h for h in pool['hosts'] if h['ip'] in ips]
            if chosen:
                hosts = [
                    HostInfo(instance_id=f'{pool_name}/{h["ip"]}',
                             internal_ip=h['ip'],
                             external_ip=h.get('external_ip'),
                             ssh_port=int(h.get('port', 22)),
                             node_index=i, worker_index=0)
                    for i, h in enumerate(chosen)
                ]
                return self._info(cluster_name, pool_name, pool, hosts)
        return None

    def wait_instances(self, cluster_name: str, state: str = 'running',
                       timeout: float = 600) -> None:
        del timeout
        if state == 'running' and not self.query_instances(cluster_name):
            raise exceptions.ProvisionError(
                f'{cluster_name}: no allocated SSH hosts')
