"""Managed jobs: launch-and-forget jobs with preemption recovery.

Parity: ``sky/jobs/`` (16k LoC) — a controller per job monitors the
worker cluster, detects preemption/failure, and relaunches via a recovery
strategy (FAILOVER / EAGER_NEXT_REGION); a scheduler bounds controller
concurrency (jobs/scheduler.py:1-43). The TPU flavor: spot pod slices are
preempted as a unit, so recovery is always whole-slice relaunch +
checkpoint-resume from GCS.
"""
