"""Worker pools: pre-provisioned clusters for jobs/batch work.

Parity: ``sky jobs pool`` (SURVEY §2.8 — the reference builds pools on
the serve machinery; so do we). A pool is a service in pool mode: the
serve controller keeps N identical worker clusters alive (recovering
preempted/failed ones via the same replica manager + autoscalers), but
there is no load balancer and no HTTP readiness probe — a worker is
ready once it is provisioned and its setup ran.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.serve import core as serve_core
from skypilot_tpu.serve import serve_state
from skypilot_tpu.serve.serve_state import ReplicaStatus
from skypilot_tpu.spec.task import Task


def _is_pool(record_dict: Dict[str, Any]) -> bool:
    return bool((record_dict.get('spec') or {}).get('pool'))


def apply(task: Task, pool_name: str,
          workers: Optional[int] = None) -> Dict[str, Any]:
    """Create (or resize) a pool of identical workers from a task.

    The task's ``run`` section is ignored for pool workers (they idle
    until batch/jobs dispatch work onto them); ``setup`` is where the
    expensive environment preparation goes.
    """
    service = dict(task.service or {})
    service['pool'] = True
    if workers is not None:
        service['workers'] = int(workers)
    service.setdefault('workers', service.pop('replicas', 1))
    task.service = service
    task.run = None  # workers idle; work arrives via exec
    existing = serve_state.get_service(pool_name)
    if existing is not None:
        if not _is_pool(existing.to_dict()):
            raise exceptions.ServiceAlreadyExistsError(
                f'{pool_name!r} exists and is a service, not a pool.')
        # Resize IN PLACE: push the new spec; the pool's controller
        # hot-reloads it and scales up/down without touching the warm
        # workers that already exist.
        from skypilot_tpu.serve.service_spec import ServiceSpec
        spec = ServiceSpec.from_yaml_config(service)
        serve_state.set_service_spec(pool_name, spec.to_yaml_config())
        return {'name': pool_name, 'resized': True}
    return serve_core.up(task, pool_name)


def status(pool_name: Optional[str] = None) -> List[Dict[str, Any]]:
    records = [r for r in serve_core.status(None) if _is_pool(r)]
    if pool_name is not None:
        records = [r for r in records if r['name'] == pool_name]
        if not records:
            raise exceptions.ServiceNotFoundError(
                f'No pool {pool_name!r}.')
    return records


def down(pool_name: str, purge: bool = False) -> None:
    record = serve_state.get_service(pool_name)
    if record is None or not _is_pool(record.to_dict()):
        raise exceptions.ServiceNotFoundError(f'No pool {pool_name!r}.')
    serve_core.down(pool_name, purge=purge)


def ready_workers(pool_name: str) -> List[str]:
    """Cluster names of READY workers (batch dispatch targets)."""
    record = serve_state.get_service(pool_name)
    if record is None:
        raise exceptions.ServiceNotFoundError(f'No pool {pool_name!r}.')
    return [r.cluster_name
            for r in serve_state.list_replicas(pool_name,
                                               include_terminal=False)
            if r.status == ReplicaStatus.READY]


def wait_ready(pool_name: str, min_workers: int = 1,
               timeout: float = 300.0) -> List[str]:
    """Block until >= min_workers are READY; returns their clusters."""
    import time
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        workers = ready_workers(pool_name)
        if len(workers) >= min_workers:
            return workers
        time.sleep(1)
    raise TimeoutError(
        f'Pool {pool_name!r}: {len(ready_workers(pool_name))}/'
        f'{min_workers} workers ready after {timeout}s.')
