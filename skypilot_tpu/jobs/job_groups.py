"""Gang-scheduled managed-job groups with cross-task host discovery.

Parity: ``sky/jobs/job_group_networking.py:118-217`` — the reference
gang-schedules multi-task groups and wires cross-task DNS. The
TPU-native shape here:

1. Every member's controller provisions + sets up its cluster but does
   NOT start the task.
2. It publishes its cluster's host IPs to the managed-jobs DB and waits
   at a barrier for all siblings to do the same ("all slices up before
   any rank runs" — the same all-or-nothing discipline a TPU pod slice
   gives within one cluster, lifted to groups of clusters).
3. Once the group is fully provisioned, each member starts its task
   with ``SKYT_JOBGROUP`` and per-sibling
   ``SKYT_JOBGROUP_HOSTS_<TASKNAME>`` env vars (comma-separated IPs) —
   a rendezvous map instead of the reference's DNS names.
4. If any member fails (provisioning, user code, cancel), every other
   member is gang-cancelled: a partial group never burns TPU-hours.
"""
from __future__ import annotations

import re
import time
from typing import Dict, List, Optional

from skypilot_tpu import exceptions, state
from skypilot_tpu.jobs import state as jobs_state
from skypilot_tpu.jobs.state import ManagedJobStatus
from skypilot_tpu.utils import log

logger = log.init_logger(__name__)

_FAILED_STATUSES = (ManagedJobStatus.FAILED,
                    ManagedJobStatus.FAILED_SETUP,
                    ManagedJobStatus.FAILED_NO_RESOURCE,
                    ManagedJobStatus.FAILED_CONTROLLER,
                    ManagedJobStatus.CANCELLED,
                    ManagedJobStatus.CANCELLING)


class GangAborted(exceptions.SkytError):
    """A sibling failed; this member must stand down."""


def _env_key(task_name: Optional[str], job_id: int) -> str:
    name = task_name or f'job{job_id}'
    return 'SKYT_JOBGROUP_HOSTS_' + re.sub(r'[^A-Za-z0-9]', '_',
                                           name).upper()


def publish_hosts(job_id: int, cluster_name: str) -> None:
    record = state.get_cluster(cluster_name)
    hosts: List[str] = []
    if record is not None:
        for host in record.handle.get('hosts', []):
            hosts.append(host.get('external_ip') or
                         host.get('internal_ip'))
    jobs_state.set_group_hosts(job_id, [h for h in hosts if h])


def _is_elastic_member(sibling: jobs_state.JobRecord) -> bool:
    """RL-pipeline rollout members are *elastic* gang members: losing
    one shrinks the rollout fleet (the pipeline redistributes waves
    and the staleness valve absorbs the throughput dip) instead of
    cancelling the whole gang.  A learner failure still gang-cancels —
    rollouts without a consumer burn TPU-hours for nothing."""
    envs = sibling.task_config.get('envs') or {}
    return envs.get('SKYT_RL_ROLE') == 'rollout'


def sibling_failed(record: jobs_state.JobRecord) -> Optional[str]:
    """Name of a failed sibling, or None while the gang is healthy.
    Elastic (rollout-role) siblings never trip the gang-cancel."""
    assert record.group_name is not None
    for sibling in jobs_state.list_group(record.group_name):
        if sibling.job_id == record.job_id:
            continue
        if sibling.status in _FAILED_STATUSES:
            if _is_elastic_member(sibling):
                logger.info(
                    'Group %s: elastic rollout member %s is %s; '
                    'fleet shrinks, gang continues.',
                    record.group_name, sibling.name or sibling.job_id,
                    sibling.status.value)
                continue
            return (f'{sibling.name or sibling.job_id} '
                    f'({sibling.status.value})')
    return None


def rebuild_env(record: jobs_state.JobRecord) -> Dict[str, str]:
    """Rendezvous env from the persisted group state — used by recovery
    and HA-replacement controllers, whose in-memory env from the
    original barrier is gone."""
    assert record.group_name is not None
    env = {'SKYT_JOBGROUP': record.group_name}
    for member in jobs_state.list_group(record.group_name):
        env[_env_key(member.name, member.job_id)] = ','.join(
            member.group_hosts)
    return env


def barrier_and_env(record: jobs_state.JobRecord,
                    timeout: float = 1800.0,
                    poll: float = 1.0) -> Dict[str, str]:
    """Wait for every group member to publish hosts; return the
    rendezvous env map. Raises GangAborted if a sibling fails first."""
    assert record.group_name is not None
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        failed = sibling_failed(record)
        if failed is not None:
            raise GangAborted(
                f'group {record.group_name}: member {failed} failed '
                f'before the gang barrier')
        members = jobs_state.list_group(record.group_name)
        if members and all(m.group_hosts for m in members):
            return rebuild_env(record)
        time.sleep(poll)
    raise GangAborted(
        f'group {record.group_name}: barrier timed out after '
        f'{timeout:.0f}s (members still provisioning)')
