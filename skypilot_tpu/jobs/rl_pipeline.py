"""Live-sync GRPO rollout pipeline: delta weight refresh without
stopping generation.

One elastic unit, two roles:

* a **learner** consuming rollout batches (``train/grpo.py``'s
  :class:`GrpoLearner`) and committing each new policy as a *delta
  manifest* — only the shards whose bytes changed — to a
  :class:`PolicyStore`;
* a **rollout fleet** serving generation through the continuous
  batching engine (paged KV, prompt-set prefix reuse, prompt-lookup
  speculative drafts) and live-refreshing weights in place: each
  replica pulls just the changed shards over the PR-17 fan-out path,
  swaps them at a serving-loop step boundary, and resumes — staggered
  by :data:`SKYT_RL_REFRESH_CONCURRENCY` so generation never stops
  fleet-wide.

Off-policy staleness (learner version at consume minus the policy
version that generated the batch) is stamped on every batch and
bounded by the ``max_staleness`` **backpressure valve**: a producer
whose batch would exceed the bound *if it landed now* waits (with a
timeout that loops it back through the refresh step — consuming a
batch bumps the learner version AND shrinks the queue by one, so lag
plus depth is invariant under consumption and only a weight refresh
can reopen the valve).

Batch hand-off is the :class:`RolloutQueue` protocol: ``put`` /
``pop`` / ``ack`` / ``requeue``.  A popped batch stays accounted as
in-flight until the learner acks it; a learner fault mid-step requeues
it at the *front*, so no rollout batch is ever lost.  The same
protocol has a file-backed twin (:class:`FileBatchQueue`) for the
distributed roles launched by a ``pipeline:`` task spec — batches are
committed ``tmp -> rename`` under the store root, claims are renames,
acks are deletes, so a crashed learner's claim is recoverable.

Chaos sites (``utils/fault_injection``)::

    rl.rollout.generate    a rollout wave, before submission
    rl.refresh.pull        a replica's delta pull, before fetching
    rl.learn.step          the learner step, before state mutation

Parity: the train/serve split every RLHF system draws (OpenRLHF's
vLLM engines + DeepSpeed trainer; verl's hybrid controller) — here
both sides share one model implementation and one GSPMD mesh layout,
so the weight path is a same-layout per-shard ``device_put``, not a
cross-framework gather/scatter.
"""
from __future__ import annotations

import argparse
import collections
import dataclasses
import io
import json
import os
import threading
import time
from typing import Any, Callable, Dict, Iterable, List, Optional

import numpy as np

from skypilot_tpu.data import ckpt_manifest
from skypilot_tpu.data.fanout import DirectorySource, FanoutPuller
from skypilot_tpu.utils import env_registry, fault_injection, log

logger = log.init_logger(__name__)

# Chaos sites: the three host-side edges of the pipeline. Generation
# faults exercise replica loss mid-wave; pull faults exercise a
# replica dying mid-refresh (the store manifest commit protocol makes
# a torn pull re-pullable); learn faults exercise the ack/requeue
# no-lost-batches invariant.
ROLLOUT_GENERATE_SITE = 'rl.rollout.generate'
REFRESH_PULL_SITE = 'rl.refresh.pull'
LEARN_STEP_SITE = 'rl.learn.step'

_BATCH_DIR = 'batches'
_WEIGHTS_DIR = 'weights'
_CLAIM_SUFFIX = '.claim'


def _metrics():
    from skypilot_tpu.server import metrics
    return metrics


# --------------------------------------------------------------------
# Policy store: delta-manifest weight publication
# --------------------------------------------------------------------


class PolicyStore:
    """Committed policy weights under one directory, one ``.npy`` file
    per parameter shard (named by its ``flatten_param_paths`` path —
    the same naming contract the engine's ``request_refresh(updates=)``
    resolves), with a content-addressed ``MANIFEST.skyt.json``
    committed last (``data/ckpt_manifest``: tmp + fsync + rename, so a
    reader never sees a version whose shards aren't all on disk).

    ``publish`` skips shards whose bytes are unchanged — the manifest
    diff IS the delta a replica transfers, which is what makes a GRPO
    step (touching a subset of tensors meaningfully, at toy scale all
    of them, at scale e.g. frozen embeddings / adapters never) cheap
    to ship."""

    def __init__(self, root: str) -> None:
        self.root = os.path.join(root, _WEIGHTS_DIR)
        os.makedirs(self.root, exist_ok=True)

    # -- learner side -------------------------------------------------

    def publish(self, params: Any, version: int) -> Dict[str, Any]:
        """Write changed shards + commit the manifest at ``version``.
        Returns ``{'version', 'shards_total', 'shards_written',
        'bytes_written'}``."""
        from skypilot_tpu.inference.continuous import flatten_param_paths
        prev = ckpt_manifest.read(self.root)
        prev_map = ckpt_manifest.shard_map(prev) if prev else {}
        flat = flatten_param_paths(params)
        written = 0
        nbytes = 0
        for path, leaf in flat.items():
            rel = path + '.npy'
            buf = io.BytesIO()
            np.save(buf, np.asarray(leaf), allow_pickle=False)
            blob = buf.getvalue()
            before = prev_map.get(rel)
            if before is not None and before['size'] == len(blob):
                import hashlib
                if hashlib.sha256(blob).hexdigest() == before['sha256']:
                    continue  # unchanged shard: not part of the delta
            full = os.path.join(self.root, *rel.split('/'))
            os.makedirs(os.path.dirname(full), exist_ok=True)
            tmp = full + ckpt_manifest.TMP_INFIX
            with open(tmp, 'wb') as f:
                f.write(blob)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, full)
            written += 1
            nbytes += len(blob)
        payload = ckpt_manifest.build(self.root, step=int(version))
        ckpt_manifest.write(self.root, payload)
        return {'version': int(version),
                'shards_total': len(flat),
                'shards_written': written,
                'bytes_written': nbytes}

    # -- rollout side -------------------------------------------------

    def version(self) -> Optional[int]:
        payload = ckpt_manifest.read(self.root)
        if payload is None:
            return None
        return int(payload.get('step', 0))

    def pull(self, dest: str,
             sources: Iterable[Any] = ()) -> Optional[Dict[str, Any]]:
        """Pull the committed delta into ``dest`` (a per-replica local
        copy) through the fan-out path — peer ``sources`` first, the
        store directory as the origin bucket — and load the changed
        shards.  Returns ``{'version', 'updates', 'shards_pulled',
        'bytes_pulled'}`` or None if nothing is committed yet."""
        manifest = ckpt_manifest.read(self.root)
        if manifest is None:
            return None
        os.makedirs(dest, exist_ok=True)
        before = ckpt_manifest.read(dest)
        changed = ckpt_manifest.diff(before, manifest)
        puller = FanoutPuller(manifest, dest, sources,
                              DirectorySource(self.root))
        puller.pull()
        updates: Dict[str, np.ndarray] = {}
        nbytes = 0
        for shard in changed:
            full = os.path.join(dest, *shard['path'].split('/'))
            updates[shard['path'][:-len('.npy')]] = np.load(full)
            nbytes += int(shard['size'])
        return {'version': int(manifest.get('step', 0)),
                'updates': updates,
                'shards_pulled': len(changed),
                'bytes_pulled': nbytes}


# --------------------------------------------------------------------
# Rollout batches and the hand-off queue
# --------------------------------------------------------------------


@dataclasses.dataclass
class RolloutBatch:
    """One wave of rollouts from one replica: ``prompts`` [B, L] and
    ``generated`` [B, N] int32, ``rewards`` [B] float32 (B = prompts
    x group_size, tiled).  ``policy_version`` is the *minimum* engine
    policy version that served the wave — a refresh landing mid-wave
    makes the wave as stale as its oldest token."""
    prompts: np.ndarray
    generated: np.ndarray
    rewards: np.ndarray
    group_size: int
    policy_version: int
    rank: int = 0
    seq: int = 0


class RolloutQueue:
    """Bounded in-memory FIFO with explicit consumption accounting:
    ``pop`` moves a batch to the in-flight set, ``ack`` retires it,
    ``requeue`` puts it back at the FRONT (a learner fault must not
    reorder it behind fresher batches — that would silently raise its
    staleness).  ``depth`` counts queued + in-flight: both are batches
    the learner has yet to *retire*, which is what the staleness
    projection needs."""

    def __init__(self, capacity: int) -> None:
        self.capacity = capacity
        self._items: collections.deque = collections.deque()
        self._inflight: Dict[int, RolloutBatch] = {}
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._not_full = threading.Condition(self._lock)
        self._next_key = 0
        self.produced = 0
        self.acked = 0
        self.requeued = 0

    def put(self, batch: RolloutBatch,
            timeout: Optional[float] = None) -> bool:
        with self._lock:
            if len(self._items) >= self.capacity:
                if not self._not_full.wait_for(
                        lambda: len(self._items) < self.capacity,
                        timeout):
                    return False
            self._items.append(batch)
            self.produced += 1
            self._not_empty.notify()
        return True

    def pop(self, timeout: Optional[float] = None
            ) -> Optional[RolloutBatch]:
        with self._lock:
            if not self._items:
                if not self._not_empty.wait_for(
                        lambda: bool(self._items), timeout):
                    return None
            batch = self._items.popleft()
            self._next_key += 1
            batch._queue_key = self._next_key  # type: ignore[attr-defined]
            self._inflight[self._next_key] = batch
            self._not_full.notify()
        return batch

    def ack(self, batch: RolloutBatch) -> None:
        with self._lock:
            self._inflight.pop(getattr(batch, '_queue_key', None), None)
            self.acked += 1

    def requeue(self, batch: RolloutBatch) -> None:
        with self._lock:
            self._inflight.pop(getattr(batch, '_queue_key', None), None)
            self._items.appendleft(batch)
            self.requeued += 1
            self._not_empty.notify()

    def depth(self) -> int:
        with self._lock:
            return len(self._items) + len(self._inflight)

    def unretired(self) -> int:
        """Batches produced but never acked — the no-lost-batches
        invariant is ``produced == acked + depth()`` at quiesce."""
        with self._lock:
            return self.produced - self.acked


class FileBatchQueue:
    """The :class:`RolloutQueue` protocol over a shared directory —
    the hand-off path when learner and rollout replicas are separate
    jobs of a ``pipeline:`` gang.  A batch is one ``.npz`` committed
    tmp -> rename; ``pop`` claims by renaming to ``*.claim`` (atomic:
    two learners can't both win); ``ack`` deletes the claim;
    ``requeue`` renames it back.  A learner that dies holding a claim
    leaves the ``.claim`` file on disk — its replacement reclaims it
    first (oldest claims sort before fresh batches), so the batch is
    delayed, not lost."""

    def __init__(self, root: str, capacity: int) -> None:
        self.root = os.path.join(root, _BATCH_DIR)
        self.capacity = capacity
        os.makedirs(self.root, exist_ok=True)

    def _entries(self, suffix: str) -> List[str]:
        try:
            names = os.listdir(self.root)
        except OSError:
            return []
        out = [n for n in names if n.endswith(suffix)]
        out.sort()
        return out

    def put(self, batch: RolloutBatch,
            timeout: Optional[float] = None) -> bool:
        deadline = None if timeout is None else time.monotonic() + timeout
        while len(self._entries('.npz')) >= self.capacity:
            if deadline is not None and time.monotonic() >= deadline:
                return False
            time.sleep(0.05)
        name = (f'{batch.policy_version:08d}-{batch.rank:04d}-'
                f'{batch.seq:08d}.npz')
        tmp = os.path.join(self.root,
                           name + ckpt_manifest.TMP_INFIX)
        with open(tmp, 'wb') as f:
            np.savez(f, prompts=batch.prompts, generated=batch.generated,
                     rewards=batch.rewards,
                     meta=np.asarray([batch.group_size,
                                      batch.policy_version,
                                      batch.rank, batch.seq], np.int64))
        os.replace(tmp, os.path.join(self.root, name))
        return True

    def pop(self, timeout: Optional[float] = None
            ) -> Optional[RolloutBatch]:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            # Orphaned claims first (a predecessor died mid-step).
            for name in self._entries(_CLAIM_SUFFIX) + \
                    self._entries('.npz'):
                full = os.path.join(self.root, name)
                if name.endswith(_CLAIM_SUFFIX):
                    claim = full
                else:
                    claim = full + _CLAIM_SUFFIX
                    try:
                        os.rename(full, claim)
                    except OSError:
                        continue  # raced another consumer
                try:
                    with np.load(claim) as z:
                        meta = z['meta']
                        batch = RolloutBatch(
                            prompts=z['prompts'],
                            generated=z['generated'],
                            rewards=z['rewards'],
                            group_size=int(meta[0]),
                            policy_version=int(meta[1]),
                            rank=int(meta[2]), seq=int(meta[3]))
                except (OSError, KeyError, ValueError):
                    continue  # torn claim from a dead writer
                batch._claim_path = claim  # type: ignore[attr-defined]
                return batch
            if deadline is not None and time.monotonic() >= deadline:
                return None
            time.sleep(0.05)

    def ack(self, batch: RolloutBatch) -> None:
        claim = getattr(batch, '_claim_path', None)
        if claim:
            try:
                os.remove(claim)
            except OSError:
                pass

    def requeue(self, batch: RolloutBatch) -> None:
        claim = getattr(batch, '_claim_path', None)
        if claim:
            try:
                os.rename(claim, claim[:-len(_CLAIM_SUFFIX)])
            except OSError:
                pass

    def depth(self) -> int:
        return len(self._entries('.npz')) + \
            len(self._entries(_CLAIM_SUFFIX))


# --------------------------------------------------------------------
# Pipeline configuration (env knobs + the task-spec pipeline: block)
# --------------------------------------------------------------------


@dataclasses.dataclass
class PipelineConfig:
    rollout_replicas: int = 1
    max_staleness: int = 4
    queue_batches: int = 2
    refresh_mode: str = 'step'
    refresh_concurrency: int = 1
    store: Optional[str] = None

    @classmethod
    def from_env(cls) -> 'PipelineConfig':
        return cls(
            rollout_replicas=max(
                1, env_registry.get_int('SKYT_RL_FLEET')),
            max_staleness=env_registry.get_int('SKYT_RL_MAX_STALENESS'),
            queue_batches=max(
                1, env_registry.get_int('SKYT_RL_QUEUE_BATCHES')),
            refresh_mode=env_registry.get_str('SKYT_RL_REFRESH_MODE')
            or 'step',
            refresh_concurrency=max(1, env_registry.get_int(
                'SKYT_RL_REFRESH_CONCURRENCY')),
            store=env_registry.get_str('SKYT_RL_STORE'),
        )

    @classmethod
    def from_pipeline_block(cls, block: Dict[str, Any]
                            ) -> 'PipelineConfig':
        return cls(
            rollout_replicas=int(block['rollout_replicas']),
            max_staleness=int(block.get('max_staleness', 4)),
            queue_batches=int(block.get('queue_batches', 2)),
            refresh_mode=str(block.get('refresh_mode', 'step')),
            refresh_concurrency=int(block.get('refresh_concurrency', 1)),
            store=block.get('store'),
        )


# --------------------------------------------------------------------
# Rollout worker: generate -> valve -> refresh, forever
# --------------------------------------------------------------------


class RolloutWorker:
    """One rollout replica: owns a continuous-batching engine serving
    one wave at a time.  Loop order is refresh -> valve -> generate:
    the valve can only reopen via a refresh, so a valve-blocked worker
    times out back into the refresh step rather than deadlocking."""

    def __init__(self, rank: int, engine: Any, queue: Any,
                 store: PolicyStore, pcfg: PipelineConfig, *,
                 make_wave: Callable[[int, int], Any],
                 reward_fn: Callable[..., np.ndarray],
                 learner_version: Callable[[], int],
                 refresh_slots: threading.Semaphore,
                 producing: 'collections.Counter',
                 pull_dest: str,
                 max_new_tokens: int = 8,
                 temperature: float = 1.0,
                 valve_timeout: float = 0.2) -> None:
        self.rank = rank
        self.engine = engine
        self.queue = queue
        self.store = store
        self.pcfg = pcfg
        self.make_wave = make_wave
        self.reward_fn = reward_fn
        self.learner_version = learner_version
        self.refresh_slots = refresh_slots
        self.producing = producing
        self.pull_dest = pull_dest
        self.max_new_tokens = max_new_tokens
        self.temperature = temperature
        self.valve_timeout = valve_timeout
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.seq = 0
        self.waves = 0
        self.tokens = 0
        self.refreshes = 0
        self.valve_waits = 0
        self.errors: List[BaseException] = []
        # Transient pull/swap failures (e.g. the learner committed a
        # newer version mid-pull, failing the digest check) — retried
        # on the next loop, tracked apart from wave errors.
        self.refresh_errors: List[BaseException] = []
        self.refresh_latencies: List[float] = []
        # version -> publish wall-clock, filled by the pipeline so a
        # replica can report commit->applied sync latency.
        self.publish_wall: Dict[int, float] = {}

    # -- refresh ------------------------------------------------------

    def maybe_refresh(self) -> bool:
        """Pull + apply the latest committed policy if it's newer than
        the engine's.  Staggered: at most ``refresh_concurrency``
        replicas are inside a pull/swap at once, so the rest of the
        fleet keeps generating."""
        committed = self.store.version()
        if committed is None or committed <= self.engine.policy_version:
            return False
        if not self.refresh_slots.acquire(timeout=self.valve_timeout):
            return False
        t0 = time.monotonic()
        try:
            fault_injection.inject(REFRESH_PULL_SITE)
            pulled = self.store.pull(self.pull_dest)
            if pulled is None or not pulled['updates']:
                return False
            self.engine.refresh_weights(pulled['updates'],
                                        version=pulled['version'],
                                        mode=self.pcfg.refresh_mode)
            self.refreshes += 1
            m = _metrics()
            m.RL_WEIGHT_REFRESHES.inc(outcome='ok')
            wall = time.monotonic() - t0
            published = self.publish_wall.get(pulled['version'])
            if published is not None:
                wall = time.monotonic() - published
            self.refresh_latencies.append(wall)
            m.RL_WEIGHT_SYNC_SECONDS.observe(wall)
            return True
        except BaseException as e:  # noqa: BLE001 - chaos surfaces here
            _metrics().RL_WEIGHT_REFRESHES.inc(outcome='error')
            self.refresh_errors.append(e)
            logger.warning('rollout[%d] refresh failed: %s',
                           self.rank, e)
            return False
        finally:
            self.refresh_slots.release()

    # -- valve --------------------------------------------------------

    def projected_staleness(self) -> int:
        """Staleness this replica's NEXT batch would see at consume
        time if produced now: the learner's lead over the engine, plus
        every unretired batch ahead of it (each consumption bumps the
        learner version by one), plus waves other replicas are
        mid-generating.  Consumption cancels itself out of this sum —
        only a refresh lowers it."""
        lag = max(0, self.learner_version() - self.engine.policy_version)
        others = sum(v for k, v in self.producing.items()
                     if k != self.rank)
        return lag + self.queue.depth() + others

    # -- generate -----------------------------------------------------

    def generate_wave(self) -> Optional[RolloutBatch]:
        from skypilot_tpu.train.grpo import engine_rollouts
        fault_injection.inject(ROLLOUT_GENERATE_SITE)
        tiled, targets, group_size = self.make_wave(self.rank, self.seq)
        generated, version = engine_rollouts(
            self.engine, [list(map(int, row)) for row in tiled],
            max_new_tokens=self.max_new_tokens,
            temperature=self.temperature,
            step=(self.seq * 131 + self.rank))
        rewards = np.asarray(self.reward_fn(generated, targets),
                             np.float32)
        batch = RolloutBatch(
            prompts=np.asarray(tiled, np.int32),
            generated=np.asarray(generated, np.int32),
            rewards=rewards, group_size=group_size,
            policy_version=int(version), rank=self.rank, seq=self.seq)
        self.seq += 1
        self.waves += 1
        ntok = int(np.asarray(generated).size)
        self.tokens += ntok
        m = _metrics()
        m.RL_ROLLOUT_TOKENS.inc(ntok, rank=str(self.rank))
        m.RL_ROLLOUT_BATCHES.inc(outcome='produced')
        return batch

    # -- loop ---------------------------------------------------------

    def run_once(self) -> bool:
        """One worker iteration; returns True if a batch was queued."""
        self.maybe_refresh()
        if self.projected_staleness() >= self.pcfg.max_staleness:
            self.valve_waits += 1
            _metrics().RL_VALVE_WAITS.inc(rank=str(self.rank))
            # Timed wait, then loop back through maybe_refresh() —
            # NOT a wait-for-consumption: consuming can never reopen
            # the valve (see projected_staleness).
            self._stop.wait(self.valve_timeout)
            return False
        self.producing[self.rank] += 1
        try:
            batch = self.generate_wave()
        except BaseException as e:  # noqa: BLE001
            self.errors.append(e)
            logger.warning('rollout[%d] wave failed: %s', self.rank, e)
            self._stop.wait(self.valve_timeout)
            return False
        finally:
            self.producing[self.rank] -= 1
        while not self._stop.is_set():
            if self.queue.put(batch, timeout=self.valve_timeout):
                return True
        return False

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name=f'rl-rollout-{self.rank}',
            daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            self.run_once()

    def stop(self, timeout: float = 30.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)


# --------------------------------------------------------------------
# The in-process pipeline (also the simulation/bench harness)
# --------------------------------------------------------------------


class RLPipeline:
    """Learner + rollout fleet in one process: the default execution
    mode of ``pipeline:`` recipes at smoke scale, and the harness the
    chaos tests and ``bench_rl.py`` drive.  The distributed roles
    (``main --role learner|rollout``) run the same classes over a
    :class:`FileBatchQueue` instead of the in-memory one."""

    def __init__(self, model_cfg, pcfg: PipelineConfig, *,
                 steps: int = 8,
                 prompts_per_step: int = 2,
                 group_size: int = 4,
                 prompt_len: int = 8,
                 max_new_tokens: int = 8,
                 num_prompts: int = 64,
                 temperature: float = 1.0,
                 learning_rate: float = 1e-3,
                 checkpoint_dir: Optional[str] = None,
                 max_slots: int = 8,
                 seed: int = 0) -> None:
        if not pcfg.store:
            raise ValueError('pipeline needs a store directory '
                             '(pipeline.store / SKYT_RL_STORE)')
        self.model_cfg = model_cfg
        self.pcfg = pcfg
        self.steps = steps
        self.prompts_per_step = prompts_per_step
        self.group_size = group_size
        self.prompt_len = prompt_len
        self.max_new_tokens = max_new_tokens
        self.num_prompts = num_prompts
        self.temperature = temperature
        self.learning_rate = learning_rate
        self.checkpoint_dir = checkpoint_dir
        self.max_slots = max_slots
        self.seed = seed
        self.workers: List[RolloutWorker] = []
        self.learner = None
        self.queue: Optional[RolloutQueue] = None
        self.staleness: List[int] = []
        self.learn_metrics: List[Dict[str, float]] = []
        self.learn_faults = 0
        self.publish_wall: Dict[int, float] = {}

    # -- construction -------------------------------------------------

    def _build(self):
        import jax
        from skypilot_tpu.inference.continuous import \
            ContinuousBatchingEngine
        from skypilot_tpu.train import grpo

        self.learner = grpo.GrpoLearner(
            self.model_cfg, learning_rate=self.learning_rate,
            checkpoint_dir=self.checkpoint_dir, seed=self.seed)
        self.store = PolicyStore(self.pcfg.store)
        info = self.store.publish(self.learner.params,
                                  self.learner.version)
        self.publish_wall[info['version']] = time.monotonic()
        self.queue = RolloutQueue(self.pcfg.queue_batches)

        pool, pool_targets = grpo.make_prompts(
            jax.random.key(42), self.num_prompts, self.prompt_len,
            self.model_cfg.vocab_size)
        pool = np.asarray(pool)
        pool_targets = np.asarray(pool_targets)
        p, g = self.prompts_per_step, self.group_size

        def make_wave(rank: int, seq: int):
            idx = ((seq * self.pcfg.rollout_replicas + rank) * p
                   + np.arange(p)) % self.num_prompts
            prompts = pool[idx]
            targets = np.repeat(pool_targets[idx], g)
            tiled = np.repeat(prompts, g, axis=0)
            return tiled, targets, g

        def reward(generated, targets):
            import jax.numpy as jnp
            return np.asarray(grpo.reward_fn(jnp.asarray(generated),
                                             jnp.asarray(targets)))

        refresh_slots = threading.Semaphore(
            self.pcfg.refresh_concurrency)
        producing: collections.Counter = collections.Counter()
        for rank in range(self.pcfg.rollout_replicas):
            engine = ContinuousBatchingEngine(
                cfg=self.model_cfg, params=self.learner.params,
                max_slots=min(p * g, self.max_slots),
                max_len=min(self.model_cfg.max_seq_len,
                            self.prompt_len + self.max_new_tokens + 1))
            worker = RolloutWorker(
                rank, engine, self.queue, self.store, self.pcfg,
                make_wave=make_wave, reward_fn=reward,
                learner_version=lambda: self.learner.version,
                refresh_slots=refresh_slots, producing=producing,
                pull_dest=os.path.join(self.pcfg.store,
                                       f'replica-{rank}'),
                max_new_tokens=self.max_new_tokens,
                temperature=self.temperature)
            worker.publish_wall = self.publish_wall
            self.workers.append(worker)

    # -- learner loop -------------------------------------------------

    def _consume_one(self, timeout: float = 60.0) -> bool:
        batch = self.queue.pop(timeout=timeout)
        if batch is None:
            return False
        m = _metrics()
        try:
            # Chaos BEFORE any state mutation: an injected learner
            # fault must leave the optimizer state untouched and the
            # batch re-consumable.
            fault_injection.inject(LEARN_STEP_SITE)
            consumed_at = self.learner.version
            out = self.learner.learn_rollouts(
                batch.prompts, batch.generated, batch.rewards,
                batch.group_size)
        except BaseException as e:  # noqa: BLE001
            self.learn_faults += 1
            self.queue.requeue(batch)
            m.RL_ROLLOUT_BATCHES.inc(outcome='requeued')
            logger.warning('learner step faulted (%s); batch '
                           'rank=%d seq=%d requeued', e, batch.rank,
                           batch.seq)
            return False
        stale = max(0, consumed_at - batch.policy_version)
        self.staleness.append(stale)
        self.learn_metrics.append(out)
        self.queue.ack(batch)
        info = self.store.publish(self.learner.params,
                                  self.learner.version)
        self.publish_wall[info['version']] = time.monotonic()
        m.RL_ROLLOUT_BATCHES.inc(outcome='consumed')
        m.RL_STALENESS.observe(stale)
        m.RL_LEARNER_VERSION.set(self.learner.version)
        m.RL_QUEUE_DEPTH.set(self.queue.depth())
        return True

    # -- run ----------------------------------------------------------

    def run(self) -> Dict[str, Any]:
        self._build()
        t0 = time.monotonic()
        for worker in self.workers:
            worker.start()
        try:
            consumed = 0
            deadline = time.monotonic() + 600.0
            while consumed < self.steps:
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f'pipeline stalled at step {consumed}')
                if self._consume_one(timeout=5.0):
                    consumed += 1
        finally:
            for worker in self.workers:
                worker.stop()
            for worker in self.workers:
                worker.engine.shutdown()
        elapsed = time.monotonic() - t0
        if self.learner.checkpoint_dir:
            self.learner.save()
        return self.summary(elapsed)

    def summary(self, elapsed: float) -> Dict[str, Any]:
        lat = sorted(x for w in self.workers
                     for x in w.refresh_latencies)

        def pct(p: float) -> float:
            if not lat:
                return 0.0
            return lat[min(len(lat) - 1, int(p * len(lat)))]

        tokens = sum(w.tokens for w in self.workers)
        return {
            'steps': len(self.staleness),
            'elapsed_s': elapsed,
            'rollout_tokens': tokens,
            'rollout_tokens_per_s': tokens / max(elapsed, 1e-9),
            'waves': sum(w.waves for w in self.workers),
            'refreshes': sum(w.refreshes for w in self.workers),
            'refresh_p50_s': pct(0.50),
            'refresh_p99_s': pct(0.99),
            'staleness_max': max(self.staleness, default=0),
            'staleness_mean': (sum(self.staleness)
                               / max(len(self.staleness), 1)),
            'valve_waits': sum(w.valve_waits for w in self.workers),
            'learn_faults': self.learn_faults,
            'batches_produced': self.queue.produced,
            'batches_acked': self.queue.acked,
            'batches_requeued': self.queue.requeued,
            'batches_unretired': self.queue.unretired(),
            'mean_reward_last': (self.learn_metrics[-1]['mean_reward']
                                 if self.learn_metrics else 0.0),
            'worker_errors': sum(len(w.errors) for w in self.workers),
            'refresh_errors': sum(len(w.refresh_errors)
                                  for w in self.workers),
        }


# --------------------------------------------------------------------
# Task-spec expansion: one pipeline task -> a gang-scheduled group
# --------------------------------------------------------------------


def expand_pipeline(task) -> List[Any]:
    """Expand a task carrying a ``pipeline:`` block into the job-group
    members: ``<name>-learner`` plus ``<name>-rollout-<i>``.  Every
    member gets the pipeline knobs as SKYT_RL_* env; rollout members
    are marked ``SKYT_RL_ROLE=rollout`` — the group controller treats
    those as *elastic* members (their failure shrinks the fleet
    instead of gang-cancelling; see ``job_groups.sibling_failed``)."""
    from skypilot_tpu.spec.task import Task
    block = task.pipeline
    assert block, 'expand_pipeline needs a pipeline: block'
    pcfg = PipelineConfig.from_pipeline_block(block)
    base = task.to_yaml_config()
    base.pop('pipeline', None)
    name = task.name or 'rl'
    common = {
        'SKYT_RL_MAX_STALENESS': str(pcfg.max_staleness),
        'SKYT_RL_QUEUE_BATCHES': str(pcfg.queue_batches),
        'SKYT_RL_REFRESH_MODE': pcfg.refresh_mode,
        'SKYT_RL_REFRESH_CONCURRENCY': str(pcfg.refresh_concurrency),
        'SKYT_RL_FLEET': str(pcfg.rollout_replicas),
    }
    if pcfg.store:
        common['SKYT_RL_STORE'] = pcfg.store
    members = []
    learner_cfg = json.loads(json.dumps(base))
    learner_cfg['name'] = f'{name}-learner'
    learner = Task.from_yaml_config(learner_cfg)
    learner.update_envs(dict(common, SKYT_RL_ROLE='learner',
                             SKYT_RL_RANK='0'))
    members.append(learner)
    rollout_run = block.get('rollout_run') or task.run
    for i in range(pcfg.rollout_replicas):
        cfg = json.loads(json.dumps(base))
        cfg['name'] = f'{name}-rollout-{i}'
        if rollout_run:
            cfg['run'] = rollout_run
        member = Task.from_yaml_config(cfg)
        member.update_envs(dict(common, SKYT_RL_ROLE='rollout',
                                SKYT_RL_RANK=str(i)))
        members.append(member)
    return members


def launch_pipeline(task, group_name: Optional[str] = None) -> List[int]:
    """Expand + submit the gang (``jobs.core.launch_group``)."""
    from skypilot_tpu.jobs import core
    members = expand_pipeline(task)
    return core.launch_group(
        members, group_name or f'{task.name or "rl"}-pipeline')


# --------------------------------------------------------------------
# CLI: the recipe entry point for every role
# --------------------------------------------------------------------


def main(argv=None) -> int:
    from skypilot_tpu.utils.jax_env import honor_jax_platforms
    honor_jax_platforms()
    parser = argparse.ArgumentParser(
        description='Live-sync GRPO rollout pipeline')
    parser.add_argument('--role', default=None,
                        choices=(None, 'inprocess', 'learner',
                                 'rollout'),
                        help='Pipeline role; default comes from '
                             'SKYT_RL_ROLE (empty = run learner + '
                             'rollout fleet in-process).')
    parser.add_argument('--model', default='tiny')
    parser.add_argument('--vocab-size', type=int, default=None)
    parser.add_argument('--steps', type=int, default=8)
    parser.add_argument('--prompts-per-step', type=int, default=2)
    parser.add_argument('--group-size', type=int, default=4)
    parser.add_argument('--prompt-len', type=int, default=8)
    parser.add_argument('--max-new-tokens', type=int, default=8)
    parser.add_argument('--temperature', type=float, default=1.0)
    parser.add_argument('--learning-rate', type=float, default=1e-3)
    parser.add_argument('--checkpoint-dir', default=None)
    parser.add_argument('--store', default=None)
    parser.add_argument('--rollout-replicas', type=int, default=None)
    args = parser.parse_args(argv)

    from skypilot_tpu.models.config import get_model_config
    overrides = {}
    if args.vocab_size:
        overrides['vocab_size'] = args.vocab_size
    model_cfg = get_model_config(args.model, **overrides)

    pcfg = PipelineConfig.from_env()
    if args.store:
        pcfg.store = args.store
    if args.rollout_replicas is not None:
        pcfg.rollout_replicas = args.rollout_replicas

    role = args.role or env_registry.get_str('SKYT_RL_ROLE') or \
        'inprocess'
    if role == 'inprocess':
        pipe = RLPipeline(
            model_cfg, pcfg, steps=args.steps,
            prompts_per_step=args.prompts_per_step,
            group_size=args.group_size, prompt_len=args.prompt_len,
            max_new_tokens=args.max_new_tokens,
            temperature=args.temperature,
            learning_rate=args.learning_rate,
            checkpoint_dir=args.checkpoint_dir)
        summary = pipe.run()
        print(json.dumps(summary), flush=True)
        return 0
    if role == 'learner':
        return _run_learner_role(model_cfg, pcfg, args)
    return _run_rollout_role(model_cfg, pcfg, args)


def _run_learner_role(model_cfg, pcfg: PipelineConfig, args) -> int:
    """Distributed learner: consume file-queue batches, publish
    deltas.  The rollout fleet discovers new versions by watching the
    store manifest."""
    from skypilot_tpu.train import grpo
    learner = grpo.GrpoLearner(
        model_cfg, learning_rate=args.learning_rate,
        checkpoint_dir=args.checkpoint_dir)
    store = PolicyStore(pcfg.store)
    queue = FileBatchQueue(pcfg.store, pcfg.queue_batches)
    store.publish(learner.params, learner.version)
    m = _metrics()
    consumed = learner.version
    while consumed < args.steps:
        batch = queue.pop(timeout=300.0)
        if batch is None:
            logger.warning('learner: no rollout batch in 300s; '
                           'exiting at step %d', consumed)
            return 1
        try:
            fault_injection.inject(LEARN_STEP_SITE)
            before = learner.version
            out = learner.learn_rollouts(
                batch.prompts, batch.generated, batch.rewards,
                batch.group_size)
        except BaseException as e:  # noqa: BLE001
            queue.requeue(batch)
            m.RL_ROLLOUT_BATCHES.inc(outcome='requeued')
            logger.warning('learner step faulted (%s); requeued', e)
            continue
        queue.ack(batch)
        store.publish(learner.params, learner.version)
        m.RL_ROLLOUT_BATCHES.inc(outcome='consumed')
        m.RL_STALENESS.observe(max(0, before - batch.policy_version))
        m.RL_LEARNER_VERSION.set(learner.version)
        m.RL_QUEUE_DEPTH.set(queue.depth())
        consumed += 1
        print(json.dumps({'step': consumed, **out}), flush=True)
    learner.save()
    return 0


def _run_rollout_role(model_cfg, pcfg: PipelineConfig, args) -> int:
    """Distributed rollout replica: file-queue producer.  Runs until
    the learner's committed version reaches --steps."""
    import jax
    from skypilot_tpu.inference.continuous import \
        ContinuousBatchingEngine
    from skypilot_tpu.train import grpo
    rank = env_registry.get_int('SKYT_RL_RANK')
    store = PolicyStore(pcfg.store)
    queue = FileBatchQueue(pcfg.store, pcfg.queue_batches)
    # Wait for the learner's first publication — the policy init.
    deadline = time.monotonic() + 300.0
    while store.version() is None:
        if time.monotonic() > deadline:
            raise TimeoutError('no policy published within 300s')
        time.sleep(0.2)
    pulled = store.pull(os.path.join(pcfg.store, f'replica-{rank}'))
    params = _params_from_store(model_cfg, pulled['updates'])
    engine = ContinuousBatchingEngine(
        cfg=model_cfg, params=params,
        max_slots=min(args.prompts_per_step * args.group_size, 8),
        max_len=min(model_cfg.max_seq_len,
                    args.prompt_len + args.max_new_tokens + 1))
    engine.policy_version = pulled['version']
    pool, pool_targets = grpo.make_prompts(
        jax.random.key(42), 64, args.prompt_len,
        model_cfg.vocab_size)
    pool = np.asarray(pool)
    pool_targets = np.asarray(pool_targets)
    p, g = args.prompts_per_step, args.group_size

    def make_wave(worker_rank: int, seq: int):
        idx = ((seq * pcfg.rollout_replicas + worker_rank) * p
               + np.arange(p)) % len(pool)
        return (np.repeat(pool[idx], g, axis=0),
                np.repeat(pool_targets[idx], g), g)

    def reward(generated, targets):
        import jax.numpy as jnp
        return np.asarray(grpo.reward_fn(jnp.asarray(generated),
                                         jnp.asarray(targets)))

    worker = RolloutWorker(
        rank, engine, queue, store, pcfg,
        make_wave=make_wave, reward_fn=reward,
        learner_version=lambda: store.version() or 0,
        refresh_slots=threading.Semaphore(pcfg.refresh_concurrency),
        producing=collections.Counter(),
        pull_dest=os.path.join(pcfg.store, f'replica-{rank}'),
        max_new_tokens=args.max_new_tokens,
        temperature=args.temperature)
    try:
        while (store.version() or 0) < args.steps:
            worker.run_once()
    finally:
        engine.shutdown()
    return 0


def _params_from_store(model_cfg, updates: Dict[str, np.ndarray]):
    """Rebuild a param tree from a full store pull: init the skeleton
    (shapes/dtypes/sharding), then overlay every stored shard."""
    import jax
    from skypilot_tpu.inference.continuous import flatten_param_paths
    from skypilot_tpu.models import llama
    params = llama.init_params(jax.random.key(0), model_cfg)
    flat = flatten_param_paths(params)
    missing = set(flat) - set(updates)
    if missing:
        raise ValueError(f'store pull missing shards: {sorted(missing)}')

    def overlay(tree, prefix=''):
        if isinstance(tree, dict):
            return {k: overlay(v, f'{prefix}{k}/')
                    for k, v in tree.items()}
        if isinstance(tree, (list, tuple)):
            return type(tree)(
                overlay(v, f'{prefix}{i}/')
                for i, v in enumerate(tree))
        import jax.numpy as jnp
        return jnp.asarray(updates[prefix[:-1]], dtype=tree.dtype)

    return overlay(params)


if __name__ == '__main__':
    raise SystemExit(main())
