"""Managed-job log retention (parity: ``sky/jobs/log_gc.py``).

Controller logs accumulate one file per managed job forever (VERDICT r3
missing #7). ``collect()`` prunes logs of jobs that finished more than
the retention window ago — and orphan log files whose job row is gone —
and runs from the server's managed-jobs refresh tick, like the
reference's GC runs from its controller heartbeat.

Retention resolves env > config > default::

    SKYT_JOBS_LOG_RETENTION_HOURS=24          # env override
    jobs:
      log_retention_hours: 24                 # config.yaml

A non-positive retention disables GC (keep everything).
"""
from __future__ import annotations

import os
import re
import time
from typing import Optional

from skypilot_tpu.jobs import state as jobs_state
from skypilot_tpu.utils import env_registry, log

logger = log.init_logger(__name__)

DEFAULT_RETENTION_HOURS = 24 * 7

_LOG_RE = re.compile(r'^controller-(\d+)\.log$')


def retention_seconds() -> float:
    env = env_registry.get_float('SKYT_JOBS_LOG_RETENTION_HOURS',
                                 default=None)
    if env is not None:
        return env * 3600.0
    from skypilot_tpu import config
    hours = config.get_nested(('jobs', 'log_retention_hours'),
                              DEFAULT_RETENTION_HOURS)
    return float(hours) * 3600.0


def _expired(ended_at: Optional[float], cutoff: float) -> bool:
    return ended_at is not None and ended_at < cutoff


def collect(now: Optional[float] = None) -> int:
    """Prune expired controller logs; returns the number removed."""
    retention = retention_seconds()
    if retention <= 0:
        return 0
    now = now if now is not None else time.time()
    cutoff = now - retention
    logs_dir = os.path.join(jobs_state.jobs_dir(), 'logs')
    if not os.path.isdir(logs_dir):
        return 0
    records = {r.job_id: r for r in jobs_state.list_jobs()}
    removed = 0
    for entry in os.listdir(logs_dir):
        m = _LOG_RE.match(entry)
        if m is None:
            continue
        path = os.path.join(logs_dir, entry)
        record = records.get(int(m.group(1)))
        if record is not None:
            # Live/running jobs keep their logs whatever their age.
            if not record.status.is_terminal():
                continue
            if not _expired(record.ended_at, cutoff):
                continue
        else:
            # Orphan (job row deleted): age by file mtime.
            try:
                if os.path.getmtime(path) >= cutoff:
                    continue
            except OSError:
                continue
        try:
            os.remove(path)
            removed += 1
        except OSError as e:
            logger.debug('log GC could not remove %s: %s', path, e)
    if removed:
        logger.info('Managed-job log GC removed %d expired log(s)',
                    removed)
    return removed
