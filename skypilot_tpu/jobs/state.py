"""Managed-job DB: job records + the schedule state machine.

Parity: ``sky/jobs/state.py`` (ManagedJobStatus, ManagedJobScheduleState
:688). Two state axes per job:

* **status** — user-visible lifecycle
  (PENDING → STARTING → RUNNING → {RECOVERING ↔ RUNNING} → terminal).
* **schedule_state** — the scheduler's controller-slot accounting
  (WAITING → LAUNCHING → ALIVE → DONE); LAUNCHING slots are scarce
  (provisioning is heavy), ALIVE slots are cheap (monitor loops).
"""
from __future__ import annotations

import enum
import json
import os
import sqlite3
import threading
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu.utils import common_utils
from skypilot_tpu.utils import events


class ManagedJobStatus(enum.Enum):
    PENDING = 'PENDING'
    STARTING = 'STARTING'
    RUNNING = 'RUNNING'
    RECOVERING = 'RECOVERING'
    SUCCEEDED = 'SUCCEEDED'
    FAILED = 'FAILED'                    # user code failed
    FAILED_SETUP = 'FAILED_SETUP'
    FAILED_NO_RESOURCE = 'FAILED_NO_RESOURCE'
    FAILED_CONTROLLER = 'FAILED_CONTROLLER'
    CANCELLING = 'CANCELLING'
    CANCELLED = 'CANCELLED'

    def is_terminal(self) -> bool:
        return self in (ManagedJobStatus.SUCCEEDED, ManagedJobStatus.FAILED,
                        ManagedJobStatus.FAILED_SETUP,
                        ManagedJobStatus.FAILED_NO_RESOURCE,
                        ManagedJobStatus.FAILED_CONTROLLER,
                        ManagedJobStatus.CANCELLED)


class ScheduleState(enum.Enum):
    WAITING = 'WAITING'
    LAUNCHING = 'LAUNCHING'
    ALIVE = 'ALIVE'
    DONE = 'DONE'


def jobs_dir() -> str:
    return os.path.join(
        os.environ.get('SKYT_STATE_DIR', os.path.expanduser('~/.skyt')),
        'managed_jobs')


def controller_log_path(job_id: int) -> str:
    return os.path.join(jobs_dir(), 'logs', f'controller-{job_id}.log')


def change_signal() -> 'events.ExternalSignal | None':
    """Cross-process change signal for the managed-jobs table (the
    server's jobs-refresh daemon wakes on submits/transitions made by
    request children and controllers)."""
    from skypilot_tpu import state as state_lib
    return events.external_signal(
        state_lib.db_url(), os.path.join(jobs_dir(), 'jobs.db'),
        events.MANAGED_JOBS)


_local = threading.local()


# (url, pid) pairs whose shared-DB schema this process already ensured.
_pg_schema_ready: set = set()


def _db():
    """Per-thread dual-backend connection — same factory as the cluster
    state DB (utils/pg.connect_dual_backend): managed jobs must be
    visible to every API-server replica AND to controllers running off
    the server host (controller-offload mode)."""
    from skypilot_tpu import state as state_lib
    from skypilot_tpu.utils import pg

    def init_schema(conn) -> None:
        from skypilot_tpu.utils import pg as _pg_lib
        _pg_lib.enable_wal(conn)
        conn.executescript("""
            CREATE TABLE IF NOT EXISTS jobs (
                job_id INTEGER PRIMARY KEY AUTOINCREMENT,
                name TEXT,
                task_config TEXT NOT NULL,  -- Task yaml-config JSON
                cluster_name TEXT,
                status TEXT NOT NULL,
                schedule_state TEXT NOT NULL,
                strategy TEXT,
                max_restarts_on_errors INTEGER DEFAULT 0,
                recovery_count INTEGER DEFAULT 0,
                failure_reason TEXT,
                controller_pid INTEGER,
                submitted_at REAL,
                started_at REAL,
                ended_at REAL,
                last_recovered_at REAL,
                group_name TEXT,            -- gang-scheduled job group
                group_hosts TEXT            -- JSON host IPs, published
                                            -- at provision for sibling
                                            -- discovery
            );
            CREATE TABLE IF NOT EXISTS recovery_events (
                id INTEGER PRIMARY KEY AUTOINCREMENT,
                job_id INTEGER NOT NULL,
                ts REAL NOT NULL,
                mode TEXT NOT NULL,         -- launch|relaunch|shrink|grow
                from_slices INTEGER,
                to_slices INTEGER,
                seconds REAL                -- detection -> RUNNING again
            );
        """)
        cols = {r['name'] for r in
                conn.execute('PRAGMA table_info(jobs)')}

        def _add_column(ddl: str) -> None:
            common_utils.add_column_if_missing(conn, ddl)

        # Each column gated independently: DDL autocommits per
        # statement, so a process killed mid-migration can leave any
        # prefix of these applied.
        if 'group_name' not in cols:  # pre-existing older DB
            _add_column('ALTER TABLE jobs ADD COLUMN group_name TEXT')
        if 'group_hosts' not in cols:
            _add_column('ALTER TABLE jobs ADD COLUMN group_hosts TEXT')
        if 'controller_restarts' not in cols:
            _add_column('ALTER TABLE jobs ADD COLUMN '
                        'controller_restarts INTEGER DEFAULT 0')
        if 'workspace' not in cols:
            _add_column("ALTER TABLE jobs ADD COLUMN workspace TEXT "
                        "DEFAULT 'default'")
        if 'controller_claimed_at' not in cols:
            _add_column('ALTER TABLE jobs ADD COLUMN '
                        'controller_claimed_at REAL')
        if 'controller_cluster' not in cols:
            # Controller-offload mode: which cluster hosts this job's
            # controller (NULL = a local process on the server).
            _add_column('ALTER TABLE jobs ADD COLUMN '
                        'controller_cluster TEXT')
        if 'elastic' not in cols:
            # JSON elastic spec ({min_slices, max_slices, ...}); NULL =
            # rigid world size (always full relaunch on preemption).
            _add_column('ALTER TABLE jobs ADD COLUMN elastic TEXT')
        if 'current_slices' not in cols:
            # Current gang topology (slices actually running); the
            # world-size HISTORY is the recovery_events table.
            _add_column('ALTER TABLE jobs ADD COLUMN '
                        'current_slices INTEGER')
        conn.commit()

    os.makedirs(jobs_dir(), exist_ok=True)
    return pg.connect_dual_backend(
        _local, _pg_schema_ready, url=state_lib.db_url(),
        sqlite_path=os.path.join(jobs_dir(), 'jobs.db'),
        init_schema=init_schema)


class JobRecord:
    def __init__(self, row: sqlite3.Row) -> None:
        self.job_id: int = row['job_id']
        self.name: Optional[str] = row['name']
        self.task_config: Dict[str, Any] = json.loads(row['task_config'])
        self.cluster_name: Optional[str] = row['cluster_name']
        self.status = ManagedJobStatus(row['status'])
        self.schedule_state = ScheduleState(row['schedule_state'])
        self.strategy: str = row['strategy'] or 'FAILOVER'
        self.max_restarts_on_errors: int = row['max_restarts_on_errors']
        self.recovery_count: int = row['recovery_count']
        self.failure_reason: Optional[str] = row['failure_reason']
        self.controller_pid: Optional[int] = row['controller_pid']
        self.submitted_at: Optional[float] = row['submitted_at']
        self.started_at: Optional[float] = row['started_at']
        self.ended_at: Optional[float] = row['ended_at']
        self.last_recovered_at: Optional[float] = row['last_recovered_at']
        self.group_name: Optional[str] = row['group_name']
        self.group_hosts: List[str] = json.loads(row['group_hosts'] or
                                                 '[]')
        self.controller_restarts: int = row['controller_restarts'] or 0
        self.workspace: str = row['workspace'] or 'default'
        self.controller_claimed_at: Optional[float] = (
            row['controller_claimed_at'])
        self.controller_cluster: Optional[str] = row['controller_cluster']
        self.elastic: Optional[Dict[str, Any]] = (
            json.loads(row['elastic']) if row['elastic'] else None)
        self.current_slices: Optional[int] = row['current_slices']

    def to_dict(self) -> Dict[str, Any]:
        return {
            'job_id': self.job_id,
            'name': self.name,
            'cluster_name': self.cluster_name,
            'status': self.status.value,
            'schedule_state': self.schedule_state.value,
            'strategy': self.strategy,
            'recovery_count': self.recovery_count,
            'failure_reason': self.failure_reason,
            'submitted_at': self.submitted_at,
            'started_at': self.started_at,
            'ended_at': self.ended_at,
            'group_name': self.group_name,
            'elastic': self.elastic,
            'current_slices': self.current_slices,
        }


def submit(task_config: Dict[str, Any],
           name: Optional[str],
           strategy: str,
           max_restarts_on_errors: int,
           group_name: Optional[str] = None,
           elastic: Optional[Dict[str, Any]] = None) -> int:
    # The submitter's workspace is PERSISTED: controllers (and their HA
    # replacements, spawned later by arbitrary processes) must run in
    # the job's workspace, not the spawner's.
    from skypilot_tpu import workspaces
    conn = _db()
    sql = ('INSERT INTO jobs (name, task_config, status, schedule_state, '
           'strategy, max_restarts_on_errors, submitted_at, group_name, '
           'workspace, elastic) VALUES (?, ?, ?, ?, ?, ?, ?, ?, ?, ?)')
    params = (name, json.dumps(task_config),
              ManagedJobStatus.PENDING.value, ScheduleState.WAITING.value,
              strategy, max_restarts_on_errors, time.time(), group_name,
              workspaces.active_workspace(),
              json.dumps(elastic) if elastic else None)
    if getattr(conn, 'is_postgres', False):
        job_id = conn.insert_returning(sql, params, 'job_id')
    else:
        cur = conn.execute(sql, params)
        conn.commit()
        job_id = cur.lastrowid
    # Wake the server's managed-jobs daemon (another process): the
    # WAITING job is claimed within milliseconds instead of the
    # jobs_refresh_interval.
    events.publish(events.MANAGED_JOBS, conn=conn)
    return job_id


def list_group(group_name: str) -> List['JobRecord']:
    rows = _db().execute(
        'SELECT * FROM jobs WHERE group_name = ? ORDER BY job_id',
        (group_name,)).fetchall()
    return [JobRecord(r) for r in rows]


def set_group_hosts(job_id: int, hosts: List[str]) -> None:
    conn = _db()
    conn.execute('UPDATE jobs SET group_hosts = ? WHERE job_id = ?',
                 (json.dumps(hosts), job_id))
    conn.commit()


def get(job_id: int) -> Optional[JobRecord]:
    row = _db().execute('SELECT * FROM jobs WHERE job_id = ?',
                        (job_id,)).fetchone()
    return JobRecord(row) if row else None


def list_jobs(skip_finished: bool = False) -> List[JobRecord]:
    rows = _db().execute(
        'SELECT * FROM jobs ORDER BY job_id DESC').fetchall()
    records = [JobRecord(r) for r in rows]
    if skip_finished:
        records = [r for r in records if not r.status.is_terminal()]
    return records


def set_status(job_id: int,
               status: ManagedJobStatus,
               failure_reason: Optional[str] = None) -> bool:
    """Guarded status write: terminal states are never overwritten, and a
    pending CANCELLING is only ever resolved to a terminal state — a
    controller transitioning to RUNNING must not swallow a concurrent
    cancel (first-writer-wins, same discipline as requests_db.finalize)."""
    conn = _db()
    sets = ['status = ?']
    args: List[Any] = [status.value]
    if failure_reason is not None:
        sets.append('failure_reason = ?')
        args.append(failure_reason)
    if status == ManagedJobStatus.RUNNING:
        sets.append('started_at = COALESCE(started_at, ?)')
        args.append(time.time())
    if status.is_terminal():
        sets.append('ended_at = ?')
        args.append(time.time())
    args.append(job_id)
    blocked = [s.value for s in ManagedJobStatus if s.is_terminal()]
    if not status.is_terminal():
        blocked.append(ManagedJobStatus.CANCELLING.value)
    placeholders = ','.join('?' * len(blocked))
    cur = conn.execute(
        f'UPDATE jobs SET {", ".join(sets)} WHERE job_id = ? '
        f'AND status NOT IN ({placeholders})', args + blocked)
    conn.commit()
    if cur.rowcount == 1:
        events.publish(events.MANAGED_JOBS, conn=conn)
    return cur.rowcount == 1


def request_cancel(job_id: int) -> bool:
    """CANCELLING if non-terminal; returns False if already terminal.

    The guarded UPDATE makes cancel-vs-finish a first-writer-wins race,
    same discipline as requests_db.finalize."""
    conn = _db()
    terminal = [s.value for s in ManagedJobStatus if s.is_terminal()]
    placeholders = ','.join('?' * len(terminal))
    cur = conn.execute(
        f'UPDATE jobs SET status = ? WHERE job_id = ? '
        f'AND status NOT IN ({placeholders}) AND status != ?',
        [ManagedJobStatus.CANCELLING.value, job_id] + terminal +
        [ManagedJobStatus.CANCELLING.value])
    conn.commit()
    if cur.rowcount == 1:
        # The controller's cancel check must see this promptly.
        events.publish(events.MANAGED_JOBS, conn=conn)
    return cur.rowcount == 1


def cancel_requested(job_id: int) -> bool:
    record = get(job_id)
    return record is not None and record.status in (
        ManagedJobStatus.CANCELLING, ManagedJobStatus.CANCELLED)


def set_schedule_state(job_id: int, schedule_state: ScheduleState) -> None:
    conn = _db()
    conn.execute('UPDATE jobs SET schedule_state = ? WHERE job_id = ?',
                 (schedule_state.value, job_id))
    conn.commit()
    events.publish(events.MANAGED_JOBS, conn=conn)


def claim_waiting_job(max_launching: int, max_alive: int) -> Optional[int]:
    """Atomically move the oldest WAITING job to LAUNCHING if slots allow
    (parity: the jobs scheduler's single-transaction claim,
    jobs/scheduler.py:29-33)."""
    conn = _db()
    is_pg = getattr(conn, 'is_postgres', False)
    with _claim_lock:
        # Schedulers run in many processes (API-server workers and every
        # controller); BEGIN IMMEDIATE takes the write lock up front so
        # count-then-claim is atomic across processes, not just threads.
        # On the shared-Postgres backend the atomicity comes from an
        # advisory lock on THIS connection instead (replicas on other
        # machines also claim; session locks are transaction-independent
        # and cost no extra connection handshake).
        locked = False
        try:
            if is_pg:
                while True:
                    got = conn.execute(
                        f'SELECT pg_try_advisory_lock({_CLAIM_LOCK_KEY})'
                        ' AS ok').fetchone()['ok']
                    if got is True or got == 't':
                        locked = True
                        break
                    time.sleep(0.05)
            conn.commit()
            conn.execute('BEGIN IMMEDIATE')
            try:
                launching = conn.execute(
                    'SELECT COUNT(*) FROM jobs WHERE schedule_state = ?',
                    (ScheduleState.LAUNCHING.value,)).fetchone()[0]
                alive = conn.execute(
                    'SELECT COUNT(*) FROM jobs WHERE schedule_state '
                    'IN (?, ?)',
                    (ScheduleState.LAUNCHING.value,
                     ScheduleState.ALIVE.value)).fetchone()[0]
                if launching >= max_launching or alive >= max_alive:
                    conn.rollback()
                    return None
                row = conn.execute(
                    'SELECT job_id FROM jobs WHERE schedule_state = ? '
                    'ORDER BY job_id LIMIT 1',
                    (ScheduleState.WAITING.value,)).fetchone()
                if row is None:
                    conn.rollback()
                    return None
                cur = conn.execute(
                    'UPDATE jobs SET schedule_state = ? WHERE job_id = ? '
                    'AND schedule_state = ?',
                    (ScheduleState.LAUNCHING.value, row['job_id'],
                     ScheduleState.WAITING.value))
                if cur.rowcount != 1:
                    conn.rollback()
                    return None
                conn.commit()
                return row['job_id']
            except Exception:
                # Roll back on ANY failure — a PG error would otherwise
                # leave this thread's cached connection wedged in an
                # aborted transaction (every later call fails).
                conn.rollback()
                raise
        finally:
            if locked:
                try:
                    conn.execute('SELECT pg_advisory_unlock'
                                 f'({_CLAIM_LOCK_KEY})')
                except Exception:  # pylint: disable=broad-except
                    pass  # session death releases it server-side


_claim_lock = threading.Lock()
# Stable 64-bit advisory-lock key for the cross-replica claim section
# (= int.from_bytes(sha256(b'jobs-scheduler-claim')[:8], signed)).
_CLAIM_LOCK_KEY = 2766150969836407153


def set_controller_pid(job_id: int, pid: int,
                       controller_cluster: Optional[str] = None) -> None:
    """Record where this job's controller runs: a local pid
    (controller_cluster None) or a job id ON the named controller
    cluster (offload mode)."""
    conn = _db()
    conn.execute(
        'UPDATE jobs SET controller_pid = ?, controller_cluster = ? '
        'WHERE job_id = ?', (pid, controller_cluster, job_id))
    conn.commit()


def set_cluster_name(job_id: int, cluster_name: str) -> None:
    conn = _db()
    conn.execute('UPDATE jobs SET cluster_name = ? WHERE job_id = ?',
                 (cluster_name, job_id))
    conn.commit()


def claim_controller_restart(job_id: int, dead_pid: int,
                             max_restarts: int) -> bool:
    """Atomically claim the right to spawn a replacement controller.

    Multiple processes observe dead controllers concurrently (every
    queue inspection + the server daemon); the conditional UPDATE on the
    observed pid makes exactly one of them the spawner.
    """
    conn = _db()
    cur = conn.execute(
        'UPDATE jobs SET controller_restarts = controller_restarts + 1, '
        'controller_pid = NULL, controller_claimed_at = ? '
        'WHERE job_id = ? AND controller_pid = ? '
        'AND controller_restarts < ?',
        (time.time(), job_id, dead_pid, max_restarts))
    conn.commit()
    return cur.rowcount == 1


def reclaim_stale_controller_claim(job_id: int,
                                   stale_after: float = 30.0) -> bool:
    """Claim a job whose previous claimant died between NULLing the pid
    and spawning the replacement (the claim-window orphan). Atomic: the
    conditional UPDATE on (pid IS NULL, old claim time) lets exactly one
    caller through.

    Deliberately WALL clock on both sides (skylint SKYT009's
    persisted-timestamp exemption): ``controller_claimed_at`` is
    written by one process and judged by another, so a monotonic
    reading would be meaningless — staleness here must ride the
    shared wall clock, same as the server heartbeat table."""
    conn = _db()
    cur = conn.execute(
        'UPDATE jobs SET controller_claimed_at = ? '
        'WHERE job_id = ? AND controller_pid IS NULL '
        'AND controller_claimed_at IS NOT NULL '
        'AND controller_claimed_at < ?',
        (time.time(), job_id, time.time() - stale_after))
    conn.commit()
    return cur.rowcount == 1


def bump_recovery(job_id: int) -> None:
    conn = _db()
    conn.execute(
        'UPDATE jobs SET recovery_count = recovery_count + 1, '
        'last_recovered_at = ? WHERE job_id = ?', (time.time(), job_id))
    conn.commit()


# -- elastic topology bookkeeping ---------------------------------------


def set_current_slices(job_id: int, slices: int) -> None:
    """Record the gang's live topology (shrunken or full)."""
    conn = _db()
    conn.execute('UPDATE jobs SET current_slices = ? WHERE job_id = ?',
                 (slices, job_id))
    conn.commit()
    events.publish(events.MANAGED_JOBS, conn=conn)


def record_recovery(job_id: int,
                    mode: str,
                    from_slices: Optional[int],
                    to_slices: Optional[int],
                    seconds: Optional[float] = None) -> None:
    """Append one world-size transition to the job's topology history.

    ``mode``: launch (initial topology), relaunch (rigid full recovery),
    shrink (elastic degrade to surviving slices), grow (elastic
    re-expansion). ``seconds`` is detection→RUNNING-again; /api/metrics
    derives skyt_job_recoveries_total and skyt_job_resize_seconds from
    these rows (controllers run out-of-process, so the DB is the only
    durable metrics source)."""
    conn = _db()
    conn.execute(
        'INSERT INTO recovery_events (job_id, ts, mode, from_slices, '
        'to_slices, seconds) VALUES (?, ?, ?, ?, ?, ?)',
        (job_id, time.time(), mode, from_slices, to_slices, seconds))
    conn.commit()
    events.publish(events.MANAGED_JOBS, conn=conn)


def recovery_events(job_id: Optional[int] = None,
                    after_id: int = 0) -> List[Dict[str, Any]]:
    """World-size history, oldest first (one job or all jobs).

    ``after_id`` returns only rows past that event id — the append-only
    table grows for the deployment's lifetime, so incremental consumers
    (/api/metrics) page from their cursor instead of re-reading it all.
    """
    conn = _db()
    if job_id is None:
        rows = conn.execute(
            'SELECT * FROM recovery_events WHERE id > ? ORDER BY id',
            (after_id,)).fetchall()
    else:
        rows = conn.execute(
            'SELECT * FROM recovery_events WHERE job_id = ? AND id > ? '
            'ORDER BY id', (job_id, after_id)).fetchall()
    return [{
        'id': r['id'],
        'job_id': r['job_id'],
        'ts': r['ts'],
        'mode': r['mode'],
        'from_slices': r['from_slices'],
        'to_slices': r['to_slices'],
        'seconds': r['seconds'],
    } for r in rows]
