"""Recovery strategies: how a managed job relaunches after preemption.

Parity: ``sky/jobs/recovery_strategy.py`` (StrategyExecutor :75,
FailoverStrategyExecutor :842, EagerFailoverStrategyExecutor :963),
registered in JOBS_RECOVERY_STRATEGY_REGISTRY (sky/__init__.py:146).

TPU semantics: a preempted spot pod slice disappears as a unit, so
"recover" is always teardown + full relaunch; the job then resumes from
its GCS checkpoint (the checkpoint-resume pattern, SURVEY.md §5). The
two strategies differ only in *where* they retry first:

* FAILOVER — retry the same region first (capacity often returns within
  minutes), then widen with the preempted zone blocklisted.
* EAGER_NEXT_REGION — blocklist the whole region immediately (cross-region
  stockouts are correlated for TPU pods; eagerly pay the egress).
"""
from __future__ import annotations

import os
import time
from typing import Dict, List, Optional, Tuple

from skypilot_tpu import exceptions, execution, state
from skypilot_tpu.backend.tpu_backend import TpuPodBackend
from skypilot_tpu.provision.api import (ClusterInfo, HostInfo,
                                        ProvisionRequest, get_provider)
from skypilot_tpu.provision.provisioner import Blocklist
from skypilot_tpu.spec.task import Task
from skypilot_tpu.utils import common_utils
from skypilot_tpu.utils import env_registry
from skypilot_tpu.utils import fault_injection
from skypilot_tpu.utils import log
from skypilot_tpu.utils import resilience
from skypilot_tpu.utils.registry import JOBS_RECOVERY_STRATEGY_REGISTRY

logger = log.init_logger(__name__)

# Initial-launch retry cadence on full stockout. Env > per-task config
# (`config: {jobs: {launch_retry_gap: N}}`) > global config > default
# (the reference backs off up to RETRY_INIT_GAP_SECONDS=60).


def _record_slices(job_id: int, slices: int) -> None:
    """Durable world-size bookkeeping AFTER the gang is already running:
    retried briefly, then logged and dropped — a transient DB blip must
    not bubble out of a recover()/resize that already succeeded (the
    controller would re-run it, tearing down the just-launched payload;
    the next resize re-derives the census from the provider anyway)."""
    from skypilot_tpu.jobs import state as jobs_state
    try:
        resilience.call_with_retry(
            lambda: jobs_state.set_current_slices(job_id, slices),
            deadline=5.0, what='set_current_slices')
    except Exception as e:  # pylint: disable=broad-except
        logger.warning(
            'Job %s: failed to record current_slices=%d (%s: %s); '
            'continuing with the gang up.', job_id, slices,
            type(e).__name__, e)


def _retry_gap(task: Task) -> float:
    env = env_registry.get_float('SKYT_JOBS_LAUNCH_RETRY_GAP')
    if env is not None:
        return env
    from skypilot_tpu import config
    return float(config.get_nested(
        ('jobs', 'launch_retry_gap'), 20,
        override_configs=task.config_overrides))


def _max_retries(task: Task) -> int:
    env = env_registry.get_int('SKYT_JOBS_MAX_LAUNCH_RETRIES')
    if env is not None:
        return env
    from skypilot_tpu import config
    return int(config.get_nested(
        ('jobs', 'max_launch_retries'), 30,
        override_configs=task.config_overrides))


class StrategyExecutor:
    """Drives launch/recover for one managed job (ref :75)."""

    # ElasticStrategy overrides to True; the controller branches on it
    # for resize bookkeeping, grow-back, and current-topology exec.
    is_elastic = False

    def __init__(self, job_id: int, task: Task, cluster_name: str) -> None:
        self.job_id = job_id
        self.task = task
        self.cluster_name = cluster_name
        self.backend = TpuPodBackend()
        self.blocklist = Blocklist()
        # Gang groups provision first and exec after the group barrier
        # (jobs/job_groups.py): the controller narrows the stages for
        # the initial launch, then resets to None (full launch) for
        # recoveries.
        self.launch_stages = None

    @classmethod
    def make(cls, strategy: Optional[str], job_id: int, task: Task,
             cluster_name: str) -> 'StrategyExecutor':
        name = (strategy or 'FAILOVER').upper()
        strategy_cls = JOBS_RECOVERY_STRATEGY_REGISTRY.get(name)
        return strategy_cls(job_id, task, cluster_name)

    # ------------------------------------------------------------------

    def launch(self) -> int:
        """Initial launch: retry on stockout with a gap until resources
        appear (parity: StrategyExecutor._launch retry loop)."""
        return self._launch_with_retries(self.blocklist)

    def recover(self) -> int:
        """Relaunch after preemption/failure. Returns the new cluster job
        id. Subclasses choose the blocklist seeding."""
        raise NotImplementedError

    def _relaunch_once(self, blocklist: Blocklist) -> Optional[int]:
        """One launch attempt with the given blocklist (no retry loop)."""
        fault_injection.inject('jobs.recovery.launch')
        results = execution.launch(self.task,
                                   self.cluster_name,
                                   detach_run=True,
                                   backend=self.backend,
                                   provision_blocklist=blocklist,
                                   stages=self.launch_stages)
        job_id = results[0][1]
        from skypilot_tpu.execution import Stage
        if self.launch_stages is None or Stage.EXEC in self.launch_stages:
            assert job_id is not None
        return job_id

    # ------------------------------------------------------------------

    def _current_location(self) -> Optional[Tuple[str, str, Optional[str]]]:
        record = state.get_cluster(self.cluster_name)
        if record is None or record.cloud is None:
            return None
        return (record.cloud, record.region, record.zone)

    def _teardown(self) -> None:
        try:
            self.backend.teardown(self.cluster_name, terminate=True)
        except exceptions.ClusterDoesNotExist:
            pass
        except Exception as e:  # pylint: disable=broad-except
            logger.warning('Teardown of %s failed: %s', self.cluster_name,
                           e)
            # The cloud may have already reclaimed it (preemption).
            state.remove_cluster(self.cluster_name)

    def _launch_with_retries(self, blocklist: Blocklist) -> int:
        gap = _retry_gap(self.task)
        max_retries = _max_retries(self.task)
        backoff = common_utils.Backoff(gap, gap * 10)
        for attempt in range(max_retries):
            try:
                return self._relaunch_once(blocklist)
            except exceptions.ResourcesUnavailableError as e:
                logger.info(
                    'Job %s: no resources anywhere (attempt %d/%d): %s',
                    self.job_id, attempt + 1, max_retries, e)
                # Full stockout: clear location blocklists (stockouts are
                # transient) and wait for capacity.
                blocklist.zones.clear()
                blocklist.regions.clear()
            except resilience.transient_db_errors() as e:
                # Infra blips (DB contention, provider API resets, the
                # jobs.recovery.launch chaos site) spend the same retry
                # budget; blocklists stay — the locations weren't probed.
                logger.warning(
                    'Job %s: transient launch failure (attempt %d/%d): '
                    '%s', self.job_id, attempt + 1, max_retries, e)
            if attempt + 1 < max_retries:
                # No sleep after the FINAL failure: the raise below is
                # imminent and a trailing backoff (up to gap*10 s) would
                # only delay the FAILED_NO_RESOURCE verdict.
                time.sleep(backoff.current_backoff())
        raise exceptions.ResourcesUnavailableError(
            f'Managed job {self.job_id}: exhausted {max_retries} '
            'launch attempts across all locations.')


@JOBS_RECOVERY_STRATEGY_REGISTRY.register('FAILOVER')
class FailoverStrategy(StrategyExecutor):
    """Retry the same region first, then fail over (ref :842)."""

    def recover(self) -> int:
        location = self._current_location()
        self._teardown()
        widened = Blocklist()
        if location is not None:
            cloud, region, zone = location
            # First pass: pin to the previous region (cheap, data local).
            pinned = Blocklist()
            pinned.regions.update(
                {(cloud, r)
                 for r in _other_regions(self.task, cloud, region)})
            try:
                return self._relaunch_once(pinned)
            except exceptions.ResourcesUnavailableError:
                logger.info('Job %s: previous region %s has no capacity; '
                            'widening failover.', self.job_id, region)
            # Widened pass: everywhere except the just-preempted zone
            # (its capacity was literally just reclaimed).
            if zone is not None:
                widened.zones.add((cloud, zone))
        return self._launch_with_retries(widened)


@JOBS_RECOVERY_STRATEGY_REGISTRY.register('EAGER_NEXT_REGION')
class EagerNextRegionStrategy(StrategyExecutor):
    """Blocklist the preempted region immediately (ref :963)."""

    def recover(self) -> int:
        location = self._current_location()
        self._teardown()
        blocklist = Blocklist()
        if location is not None:
            cloud, region, _zone = location
            blocklist.regions.add((cloud, region))
        try:
            return self._relaunch_once(blocklist)
        except exceptions.ResourcesUnavailableError:
            # Every other region is out too; allow the original again.
            return self._launch_with_retries(Blocklist())


@JOBS_RECOVERY_STRATEGY_REGISTRY.register('ELASTIC')
class ElasticStrategy(FailoverStrategy):
    """Elastic world-size recovery for gang-scheduled multi-slice jobs.

    On preemption of a strict subset of the gang's pod slices, shrink to
    the surviving slices (teardown only the dead slice, keep the gang)
    and resume from the latest checkpoint at the new topology — roughly
    one checkpoint-restore of downtime instead of a full teardown +
    re-provision + wait-for-full-capacity (the Bamboo/Oobleck result,
    ISSUE 6). A grow-back watcher (driven by the controller loop)
    re-expands to ``max_slices`` once the optimizer finds capacity on
    the gang's placement again. Falls back to the FAILOVER relaunch when
    fewer than ``min_slices`` survive, when the provider lacks the
    trim/grow capability, or when anything in the shrink path fails.
    """

    is_elastic = True

    def __init__(self, job_id: int, task: Task, cluster_name: str) -> None:
        super().__init__(job_id, task, cluster_name)
        spec = task.elastic or {}
        resources = task.resources[0] if task.resources else None
        full = (resources.num_slices
                if resources is not None and resources.is_tpu else 1)
        self.full_slices = int(spec.get('max_slices', full) or full)
        self.min_slices = int(spec.get('min_slices', 1))
        self.drain_seconds = float(spec.get('drain_seconds', 30.0))
        self.grow_check_seconds = float(
            spec.get('grow_check_seconds', 30.0))
        # The cluster job the gang is currently running — set by the
        # controller before recover()/try_grow() so the old gang can be
        # cancelled (shrink) or drained at a step boundary (grow).
        self.prev_cluster_job_id: Optional[int] = None
        # What the last recover()/try_grow() actually did, for the
        # controller's recovery_events row (metrics + history).
        self.last_mode: Optional[str] = None
        self.last_from_slices: Optional[int] = None
        self.last_to_slices: Optional[int] = None
        # The INITIAL launch runs at the full world size; exporting the
        # elastic envs from the start means the payload resolves its
        # mesh the same way on every incarnation (full, shrunken,
        # grown-back) and watches the resize signal from step one.
        task.update_envs(self.elastic_envs(self.full_slices))

    # -- topology census -----------------------------------------------

    def _hosts_per_slice(self) -> int:
        resources = self.task.resources[0] if self.task.resources else None
        if resources is not None and resources.is_tpu:
            return resources.tpu.hosts_per_slice
        return 1

    def current_slices(self) -> int:
        from skypilot_tpu.jobs import state as jobs_state
        record = jobs_state.get(self.job_id)
        if record is not None and record.current_slices:
            return record.current_slices
        return self.full_slices

    def resize_signal_path(self) -> str:
        """Step-boundary resize handshake file: the controller touches
        it, the payload checkpoints and exits at its next step boundary
        (SKYT_RESIZE_SIGNAL env contract, docs/elastic_training.md)."""
        from skypilot_tpu.jobs import state as jobs_state
        return os.path.join(jobs_state.jobs_dir(),
                            f'resize-{self.job_id}.signal')

    def elastic_envs(self, slices: int) -> Dict[str, str]:
        return {
            'SKYT_ELASTIC': '1',
            'SKYT_ELASTIC_SLICES': str(slices),
            'SKYT_RESIZE_SIGNAL': self.resize_signal_path(),
        }

    def _slice_census(self) -> Optional[Tuple[List[int],
                                              Dict[int, List[HostInfo]],
                                              'state.ClusterRecord']]:
        """(surviving slice ids, slice->hosts, cluster record) from the
        provider's instance states; None when the cluster is gone or the
        provider is unreachable (both mean: full relaunch)."""
        record = state.get_cluster(self.cluster_name)
        if record is None or record.cloud is None or not record.handle:
            return None
        try:
            states = get_provider(record.cloud).query_instances(
                self.cluster_name)
        except Exception:  # pylint: disable=broad-except
            return None
        if not states:
            return None
        info = ClusterInfo.from_dict(record.handle)
        per_slice = self._hosts_per_slice()
        slices: Dict[int, List[HostInfo]] = {}
        for host in info.hosts:
            slices.setdefault(host.worker_index // per_slice,
                              []).append(host)
        surviving = [
            sid for sid, hosts in sorted(slices.items())
            if all(states.get(h.instance_id) == 'running' for h in hosts)
        ]
        return surviving, slices, record

    def exec_task(self) -> Task:
        """The task to (re-)execute at the gang's CURRENT topology.

        A restart-in-place (user-code failure, max_restarts_on_errors)
        on a shrunken gang must not run the full-size task: its envs say
        SKYT_ELASTIC_SLICES=full and its mesh would not fit the
        surviving slices' devices."""
        current = self.current_slices()
        if current >= self.full_slices:
            return self.task
        task, _ = self._resized_task(current)
        return task

    def clear_resize_signal(self) -> None:
        """Remove a leftover resize-signal file. A controller that died
        between writing the signal and its finally-removal must not make
        every later payload incarnation checkpoint and exit 0 at its
        first step boundary (which would finalize a half-trained job as
        SUCCEEDED)."""
        try:
            os.remove(self.resize_signal_path())
        except OSError:
            pass

    def launch(self) -> int:
        self.clear_resize_signal()
        return super().launch()

    def _resized_task(self, slices: int) -> Tuple[Task, 'object']:
        """A derived exec task at the given topology. The elastic block
        is dropped (it describes the FULL job, and would fail validation
        against the shrunken resources); SKYT_ELASTIC_* envs carry the
        degraded world size to the payload instead."""
        config = self.task.to_yaml_config()
        config.pop('elastic', None)
        task = Task.from_yaml_config(config)
        resources = task.resources[0]
        if resources.is_tpu:
            resources = resources.copy(num_slices=slices)
            task.set_resources(resources)
        task.update_envs(self.elastic_envs(slices))
        return task, resources

    # -- recover: shrink if possible, else relaunch ----------------------

    def recover(self) -> int:
        from_slices = self.current_slices()
        self.last_mode = 'relaunch'
        self.last_from_slices = from_slices
        self.last_to_slices = self.full_slices
        census = self._slice_census()
        if census is not None:
            surviving, slices, record = census
            if (surviving and len(surviving) < from_slices and
                    len(surviving) >= self.min_slices):
                try:
                    return self._shrink(surviving, slices, record)
                except Exception as e:  # pylint: disable=broad-except
                    logger.warning(
                        'Job %s: elastic shrink to %d slices failed '
                        '(%s: %s); falling back to full relaunch.',
                        self.job_id, len(surviving), type(e).__name__, e)
            elif surviving and len(surviving) < self.min_slices:
                logger.info(
                    'Job %s: only %d/%d slices survive (< min_slices '
                    '%d); full relaunch.', self.job_id, len(surviving),
                    from_slices, self.min_slices)
        job_id = super().recover()
        # A full relaunch restores the full gang.
        self.last_mode = 'relaunch'
        self.last_to_slices = self.full_slices
        _record_slices(self.job_id, self.full_slices)
        return job_id

    def _shrink(self, surviving: List[int],
                slices: Dict[int, List[HostInfo]], record) -> int:
        provider = get_provider(record.cloud)
        old_info = ClusterInfo.from_dict(record.handle)
        # Stop the survivors' ranks first: they are blocked on dead DCN
        # peers and must not keep running when the world re-forms.
        if self.prev_cluster_job_id is not None:
            try:
                self.backend.cancel(old_info, self.prev_cluster_job_id)
            except Exception:  # pylint: disable=broad-except
                pass
        keep = [h.instance_id for sid in surviving for h in slices[sid]]
        # Teardown ONLY the dead slice (raises NotImplementedError on
        # providers without the capability -> caller relaunches fully).
        provider.trim_instances(self.cluster_name, keep)
        new_info = provider.get_cluster_info(self.cluster_name)
        if new_info is None:
            raise exceptions.ClusterNotUpError(
                f'{self.cluster_name} vanished during elastic trim')
        to_slices = len(surviving)
        task, resources = self._resized_task(to_slices)
        state.add_or_update_cluster(
            self.cluster_name,
            status=state.ClusterStatus.UP,
            resources=resources.to_yaml_config(),
            handle=new_info.to_dict())
        state.add_cluster_event(
            self.cluster_name, 'ELASTIC_SHRINK',
            f'{self.last_from_slices}->{to_slices} slices')
        cluster_job_id = self.backend.execute(new_info, task, detach=True)
        _record_slices(self.job_id, to_slices)
        self.last_mode = 'shrink'
        self.last_to_slices = to_slices
        logger.info(
            'Job %s: shrank gang %d -> %d slices; resumed as cluster '
            'job %s from the latest checkpoint.', self.job_id,
            self.last_from_slices, to_slices, cluster_job_id)
        return cluster_job_id

    # -- grow-back watcher (driven by the controller loop) ---------------

    def try_grow(self) -> Optional[int]:
        """Re-expand a shrunken gang to ``full_slices`` if capacity is
        back; returns the new cluster job id, or None (quietly) while
        capacity is still short. The running shrunken job is drained at
        a step boundary via the resize-signal handshake first."""
        from_slices = self.current_slices()
        if from_slices >= self.full_slices:
            return None
        record = state.get_cluster(self.cluster_name)
        if record is None or record.cloud is None or not record.handle:
            return None
        full_task, full_resources = self._resized_task(self.full_slices)
        # DCN-aware placement gate: the joint optimizer must still rank
        # the gang's current (cloud, region) feasible at FULL size —
        # slices of one gang ride DCN within a locality; growing onto a
        # different region would be a different job.
        try:
            from skypilot_tpu.optimizer import Optimizer
            candidates = Optimizer.plan_task(full_task)
        except Exception:  # pylint: disable=broad-except
            return None
        if not any(c.resources.cloud == record.cloud and
                   c.resources.region == record.region
                   for c in candidates):
            return None
        launchable = full_resources.copy(
            cloud=record.cloud, region=record.region, zone=record.zone)
        request = ProvisionRequest(
            cluster_name=self.cluster_name,
            resources=launchable,
            num_nodes=self.task.num_nodes,
            region=record.region,
            zone=record.zone)
        provider = get_provider(record.cloud)
        try:
            new_info = provider.grow_instances(request)
        except NotImplementedError:
            return None
        except (exceptions.CapacityError,
                exceptions.QuotaExceededError) as e:
            logger.debug('Job %s: grow-back still blocked: %s',
                         self.job_id, e)
            return None
        # Capacity secured BEFORE pausing the shrunken gang: drain at a
        # step boundary, then restart at the full topology (full_task
        # already carries the full-size SKYT_ELASTIC_* envs from
        # _resized_task).
        self._drain_at_step_boundary(ClusterInfo.from_dict(record.handle))
        state.add_or_update_cluster(
            self.cluster_name,
            status=state.ClusterStatus.UP,
            resources=launchable.to_yaml_config(),
            handle=new_info.to_dict())
        state.add_cluster_event(
            self.cluster_name, 'ELASTIC_GROW',
            f'{from_slices}->{self.full_slices} slices')
        try:
            from skypilot_tpu.backend import runtime_setup
            runtime_setup.ensure_runtime(new_info)
        except Exception:  # pylint: disable=broad-except
            logger.warning('Job %s: runtime re-ensure after grow failed; '
                           'relying on the existing daemon.', self.job_id)
        cluster_job_id = self.backend.execute(new_info, full_task,
                                              detach=True)
        _record_slices(self.job_id, self.full_slices)
        self.last_mode = 'grow'
        self.last_from_slices = from_slices
        self.last_to_slices = self.full_slices
        logger.info(
            'Job %s: grew gang back %d -> %d slices as cluster job %s.',
            self.job_id, from_slices, self.full_slices, cluster_job_id)
        return cluster_job_id

    def _drain_at_step_boundary(self, info: ClusterInfo) -> None:
        """Signal the payload to checkpoint + exit at its next step
        boundary; cancel after ``drain_seconds`` if it doesn't."""
        signal_path = self.resize_signal_path()
        drained = False
        try:
            os.makedirs(os.path.dirname(signal_path), exist_ok=True)
            with open(signal_path, 'w', encoding='utf-8') as f:
                f.write('grow\n')
            deadline = time.monotonic() + self.drain_seconds
            while time.monotonic() < deadline:
                if self.prev_cluster_job_id is None:
                    break
                try:
                    jobs = {j['job_id']: j['status']
                            for j in self.backend.queue(info)}
                except Exception:  # pylint: disable=broad-except
                    break
                if jobs.get(self.prev_cluster_job_id) in (
                        'SUCCEEDED', 'FAILED', 'CANCELLED', None):
                    drained = True
                    break
                time.sleep(0.1)
        finally:
            try:
                os.remove(signal_path)
            except OSError:
                pass
        if not drained and self.prev_cluster_job_id is not None:
            try:
                self.backend.cancel(info, self.prev_cluster_job_id)
            except Exception:  # pylint: disable=broad-except
                pass


def _other_regions(task: Task, cloud: str, keep_region: str) -> list:
    """All candidate regions except `keep_region` (to pin a relaunch)."""
    from skypilot_tpu.optimizer import Optimizer
    regions = set()
    for candidate in Optimizer.plan_task(task):
        if candidate.resources.cloud == cloud:
            regions.add(candidate.resources.region)
    regions.discard(keep_region)
    return sorted(regions)
