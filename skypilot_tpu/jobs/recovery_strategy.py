"""Recovery strategies: how a managed job relaunches after preemption.

Parity: ``sky/jobs/recovery_strategy.py`` (StrategyExecutor :75,
FailoverStrategyExecutor :842, EagerFailoverStrategyExecutor :963),
registered in JOBS_RECOVERY_STRATEGY_REGISTRY (sky/__init__.py:146).

TPU semantics: a preempted spot pod slice disappears as a unit, so
"recover" is always teardown + full relaunch; the job then resumes from
its GCS checkpoint (the checkpoint-resume pattern, SURVEY.md §5). The
two strategies differ only in *where* they retry first:

* FAILOVER — retry the same region first (capacity often returns within
  minutes), then widen with the preempted zone blocklisted.
* EAGER_NEXT_REGION — blocklist the whole region immediately (cross-region
  stockouts are correlated for TPU pods; eagerly pay the egress).
"""
from __future__ import annotations

import os
import time
from typing import Optional, Tuple

from skypilot_tpu import exceptions, execution, state
from skypilot_tpu.backend.tpu_backend import TpuPodBackend
from skypilot_tpu.provision.provisioner import Blocklist
from skypilot_tpu.spec.task import Task
from skypilot_tpu.utils import common_utils
from skypilot_tpu.utils import log
from skypilot_tpu.utils.registry import JOBS_RECOVERY_STRATEGY_REGISTRY

logger = log.init_logger(__name__)

# Initial-launch retry cadence on full stockout. Env > per-task config
# (`config: {jobs: {launch_retry_gap: N}}`) > global config > default
# (the reference backs off up to RETRY_INIT_GAP_SECONDS=60).


def _retry_gap(task: Task) -> float:
    if 'SKYT_JOBS_LAUNCH_RETRY_GAP' in os.environ:
        return float(os.environ['SKYT_JOBS_LAUNCH_RETRY_GAP'])
    from skypilot_tpu import config
    return float(config.get_nested(
        ('jobs', 'launch_retry_gap'), 20,
        override_configs=task.config_overrides))


def _max_retries(task: Task) -> int:
    if 'SKYT_JOBS_MAX_LAUNCH_RETRIES' in os.environ:
        return int(os.environ['SKYT_JOBS_MAX_LAUNCH_RETRIES'])
    from skypilot_tpu import config
    return int(config.get_nested(
        ('jobs', 'max_launch_retries'), 30,
        override_configs=task.config_overrides))


class StrategyExecutor:
    """Drives launch/recover for one managed job (ref :75)."""

    def __init__(self, job_id: int, task: Task, cluster_name: str) -> None:
        self.job_id = job_id
        self.task = task
        self.cluster_name = cluster_name
        self.backend = TpuPodBackend()
        self.blocklist = Blocklist()
        # Gang groups provision first and exec after the group barrier
        # (jobs/job_groups.py): the controller narrows the stages for
        # the initial launch, then resets to None (full launch) for
        # recoveries.
        self.launch_stages = None

    @classmethod
    def make(cls, strategy: Optional[str], job_id: int, task: Task,
             cluster_name: str) -> 'StrategyExecutor':
        name = (strategy or 'FAILOVER').upper()
        strategy_cls = JOBS_RECOVERY_STRATEGY_REGISTRY.get(name)
        return strategy_cls(job_id, task, cluster_name)

    # ------------------------------------------------------------------

    def launch(self) -> int:
        """Initial launch: retry on stockout with a gap until resources
        appear (parity: StrategyExecutor._launch retry loop)."""
        return self._launch_with_retries(self.blocklist)

    def recover(self) -> int:
        """Relaunch after preemption/failure. Returns the new cluster job
        id. Subclasses choose the blocklist seeding."""
        raise NotImplementedError

    def _relaunch_once(self, blocklist: Blocklist) -> Optional[int]:
        """One launch attempt with the given blocklist (no retry loop)."""
        results = execution.launch(self.task,
                                   self.cluster_name,
                                   detach_run=True,
                                   backend=self.backend,
                                   provision_blocklist=blocklist,
                                   stages=self.launch_stages)
        job_id = results[0][1]
        from skypilot_tpu.execution import Stage
        if self.launch_stages is None or Stage.EXEC in self.launch_stages:
            assert job_id is not None
        return job_id

    # ------------------------------------------------------------------

    def _current_location(self) -> Optional[Tuple[str, str, Optional[str]]]:
        record = state.get_cluster(self.cluster_name)
        if record is None or record.cloud is None:
            return None
        return (record.cloud, record.region, record.zone)

    def _teardown(self) -> None:
        try:
            self.backend.teardown(self.cluster_name, terminate=True)
        except exceptions.ClusterDoesNotExist:
            pass
        except Exception as e:  # pylint: disable=broad-except
            logger.warning('Teardown of %s failed: %s', self.cluster_name,
                           e)
            # The cloud may have already reclaimed it (preemption).
            state.remove_cluster(self.cluster_name)

    def _launch_with_retries(self, blocklist: Blocklist) -> int:
        gap = _retry_gap(self.task)
        max_retries = _max_retries(self.task)
        backoff = common_utils.Backoff(gap, gap * 10)
        for attempt in range(max_retries):
            try:
                return self._relaunch_once(blocklist)
            except exceptions.ResourcesUnavailableError as e:
                logger.info(
                    'Job %s: no resources anywhere (attempt %d/%d): %s',
                    self.job_id, attempt + 1, max_retries, e)
                # Full stockout: clear location blocklists (stockouts are
                # transient) and wait for capacity.
                blocklist.zones.clear()
                blocklist.regions.clear()
                time.sleep(backoff.current_backoff())
        raise exceptions.ResourcesUnavailableError(
            f'Managed job {self.job_id}: exhausted {max_retries} '
            'launch attempts across all locations.')


@JOBS_RECOVERY_STRATEGY_REGISTRY.register('FAILOVER')
class FailoverStrategy(StrategyExecutor):
    """Retry the same region first, then fail over (ref :842)."""

    def recover(self) -> int:
        location = self._current_location()
        self._teardown()
        widened = Blocklist()
        if location is not None:
            cloud, region, zone = location
            # First pass: pin to the previous region (cheap, data local).
            pinned = Blocklist()
            pinned.regions.update(
                {(cloud, r)
                 for r in _other_regions(self.task, cloud, region)})
            try:
                return self._relaunch_once(pinned)
            except exceptions.ResourcesUnavailableError:
                logger.info('Job %s: previous region %s has no capacity; '
                            'widening failover.', self.job_id, region)
            # Widened pass: everywhere except the just-preempted zone
            # (its capacity was literally just reclaimed).
            if zone is not None:
                widened.zones.add((cloud, zone))
        return self._launch_with_retries(widened)


@JOBS_RECOVERY_STRATEGY_REGISTRY.register('EAGER_NEXT_REGION')
class EagerNextRegionStrategy(StrategyExecutor):
    """Blocklist the preempted region immediately (ref :963)."""

    def recover(self) -> int:
        location = self._current_location()
        self._teardown()
        blocklist = Blocklist()
        if location is not None:
            cloud, region, _zone = location
            blocklist.regions.add((cloud, region))
        try:
            return self._relaunch_once(blocklist)
        except exceptions.ResourcesUnavailableError:
            # Every other region is out too; allow the original again.
            return self._launch_with_retries(Blocklist())


def _other_regions(task: Task, cloud: str, keep_region: str) -> list:
    """All candidate regions except `keep_region` (to pin a relaunch)."""
    from skypilot_tpu.optimizer import Optimizer
    regions = set()
    for candidate in Optimizer.plan_task(task):
        if candidate.resources.cloud == cloud:
            regions.add(candidate.resources.region)
    regions.discard(keep_region)
    return sorted(regions)
