"""Managed-job scheduler: bounds concurrent controllers.

Parity: ``sky/jobs/scheduler.py`` (:1-43 docstring — launching is limited
because provisioning holds locks and cloud quota; alive is limited by
controller memory). Controllers here are detached local processes (one per
job); the controller-as-a-dedicated-cluster deployment mode layers on top
the same way the reference's jobs controller runs on a SkyPilot cluster.

Anyone may call ``maybe_schedule_next_jobs()`` — on submit, on controller
state transitions, and on queue inspection — it is an idempotent
claim-and-spawn loop over the WAITING jobs.

**Controller offload** (parity: the reference's jobs controller runs on
a provisioned SkyPilot cluster, sky/jobs/server/core.py:521): set
``jobs.controller_cluster: <name>`` (or SKYT_JOBS_CONTROLLER_CLUSTER)
to a pre-launched CPU cluster and controllers run there as detached
cluster jobs instead of local processes — the API-server host stops
being the ceiling on concurrent jobs. Controllers reach the shared
state through SKYT_DB_URL (forwarded automatically), so this composes
with the Postgres HA mode. Liveness = the controller job's status on
that cluster; replacements respawn there under the same restart budget.
"""
from __future__ import annotations

import os
import sys

import psutil

from skypilot_tpu.jobs import state as jobs_state
from skypilot_tpu.utils import env_registry, log, subprocess_utils

logger = log.init_logger(__name__)


def _max_launching() -> int:
    """Env > config > default (ref: controller CPU-bounded limits)."""
    from skypilot_tpu import config
    env = env_registry.get_int('SKYT_JOBS_MAX_LAUNCHING')
    if env is not None:
        return env
    return int(config.get_nested(('jobs', 'max_launching'), 8))


def _max_alive() -> int:
    from skypilot_tpu import config
    env = env_registry.get_int('SKYT_JOBS_MAX_ALIVE')
    if env is not None:
        return env
    return int(config.get_nested(('jobs', 'max_alive'), 64))


def controller_cluster() -> 'str | None':
    """Offload target, when configured (env > config > None=local)."""
    from skypilot_tpu import config
    return (os.environ.get('SKYT_JOBS_CONTROLLER_CLUSTER')
            or config.get_nested(('jobs', 'controller_cluster'), None))


class ControllerSpawnError(Exception):
    """The controller process/job could NOT be started (the claimed
    slot is safe to release). Post-spawn bookkeeping failures are NOT
    this — there the controller is already running."""


def _spawn_local(job_id: int, resume: bool) -> None:
    log_path = jobs_state.controller_log_path(job_id)
    args = [sys.executable, '-m', 'skypilot_tpu.jobs.controller',
            '--job-id', str(job_id)]
    if resume:
        args.append('--resume')
    try:
        pid = subprocess_utils.daemonize_and_run(args, log_path=log_path)
    except Exception as e:
        raise ControllerSpawnError(str(e)) from e
    jobs_state.set_controller_pid(job_id, pid)
    logger.info('Managed job %s: controller pid %s%s', job_id, pid,
                ' (resume)' if resume else '')


def _spawn_controller(job_id: int, resume: bool = False) -> None:
    """Start the controller process — locally, or as a detached CPU job
    on the configured controller cluster — and record its identity."""
    cluster = controller_cluster()
    if cluster is None:
        _spawn_local(job_id, resume)
        return
    from skypilot_tpu import execution
    from skypilot_tpu import state as state_lib
    from skypilot_tpu.spec.resources import Resources
    from skypilot_tpu.spec.task import Task
    # The offloaded controller must see the SAME jobs/cluster state as
    # the server: via the shared Postgres (SKYT_DB_URL — the HA story)
    # or a shared-filesystem state dir. With neither, a remote
    # controller would find an empty DB and burn the restart budget —
    # run locally instead, loudly.
    # The controller's own scheduler ticks (launch_done/job_done) spawn
    # SIBLING controllers — they must land on this same cluster, not as
    # local processes on the controller-cluster node.
    envs = {'SKYT_JOBS_CONTROLLER_CLUSTER': cluster}
    if state_lib.db_url():
        envs['SKYT_DB_URL'] = state_lib.db_url()
    if os.environ.get('SKYT_STATE_DIR'):
        envs['SKYT_STATE_DIR'] = os.environ['SKYT_STATE_DIR']
    if not envs:
        logger.error(
            'jobs.controller_cluster=%r is set but neither SKYT_DB_URL '
            'nor a shared SKYT_STATE_DIR is configured — an offloaded '
            'controller could not see the jobs DB. Running the '
            'controller locally instead; configure a shared Postgres '
            '(SKYT_DB_URL) to actually offload.', cluster)
        _spawn_local(job_id, resume)
        return
    resume_flag = ' --resume' if resume else ''
    task = Task(
        name=f'skyt-controller-{job_id}',
        run=('PYTHONPATH=~/.skyt_runtime/runtime:$PYTHONPATH '
             f'python3 -um skypilot_tpu.jobs.controller '
             f'--job-id {job_id}{resume_flag}'),
        envs=envs,
        # CPU-only: controller jobs SHARE the controller cluster (the
        # daemon admits them concurrently; TPU exclusivity untouched).
        resources=Resources())
    try:
        results = execution.exec_(task, cluster, detach_run=True)
    except Exception as e:
        raise ControllerSpawnError(str(e)) from e
    cluster_job_id = results[0][1]
    jobs_state.set_controller_pid(job_id, cluster_job_id,
                                  controller_cluster=cluster)
    logger.info('Managed job %s: controller is job %s on cluster %s%s',
                job_id, cluster_job_id, cluster,
                ' (resume)' if resume else '')


def maybe_schedule_next_jobs() -> None:
    """Claim WAITING jobs into LAUNCHING slots and spawn controllers."""
    while True:
        job_id = jobs_state.claim_waiting_job(_max_launching(),
                                              _max_alive())
        if job_id is None:
            return
        try:
            _spawn_controller(job_id)
        except ControllerSpawnError as e:
            # Nothing started: RELEASE the claimed slot or the job is
            # stuck LAUNCHING with no controller forever; the next
            # scheduler tick retries from WAITING.
            logger.error(
                'Managed job %s: controller spawn failed (%s); '
                'returning the job to WAITING for retry', job_id, e)
            jobs_state.set_schedule_state(
                job_id, jobs_state.ScheduleState.WAITING)
            return
        except Exception as e:  # pylint: disable=broad-except
            # The controller IS running but its identity wasn't
            # recorded (transient DB blip). Re-WAITING would spawn a
            # DUPLICATE controller — leave the job; the controller
            # itself advances the schedule state, only crash-restart
            # coverage is degraded for this job.
            logger.error(
                'Managed job %s: controller started but bookkeeping '
                'failed (%s); crash-restart coverage degraded for this '
                'job.', job_id, e)
            return


def launch_done(job_id: int) -> None:
    """LAUNCHING -> ALIVE: frees a launching slot (called by the
    controller once provisioning finished or conclusively failed)."""
    jobs_state.set_schedule_state(job_id, jobs_state.ScheduleState.ALIVE)
    maybe_schedule_next_jobs()


def job_done(job_id: int) -> None:
    """-> DONE: frees all slots for this job."""
    jobs_state.set_schedule_state(job_id, jobs_state.ScheduleState.DONE)
    maybe_schedule_next_jobs()


def _controller_max_restarts() -> int:
    from skypilot_tpu import config
    env = env_registry.get_int('SKYT_JOBS_CONTROLLER_MAX_RESTARTS')
    if env is not None:
        return env
    return int(config.get_nested(('jobs', 'controller_max_restarts'), 3))


def _controller_alive(pid: int) -> bool:
    """pid_exists that treats zombies as dead (and reaps them when they
    are our children — controllers spawned from a long-lived server
    process are not reparented to init)."""
    try:
        proc = psutil.Process(pid)
    except psutil.NoSuchProcess:
        return False
    if proc.status() == psutil.STATUS_ZOMBIE:
        try:
            os.waitpid(pid, os.WNOHANG)
        except (ChildProcessError, OSError):
            pass
        return False
    return True


def _try_spawn_replacement(record, old_pid) -> None:
    """Replacement spawn that never propagates: the reaper runs inline
    from `skyt jobs queue` and must keep reaping the other jobs. A
    failed spawn (offload cluster briefly down) leaves the claim
    timestamp in place, so the stale-claim path retries after its
    grace."""
    try:
        _spawn_replacement(record, old_pid)
    except Exception as e:  # pylint: disable=broad-except
        logger.error(
            'Managed job %s: replacement controller spawn failed (%s); '
            'will retry after the claim grace period.',
            record.job_id, e)


def _spawn_replacement(record, old_pid) -> None:
    logger.warning(
        'Managed job %s: controller %s died; spawning replacement '
        '(restart %d/%d).', record.job_id, old_pid,
        record.controller_restarts + 1, _controller_max_restarts())
    _spawn_controller(record.job_id, resume=True)


def _controller_alive_for(record, queue_cache=None) -> bool:
    """Liveness for either controller placement: a local pid, or a
    controller job on the offload cluster (shared GONE-vs-UNREACHABLE
    logic: utils/controller_liveness.py)."""
    if record.controller_cluster:
        from skypilot_tpu.utils import controller_liveness
        return controller_liveness.cluster_job_alive(
            record.controller_cluster, record.controller_pid,
            queue_cache)
    return _controller_alive(record.controller_pid)


def reap_dead_controllers() -> None:
    """HA controller recovery (parity: the reference's HA controllers —
    autostop_lib.high_availability_specified, k8s-redeployed controllers
    that re-attach after a crash): a job whose controller process died
    gets a REPLACEMENT controller that re-attaches to the live cluster
    (or recovers it), up to ``jobs.controller_max_restarts`` times; only
    past that budget is the job failed as FAILED_CONTROLLER. Run on
    queue inspection + by the server's jobs-refresh daemon, so jobs
    survive an API-server restart too."""
    queue_cache: dict = {}
    for record in jobs_state.list_jobs(skip_finished=True):
        if record.schedule_state in (jobs_state.ScheduleState.WAITING,
                                     jobs_state.ScheduleState.DONE):
            continue
        pid = record.controller_pid
        if pid is None:  # pylint: disable=duplicate-code
            # Claim-window orphan: a previous reaper NULLed the pid but
            # died before spawning the replacement. After a grace period
            # the stale claim is re-claimable (atomic; normal in-flight
            # spawns are younger than the grace and skipped).
            if (record.controller_claimed_at is not None and
                    jobs_state.reclaim_stale_controller_claim(
                        record.job_id)):
                _try_spawn_replacement(record, old_pid=None)
            continue
        if _controller_alive_for(record, queue_cache):
            continue
        if jobs_state.claim_controller_restart(
                record.job_id, pid, _controller_max_restarts()):
            _try_spawn_replacement(record, old_pid=pid)
            continue
        # Claim lost: either another process is spawning the replacement
        # right now, or the restart budget is spent. Only the latter
        # fails the job (re-read to tell them apart).
        refreshed = jobs_state.get(record.job_id)
        if (refreshed is None or refreshed.controller_pid != pid or
                refreshed.controller_restarts < _controller_max_restarts()):
            continue
        logger.warning('Managed job %s: controller %s died; restart '
                       'budget exhausted.', record.job_id, pid)
        jobs_state.set_status(
            record.job_id, jobs_state.ManagedJobStatus.FAILED_CONTROLLER,
            failure_reason='controller process died repeatedly')
        jobs_state.set_schedule_state(record.job_id,
                                      jobs_state.ScheduleState.DONE)
    maybe_schedule_next_jobs()
