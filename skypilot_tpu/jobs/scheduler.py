"""Managed-job scheduler: bounds concurrent controllers.

Parity: ``sky/jobs/scheduler.py`` (:1-43 docstring — launching is limited
because provisioning holds locks and cloud quota; alive is limited by
controller memory). Controllers here are detached local processes (one per
job); the controller-as-a-dedicated-cluster deployment mode layers on top
the same way the reference's jobs controller runs on a SkyPilot cluster.

Anyone may call ``maybe_schedule_next_jobs()`` — on submit, on controller
state transitions, and on queue inspection — it is an idempotent
claim-and-spawn loop over the WAITING jobs.
"""
from __future__ import annotations

import os
import sys

import psutil

from skypilot_tpu.jobs import state as jobs_state
from skypilot_tpu.utils import log, subprocess_utils

logger = log.init_logger(__name__)


def _max_launching() -> int:
    """Env > config > default (ref: controller CPU-bounded limits)."""
    from skypilot_tpu import config
    if 'SKYT_JOBS_MAX_LAUNCHING' in os.environ:
        return int(os.environ['SKYT_JOBS_MAX_LAUNCHING'])
    return int(config.get_nested(('jobs', 'max_launching'), 8))


def _max_alive() -> int:
    from skypilot_tpu import config
    if 'SKYT_JOBS_MAX_ALIVE' in os.environ:
        return int(os.environ['SKYT_JOBS_MAX_ALIVE'])
    return int(config.get_nested(('jobs', 'max_alive'), 64))


def maybe_schedule_next_jobs() -> None:
    """Claim WAITING jobs into LAUNCHING slots and spawn controllers."""
    while True:
        job_id = jobs_state.claim_waiting_job(_max_launching(),
                                              _max_alive())
        if job_id is None:
            return
        log_path = jobs_state.controller_log_path(job_id)
        pid = subprocess_utils.daemonize_and_run(
            [sys.executable, '-m', 'skypilot_tpu.jobs.controller',
             '--job-id', str(job_id)],
            log_path=log_path)
        jobs_state.set_controller_pid(job_id, pid)
        logger.info('Managed job %s: controller pid %s', job_id, pid)


def launch_done(job_id: int) -> None:
    """LAUNCHING -> ALIVE: frees a launching slot (called by the
    controller once provisioning finished or conclusively failed)."""
    jobs_state.set_schedule_state(job_id, jobs_state.ScheduleState.ALIVE)
    maybe_schedule_next_jobs()


def job_done(job_id: int) -> None:
    """-> DONE: frees all slots for this job."""
    jobs_state.set_schedule_state(job_id, jobs_state.ScheduleState.DONE)
    maybe_schedule_next_jobs()


def _controller_max_restarts() -> int:
    from skypilot_tpu import config
    if 'SKYT_JOBS_CONTROLLER_MAX_RESTARTS' in os.environ:
        return int(os.environ['SKYT_JOBS_CONTROLLER_MAX_RESTARTS'])
    return int(config.get_nested(('jobs', 'controller_max_restarts'), 3))


def _controller_alive(pid: int) -> bool:
    """pid_exists that treats zombies as dead (and reaps them when they
    are our children — controllers spawned from a long-lived server
    process are not reparented to init)."""
    try:
        proc = psutil.Process(pid)
    except psutil.NoSuchProcess:
        return False
    if proc.status() == psutil.STATUS_ZOMBIE:
        try:
            os.waitpid(pid, os.WNOHANG)
        except (ChildProcessError, OSError):
            pass
        return False
    return True


def _spawn_replacement(record, old_pid) -> None:
    log_path = jobs_state.controller_log_path(record.job_id)
    new_pid = subprocess_utils.daemonize_and_run(
        [sys.executable, '-m', 'skypilot_tpu.jobs.controller',
         '--job-id', str(record.job_id), '--resume'],
        log_path=log_path)
    jobs_state.set_controller_pid(record.job_id, new_pid)
    logger.warning(
        'Managed job %s: controller %s died; resumed with replacement '
        'pid %s (restart %d/%d).', record.job_id, old_pid, new_pid,
        record.controller_restarts + 1, _controller_max_restarts())


def reap_dead_controllers() -> None:
    """HA controller recovery (parity: the reference's HA controllers —
    autostop_lib.high_availability_specified, k8s-redeployed controllers
    that re-attach after a crash): a job whose controller process died
    gets a REPLACEMENT controller that re-attaches to the live cluster
    (or recovers it), up to ``jobs.controller_max_restarts`` times; only
    past that budget is the job failed as FAILED_CONTROLLER. Run on
    queue inspection + by the server's jobs-refresh daemon, so jobs
    survive an API-server restart too."""
    for record in jobs_state.list_jobs(skip_finished=True):
        if record.schedule_state in (jobs_state.ScheduleState.WAITING,
                                     jobs_state.ScheduleState.DONE):
            continue
        pid = record.controller_pid
        if pid is None:
            # Claim-window orphan: a previous reaper NULLed the pid but
            # died before spawning the replacement. After a grace period
            # the stale claim is re-claimable (atomic; normal in-flight
            # spawns are younger than the grace and skipped).
            if (record.controller_claimed_at is not None and
                    jobs_state.reclaim_stale_controller_claim(
                        record.job_id)):
                _spawn_replacement(record, old_pid=None)
            continue
        if _controller_alive(pid):
            continue
        if jobs_state.claim_controller_restart(
                record.job_id, pid, _controller_max_restarts()):
            _spawn_replacement(record, old_pid=pid)
            continue
        # Claim lost: either another process is spawning the replacement
        # right now, or the restart budget is spent. Only the latter
        # fails the job (re-read to tell them apart).
        refreshed = jobs_state.get(record.job_id)
        if (refreshed is None or refreshed.controller_pid != pid or
                refreshed.controller_restarts < _controller_max_restarts()):
            continue
        logger.warning('Managed job %s: controller %s died; restart '
                       'budget exhausted.', record.job_id, pid)
        jobs_state.set_status(
            record.job_id, jobs_state.ManagedJobStatus.FAILED_CONTROLLER,
            failure_reason='controller process died repeatedly')
        jobs_state.set_schedule_state(record.job_id,
                                      jobs_state.ScheduleState.DONE)
    maybe_schedule_next_jobs()
