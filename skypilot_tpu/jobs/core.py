"""Managed-jobs API: launch/queue/cancel/logs.

Parity: ``sky/jobs/server/core.py`` (launch :657, queue, cancel,
tail_logs). Submission writes the job row and kicks the scheduler; all
heavy lifting happens in the detached controller process.
"""
from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.jobs import scheduler
from skypilot_tpu.jobs import state as jobs_state
from skypilot_tpu.spec.task import Task
from skypilot_tpu.utils import log

logger = log.init_logger(__name__)


def launch(task: Task, name: Optional[str] = None) -> int:
    """Submit a managed job; returns its job id immediately."""
    from skypilot_tpu import admin_policy
    if task.pipeline:
        # A pipeline: task is sugar for a gang-scheduled learner +
        # rollout group; expansion lives with the pipeline runtime.
        from skypilot_tpu.jobs import rl_pipeline
        job_ids = rl_pipeline.launch_pipeline(task, name)
        return job_ids[0]
    task = admin_policy.apply(task, 'jobs.launch')
    resources = task.resources[0] if task.resources else None
    strategy = 'FAILOVER'
    max_restarts = 0
    if resources is not None and resources.job_recovery:
        recovery = resources.job_recovery
        if isinstance(recovery, str):
            strategy = recovery
        else:
            strategy = recovery.get('strategy') or 'FAILOVER'
            max_restarts = int(recovery.get('max_restarts_on_errors', 0))
    if task.elastic:
        # An elastic spec needs the elastic recovery machinery; an
        # explicit conflicting job_recovery strategy would silently
        # disable shrink-to-surviving-slices, so elastic wins loudly.
        if strategy not in ('FAILOVER', 'ELASTIC'):
            logger.warning(
                'Task requests job_recovery strategy %s AND an elastic '
                'block; elastic recovery (ELASTIC) takes precedence.',
                strategy)
        strategy = 'ELASTIC'
    job_id = jobs_state.submit(task.to_yaml_config(),
                               name or task.name,
                               strategy=strategy,
                               max_restarts_on_errors=max_restarts,
                               elastic=task.elastic)
    logger.info('Managed job %s submitted (strategy=%s).', job_id,
                strategy)
    scheduler.maybe_schedule_next_jobs()
    return job_id


def launch_group(tasks: List[Task],
                 group_name: str) -> List[int]:
    """Submit a gang-scheduled job group (parity:
    jobs/job_group_networking.py): every member provisions, the group
    barriers, then all tasks start with each other's host IPs in env;
    one member failing cancels the rest. Returns the job ids."""
    if len(tasks) < 2:
        raise exceptions.InvalidSpecError(
            'a job group needs at least 2 tasks')
    names = [t.name for t in tasks]
    if len(set(names)) != len(names) or None in names:
        raise exceptions.InvalidSpecError(
            'every task in a job group needs a unique name '
            f'(got {names})')
    from skypilot_tpu import admin_policy
    job_ids = []
    for task in tasks:
        task = admin_policy.apply(task, 'jobs.launch')
        if task.elastic:
            # Group members barrier on each other's host IPs at start;
            # resizing one member would invalidate the gang's env, so
            # elastic recovery is not supported here — say so instead of
            # silently running the member rigid.
            logger.warning(
                'Job group %s: task %s has an elastic block, but job '
                'groups do not support elastic recovery; the member '
                'will use rigid FAILOVER relaunch.', group_name,
                task.name)
        job_ids.append(
            jobs_state.submit(task.to_yaml_config(), task.name,
                              strategy='FAILOVER',
                              max_restarts_on_errors=0,
                              group_name=group_name))
    logger.info('Job group %s submitted: jobs %s.', group_name, job_ids)
    scheduler.maybe_schedule_next_jobs()
    return job_ids


def queue(skip_finished: bool = False) -> List[Dict[str, Any]]:
    scheduler.reap_dead_controllers()
    return [r.to_dict() for r in jobs_state.list_jobs(skip_finished)]


def cancel(job_id: int) -> bool:
    """Request cancellation; the controller tears the cluster down."""
    record = jobs_state.get(job_id)
    if record is None:
        raise exceptions.JobNotFoundError(f'No managed job {job_id}.')
    if record.schedule_state == jobs_state.ScheduleState.WAITING:
        # No controller yet: cancel directly.
        if jobs_state.request_cancel(job_id):
            jobs_state.set_status(job_id,
                                  jobs_state.ManagedJobStatus.CANCELLED)
            jobs_state.set_schedule_state(job_id,
                                          jobs_state.ScheduleState.DONE)
            return True
        return False
    return jobs_state.request_cancel(job_id)


def tail_logs(job_id: int, controller: bool = False) -> str:
    """The job's run logs (or its controller's log with
    ``controller=True``)."""
    record = jobs_state.get(job_id)
    if record is None:
        raise exceptions.JobNotFoundError(f'No managed job {job_id}.')
    if controller:
        if record.controller_cluster and record.controller_pid:
            # Offloaded controller: its log is a cluster job log. A
            # NULL pid (claim window mid-respawn) has no log to read.
            import io
            from skypilot_tpu import state as state_lib
            from skypilot_tpu.backend.tpu_backend import TpuPodBackend
            from skypilot_tpu.provision.api import ClusterInfo
            cluster = state_lib.get_cluster(record.controller_cluster)
            if cluster is None or not cluster.handle.get('hosts'):
                return ''  # stopped/mid-relaunch: no hosts to read from
            buf = io.StringIO()
            try:
                TpuPodBackend().tail_logs(
                    ClusterInfo.from_dict(cluster.handle),
                    record.controller_pid, stream=buf)
            except exceptions.SkytError:
                pass
            return buf.getvalue()
        path = jobs_state.controller_log_path(job_id)
        if not os.path.exists(path):
            return ''
        with open(path, encoding='utf-8') as f:
            return f.read()
    if record.cluster_name is None:
        return ''
    from skypilot_tpu import core as sky_core
    try:
        # Streams to stdout itself; return '' so callers that print the
        # return value don't emit every line twice.
        sky_core.tail_logs(record.cluster_name)
        return ''
    except exceptions.SkytError:
        return (f'(cluster {record.cluster_name} is gone; '
                f'job status: {record.status.value})\n')
