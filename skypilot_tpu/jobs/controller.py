"""Per-job controller: launch → monitor → recover → cleanup.

Parity: ``sky/jobs/controller.py`` (JobController :152). Runs as a
detached process (`python -m skypilot_tpu.jobs.controller --job-id N`)
spawned by the scheduler. The monitor loop watches two signals:

* the cluster job's status in the on-cluster job table (user-code
  success/failure), and
* cluster health from the provider (spot preemption: a TPU slice
  vanishes as a unit).

On preemption it enters RECOVERING and delegates to the job's recovery
strategy; on user-code failure it restarts in place up to
``max_restarts_on_errors`` times (ref recovery_strategy.py:92).

The monitor ticks are event-driven: the loop waits on the CLUSTERS
notification topic (utils/events.py) with POLL_SECONDS as the degraded
fallback, so a provider health write (preemption, capacity return)
wakes the controller in milliseconds instead of a poll interval. For
elastic jobs (ElasticStrategy) the same wakeups drive the grow-back
watcher that re-expands a shrunken gang when capacity returns.
"""
from __future__ import annotations

import argparse
import os
import time
from typing import Optional

from skypilot_tpu import exceptions, state
from skypilot_tpu.backend.tpu_backend import TpuPodBackend
from skypilot_tpu.jobs import scheduler
from skypilot_tpu.jobs import state as jobs_state
from skypilot_tpu.jobs.recovery_strategy import StrategyExecutor
from skypilot_tpu.jobs.state import ManagedJobStatus
from skypilot_tpu.provision.api import ClusterInfo, get_provider
from skypilot_tpu.spec.task import Task
from skypilot_tpu.utils import events
from skypilot_tpu.utils import fault_injection
from skypilot_tpu.utils import env_registry
from skypilot_tpu.utils import log
from skypilot_tpu.utils import resilience

logger = log.init_logger(__name__)

POLL_SECONDS = env_registry.get_float('SKYT_JOBS_CONTROLLER_POLL')
# The CLUSTERS topic is global: every cluster write anywhere wakes every
# controller. The first wake after a quiet period ticks immediately
# (preemption -> shrink stays at event latency); bursts are coalesced so
# one controller never probes its runtime job table more than once per
# gap, no matter how busy the fleet's cluster table is.
EVENT_MIN_GAP = env_registry.get_float('SKYT_JOBS_EVENT_MIN_GAP')
# Consecutive failed monitor probes (jobs.controller.monitor faults, DB
# contention) tolerated before the controller stops trusting its view
# and degrades to recovery — bounded, so injected faults can never
# hang the loop in a probe-retry spin.
MONITOR_FAULT_LIMIT = 3
# Transient-failure retry budget for one recovery attempt
# (jobs.controller.recover site): ~6 tries over a few seconds.
RECOVER_RETRIES = 6


class JobController:
    def __init__(self, job_id: int) -> None:
        record = jobs_state.get(job_id)
        assert record is not None, f'managed job {job_id} not in DB'
        self.job_id = job_id
        self.record = record
        # Run in the JOB's workspace regardless of which process spawned
        # this controller (scheduler in a request child, the server's
        # jobs-refresh daemon, an HA replacement).
        os.environ['SKYT_WORKSPACE'] = record.workspace
        self.task = Task.from_yaml_config(record.task_config)
        self.cluster_name = (record.cluster_name or
                             f'{record.name or "job"}-{job_id}')
        jobs_state.set_cluster_name(job_id, self.cluster_name)
        self.strategy = StrategyExecutor.make(record.strategy, job_id,
                                              self.task, self.cluster_name)
        self.backend = TpuPodBackend()
        self.restarts_left = record.max_restarts_on_errors
        # Event-driven monitor: wake on cluster-state writes (preemption
        # marks, capacity events) instead of sleeping the full poll
        # interval; POLL_SECONDS stays as the degraded fallback cadence.
        self._clusters_signal = state.change_signal()
        self._clusters_cursor = events.cursor(events.CLUSTERS)
        self._monitor_failures = 0
        self._last_event_tick = 0.0
        self._last_grow_attempt = 0.0

    # -- cluster probes ------------------------------------------------

    def _cluster_info(self) -> Optional[ClusterInfo]:
        record = state.get_cluster(self.cluster_name)
        if record is None or record.status != state.ClusterStatus.UP:
            return None
        return ClusterInfo.from_dict(record.handle)

    def _cluster_healthy(self) -> bool:
        record = state.get_cluster(self.cluster_name)
        if record is None or record.cloud is None:
            return False
        try:
            states = get_provider(record.cloud).query_instances(
                self.cluster_name)
        except Exception:  # pylint: disable=broad-except
            return False
        return bool(states) and set(states.values()) == {'running'}

    def _job_status(self, cluster_job_id: int) -> Optional[str]:
        """Status string from the on-cluster job table, None if
        unreachable."""
        info = self._cluster_info()
        if info is None:
            return None
        try:
            for job in self.backend.queue(info):
                if job['job_id'] == cluster_job_id:
                    return job['status']
        except Exception:  # pylint: disable=broad-except
            return None
        return None

    # -- lifecycle -----------------------------------------------------

    def _finalize(self, status: ManagedJobStatus,
                  reason: Optional[str] = None,
                  teardown: bool = True) -> None:
        if teardown:
            try:
                self.backend.teardown(self.cluster_name, terminate=True)
            except exceptions.ClusterDoesNotExist:
                pass
            except Exception as e:  # pylint: disable=broad-except
                logger.warning('Cleanup teardown failed: %s', e)
        jobs_state.set_status(self.job_id, status, failure_reason=reason)
        logger.info('Managed job %s: %s', self.job_id, status.value)

    def _recover(self,
                 cluster_job_id: Optional[int] = None) -> Optional[int]:
        if jobs_state.cancel_requested(self.job_id):
            self._finalize(ManagedJobStatus.CANCELLED)
            return None
        detect_t0 = time.monotonic()
        jobs_state.set_status(self.job_id, ManagedJobStatus.RECOVERING)
        jobs_state.bump_recovery(self.job_id)
        if self.record.group_name:
            # Recovery relaunches run self.task; rebuild the rendezvous
            # env from the DB (an HA replacement never saw the original
            # barrier's in-memory env).
            from skypilot_tpu.jobs import job_groups
            self.task.update_envs(job_groups.rebuild_env(self.record))
        if self.strategy.is_elastic:
            # The elastic shrink path cancels the survivors' ranks
            # before re-forming the world at the smaller topology.
            self.strategy.prev_cluster_job_id = cluster_job_id
        def _attempt():
            fault_injection.inject('jobs.controller.recover')
            return self.strategy.recover()

        try:
            # Transient chaos / DB contention around the recovery
            # machinery itself gets bounded retries
            # (resilience.call_with_retry); ResourcesUnavailableError is
            # the strategy's final word and is never retried here.
            new_cluster_job_id = resilience.call_with_retry(
                _attempt, base=0.2, cap=2.0, deadline=None,
                max_attempts=RECOVER_RETRIES,
                what=f'managed job {self.job_id} recover')
        except exceptions.ResourcesUnavailableError as e:
            self._finalize(ManagedJobStatus.FAILED_NO_RESOURCE, str(e))
            return None
        if self.record.group_name:
            # Recovered on (possibly) new hosts: refresh the rendezvous
            # map for siblings that re-resolve it.
            from skypilot_tpu.jobs import job_groups
            job_groups.publish_hosts(self.job_id, self.cluster_name)
        jobs_state.set_status(self.job_id, ManagedJobStatus.RUNNING)
        jobs_state.record_recovery(
            self.job_id,
            getattr(self.strategy, 'last_mode', None) or 'relaunch',
            getattr(self.strategy, 'last_from_slices', None),
            getattr(self.strategy, 'last_to_slices', None),
            time.monotonic() - detect_t0)
        return new_cluster_job_id

    def _gang_launch(self) -> int:
        """Group member: provision+setup, publish hosts, barrier, exec
        with the rendezvous env (jobs/job_groups.py)."""
        from skypilot_tpu.execution import Stage
        from skypilot_tpu.jobs import job_groups
        self.strategy.launch_stages = [
            Stage.OPTIMIZE, Stage.PROVISION, Stage.SYNC_WORKDIR,
            Stage.SYNC_FILE_MOUNTS, Stage.SETUP]
        try:
            self.strategy.launch()
        finally:
            self.strategy.launch_stages = None  # recoveries relaunch fully
        job_groups.publish_hosts(self.job_id, self.cluster_name)
        env = job_groups.barrier_and_env(
            self.record,
            timeout=env_registry.get_float(
                'SKYT_JOBGROUP_BARRIER_TIMEOUT'))
        # The env lands on the task itself so recoveries (full
        # relaunches) keep the rendezvous map.
        self.task.update_envs(env)
        info = self._cluster_info()
        if info is None:
            raise exceptions.ClusterNotUpError(
                f'{self.cluster_name} vanished between barrier and exec')
        return self.backend.execute(info, self.task, detach=True)

    def _wait_tick(self) -> str:
        """One monitor-loop wait: returns early on a CLUSTERS topic
        wake (preemption/health/capacity write from any process), else
        after POLL_SECONDS. Returns the wake source."""
        self._clusters_cursor, source = events.wait_for(
            events.CLUSTERS, self._clusters_cursor, POLL_SECONDS,
            external=self._clusters_signal)
        if source != 'fallback':
            # Coalesce event bursts (see EVENT_MIN_GAP). Only
            # event-triggered ticks arm the gap: a lone preemption event
            # after a quiet stretch still reacts at event latency, while
            # writes landing during the gap are already past our cursor,
            # so a burst costs one probe per gap instead of one per
            # write.
            remaining = (EVENT_MIN_GAP -
                         (time.monotonic() - self._last_event_tick))
            if remaining > 0:
                time.sleep(remaining)
            self._last_event_tick = time.monotonic()
        return source

    def _record_initial_topology(self) -> None:
        """Seed the world-size history at first RUNNING (elastic jobs
        track current_slices from the start; the initial row makes the
        recovery_events trajectory complete: launch -> shrink -> grow)."""
        if not self.strategy.is_elastic:
            return
        record = jobs_state.get(self.job_id)
        if record is not None and record.current_slices:
            # HA replacement adopting a (possibly shrunken) gang: the
            # topology history is already being written.
            return
        jobs_state.set_current_slices(self.job_id,
                                      self.strategy.full_slices)
        jobs_state.record_recovery(self.job_id, 'launch', None,
                                   self.strategy.full_slices)

    def _exec_task(self):
        """The task for a restart-in-place (user-code failure with
        restarts budget): at the gang's CURRENT topology when elastic —
        a shrunken gang must not re-exec the full-size task, whose envs
        and mesh describe more devices than survive."""
        if self.strategy.is_elastic:
            return self.strategy.exec_task()
        return self.task

    def _maybe_grow(self, cluster_job_id: int, source: str
                    ) -> Optional[int]:
        """Grow-back watcher: when an elastic gang runs shrunken, retry
        re-expansion every ``grow_check_seconds`` — and immediately on a
        cluster-event wake (capacity returning IS a cluster-state
        write), floored at 1s so a write-busy control plane doesn't
        spin the optimizer. Returns the new cluster job id after a
        successful grow, else None. Exceptions propagate: a failure
        after the drain started must fall into normal recovery, not be
        swallowed (the old payload may already be cancelled)."""
        strategy = self.strategy
        if not strategy.is_elastic:
            return None
        if strategy.current_slices() >= strategy.full_slices:
            return None
        now = time.monotonic()
        elapsed = now - self._last_grow_attempt
        due = elapsed >= strategy.grow_check_seconds
        if not due and not (source != 'fallback' and elapsed >= 1.0):
            return None
        self._last_grow_attempt = now
        t0 = time.monotonic()
        strategy.prev_cluster_job_id = cluster_job_id
        new_cluster_job_id = strategy.try_grow()
        if new_cluster_job_id is None:
            return None
        jobs_state.record_recovery(
            self.job_id, 'grow', strategy.last_from_slices,
            strategy.last_to_slices, time.monotonic() - t0)
        return new_cluster_job_id

    def _reattach(self) -> Optional[int]:
        """Replacement-controller path (HA recovery): adopt the live
        cluster job if there is one; finalize directly if it already
        finished; otherwise fall back to a normal recovery. Returns the
        cluster job id to monitor, or None when the job is finalized."""
        # The dead controller may have been mid-drain: a leftover
        # resize-signal file would make every later payload incarnation
        # checkpoint and exit 0 at its first step boundary, finalizing a
        # half-trained job as SUCCEEDED.
        if self.strategy.is_elastic:
            self.strategy.clear_resize_signal()
        # A transient queue-read failure must NOT look like an empty
        # queue: falling into recovery while the original cluster job
        # still runs would execute the workload twice. Keep probing as
        # long as the cluster itself stays healthy.
        while True:
            info = self._cluster_info()
            if info is None or not self._cluster_healthy():
                break
            try:
                cluster_jobs = self.backend.queue(info)
            except Exception:  # pylint: disable=broad-except
                logger.warning(
                    'Managed job %s: cluster %s healthy but job table '
                    'unreachable; retrying.', self.job_id,
                    self.cluster_name)
                time.sleep(POLL_SECONDS)
                continue
            active = [j for j in cluster_jobs
                      if j['status'] in ('PENDING', 'SETTING_UP',
                                         'RUNNING')]
            if active:
                logger.info(
                    'Managed job %s: replacement controller adopted '
                    'cluster job %s.', self.job_id,
                    active[-1]['job_id'])
                jobs_state.set_status(self.job_id,
                                      ManagedJobStatus.RUNNING)
                return active[-1]['job_id']
            if any(j['status'] == 'SUCCEEDED' for j in cluster_jobs):
                # Finished while no controller was watching.
                self._finalize(ManagedJobStatus.SUCCEEDED)
                return None
            if any(j['status'] == 'FAILED' for j in cluster_jobs):
                # User code failed unwatched: same budget discipline as
                # the monitor loop — restart in place if allowed, never
                # silently re-run side-effectful work via recovery.
                if self.restarts_left > 0:
                    self.restarts_left -= 1
                    jobs_state.set_status(self.job_id,
                                          ManagedJobStatus.RECOVERING)
                    jobs_state.bump_recovery(self.job_id)
                    cluster_job_id = self.backend.execute(
                        info, self._exec_task(), detach=True)
                    jobs_state.set_status(self.job_id,
                                          ManagedJobStatus.RUNNING)
                    return cluster_job_id
                self._finalize(ManagedJobStatus.FAILED,
                               'task exited non-zero (finished while '
                               'no controller was watching)')
                return None
            if any(j['status'] == 'CANCELLED' for j in cluster_jobs):
                self._finalize(ManagedJobStatus.CANCELLED)
                return None
            break  # queue readable but empty -> recover
        # Cluster gone or job died with it: normal recovery machinery.
        return self._recover()

    def run(self, resume: bool = False) -> None:
        from skypilot_tpu.jobs import job_groups
        if resume:
            # The first controller may have died mid-LAUNCHING; the
            # replacement must not pin that launching slot forever.
            scheduler.launch_done(self.job_id)
            cluster_job_id = self._reattach()
            if cluster_job_id is None:
                return
        else:
            jobs_state.set_status(self.job_id, ManagedJobStatus.STARTING)
            try:
                if self.record.group_name:
                    cluster_job_id = self._gang_launch()
                else:
                    cluster_job_id = self.strategy.launch()
            except job_groups.GangAborted as e:
                scheduler.launch_done(self.job_id)
                self._finalize(ManagedJobStatus.CANCELLED, str(e))
                return
            except exceptions.ResourcesUnavailableError as e:
                scheduler.launch_done(self.job_id)
                self._finalize(ManagedJobStatus.FAILED_NO_RESOURCE,
                               str(e))
                return
            except Exception as e:  # pylint: disable=broad-except
                logger.exception('Managed job %s: launch failed',
                                 self.job_id)
                scheduler.launch_done(self.job_id)
                self._finalize(ManagedJobStatus.FAILED_SETUP,
                               f'{type(e).__name__}: {e}')
                return
            scheduler.launch_done(self.job_id)
            jobs_state.set_status(self.job_id, ManagedJobStatus.RUNNING)
        self._record_initial_topology()

        while True:
            source = self._wait_tick()
            if jobs_state.cancel_requested(self.job_id):
                info = self._cluster_info()
                if info is not None and cluster_job_id is not None:
                    try:
                        self.backend.cancel(info, cluster_job_id)
                    except Exception:  # pylint: disable=broad-except
                        pass
                self._finalize(ManagedJobStatus.CANCELLED)
                return

            try:
                fault_injection.inject('jobs.controller.monitor')
                self._monitor_failures = 0
            except resilience.transient_db_errors() as e:
                # Chaos/DB faults on the probe path: a broken view must
                # degrade to recovery after a bounded number of ticks,
                # never hang the monitor (tests/test_elastic_training).
                self._monitor_failures += 1
                logger.warning(
                    'Managed job %s: monitor probe fault (%d/%d): %s',
                    self.job_id, self._monitor_failures,
                    MONITOR_FAULT_LIMIT, e)
                if self._monitor_failures < MONITOR_FAULT_LIMIT:
                    continue
                self._monitor_failures = 0
                cluster_job_id = self._recover(cluster_job_id)
                if cluster_job_id is None:
                    return
                continue

            job_status = self._job_status(cluster_job_id)
            if job_status == 'SUCCEEDED':
                self._finalize(ManagedJobStatus.SUCCEEDED)
                return
            if self.record.group_name:
                failed_sibling = job_groups.sibling_failed(self.record)
                if failed_sibling is not None:
                    # Gang semantics: a partial group never keeps
                    # burning TPU-hours.
                    info = self._cluster_info()
                    if info is not None and cluster_job_id is not None:
                        try:
                            self.backend.cancel(info, cluster_job_id)
                        except Exception:  # pylint: disable=broad-except
                            pass
                    self._finalize(
                        ManagedJobStatus.CANCELLED,
                        f'gang: sibling {failed_sibling} failed')
                    return
            if job_status == 'FAILED':
                # User code failed on a healthy cluster: restart in place
                # if budget remains (ref max_restarts_on_errors).
                if self.restarts_left > 0:
                    info = self._cluster_info()
                    if info is None or not self._cluster_healthy():
                        # Cluster died between the failure and the restart:
                        # this is a preemption, not a user-code retry.
                        cluster_job_id = self._recover(cluster_job_id)
                        if cluster_job_id is None:
                            return
                        continue
                    self.restarts_left -= 1
                    logger.info(
                        'Managed job %s: task failed; restarting in place '
                        '(%d restarts left).', self.job_id,
                        self.restarts_left)
                    jobs_state.set_status(self.job_id,
                                          ManagedJobStatus.RECOVERING)
                    jobs_state.bump_recovery(self.job_id)
                    cluster_job_id = self.backend.execute(
                        info, self._exec_task(), detach=True)
                    jobs_state.set_status(self.job_id,
                                          ManagedJobStatus.RUNNING)
                    continue
                self._finalize(ManagedJobStatus.FAILED,
                               'task exited non-zero')
                return
            if job_status == 'CANCELLED':
                self._finalize(ManagedJobStatus.CANCELLED)
                return
            if job_status in ('PENDING', 'SETTING_UP', 'RUNNING'):
                if not self._cluster_healthy():
                    # Preempted mid-run (TPU slices vanish as a unit).
                    # Checked BEFORE any grow attempt: the runtime job
                    # table can still answer RUNNING after a preemption,
                    # and growing an unhealthy gang would top up around
                    # dead hosts and re-exec onto them.
                    logger.warning(
                        'Managed job %s: cluster %s unhealthy; '
                        'recovering.', self.job_id, self.cluster_name)
                    cluster_job_id = self._recover(cluster_job_id)
                    if cluster_job_id is None:
                        return
                    continue
                # Grow only while the payload is live: attempting it
                # before the status read could drain and re-expand a job
                # that already SUCCEEDED this tick, re-running finished
                # work at full size instead of finalizing it.
                try:
                    grown = self._maybe_grow(cluster_job_id, source)
                except Exception as e:  # pylint: disable=broad-except
                    # The drain may already have stopped the shrunken
                    # payload — a failed grow is a preemption-equivalent.
                    logger.warning(
                        'Managed job %s: grow-back failed mid-flight '
                        '(%s: %s); entering recovery.', self.job_id,
                        type(e).__name__, e)
                    cluster_job_id = self._recover(cluster_job_id)
                    if cluster_job_id is None:
                        return
                    continue
                if grown is not None:
                    cluster_job_id = grown
                continue
            # Job table unreachable: the cluster is gone.
            logger.warning('Managed job %s: lost cluster %s; recovering.',
                           self.job_id, self.cluster_name)
            cluster_job_id = self._recover(cluster_job_id)
            if cluster_job_id is None:
                return


def main(argv: Optional[list] = None) -> None:
    parser = argparse.ArgumentParser('managed-job controller')
    parser.add_argument('--job-id', type=int, required=True)
    parser.add_argument('--resume', action='store_true', default=False,
                        help='Replacement controller: re-attach to the '
                             'live cluster instead of launching.')
    args = parser.parse_args(argv)
    controller = JobController(args.job_id)
    try:
        controller.run(resume=args.resume)
    except Exception:  # pylint: disable=broad-except
        logger.exception('Controller for job %s crashed', args.job_id)
        jobs_state.set_status(args.job_id,
                              ManagedJobStatus.FAILED_CONTROLLER,
                              failure_reason='controller crashed')
        raise
    finally:
        scheduler.job_done(args.job_id)


if __name__ == '__main__':
    main()
