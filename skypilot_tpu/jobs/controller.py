"""Per-job controller: launch → monitor → recover → cleanup.

Parity: ``sky/jobs/controller.py`` (JobController :152). Runs as a
detached process (`python -m skypilot_tpu.jobs.controller --job-id N`)
spawned by the scheduler. The monitor loop watches two signals:

* the cluster job's status in the on-cluster job table (user-code
  success/failure), and
* cluster health from the provider (spot preemption: a TPU slice
  vanishes as a unit).

On preemption it enters RECOVERING and delegates to the job's recovery
strategy; on user-code failure it restarts in place up to
``max_restarts_on_errors`` times (ref recovery_strategy.py:92).
"""
from __future__ import annotations

import argparse
import os
import time
from typing import Optional

from skypilot_tpu import exceptions, state
from skypilot_tpu.backend.tpu_backend import TpuPodBackend
from skypilot_tpu.jobs import scheduler
from skypilot_tpu.jobs import state as jobs_state
from skypilot_tpu.jobs.recovery_strategy import StrategyExecutor
from skypilot_tpu.jobs.state import ManagedJobStatus
from skypilot_tpu.provision.api import ClusterInfo, get_provider
from skypilot_tpu.spec.task import Task
from skypilot_tpu.utils import log

logger = log.init_logger(__name__)

POLL_SECONDS = float(os.environ.get('SKYT_JOBS_CONTROLLER_POLL', '10'))


class JobController:
    def __init__(self, job_id: int) -> None:
        record = jobs_state.get(job_id)
        assert record is not None, f'managed job {job_id} not in DB'
        self.job_id = job_id
        self.record = record
        # Run in the JOB's workspace regardless of which process spawned
        # this controller (scheduler in a request child, the server's
        # jobs-refresh daemon, an HA replacement).
        os.environ['SKYT_WORKSPACE'] = record.workspace
        self.task = Task.from_yaml_config(record.task_config)
        self.cluster_name = (record.cluster_name or
                             f'{record.name or "job"}-{job_id}')
        jobs_state.set_cluster_name(job_id, self.cluster_name)
        self.strategy = StrategyExecutor.make(record.strategy, job_id,
                                              self.task, self.cluster_name)
        self.backend = TpuPodBackend()
        self.restarts_left = record.max_restarts_on_errors

    # -- cluster probes ------------------------------------------------

    def _cluster_info(self) -> Optional[ClusterInfo]:
        record = state.get_cluster(self.cluster_name)
        if record is None or record.status != state.ClusterStatus.UP:
            return None
        return ClusterInfo.from_dict(record.handle)

    def _cluster_healthy(self) -> bool:
        record = state.get_cluster(self.cluster_name)
        if record is None or record.cloud is None:
            return False
        try:
            states = get_provider(record.cloud).query_instances(
                self.cluster_name)
        except Exception:  # pylint: disable=broad-except
            return False
        return bool(states) and set(states.values()) == {'running'}

    def _job_status(self, cluster_job_id: int) -> Optional[str]:
        """Status string from the on-cluster job table, None if
        unreachable."""
        info = self._cluster_info()
        if info is None:
            return None
        try:
            for job in self.backend.queue(info):
                if job['job_id'] == cluster_job_id:
                    return job['status']
        except Exception:  # pylint: disable=broad-except
            return None
        return None

    # -- lifecycle -----------------------------------------------------

    def _finalize(self, status: ManagedJobStatus,
                  reason: Optional[str] = None,
                  teardown: bool = True) -> None:
        if teardown:
            try:
                self.backend.teardown(self.cluster_name, terminate=True)
            except exceptions.ClusterDoesNotExist:
                pass
            except Exception as e:  # pylint: disable=broad-except
                logger.warning('Cleanup teardown failed: %s', e)
        jobs_state.set_status(self.job_id, status, failure_reason=reason)
        logger.info('Managed job %s: %s', self.job_id, status.value)

    def _recover(self) -> Optional[int]:
        if jobs_state.cancel_requested(self.job_id):
            self._finalize(ManagedJobStatus.CANCELLED)
            return None
        jobs_state.set_status(self.job_id, ManagedJobStatus.RECOVERING)
        jobs_state.bump_recovery(self.job_id)
        if self.record.group_name:
            # Recovery relaunches run self.task; rebuild the rendezvous
            # env from the DB (an HA replacement never saw the original
            # barrier's in-memory env).
            from skypilot_tpu.jobs import job_groups
            self.task.update_envs(job_groups.rebuild_env(self.record))
        try:
            cluster_job_id = self.strategy.recover()
        except exceptions.ResourcesUnavailableError as e:
            self._finalize(ManagedJobStatus.FAILED_NO_RESOURCE, str(e))
            return None
        if self.record.group_name:
            # Recovered on (possibly) new hosts: refresh the rendezvous
            # map for siblings that re-resolve it.
            from skypilot_tpu.jobs import job_groups
            job_groups.publish_hosts(self.job_id, self.cluster_name)
        jobs_state.set_status(self.job_id, ManagedJobStatus.RUNNING)
        return cluster_job_id

    def _gang_launch(self) -> int:
        """Group member: provision+setup, publish hosts, barrier, exec
        with the rendezvous env (jobs/job_groups.py)."""
        from skypilot_tpu.execution import Stage
        from skypilot_tpu.jobs import job_groups
        self.strategy.launch_stages = [
            Stage.OPTIMIZE, Stage.PROVISION, Stage.SYNC_WORKDIR,
            Stage.SYNC_FILE_MOUNTS, Stage.SETUP]
        try:
            self.strategy.launch()
        finally:
            self.strategy.launch_stages = None  # recoveries relaunch fully
        job_groups.publish_hosts(self.job_id, self.cluster_name)
        env = job_groups.barrier_and_env(
            self.record,
            timeout=float(os.environ.get('SKYT_JOBGROUP_BARRIER_TIMEOUT',
                                         '1800')))
        # The env lands on the task itself so recoveries (full
        # relaunches) keep the rendezvous map.
        self.task.update_envs(env)
        info = self._cluster_info()
        if info is None:
            raise exceptions.ClusterNotUpError(
                f'{self.cluster_name} vanished between barrier and exec')
        return self.backend.execute(info, self.task, detach=True)

    def _reattach(self) -> Optional[int]:
        """Replacement-controller path (HA recovery): adopt the live
        cluster job if there is one; finalize directly if it already
        finished; otherwise fall back to a normal recovery. Returns the
        cluster job id to monitor, or None when the job is finalized."""
        # A transient queue-read failure must NOT look like an empty
        # queue: falling into recovery while the original cluster job
        # still runs would execute the workload twice. Keep probing as
        # long as the cluster itself stays healthy.
        while True:
            info = self._cluster_info()
            if info is None or not self._cluster_healthy():
                break
            try:
                cluster_jobs = self.backend.queue(info)
            except Exception:  # pylint: disable=broad-except
                logger.warning(
                    'Managed job %s: cluster %s healthy but job table '
                    'unreachable; retrying.', self.job_id,
                    self.cluster_name)
                time.sleep(POLL_SECONDS)
                continue
            active = [j for j in cluster_jobs
                      if j['status'] in ('PENDING', 'SETTING_UP',
                                         'RUNNING')]
            if active:
                logger.info(
                    'Managed job %s: replacement controller adopted '
                    'cluster job %s.', self.job_id,
                    active[-1]['job_id'])
                jobs_state.set_status(self.job_id,
                                      ManagedJobStatus.RUNNING)
                return active[-1]['job_id']
            if any(j['status'] == 'SUCCEEDED' for j in cluster_jobs):
                # Finished while no controller was watching.
                self._finalize(ManagedJobStatus.SUCCEEDED)
                return None
            if any(j['status'] == 'FAILED' for j in cluster_jobs):
                # User code failed unwatched: same budget discipline as
                # the monitor loop — restart in place if allowed, never
                # silently re-run side-effectful work via recovery.
                if self.restarts_left > 0:
                    self.restarts_left -= 1
                    jobs_state.set_status(self.job_id,
                                          ManagedJobStatus.RECOVERING)
                    jobs_state.bump_recovery(self.job_id)
                    cluster_job_id = self.backend.execute(
                        info, self.task, detach=True)
                    jobs_state.set_status(self.job_id,
                                          ManagedJobStatus.RUNNING)
                    return cluster_job_id
                self._finalize(ManagedJobStatus.FAILED,
                               'task exited non-zero (finished while '
                               'no controller was watching)')
                return None
            if any(j['status'] == 'CANCELLED' for j in cluster_jobs):
                self._finalize(ManagedJobStatus.CANCELLED)
                return None
            break  # queue readable but empty -> recover
        # Cluster gone or job died with it: normal recovery machinery.
        return self._recover()

    def run(self, resume: bool = False) -> None:
        from skypilot_tpu.jobs import job_groups
        if resume:
            # The first controller may have died mid-LAUNCHING; the
            # replacement must not pin that launching slot forever.
            scheduler.launch_done(self.job_id)
            cluster_job_id = self._reattach()
            if cluster_job_id is None:
                return
        else:
            jobs_state.set_status(self.job_id, ManagedJobStatus.STARTING)
            try:
                if self.record.group_name:
                    cluster_job_id = self._gang_launch()
                else:
                    cluster_job_id = self.strategy.launch()
            except job_groups.GangAborted as e:
                scheduler.launch_done(self.job_id)
                self._finalize(ManagedJobStatus.CANCELLED, str(e))
                return
            except exceptions.ResourcesUnavailableError as e:
                scheduler.launch_done(self.job_id)
                self._finalize(ManagedJobStatus.FAILED_NO_RESOURCE,
                               str(e))
                return
            except Exception as e:  # pylint: disable=broad-except
                logger.exception('Managed job %s: launch failed',
                                 self.job_id)
                scheduler.launch_done(self.job_id)
                self._finalize(ManagedJobStatus.FAILED_SETUP,
                               f'{type(e).__name__}: {e}')
                return
            scheduler.launch_done(self.job_id)
            jobs_state.set_status(self.job_id, ManagedJobStatus.RUNNING)

        while True:
            time.sleep(POLL_SECONDS)
            if jobs_state.cancel_requested(self.job_id):
                info = self._cluster_info()
                if info is not None and cluster_job_id is not None:
                    try:
                        self.backend.cancel(info, cluster_job_id)
                    except Exception:  # pylint: disable=broad-except
                        pass
                self._finalize(ManagedJobStatus.CANCELLED)
                return

            job_status = self._job_status(cluster_job_id)
            if job_status == 'SUCCEEDED':
                self._finalize(ManagedJobStatus.SUCCEEDED)
                return
            if self.record.group_name:
                failed_sibling = job_groups.sibling_failed(self.record)
                if failed_sibling is not None:
                    # Gang semantics: a partial group never keeps
                    # burning TPU-hours.
                    info = self._cluster_info()
                    if info is not None and cluster_job_id is not None:
                        try:
                            self.backend.cancel(info, cluster_job_id)
                        except Exception:  # pylint: disable=broad-except
                            pass
                    self._finalize(
                        ManagedJobStatus.CANCELLED,
                        f'gang: sibling {failed_sibling} failed')
                    return
            if job_status == 'FAILED':
                # User code failed on a healthy cluster: restart in place
                # if budget remains (ref max_restarts_on_errors).
                if self.restarts_left > 0:
                    info = self._cluster_info()
                    if info is None or not self._cluster_healthy():
                        # Cluster died between the failure and the restart:
                        # this is a preemption, not a user-code retry.
                        cluster_job_id = self._recover()
                        if cluster_job_id is None:
                            return
                        continue
                    self.restarts_left -= 1
                    logger.info(
                        'Managed job %s: task failed; restarting in place '
                        '(%d restarts left).', self.job_id,
                        self.restarts_left)
                    jobs_state.set_status(self.job_id,
                                          ManagedJobStatus.RECOVERING)
                    jobs_state.bump_recovery(self.job_id)
                    cluster_job_id = self.backend.execute(info, self.task,
                                                          detach=True)
                    jobs_state.set_status(self.job_id,
                                          ManagedJobStatus.RUNNING)
                    continue
                self._finalize(ManagedJobStatus.FAILED,
                               'task exited non-zero')
                return
            if job_status == 'CANCELLED':
                self._finalize(ManagedJobStatus.CANCELLED)
                return
            if job_status in ('PENDING', 'SETTING_UP', 'RUNNING'):
                if not self._cluster_healthy():
                    # Preempted mid-run (TPU slices vanish as a unit).
                    logger.warning(
                        'Managed job %s: cluster %s unhealthy; '
                        'recovering.', self.job_id, self.cluster_name)
                    cluster_job_id = self._recover()
                    if cluster_job_id is None:
                        return
                continue
            # Job table unreachable: the cluster is gone.
            logger.warning('Managed job %s: lost cluster %s; recovering.',
                           self.job_id, self.cluster_name)
            cluster_job_id = self._recover()
            if cluster_job_id is None:
                return


def main(argv: Optional[list] = None) -> None:
    parser = argparse.ArgumentParser('managed-job controller')
    parser.add_argument('--job-id', type=int, required=True)
    parser.add_argument('--resume', action='store_true', default=False,
                        help='Replacement controller: re-attach to the '
                             'live cluster instead of launching.')
    args = parser.parse_args(argv)
    controller = JobController(args.job_id)
    try:
        controller.run(resume=args.resume)
    except Exception:  # pylint: disable=broad-except
        logger.exception('Controller for job %s crashed', args.job_id)
        jobs_state.set_status(args.job_id,
                              ManagedJobStatus.FAILED_CONTROLLER,
                              failure_reason='controller crashed')
        raise
    finally:
        scheduler.job_done(args.job_id)


if __name__ == '__main__':
    main()
