"""Pretraining driver: the in-tree trainer recipes launch.

    python -m skypilot_tpu.train.pretrain --model bench-1b7 --steps 100 \
        --checkpoint-dir ~/ckpts --mesh fsdp=-1

TPU-native equivalents of the reference's GPU payload drivers
(``examples/tpu/v6e/train-llama3-8b.yaml`` runs PyTorch/XLA FSDP via HF
trainer): multi-host wiring comes from the backend's env contract
(JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID,
backend/codegen.py) -> ``jax.distributed.initialize``; sharding is a
``--mesh`` string over the named axes (data/stage/fsdp/seq/expert/
tensor); checkpoints go to --checkpoint-dir (a storage mount in the
recipe YAML) and training transparently resumes from the latest one --
the managed-jobs recovery contract.

Emits one JSON line per --log-every steps:
    {"step": N, "loss": x, "tokens_per_sec": y, "mfu_pct": z}
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np


def maybe_init_distributed() -> None:
    """Join the jax.distributed gang when launched multi-host by the
    backend (env contract from backend/codegen.py; replaces the
    reference's torchrun/NCCL env block, SURVEY.md §2.9)."""
    num_processes = int(os.environ.get('JAX_NUM_PROCESSES', '1'))
    if num_processes <= 1:
        return
    jax.distributed.initialize(
        coordinator_address=os.environ['JAX_COORDINATOR_ADDRESS'],
        num_processes=num_processes,
        process_id=int(os.environ['JAX_PROCESS_ID']))


def parse_mesh(spec: Optional[str]) -> Dict[str, int]:
    """'fsdp=-1,tensor=2' -> {'fsdp': -1, 'tensor': 2}."""
    if not spec:
        return {'fsdp': -1}
    out: Dict[str, int] = {}
    for part in spec.split(','):
        key, _, value = part.partition('=')
        out[key.strip()] = int(value)
    return out


def synthetic_batch(step: int, batch: int, seq: int,
                    vocab_size: int) -> Dict[str, jax.Array]:
    """Deterministic synthetic LM data (zipf-ish marginals so loss moves)."""
    rng = jax.random.key(step)
    r1, r2 = jax.random.split(rng)
    base = jax.random.randint(r1, (batch, seq), 0, vocab_size)
    # inject learnable structure: every other token repeats its left
    # neighbor, so a real model drives loss well below uniform entropy
    repeat = jnp.roll(base, 1, axis=1)
    mask = (jnp.arange(seq) % 2).astype(bool)
    tokens = jnp.where(mask[None, :], repeat, base)
    del r2
    return {
        'tokens': tokens,
        'targets': jnp.roll(tokens, -1, axis=1),
        'weights': jnp.ones((batch, seq), jnp.float32),
    }


def file_batch_iterator(path: str, batch: int, seq: int):
    """Stream batches from a flat .npy/int32 token file (memmapped)."""
    import numpy as np
    data = np.load(os.path.expanduser(path), mmap_mode='r')
    tokens_per_batch = batch * (seq + 1)
    offset = 0
    while True:
        if offset + tokens_per_batch > data.shape[0]:
            offset = 0
        chunk = np.asarray(
            data[offset:offset + tokens_per_batch]).reshape(
                batch, seq + 1)
        offset += tokens_per_batch
        yield {
            'tokens': jnp.asarray(chunk[:, :-1]),
            'targets': jnp.asarray(chunk[:, 1:]),
            'weights': jnp.ones((batch, seq), jnp.float32),
        }


def main(argv=None) -> int:
    from skypilot_tpu.utils.jax_env import honor_jax_platforms
    honor_jax_platforms()
    parser = argparse.ArgumentParser()
    parser.add_argument('--model', default='tiny')
    parser.add_argument('--steps', type=int, default=20)
    parser.add_argument('--batch', type=int, default=4)
    parser.add_argument('--seq', type=int, default=None)
    parser.add_argument('--learning-rate', type=float, default=3e-4)
    parser.add_argument('--warmup-steps', type=int, default=10)
    parser.add_argument('--optimizer', default='adamw',
                        choices=['adamw', 'adafactor'])
    parser.add_argument('--mesh', default=None,
                        help="e.g. 'data=2,fsdp=-1,tensor=2'")
    parser.add_argument('--data', default='synthetic',
                        help="'synthetic' or a flat token .npy file")
    parser.add_argument('--packed', action='store_true', default=False,
                        help='Pack EOS-delimited documents from --data '
                             'into padding-free batches (native C++ '
                             'packer; segment-masked attention).')
    parser.add_argument('--eos-id', type=int, default=1,
                        help='Document delimiter token for --packed.')
    parser.add_argument('--checkpoint-dir', default=None)
    parser.add_argument('--checkpoint-every', type=int, default=50)
    parser.add_argument('--log-every', type=int, default=10)
    parser.add_argument('--param-dtype', default=None,
                        choices=[None, 'float32', 'bfloat16'])
    parser.add_argument('--remat-policy', default=None,
                        choices=[None, 'none', 'dots', 'save_attn',
                                 'save_dots', 'full'],
                        help='activation remat: full = least memory; '
                             'save_attn/save_dots trade memory for '
                             'less recompute (models/config.py).')
    parser.add_argument('--moe-dispatch', default=None,
                        choices=[None, 'dense', 'capacity'],
                        help='MoE routing: dense = exact, O(E/k)x MLP '
                             'FLOPs; capacity = fixed per-expert '
                             'capacity, ~capacity_factor x active '
                             'FLOPs (drops over-capacity tokens).')
    parser.add_argument('--capacity-factor', type=float, default=None)
    args = parser.parse_args(argv)

    maybe_init_distributed()

    from skypilot_tpu.models.config import get_model_config
    from skypilot_tpu.parallel.mesh import MeshConfig, build_mesh
    from skypilot_tpu.train import checkpoint as ckpt_lib
    from skypilot_tpu.train.step import (TrainHParams, create_train_state,
                                         make_train_step, resize_requested,
                                         state_shardings)

    overrides = {}
    if args.param_dtype:
        overrides['param_dtype'] = jnp.dtype(args.param_dtype)
    if args.remat_policy:
        overrides['remat_policy'] = args.remat_policy
    if args.moe_dispatch:
        overrides['moe_dispatch'] = args.moe_dispatch
    if args.capacity_factor is not None:
        overrides['capacity_factor'] = args.capacity_factor
    cfg = get_model_config(args.model, **overrides)
    seq = min(args.seq or 1024, cfg.max_seq_len)
    hp = TrainHParams(learning_rate=args.learning_rate,
                      warmup_steps=args.warmup_steps,
                      total_steps=max(args.steps, args.warmup_steps + 1),
                      optimizer=args.optimizer)
    mesh_config = MeshConfig(**parse_mesh(args.mesh))
    elastic_slices = os.environ.get('SKYT_ELASTIC_SLICES')
    if elastic_slices:
        # Elastic world size (jobs/recovery_strategy.py): the recipe's
        # mesh string describes the FULL gang; the controller exports
        # the surviving slice count and the DCN axes re-solve for it —
        # the same --mesh runs shrunken and grown-back alike.
        mesh_config = mesh_config.resolve(
            len(jax.devices()), num_slices=int(elastic_slices))
    mesh = build_mesh(mesh_config)
    # The global batch shards over (data, fsdp) and seq over (seq): round
    # up so every shard is non-empty regardless of device count.
    batch_div = mesh.shape['data'] * mesh.shape['fsdp']
    batch = -(-args.batch // batch_div) * batch_div
    seq_div = mesh.shape['seq']
    seq = -(-seq // seq_div) * seq_div
    if batch != args.batch:
        print(json.dumps({'batch_rounded_to': batch}), flush=True)
    args.batch = batch
    shardings = state_shardings(mesh, cfg, hp)
    state = create_train_state(jax.random.key(0), cfg, hp, mesh,
                               shardings=shardings)
    start_step = 0
    if args.checkpoint_dir:
        latest = ckpt_lib.latest_step(args.checkpoint_dir)
        if latest is not None:
            # Topology-change restore: `state` is laid out on the
            # CURRENT mesh (possibly a shrunken/grown elastic world);
            # StandardRestore re-shards params + optimizer state from
            # whatever world size wrote the checkpoint.
            state = ckpt_lib.restore(args.checkpoint_dir, latest, state)
            start_step = int(state.step)
            print(json.dumps({'resumed_from_step': start_step}), flush=True)
            print(json.dumps({
                'mesh_devices': mesh.devices.size,
                'num_slices': mesh_config.num_slices,
            }), flush=True)
    step_fn = make_train_step(cfg, hp, mesh, shardings=shardings)

    if args.data == 'synthetic':
        data_iter = None
    elif args.packed:
        from skypilot_tpu.data.packer import packed_batch_iterator
        data_iter = packed_batch_iterator(args.data, batch=args.batch,
                                          seq=seq, eos_id=args.eos_id)
    else:
        data_iter = file_batch_iterator(args.data, args.batch, seq)
    flops_per_token = cfg.flops_per_token(seq)
    window_t0 = time.perf_counter()
    window_tokens = 0
    is_main = jax.process_index() == 0
    for step in range(start_step, args.steps):
        if data_iter is not None:
            batch = next(data_iter)
        else:
            batch = synthetic_batch(step, args.batch, seq, cfg.vocab_size)
        state, metrics = step_fn(state, batch)
        # REAL tokens, not grid cells: packed batches carry padding with
        # weight 0 and must not inflate throughput. Only the packed path
        # needs the sum (its weights are already host numpy); dense
        # paths have statically-known counts — summing a device array
        # every step would force a host transfer in the hot loop.
        weights = batch.get('weights')
        if isinstance(weights, np.ndarray):
            real_tokens = float(weights.sum())
        else:
            real_tokens = args.batch * seq
        window_tokens += real_tokens * jax.process_count()
        if (step + 1) % args.log_every == 0 or step + 1 == args.steps:
            loss = float(metrics['loss'])  # sync point
            elapsed = time.perf_counter() - window_t0
            tps = window_tokens / max(elapsed, 1e-9)
            if is_main:
                print(json.dumps({
                    'step': step + 1,
                    'loss': round(loss, 4),
                    'tokens_per_sec': round(tps, 1),
                    'achieved_tflops': round(
                        tps * flops_per_token / 1e12, 2),
                }), flush=True)
            window_t0 = time.perf_counter()
            window_tokens = 0
        saved_this_step = (args.checkpoint_dir and
                           ((step + 1) % args.checkpoint_every == 0 or
                            step + 1 == args.steps))
        if saved_this_step and is_main:
            ckpt_lib.save(args.checkpoint_dir, step + 1, state)
        if resize_requested():
            # Step boundary = the only resize-safe point (params and
            # optimizer state are consistent). Checkpoint here and exit
            # 0: the elastic controller re-execs this driver at the new
            # world size and the restore path above re-shards into it.
            if args.checkpoint_dir and is_main and not saved_this_step:
                ckpt_lib.save(args.checkpoint_dir, step + 1, state)
            if is_main:
                print(json.dumps({'resize_exit_at_step': step + 1}),
                      flush=True)
            return 0
    if is_main:
        print(json.dumps({'done': True, 'final_step': args.steps}),
              flush=True)
    return 0


if __name__ == '__main__':
    sys.exit(main())
