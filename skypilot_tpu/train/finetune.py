"""Finetuning driver: real checkpoint in, real checkpoint out.

    python -m skypilot_tpu.train.finetune \
        --hf-checkpoint /ckpts/Meta-Llama-3.1-8B --data corpus.txt \
        --lora-rank 16 --steps 200 --export-dir /ckpts/my-ft \
        --mesh fsdp=-1

TPU-native equivalent of the reference's finetuning recipes
(``/root/reference/llm/llama-3_1-finetuning/`` = torchtune
full/LoRA finetuning launched as a GPU payload). The checkpoint loads
through ``models/hf_interop.py`` (safetensors), text tokenizes with the
checkpoint's own BPE (``tokenizer.json``), and the result exports back
to HF layout (LoRA adapters merged into dense weights) — servable by
the in-tree engines or anything else that reads Llama safetensors.

Two modes:
* **full** (``--lora-rank 0``): every parameter trains; the standard
  sharded train step (fsdp/tensor mesh axes apply).
* **LoRA** (``--lora-rank R``): base weights FROZEN (bf16, no
  optimizer state — the memory point of LoRA), adapters train in fp32.

Checkpoint/resume follows the managed-jobs recovery contract
(--checkpoint-dir; restored on restart).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Iterator

import jax
import jax.numpy as jnp
import numpy as np
import optax


def text_batch_iterator(path: str, tokenizer, batch: int,
                        seq: int) -> Iterator[dict]:
    """Tokenize a text file (one document per line) into a contiguous
    stream and cut [batch, seq] LM batches, cycling at EOF."""
    ids = []
    with open(os.path.expanduser(path), encoding='utf-8') as f:
        for line in f:
            line = line.strip()
            if line:
                ids.extend(tokenizer.encode(line, add_bos=True))
                ids.append(tokenizer.eos_id)
    if not ids:
        raise ValueError(f'corpus {path} is empty (no non-blank lines)')
    if len(ids) < batch * (seq + 1):
        # Small corpora: tile so a batch always fills.
        reps = -(-batch * (seq + 1) // len(ids))
        ids = ids * reps
    data = np.asarray(ids, np.int32)
    per_batch = batch * (seq + 1)
    offset = 0
    while True:
        if offset + per_batch > data.shape[0]:
            offset = 0
        chunk = data[offset:offset + per_batch].reshape(batch, seq + 1)
        offset += per_batch
        yield {
            'tokens': jnp.asarray(chunk[:, :-1]),
            'targets': jnp.asarray(chunk[:, 1:]),
            'weights': jnp.ones((batch, seq), jnp.float32),
        }


def make_lora_step(base_params, cfg, optimizer):
    """Jitted LoRA step: grads ONLY through the adapter pytree; the
    frozen base is closed over (donated nothing, no optimizer state)."""
    from skypilot_tpu.models import llama, lora as lora_lib
    from skypilot_tpu.train.loss import cross_entropy_loss

    def loss_fn(lora_params, batch):
        params = lora_lib.attach(base_params, lora_params)
        logits = llama.forward(params, batch['tokens'], cfg)
        loss, _ = cross_entropy_loss(logits, batch['targets'],
                                     batch.get('weights'))
        return loss

    @jax.jit
    def step(lora_params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(lora_params, batch)
        updates, opt_state = optimizer.update(grads, opt_state,
                                              lora_params)
        lora_params = optax.apply_updates(lora_params, updates)
        return lora_params, opt_state, loss

    return step


def main(argv=None) -> int:
    from skypilot_tpu.utils.jax_env import honor_jax_platforms
    honor_jax_platforms()
    parser = argparse.ArgumentParser()
    parser.add_argument('--hf-checkpoint', required=True,
                        help='HF-layout dir (config.json + safetensors '
                             '+ tokenizer.json)')
    parser.add_argument('--data', required=True,
                        help='text file (one document per line) or flat '
                             'int32 token .npy')
    parser.add_argument('--steps', type=int, default=100)
    parser.add_argument('--batch', type=int, default=4)
    parser.add_argument('--seq', type=int, default=512)
    parser.add_argument('--learning-rate', type=float, default=1e-5)
    parser.add_argument('--lora-rank', type=int, default=0,
                        help='0 = full finetune; >0 = LoRA rank '
                             '(frozen bf16 base, fp32 adapters)')
    parser.add_argument('--mesh', default=None,
                        help="full-FT sharding, e.g. 'fsdp=-1'")
    parser.add_argument('--export-dir', default=None,
                        help='write the finetuned model back as an '
                             'HF-layout checkpoint (LoRA merged)')
    parser.add_argument('--adapter-export-dir', default=None,
                        help='LoRA mode: also export the UNMERGED '
                             'adapter as a content-addressed manifest '
                             'artifact under this registry root '
                             '(digest-named A/B shards + base-model '
                             'digest), servable by the multi-LoRA '
                             'engine (docs/multi_lora_serving.md)')
    parser.add_argument('--adapter-name', default=None,
                        help='registry name for --adapter-export-dir '
                             '(default: the export dir basename or '
                             '"adapter")')
    parser.add_argument('--checkpoint-dir', default=None)
    parser.add_argument('--checkpoint-every', type=int, default=50)
    parser.add_argument('--log-every', type=int, default=10)
    args = parser.parse_args(argv)

    from skypilot_tpu.inference.tokenizer import get_tokenizer
    from skypilot_tpu.models import hf_interop, lora as lora_lib
    from skypilot_tpu.train import checkpoint as ckpt_lib
    from skypilot_tpu.train.pretrain import (file_batch_iterator,
                                             maybe_init_distributed,
                                             parse_mesh)

    maybe_init_distributed()
    use_lora = args.lora_rank > 0
    if use_lora and args.mesh:
        # Adapter training runs the frozen base on the default device
        # placement; mesh sharding applies to full FT only.
        print(json.dumps({'warning': '--mesh is ignored with '
                          '--lora-rank > 0 (LoRA runs unsharded)'}),
              flush=True)
    # LoRA: frozen base in bf16 halves resident memory and no base
    # optimizer state exists. Full FT: fp32 master weights.
    params, cfg = hf_interop.load_checkpoint(
        args.hf_checkpoint,
        dtype=jnp.bfloat16 if use_lora else jnp.float32)
    seq = min(args.seq, cfg.max_seq_len)
    mesh = None
    if not use_lora:
        from skypilot_tpu.parallel.mesh import MeshConfig, build_mesh
        mesh = build_mesh(MeshConfig(**parse_mesh(args.mesh)))
        # Round the batch up to the mesh's (data, fsdp) divisor the way
        # the pretrain driver does — every shard must be non-empty.
        batch_div = mesh.shape['data'] * mesh.shape['fsdp']
        rounded = -(-args.batch // batch_div) * batch_div
        if rounded != args.batch:
            print(json.dumps({'batch_rounded_to': rounded}), flush=True)
        args.batch = rounded
    if args.data.endswith('.npy'):
        data_iter = file_batch_iterator(args.data, args.batch, seq)
    else:
        tokenizer = get_tokenizer(args.hf_checkpoint, require=True)
        data_iter = text_batch_iterator(args.data, tokenizer,
                                        args.batch, seq)

    is_main = jax.process_index() == 0
    t0 = time.perf_counter()

    if use_lora:
        lora_params = lora_lib.init_lora_params(
            jax.random.key(0), cfg, args.lora_rank)
        optimizer = optax.adamw(args.learning_rate)
        opt_state = optimizer.init(lora_params)
        start_step = 0
        if args.checkpoint_dir:
            latest = ckpt_lib.latest_step(args.checkpoint_dir)
            if latest is not None:
                restored = ckpt_lib.restore(
                    args.checkpoint_dir, latest,
                    {'lora': lora_params, 'opt': opt_state,
                     'step': 0})
                lora_params = restored['lora']
                opt_state = restored['opt']
                start_step = int(restored['step'])
                print(json.dumps({'resumed_from_step': start_step}),
                      flush=True)
        step_fn = make_lora_step(params, cfg, optimizer)
        for step in range(start_step, args.steps):
            batch = next(data_iter)
            lora_params, opt_state, loss = step_fn(lora_params,
                                                   opt_state, batch)
            if is_main and ((step + 1) % args.log_every == 0 or
                            step + 1 == args.steps):
                print(json.dumps({'step': step + 1,
                                  'loss': round(float(loss), 4),
                                  'mode': f'lora-r{args.lora_rank}'}),
                      flush=True)
            if (args.checkpoint_dir and is_main and
                    ((step + 1) % args.checkpoint_every == 0 or
                     step + 1 == args.steps)):
                ckpt_lib.save(args.checkpoint_dir, step + 1,
                              {'lora': lora_params, 'opt': opt_state,
                               'step': step + 1})
        final_params = lora_lib.merge(
            lora_lib.attach(params, lora_params))
        if args.adapter_export_dir and is_main:
            # The UNMERGED adapter, pinned to its base: the multi-LoRA
            # engine rejects this artifact against any other base
            # checkpoint (adapter_registry base_digest contract).
            from skypilot_tpu.serve import adapter_registry
            adapter_name = (args.adapter_name or
                            (os.path.basename(
                                os.path.normpath(args.export_dir))
                             if args.export_dir else 'adapter'))
            exported = adapter_registry.export_adapter(
                args.adapter_export_dir, adapter_name,
                jax.device_get(lora_params),
                alpha=lora_lib.DEFAULT_ALPHA,
                base_digest=adapter_registry.checkpoint_digest(
                    args.hf_checkpoint),
                step=args.steps,
                extra_meta={'hf_checkpoint': args.hf_checkpoint})
            print(json.dumps({'adapter_exported': exported,
                              'adapter_name': adapter_name,
                              'rank': args.lora_rank}), flush=True)
    else:
        from skypilot_tpu.train.step import (
            TrainHParams, create_train_state_from_params,
            make_train_step, state_shardings)
        hp = TrainHParams(learning_rate=args.learning_rate,
                          warmup_steps=min(10, args.steps),
                          total_steps=max(args.steps, 11))
        shardings = state_shardings(mesh, cfg, hp)
        state = create_train_state_from_params(params, cfg, hp, mesh,
                                               shardings=shardings)
        start_step = 0
        if args.checkpoint_dir:
            latest = ckpt_lib.latest_step(args.checkpoint_dir)
            if latest is not None:
                state = ckpt_lib.restore(args.checkpoint_dir, latest,
                                         state)
                start_step = int(state.step)
                print(json.dumps({'resumed_from_step': start_step}),
                      flush=True)
        step_fn = make_train_step(cfg, hp, mesh, shardings=shardings)
        for step in range(start_step, args.steps):
            batch = next(data_iter)
            state, metrics = step_fn(state, batch)
            if is_main and ((step + 1) % args.log_every == 0 or
                            step + 1 == args.steps):
                print(json.dumps({
                    'step': step + 1,
                    'loss': round(float(metrics['loss']), 4),
                    'mode': 'full'}), flush=True)
            if (args.checkpoint_dir and is_main and
                    ((step + 1) % args.checkpoint_every == 0 or
                     step + 1 == args.steps)):
                ckpt_lib.save(args.checkpoint_dir, step + 1, state)
        final_params = state.params

    if args.export_dir and is_main:
        hf_interop.save_checkpoint(
            jax.device_get(final_params), cfg, args.export_dir,
            dtype=np.float32)
        # Ship the tokenizer along so the export serves end-to-end.
        for fn in ('tokenizer.json', 'tokenizer_config.json'):
            src = os.path.join(args.hf_checkpoint, fn)
            if os.path.exists(src):
                import shutil
                shutil.copy(src, os.path.join(args.export_dir, fn))
        print(json.dumps({'exported': args.export_dir}), flush=True)
    if is_main:
        print(json.dumps({'done': True,
                          'seconds': round(time.perf_counter() - t0, 1)}),
              flush=True)
    return 0


if __name__ == '__main__':
    sys.exit(main())
