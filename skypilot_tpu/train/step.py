"""Sharded train state + train step.

TPU-first mechanics: params/opt-state initialized **directly sharded** on
the mesh (jit with out_shardings -- no host-side full materialization),
train step jitted with donated state, gradient all-reduce left to XLA via
the sharding annotations (FSDP/TP collectives on ICI, DP on DCN).
"""
from __future__ import annotations

import dataclasses
import functools
import os
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from skypilot_tpu.models import llama
from skypilot_tpu.models.config import ModelConfig
from skypilot_tpu.parallel.mesh import use_mesh
from skypilot_tpu.parallel.sharding import (DEFAULT_RULES, LogicalAxisRules,
                                            shard_params_pytree)
from skypilot_tpu.train.loss import cross_entropy_loss

Params = Dict[str, Any]

# Elastic resize handshake (jobs/recovery_strategy.py ElasticStrategy):
# the controller touches the file named by this env var when it wants
# the gang restarted at a different world size; the training loop
# checks at each step boundary — the only point where params/opt-state
# are consistent — checkpoints, and exits 0 so the controller can
# re-exec at the new topology (docs/elastic_training.md).
RESIZE_SIGNAL_ENV = 'SKYT_RESIZE_SIGNAL'


def resize_requested() -> bool:
    """True when the controller asked for a step-boundary resize.

    Cheap enough for the hot loop: one env lookup, and one stat only
    when the job runs under an elastic controller.
    """
    path = os.environ.get(RESIZE_SIGNAL_ENV)
    return bool(path) and os.path.exists(path)


@dataclasses.dataclass
class TrainHParams:
    learning_rate: float = 3e-4
    warmup_steps: int = 100
    total_steps: int = 10_000
    weight_decay: float = 0.1
    b1: float = 0.9
    b2: float = 0.95
    grad_clip_norm: float = 1.0
    z_loss_coeff: float = 1e-4
    # Pipeline microbatch count when the mesh has a stage axis > 1; None =
    # largest divisor of batch <= 2*stages (parallel/pipeline.py).
    pipeline_microbatches: Optional[int] = None
    # 'adamw' (2 fp32 moments/param) or 'adafactor' (factored second
    # moment, ~O(rows+cols) state -- the HBM-frugal choice that lets a
    # ~1.7B model train on one 16GB v5e chip; standard TPU practice).
    optimizer: str = 'adamw'


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TrainState:
    step: jax.Array
    params: Params
    opt_state: Any


def make_optimizer(hp: TrainHParams) -> optax.GradientTransformation:
    schedule = optax.warmup_cosine_decay_schedule(
        init_value=0.0,
        peak_value=hp.learning_rate,
        warmup_steps=hp.warmup_steps,
        decay_steps=max(hp.total_steps, hp.warmup_steps + 1),
        end_value=hp.learning_rate * 0.1)
    if hp.optimizer == 'adafactor':
        return optax.chain(
            optax.clip_by_global_norm(hp.grad_clip_norm),
            optax.adafactor(schedule, weight_decay_rate=hp.weight_decay,
                            decay_rate=hp.b2),
        )
    if hp.optimizer != 'adamw':
        raise ValueError(f'Unknown optimizer {hp.optimizer!r} '
                         f"(expected 'adamw' or 'adafactor')")
    return optax.chain(
        optax.clip_by_global_norm(hp.grad_clip_norm),
        optax.adamw(schedule, b1=hp.b1, b2=hp.b2,
                    weight_decay=hp.weight_decay),
    )


def state_shardings(mesh: Mesh,
                    cfg: ModelConfig,
                    hp: TrainHParams,
                    rules: LogicalAxisRules = DEFAULT_RULES) -> TrainState:
    """Shardings pytree matching TrainState (opt state mirrors params)."""
    param_sh = shard_params_pytree(mesh, llama.param_logical_axes(cfg), rules)
    optimizer = make_optimizer(hp)
    param_shapes = jax.eval_shape(
        functools.partial(llama.init_params, cfg=cfg), jax.random.key(0))
    opt_shape = jax.eval_shape(optimizer.init, param_shapes)

    # Optax state embeds params-shaped subtrees (adam mu/nu). Map each opt
    # leaf to the sharding of the param whose tree path is a suffix of the
    # opt leaf's path -- exact regardless of shape collisions (two params
    # with equal shapes but different shardings, e.g. square MLPs).
    param_shape_leaves = {
        tuple(path): leaf.shape
        for path, leaf
        in jax.tree_util.tree_flatten_with_path(param_shapes)[0]
    }
    param_paths = {
        tuple(path): sh
        for path, sh in jax.tree_util.tree_flatten_with_path(param_sh)[0]
    }
    replicated = NamedSharding(mesh, P())

    def map_opt_leaf(path, leaf):
        # Only leaves with the param's EXACT shape inherit its sharding
        # (adam mu/nu). Rank-reduced stats (adafactor v_row/v_col drop a
        # dim) stay replicated -- a shard_shape probe can't catch them on
        # meshes whose axes are all size 1, where any spec "fits".
        path = tuple(path)
        for plen in range(len(path), 0, -1):
            suffix = path[-plen:]
            if suffix in param_paths:
                if param_shape_leaves[suffix] == tuple(leaf.shape):
                    return param_paths[suffix]
                break
        return replicated

    opt_sh = jax.tree_util.tree_map_with_path(map_opt_leaf, opt_shape)
    return TrainState(step=replicated, params=param_sh, opt_state=opt_sh)


def create_train_state(rng: jax.Array,
                       cfg: ModelConfig,
                       hp: TrainHParams,
                       mesh: Mesh,
                       rules: LogicalAxisRules = DEFAULT_RULES,
                       shardings: Optional[TrainState] = None) -> TrainState:
    """Initialize params+opt state directly sharded across the mesh."""
    optimizer = make_optimizer(hp)
    if shardings is None:
        shardings = state_shardings(mesh, cfg, hp, rules)

    def init_fn(rng):
        params = llama.init_params(rng, cfg)
        opt_state = optimizer.init(params)
        return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                          opt_state=opt_state)

    with use_mesh(mesh):
        init_jit = jax.jit(init_fn, out_shardings=shardings)
        return init_jit(rng)


def create_train_state_from_params(params: Params,
                                   cfg: ModelConfig,
                                   hp: TrainHParams,
                                   mesh: Mesh,
                                   rules: LogicalAxisRules = DEFAULT_RULES,
                                   shardings: Optional[TrainState] = None
                                   ) -> TrainState:
    """TrainState around EXISTING params (finetuning a loaded
    checkpoint): params are placed on the mesh and the optimizer state
    initializes sharded on-device, mirroring create_train_state."""
    del cfg  # layout comes from the params themselves
    optimizer = make_optimizer(hp)
    if shardings is None:
        raise ValueError('shardings required (state_shardings(...))')
    params = jax.device_put(params, shardings.params)

    def init_fn(p):
        return TrainState(step=jnp.zeros((), jnp.int32), params=p,
                          opt_state=optimizer.init(p))

    with use_mesh(mesh):
        init_jit = jax.jit(init_fn, out_shardings=shardings,
                           in_shardings=(shardings.params,))
        return init_jit(params)


def train_step_fn(state: TrainState,
                  batch: Dict[str, jax.Array],
                  cfg: ModelConfig,
                  optimizer: optax.GradientTransformation,
                  hp: TrainHParams,
                  rules: LogicalAxisRules = DEFAULT_RULES,
                  pipeline_stages: int = 1
                  ) -> Tuple[TrainState, Dict[str, jax.Array]]:
    """One SGD step. batch: tokens [B,S], targets [B,S], weights [B,S]."""

    # Router load-balancing aux loss: MoE only, and not under PP (the
    # stage body carries activations only — forward would raise).
    use_aux = (cfg.is_moe and cfg.router_aux_loss_coeff > 0
               and pipeline_stages == 1)

    def loss_fn(params):
        out = llama.forward(
            params, batch['tokens'], cfg, rules=rules,
            positions=batch.get('positions'),
            segments=batch.get('segments'),
            pipeline_stages=pipeline_stages,
            pipeline_microbatches=hp.pipeline_microbatches,
            return_aux=use_aux)
        logits, aux = out if use_aux else (out, 0.0)
        loss, _ = cross_entropy_loss(logits, batch['targets'],
                                     batch.get('weights'),
                                     z_loss_coeff=hp.z_loss_coeff)
        return loss + cfg.router_aux_loss_coeff * aux, (loss, aux)

    (total_loss, (ce_loss, aux)), grads = jax.value_and_grad(
        loss_fn, has_aux=True)(state.params)
    updates, new_opt_state = optimizer.update(grads, state.opt_state,
                                              state.params)
    new_params = optax.apply_updates(state.params, updates)
    grad_norm = optax.global_norm(grads)
    metrics = {
        # 'loss' stays plain cross-entropy for cross-run comparability
        # (dense vs MoE, pre/post aux-loss runs); the optimized
        # objective is 'total_loss'.
        'loss': ce_loss,
        'total_loss': total_loss,
        'grad_norm': grad_norm,
        'step': state.step,
        # 1.0 = perfectly balanced router (dense/non-MoE report 0).
        'router_aux': jnp.asarray(aux, jnp.float32),
    }
    new_state = TrainState(step=state.step + 1, params=new_params,
                           opt_state=new_opt_state)
    return new_state, metrics


def make_train_step(cfg: ModelConfig,
                    hp: TrainHParams,
                    mesh: Mesh,
                    rules: LogicalAxisRules = DEFAULT_RULES,
                    shardings: Optional[TrainState] = None
                    ) -> Callable[[TrainState, Dict[str, jax.Array]],
                                  Tuple[TrainState, Dict[str, jax.Array]]]:
    """The jitted, donated, mesh-contextualized train step."""
    optimizer = make_optimizer(hp)
    batch_sharding = NamedSharding(mesh, rules.spec(('batch', 'act_seq')))
    if shardings is None:
        shardings = state_shardings(mesh, cfg, hp, rules)

    step = functools.partial(train_step_fn, cfg=cfg, optimizer=optimizer,
                             hp=hp, rules=rules,
                             pipeline_stages=mesh.shape.get('stage', 1))
    jitted = jax.jit(
        step,
        in_shardings=(shardings, batch_sharding),
        out_shardings=(shardings, NamedSharding(mesh, P())),
        donate_argnums=(0,),
    )

    def wrapped(state: TrainState, batch: Dict[str, jax.Array]):
        with use_mesh(mesh):
            return jitted(state, batch)

    return wrapped


def make_forward(cfg: ModelConfig,
                 mesh: Optional[Mesh] = None,
                 rules: LogicalAxisRules = DEFAULT_RULES):
    """A jitted inference forward (used by __graft_entry__.entry)."""

    def fwd(params, tokens):
        return llama.forward(params, tokens, cfg, rules=rules)

    jitted = jax.jit(fwd)
    if mesh is None:
        return jitted

    def wrapped(params, tokens):
        with use_mesh(mesh):
            return jitted(params, tokens)

    return wrapped
