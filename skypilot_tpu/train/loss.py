"""Loss functions (fp32 softmax, optional z-loss, padding-aware)."""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def cross_entropy_loss(logits: jax.Array,
                       targets: jax.Array,
                       weights: Optional[jax.Array] = None,
                       z_loss_coeff: float = 0.0
                       ) -> Tuple[jax.Array, jax.Array]:
    """Token-weighted mean cross entropy.

    logits [B,S,V] fp32, targets [B,S] int32, weights [B,S] (1 = real token,
    0 = pad). Returns (mean_loss, total_weight). z-loss (PaLM) regularizes
    the log-partition toward 0 for bf16 stability.
    """
    logits = logits.astype(jnp.float32)
    log_z = jax.nn.logsumexp(logits, axis=-1)                      # [B,S]
    target_logits = jnp.take_along_axis(
        logits, targets[..., None], axis=-1).squeeze(-1)           # [B,S]
    nll = log_z - target_logits
    if z_loss_coeff:
        nll = nll + z_loss_coeff * jnp.square(log_z)
    if weights is None:
        weights = jnp.ones_like(nll)
    weights = weights.astype(jnp.float32)
    total_weight = jnp.maximum(weights.sum(), 1.0)
    return (nll * weights).sum() / total_weight, total_weight
