"""Checkpoint save/restore (orbax-backed) + the resume pattern.

The reference does not checkpoint model state itself -- its *pattern* is
jobs writing checkpoints to a MOUNT_CACHED bucket and resuming after
recovery (SURVEY.md §5, docs/source/examples/checkpointing.rst). Here the
in-tree trainer implements that pattern natively: save to a local dir
(which a storage mount maps to a bucket), restore-latest on startup.
"""
from __future__ import annotations

import os
from typing import Any, Callable, Optional

from skypilot_tpu.utils import log

logger = log.init_logger(__name__)


def _manager(directory: str, max_to_keep: int = 3):
    import orbax.checkpoint as ocp
    directory = os.path.abspath(os.path.expanduser(directory))
    os.makedirs(directory, exist_ok=True)
    options = ocp.CheckpointManagerOptions(max_to_keep=max_to_keep,
                                           create=True)
    return ocp.CheckpointManager(directory, options=options)


def save(directory: str, step: int, tree: Any,
         max_to_keep: int = 3) -> None:
    import orbax.checkpoint as ocp
    mgr = _manager(directory, max_to_keep)
    mgr.save(step, args=ocp.args.StandardSave(tree))
    mgr.wait_until_finished()
    mgr.close()
    logger.info('Saved checkpoint step %d to %s', step, directory)


def latest_step(directory: str) -> Optional[int]:
    directory = os.path.abspath(os.path.expanduser(directory))
    if not os.path.isdir(directory):
        return None
    mgr = _manager(directory)
    step = mgr.latest_step()
    mgr.close()
    return step


def restore(directory: str, step: int, target: Any) -> Any:
    """Restore `step` into the structure/shardings of `target`."""
    import orbax.checkpoint as ocp
    mgr = _manager(directory)
    restored = mgr.restore(
        step, args=ocp.args.StandardRestore(target))
    mgr.close()
    logger.info('Restored checkpoint step %d from %s', step, directory)
    return restored


def restore_latest(directory: str,
                   init_fn: Callable[[], Any]) -> Any:
    """Restore the newest checkpoint, or build fresh state via init_fn.

    The managed-job recovery contract: a relaunched task calls this and
    transparently resumes (tests force preemption and assert the step
    counter survives).
    """
    step = latest_step(directory)
    target = init_fn()
    if step is None:
        return target
    return restore(directory, step, target)
