"""Checkpoint save/restore (orbax-backed) + the resume pattern.

The reference does not checkpoint model state itself -- its *pattern* is
jobs writing checkpoints to a MOUNT_CACHED bucket and resuming after
recovery (SURVEY.md §5, docs/source/examples/checkpointing.rst). Here the
in-tree trainer implements that pattern natively: save to a local dir
(which a storage mount maps to a bucket), restore-latest on startup.

Topology-change restore (elastic training): ``restore``/``restore_latest``
take the *target's* shardings as truth — orbax ``StandardRestore`` reads
the checkpoint written at the old world size and re-shards params and
optimizer state into the new mesh's layout, so a gang that shrank to the
surviving slices resumes from the same step at the smaller topology
(docs/elastic_training.md).

Managers are cached per directory (orbax CheckpointManager construction
is expensive and holds a thread pool); reads are non-mutating — a
``latest_step`` probe on a job that never checkpointed must not create
the directory.
"""
from __future__ import annotations

import os
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

from skypilot_tpu.data import ckpt_manifest
from skypilot_tpu.utils import fault_injection, log

logger = log.init_logger(__name__)

# Chaos site between orbax's shard writes and the manifest commit —
# the window where a killed save must stay invisible to latest_step
# (tests/test_checkpoint_manifest.py injects a kill here).
COMMIT_SITE = 'train.ckpt.commit'

_managers: Dict[str, Tuple[Any, int]] = {}
_managers_lock = threading.Lock()


def _manager(directory: str, max_to_keep: Optional[int] = None):
    """The cached per-directory CheckpointManager.

    Never creates ``directory`` (``create=False``): writers make it
    first (see :func:`save`), readers must stay side-effect free.
    Readers pass ``max_to_keep=None`` and reuse whatever manager exists
    (retention is a writer concern); only a WRITER with a different
    ``max_to_keep`` rebuilds the manager — otherwise alternating
    save/read calls with non-default retention would close and
    reconstruct it on every call, defeating the cache.
    """
    import orbax.checkpoint as ocp
    directory = os.path.abspath(os.path.expanduser(directory))
    with _managers_lock:
        cached = _managers.get(directory)
        if cached is not None and (max_to_keep is None or
                                   cached[1] == max_to_keep):
            return cached[0]
        if cached is not None:
            cached[0].close()
        keep = 3 if max_to_keep is None else max_to_keep
        options = ocp.CheckpointManagerOptions(max_to_keep=keep,
                                               create=False)
        mgr = ocp.CheckpointManager(directory, options=options)
        _managers[directory] = (mgr, keep)
        return mgr


def close_managers() -> None:
    """Close and drop every cached manager (tests / process teardown)."""
    with _managers_lock:
        for mgr, _ in _managers.values():
            try:
                mgr.close()
            except Exception:  # pylint: disable=broad-except
                pass
        _managers.clear()


def save(directory: str, step: int, tree: Any,
         max_to_keep: int = 3) -> None:
    """Write step ``step`` and COMMIT it: after orbax finishes the
    shard files, a content-addressed manifest (per-shard sha256) is
    written tmp+rename-last into the step directory. The manifest is
    the commit marker — :func:`latest_step` only reports steps that
    have one, so a save killed between shard writes and the commit
    is invisible rather than a restorable-looking torn checkpoint.
    The manifest also feeds fleet weight fan-out and incremental
    refresh (data/fanout.py, docs/weight_distribution.md)."""
    import orbax.checkpoint as ocp
    directory = os.path.abspath(os.path.expanduser(directory))
    os.makedirs(directory, exist_ok=True)
    mgr = _manager(directory, max_to_keep)
    mgr.save(step, args=ocp.args.StandardSave(tree))
    mgr.wait_until_finished()
    fault_injection.inject(COMMIT_SITE)
    step_dir = _step_dir(directory, step)
    if step_dir is not None:
        ckpt_manifest.write(step_dir,
                            ckpt_manifest.build(step_dir, step=step))
    else:  # pragma: no cover - orbax layout changed under us
        logger.warning('step dir for %d not found under %s; manifest '
                       'not committed', step, directory)
    logger.info('Saved checkpoint step %d to %s', step, directory)


def _step_dir(directory: str, step: int) -> Optional[str]:
    """The on-disk directory orbax wrote ``step`` into (digit-named
    child whose int value is the step — tolerant of zero-padding)."""
    try:
        entries = os.listdir(directory)
    except OSError:
        return None
    for name in entries:
        if name.isdigit() and int(name) == step:
            full = os.path.join(directory, name)
            if os.path.isdir(full):
                return full
    return None


def _committed_steps(directory: str) -> Tuple[List[int], List[int]]:
    """``(committed, uncommitted)`` step numbers by manifest
    presence. A torn manifest reads as absent (ckpt_manifest.read),
    so a crash mid-commit lands in ``uncommitted``."""
    committed: List[int] = []
    uncommitted: List[int] = []
    try:
        entries = os.listdir(directory)
    except OSError:
        return committed, uncommitted
    for name in entries:
        full = os.path.join(directory, name)
        if not (name.isdigit() and os.path.isdir(full)):
            continue
        if ckpt_manifest.read(full) is not None:
            committed.append(int(name))
        else:
            uncommitted.append(int(name))
    return committed, uncommitted


def step_manifest(directory: str, step: int) -> Optional[dict]:
    """The committed shard manifest of one step (None = step absent
    or uncommitted) — what the serve controller hands fan-out
    pullers and what incremental refresh diffs against."""
    directory = os.path.abspath(os.path.expanduser(directory))
    step_dir = _step_dir(directory, step)
    if step_dir is None:
        return None
    return ckpt_manifest.read(step_dir)


def latest_step(directory: str) -> Optional[int]:
    """Newest COMMITTED step, or None. Pure read: no directory is
    created and no manager is torn down per call.

    Discovery is gated on the manifest commit marker: a step whose
    save died between orbax's shard writes and the manifest commit
    must not be offered for restore. Legacy directories written
    before manifests existed (steps present, no manifest anywhere)
    fall back to orbax's own discovery so old checkpoints stay
    restorable."""
    directory = os.path.abspath(os.path.expanduser(directory))
    if not os.path.isdir(directory):
        return None
    committed, uncommitted = _committed_steps(directory)
    if committed:
        if uncommitted:
            logger.warning(
                'Ignoring uncommitted checkpoint step(s) %s in %s '
                '(save died before manifest commit)',
                sorted(uncommitted), directory)
        return max(committed)
    mgr = _manager(directory)
    # The cached manager snapshots the step list at construction; a
    # checkpoint written by ANOTHER process (the pre-preemption
    # incarnation of this job) must still be visible.
    reload_fn = getattr(mgr, 'reload', None)
    if reload_fn is not None:
        try:
            reload_fn()
        except Exception:  # pylint: disable=broad-except
            pass
    step = mgr.latest_step()
    if step is not None and uncommitted:
        logger.warning(
            'Directory %s has pre-manifest checkpoints; returning '
            'orbax latest step %d without commit-marker gating',
            directory, step)
    return step


def restore(directory: str, step: int, target: Any) -> Any:
    """Restore `step` into the structure/shardings of `target`.

    `target` may be laid out on a DIFFERENT mesh than the writer used
    (elastic shrink/grow): StandardRestore re-shards every leaf into
    the target's shardings.
    """
    import orbax.checkpoint as ocp
    directory = os.path.abspath(os.path.expanduser(directory))
    mgr = _manager(directory)
    restored = mgr.restore(
        step, args=ocp.args.StandardRestore(target))
    logger.info('Restored checkpoint step %d from %s', step, directory)
    return restored


def restore_latest(directory: str,
                   init_fn: Callable[[], Any]) -> Any:
    """Restore the newest checkpoint, or build fresh state via init_fn.

    The managed-job recovery contract: a relaunched task calls this and
    transparently resumes (tests force preemption and assert the step
    counter survives) — including at a different world size, where the
    init_fn's shardings describe the new topology.
    """
    step = latest_step(directory)
    target = init_fn()
    if step is None:
        return target
    return restore(directory, step, target)
