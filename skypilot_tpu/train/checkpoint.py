"""Checkpoint save/restore (orbax-backed) + the resume pattern.

The reference does not checkpoint model state itself -- its *pattern* is
jobs writing checkpoints to a MOUNT_CACHED bucket and resuming after
recovery (SURVEY.md §5, docs/source/examples/checkpointing.rst). Here the
in-tree trainer implements that pattern natively: save to a local dir
(which a storage mount maps to a bucket), restore-latest on startup.

Topology-change restore (elastic training): ``restore``/``restore_latest``
take the *target's* shardings as truth — orbax ``StandardRestore`` reads
the checkpoint written at the old world size and re-shards params and
optimizer state into the new mesh's layout, so a gang that shrank to the
surviving slices resumes from the same step at the smaller topology
(docs/elastic_training.md).

Managers are cached per directory (orbax CheckpointManager construction
is expensive and holds a thread pool); reads are non-mutating — a
``latest_step`` probe on a job that never checkpointed must not create
the directory.
"""
from __future__ import annotations

import os
import threading
from typing import Any, Callable, Dict, Optional, Tuple

from skypilot_tpu.utils import log

logger = log.init_logger(__name__)

_managers: Dict[str, Tuple[Any, int]] = {}
_managers_lock = threading.Lock()


def _manager(directory: str, max_to_keep: Optional[int] = None):
    """The cached per-directory CheckpointManager.

    Never creates ``directory`` (``create=False``): writers make it
    first (see :func:`save`), readers must stay side-effect free.
    Readers pass ``max_to_keep=None`` and reuse whatever manager exists
    (retention is a writer concern); only a WRITER with a different
    ``max_to_keep`` rebuilds the manager — otherwise alternating
    save/read calls with non-default retention would close and
    reconstruct it on every call, defeating the cache.
    """
    import orbax.checkpoint as ocp
    directory = os.path.abspath(os.path.expanduser(directory))
    with _managers_lock:
        cached = _managers.get(directory)
        if cached is not None and (max_to_keep is None or
                                   cached[1] == max_to_keep):
            return cached[0]
        if cached is not None:
            cached[0].close()
        keep = 3 if max_to_keep is None else max_to_keep
        options = ocp.CheckpointManagerOptions(max_to_keep=keep,
                                               create=False)
        mgr = ocp.CheckpointManager(directory, options=options)
        _managers[directory] = (mgr, keep)
        return mgr


def close_managers() -> None:
    """Close and drop every cached manager (tests / process teardown)."""
    with _managers_lock:
        for mgr, _ in _managers.values():
            try:
                mgr.close()
            except Exception:  # pylint: disable=broad-except
                pass
        _managers.clear()


def save(directory: str, step: int, tree: Any,
         max_to_keep: int = 3) -> None:
    import orbax.checkpoint as ocp
    directory = os.path.abspath(os.path.expanduser(directory))
    os.makedirs(directory, exist_ok=True)
    mgr = _manager(directory, max_to_keep)
    mgr.save(step, args=ocp.args.StandardSave(tree))
    mgr.wait_until_finished()
    logger.info('Saved checkpoint step %d to %s', step, directory)


def latest_step(directory: str) -> Optional[int]:
    """Newest checkpointed step, or None. Pure read: no directory is
    created and no manager is torn down per call."""
    directory = os.path.abspath(os.path.expanduser(directory))
    if not os.path.isdir(directory):
        return None
    mgr = _manager(directory)
    # The cached manager snapshots the step list at construction; a
    # checkpoint written by ANOTHER process (the pre-preemption
    # incarnation of this job) must still be visible.
    reload_fn = getattr(mgr, 'reload', None)
    if reload_fn is not None:
        try:
            reload_fn()
        except Exception:  # pylint: disable=broad-except
            pass
    return mgr.latest_step()


def restore(directory: str, step: int, target: Any) -> Any:
    """Restore `step` into the structure/shardings of `target`.

    `target` may be laid out on a DIFFERENT mesh than the writer used
    (elastic shrink/grow): StandardRestore re-shards every leaf into
    the target's shardings.
    """
    import orbax.checkpoint as ocp
    directory = os.path.abspath(os.path.expanduser(directory))
    mgr = _manager(directory)
    restored = mgr.restore(
        step, args=ocp.args.StandardRestore(target))
    logger.info('Restored checkpoint step %d from %s', step, directory)
    return restored


def restore_latest(directory: str,
                   init_fn: Callable[[], Any]) -> Any:
    """Restore the newest checkpoint, or build fresh state via init_fn.

    The managed-job recovery contract: a relaunched task calls this and
    transparently resumes (tests force preemption and assert the step
    counter survives) — including at a different world size, where the
    init_fn's shardings describe the new topology.
    """
    step = latest_step(directory)
    target = init_fn()
    if step is None:
        return target
    return restore(directory, step, target)
