"""GRPO post-training driver: RL with group-relative advantages.

    python -m skypilot_tpu.train.grpo --model tiny --steps 30 \
        --checkpoint-dir ~/ckpts

The TPU-native equivalent of the reference's ``llm/verl`` GRPO recipes
(BASELINE.json config #5: GRPO on preemptible TPUs with managed-job
recovery). The algorithm (DeepSeekMath-style GRPO):

  1. sample G rollouts per prompt from the current policy (KV-cache
     decode path, temperature > 0);
  2. score each rollout with a verifiable reward;
  3. advantage = (reward - group mean) / group std  -- no value network;
  4. policy-gradient step on sum(logprob * advantage) over generated
     tokens.

The built-in verifiable task: each prompt ends with a "target" token and
the reward is the fraction of generated tokens equal to it -- a policy
that learns to repeat the cue earns reward 1.0, so learning is observable
in a few dozen steps even on the tiny test model (the same contract as a
real RLVR task, minus the external grader).

Checkpoint/resume follows the managed-jobs recovery pattern: state is
saved to --checkpoint-dir every --checkpoint-every steps and restored on
restart, so a preempted spot job continues where it left off.
"""
from __future__ import annotations

import argparse
import dataclasses
import functools
import json
import os
import sys
import time
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import optax


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class GrpoState:
    step: jax.Array
    params: Dict
    opt_state: object


def make_prompts(rng: jax.Array, n: int, prompt_len: int,
                 vocab_size: int) -> Tuple[jax.Array, jax.Array]:
    """Prompts whose last token is the repeat-me cue."""
    body = jax.random.randint(rng, (n, prompt_len), 3, vocab_size)
    targets = body[:, -1]
    return body, targets


def reward_fn(generated: jax.Array, targets: jax.Array) -> jax.Array:
    """Fraction of generated tokens equal to the cue token. [P*G] -> r."""
    return jnp.mean(
        (generated == targets[:, None]).astype(jnp.float32), axis=1)


def grpo_advantages(rewards: jax.Array, group_size: int) -> jax.Array:
    """[P*G] rewards -> group-normalized advantages (GRPO core)."""
    grouped = rewards.reshape(-1, group_size)
    mean = grouped.mean(axis=1, keepdims=True)
    std = grouped.std(axis=1, keepdims=True)
    return ((grouped - mean) / (std + 1e-6)).reshape(-1)


def make_grpo_step(cfg, optimizer):
    from skypilot_tpu.models import llama

    def loss_fn(params, tokens, gen_mask, advantages):
        """tokens [B, T]: prompt+generated; gen_mask marks generated
        positions; maximize sum(adv * logprob(token))."""
        logits = llama.forward(params, tokens[:, :-1], cfg)
        logprobs = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        chosen = jnp.take_along_axis(
            logprobs, tokens[:, 1:, None], axis=-1)[..., 0]   # [B, T-1]
        mask = gen_mask[:, 1:].astype(jnp.float32)
        seq_logprob = (chosen * mask).sum(axis=1)
        loss = -(advantages * seq_logprob).mean()
        return loss, (seq_logprob.mean(),)

    @jax.jit
    def step(state: GrpoState, tokens, gen_mask, advantages):
        (loss, (mean_lp,)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state.params, tokens, gen_mask,
                                   advantages)
        updates, opt_state = optimizer.update(grads, state.opt_state,
                                              state.params)
        params = optax.apply_updates(state.params, updates)
        return GrpoState(step=state.step + 1, params=params,
                         opt_state=opt_state), {
                             'loss': loss, 'mean_logprob': mean_lp}

    return step


class GrpoLearner:
    """Reusable GRPO learner: owns the policy state, one ``learn()``
    per rollout batch, checkpoint save/restore.

    Extracted from ``main()`` so the RL pipeline
    (``jobs/rl_pipeline.py``) can drive the same optimizer loop from
    queued rollout batches while rollout generation runs elsewhere;
    ``version`` (the step counter) doubles as the published policy
    version the pipeline's staleness accounting is measured in."""

    def __init__(self, cfg, *, learning_rate: float = 1e-4,
                 checkpoint_dir=None, seed: int = 0) -> None:
        from skypilot_tpu.models import llama
        self.cfg = cfg
        self.checkpoint_dir = checkpoint_dir
        self.optimizer = optax.adamw(learning_rate)
        params = llama.init_params(jax.random.key(seed), cfg)
        self.state = GrpoState(step=jnp.zeros((), jnp.int32),
                               params=params,
                               opt_state=self.optimizer.init(params))
        self.resumed_from = None
        if checkpoint_dir:
            from skypilot_tpu.train import checkpoint as ckpt_lib
            latest = ckpt_lib.latest_step(checkpoint_dir)
            if latest is not None:
                self.state = ckpt_lib.restore(checkpoint_dir, latest,
                                              self.state)
                self.resumed_from = int(self.state.step)
        self._step_fn = make_grpo_step(cfg, self.optimizer)

    @property
    def params(self):
        return self.state.params

    @property
    def version(self) -> int:
        return int(self.state.step)

    def learn(self, tokens, gen_mask, advantages) -> Dict[str, float]:
        self.state, metrics = self._step_fn(self.state, tokens,
                                            gen_mask, advantages)
        return {k: float(v) for k, v in metrics.items()}

    def learn_rollouts(self, prompts, generated, rewards,
                       group_size: int) -> Dict[str, float]:
        """One GRPO step straight from rollout arrays: ``prompts``
        [P*G, L] (already tiled), ``generated`` [P*G, N], ``rewards``
        [P*G]."""
        prompts = jnp.asarray(prompts)
        generated = jnp.asarray(generated)
        advantages = grpo_advantages(jnp.asarray(rewards), group_size)
        tokens = jnp.concatenate([prompts, generated], axis=1)
        gen_mask = jnp.concatenate(
            [jnp.zeros_like(prompts), jnp.ones_like(generated)],
            axis=1)
        out = self.learn(tokens, gen_mask, advantages)
        out['mean_reward'] = float(jnp.asarray(rewards).mean())
        return out

    def save(self) -> None:
        if self.checkpoint_dir:
            from skypilot_tpu.train import checkpoint as ckpt_lib
            ckpt_lib.save(self.checkpoint_dir, self.version, self.state)


def engine_rollouts(engine, tiled, *, max_new_tokens: int,
                    temperature: float, step: int,
                    timeout: float = 300.0):
    """Sample one rollout wave through the continuous engine: submit
    every row of ``tiled`` [B, L] as its own request (G copies of a
    prompt share their prefill through the prefix cache; repeated
    prompts give prompt-lookup speculation its best-case acceptance),
    then harvest in order. Returns ([B, N] generated, min policy
    version that served the wave)."""
    handles = [
        engine.submit_ids(
            [int(t) for t in row],
            max_new_tokens=max_new_tokens,
            temperature=temperature,
            # Deterministic per-request seed: rollout i of step s
            # always samples the same stream, so a wave is replayable
            # while G siblings still explore G distinct streams.
            seed=(step << 20) | i)
        for i, row in enumerate(tiled)
    ]
    outs = []
    version = None
    for handle in handles:
        if not handle.done.wait(timeout):
            raise TimeoutError('rollout generation timed out')
        if handle.error is not None:
            raise handle.error
        outs.append(handle.generated)
        version = (handle.policy_version if version is None
                   else min(version, handle.policy_version))
    return jnp.asarray(outs, jnp.int32), (version or 0)


def main(argv=None) -> int:
    from skypilot_tpu.utils.jax_env import honor_jax_platforms
    honor_jax_platforms()
    parser = argparse.ArgumentParser()
    parser.add_argument('--model', default='tiny')
    parser.add_argument('--steps', type=int, default=30)
    parser.add_argument('--prompts-per-step', type=int, default=4)
    parser.add_argument('--group-size', type=int, default=4)
    parser.add_argument('--prompt-len', type=int, default=8)
    parser.add_argument('--max-new-tokens', type=int, default=8)
    parser.add_argument('--temperature', type=float, default=1.0)
    parser.add_argument('--learning-rate', type=float, default=1e-4)
    parser.add_argument('--checkpoint-dir', default=None)
    parser.add_argument('--checkpoint-every', type=int, default=10)
    parser.add_argument('--log-every', type=int, default=5)
    parser.add_argument('--vocab-size', type=int, default=None,
                        help='Override model vocab (smoke-scale runs: a '
                             'small vocab makes the repeat-reward dense '
                             'enough to learn in a few steps).')
    parser.add_argument('--num-prompts', type=int, default=256,
                        help='Size of the (synthetic) prompt dataset; '
                             'steps cycle through it.')
    parser.add_argument('--attention-impl', default=None,
                        help="Override the model's attention impl for "
                             'the policy-gradient step (default: keep '
                             "the config's, i.e. the flash kernel on "
                             'TPU; unsupported shapes fall back to XLA '
                             'inside the dispatch).')
    parser.add_argument('--rollout-backend', default='engine',
                        choices=('engine', 'loop'),
                        help='How rollouts are sampled: "engine" '
                             '(default) serves them through the '
                             'continuous batching engine — paged KV, '
                             'prompt-set prefix reuse, optional '
                             'speculative decoding — with a live '
                             'weight refresh after every learner '
                             'step; "loop" keeps the naive '
                             'decode.generate loop (the parity '
                             'baseline).')
    args = parser.parse_args(argv)

    from skypilot_tpu.models import decode
    from skypilot_tpu.models.config import get_model_config

    # The RL step used to hard-force 'xla' (r2 verdict weak #3) — the
    # O(S^2) HBM-materializing path. The kernel dispatch now handles
    # small/odd shapes (per-shape fallback) and meshes (shard_map), so
    # the config's impl is safe to keep.
    overrides = {}
    if args.attention_impl:
        overrides['attention_impl'] = args.attention_impl
    if args.vocab_size:
        overrides['vocab_size'] = args.vocab_size
    cfg = get_model_config(args.model, **overrides)
    learner = GrpoLearner(cfg, learning_rate=args.learning_rate,
                          checkpoint_dir=args.checkpoint_dir)
    start_step = learner.version
    if learner.resumed_from is not None:
        print(json.dumps({'resumed_from_step': learner.resumed_from}),
              flush=True)
    p, g = args.prompts_per_step, args.group_size
    # The prompt "dataset": a fixed pool, cycled per step (a real RLVR
    # recipe would load prompts from a file/bucket here).
    pool, pool_targets = make_prompts(jax.random.key(42),
                                      args.num_prompts, args.prompt_len,
                                      cfg.vocab_size)

    engine = None
    if args.rollout_backend == 'engine' and start_step < args.steps:
        from skypilot_tpu.inference.continuous import \
            ContinuousBatchingEngine
        engine = ContinuousBatchingEngine(
            cfg=cfg, params=learner.params,
            max_slots=min(p * g, 8),
            max_len=min(cfg.max_seq_len,
                        args.prompt_len + args.max_new_tokens + 1))

    try:
        for step in range(start_step, args.steps):
            idx = (step * p + jnp.arange(p)) % args.num_prompts
            prompts, targets = pool[idx], pool_targets[idx]
            # G rollouts per prompt: tile the batch.
            tiled = jnp.repeat(prompts, g, axis=0)          # [P*G, L]
            tiled_targets = jnp.repeat(targets, g)
            if engine is not None:
                generated, _ = engine_rollouts(
                    engine, list(map(list, tiled.tolist())),
                    max_new_tokens=args.max_new_tokens,
                    temperature=args.temperature, step=step)
            else:
                sample_rng = jax.random.key(1000 + step)
                lengths = jnp.full((p * g,), args.prompt_len,
                                   jnp.int32)
                generated, _ = decode.generate(
                    learner.params, tiled, lengths, cfg,
                    max_new_tokens=args.max_new_tokens,
                    temperature=args.temperature, rng=sample_rng)
            rewards = reward_fn(generated, tiled_targets)
            metrics = learner.learn_rollouts(tiled, generated, rewards,
                                             g)
            if engine is not None:
                # Live in-place refresh: the engine serves the next
                # wave on the post-step policy without tearing down
                # (standalone mode is fully on-policy: staleness 0).
                engine.refresh_weights(params=learner.params,
                                       version=learner.version)
            if (step + 1) % args.log_every == 0 or \
                    step + 1 == args.steps:
                print(json.dumps({
                    'step': step + 1,
                    'mean_reward': round(metrics['mean_reward'], 4),
                    'loss': round(metrics['loss'], 4),
                }), flush=True)
            if (args.checkpoint_dir and
                    ((step + 1) % args.checkpoint_every == 0 or
                     step + 1 == args.steps)):
                learner.save()
    finally:
        if engine is not None:
            engine.shutdown()
    print(json.dumps({'done': True, 'final_step': args.steps}), flush=True)
    return 0


if __name__ == '__main__':
    sys.exit(main())
