"""GRPO post-training driver: RL with group-relative advantages.

    python -m skypilot_tpu.train.grpo --model tiny --steps 30 \
        --checkpoint-dir ~/ckpts

The TPU-native equivalent of the reference's ``llm/verl`` GRPO recipes
(BASELINE.json config #5: GRPO on preemptible TPUs with managed-job
recovery). The algorithm (DeepSeekMath-style GRPO):

  1. sample G rollouts per prompt from the current policy (KV-cache
     decode path, temperature > 0);
  2. score each rollout with a verifiable reward;
  3. advantage = (reward - group mean) / group std  -- no value network;
  4. policy-gradient step on sum(logprob * advantage) over generated
     tokens.

The built-in verifiable task: each prompt ends with a "target" token and
the reward is the fraction of generated tokens equal to it -- a policy
that learns to repeat the cue earns reward 1.0, so learning is observable
in a few dozen steps even on the tiny test model (the same contract as a
real RLVR task, minus the external grader).

Checkpoint/resume follows the managed-jobs recovery pattern: state is
saved to --checkpoint-dir every --checkpoint-every steps and restored on
restart, so a preempted spot job continues where it left off.
"""
from __future__ import annotations

import argparse
import dataclasses
import functools
import json
import os
import sys
import time
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import optax


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class GrpoState:
    step: jax.Array
    params: Dict
    opt_state: object


def make_prompts(rng: jax.Array, n: int, prompt_len: int,
                 vocab_size: int) -> Tuple[jax.Array, jax.Array]:
    """Prompts whose last token is the repeat-me cue."""
    body = jax.random.randint(rng, (n, prompt_len), 3, vocab_size)
    targets = body[:, -1]
    return body, targets


def reward_fn(generated: jax.Array, targets: jax.Array) -> jax.Array:
    """Fraction of generated tokens equal to the cue token. [P*G] -> r."""
    return jnp.mean(
        (generated == targets[:, None]).astype(jnp.float32), axis=1)


def grpo_advantages(rewards: jax.Array, group_size: int) -> jax.Array:
    """[P*G] rewards -> group-normalized advantages (GRPO core)."""
    grouped = rewards.reshape(-1, group_size)
    mean = grouped.mean(axis=1, keepdims=True)
    std = grouped.std(axis=1, keepdims=True)
    return ((grouped - mean) / (std + 1e-6)).reshape(-1)


def make_grpo_step(cfg, optimizer):
    from skypilot_tpu.models import llama

    def loss_fn(params, tokens, gen_mask, advantages):
        """tokens [B, T]: prompt+generated; gen_mask marks generated
        positions; maximize sum(adv * logprob(token))."""
        logits = llama.forward(params, tokens[:, :-1], cfg)
        logprobs = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        chosen = jnp.take_along_axis(
            logprobs, tokens[:, 1:, None], axis=-1)[..., 0]   # [B, T-1]
        mask = gen_mask[:, 1:].astype(jnp.float32)
        seq_logprob = (chosen * mask).sum(axis=1)
        loss = -(advantages * seq_logprob).mean()
        return loss, (seq_logprob.mean(),)

    @jax.jit
    def step(state: GrpoState, tokens, gen_mask, advantages):
        (loss, (mean_lp,)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state.params, tokens, gen_mask,
                                   advantages)
        updates, opt_state = optimizer.update(grads, state.opt_state,
                                              state.params)
        params = optax.apply_updates(state.params, updates)
        return GrpoState(step=state.step + 1, params=params,
                         opt_state=opt_state), {
                             'loss': loss, 'mean_logprob': mean_lp}

    return step


def main(argv=None) -> int:
    from skypilot_tpu.utils.jax_env import honor_jax_platforms
    honor_jax_platforms()
    parser = argparse.ArgumentParser()
    parser.add_argument('--model', default='tiny')
    parser.add_argument('--steps', type=int, default=30)
    parser.add_argument('--prompts-per-step', type=int, default=4)
    parser.add_argument('--group-size', type=int, default=4)
    parser.add_argument('--prompt-len', type=int, default=8)
    parser.add_argument('--max-new-tokens', type=int, default=8)
    parser.add_argument('--temperature', type=float, default=1.0)
    parser.add_argument('--learning-rate', type=float, default=1e-4)
    parser.add_argument('--checkpoint-dir', default=None)
    parser.add_argument('--checkpoint-every', type=int, default=10)
    parser.add_argument('--log-every', type=int, default=5)
    parser.add_argument('--vocab-size', type=int, default=None,
                        help='Override model vocab (smoke-scale runs: a '
                             'small vocab makes the repeat-reward dense '
                             'enough to learn in a few steps).')
    parser.add_argument('--num-prompts', type=int, default=256,
                        help='Size of the (synthetic) prompt dataset; '
                             'steps cycle through it.')
    parser.add_argument('--attention-impl', default=None,
                        help="Override the model's attention impl for "
                             'the policy-gradient step (default: keep '
                             "the config's, i.e. the flash kernel on "
                             'TPU; unsupported shapes fall back to XLA '
                             'inside the dispatch).')
    args = parser.parse_args(argv)

    from skypilot_tpu.models import decode, llama
    from skypilot_tpu.models.config import get_model_config
    from skypilot_tpu.train import checkpoint as ckpt_lib

    # The RL step used to hard-force 'xla' (r2 verdict weak #3) — the
    # O(S^2) HBM-materializing path. The kernel dispatch now handles
    # small/odd shapes (per-shape fallback) and meshes (shard_map), so
    # the config's impl is safe to keep.
    overrides = {}
    if args.attention_impl:
        overrides['attention_impl'] = args.attention_impl
    if args.vocab_size:
        overrides['vocab_size'] = args.vocab_size
    cfg = get_model_config(args.model, **overrides)
    optimizer = optax.adamw(args.learning_rate)

    def init_state() -> GrpoState:
        params = llama.init_params(jax.random.key(0), cfg)
        return GrpoState(step=jnp.zeros((), jnp.int32), params=params,
                         opt_state=optimizer.init(params))

    state = init_state()
    start_step = 0
    if args.checkpoint_dir:
        latest = ckpt_lib.latest_step(args.checkpoint_dir)
        if latest is not None:
            state = ckpt_lib.restore(args.checkpoint_dir, latest, state)
            start_step = int(state.step)
            print(json.dumps({'resumed_from_step': start_step}),
                  flush=True)
    grpo_step = make_grpo_step(cfg, optimizer)
    p, g = args.prompts_per_step, args.group_size
    # The prompt "dataset": a fixed pool, cycled per step (a real RLVR
    # recipe would load prompts from a file/bucket here).
    pool, pool_targets = make_prompts(jax.random.key(42),
                                      args.num_prompts, args.prompt_len,
                                      cfg.vocab_size)

    for step in range(start_step, args.steps):
        sample_rng = jax.random.key(1000 + step)
        idx = (step * p + jnp.arange(p)) % args.num_prompts
        prompts, targets = pool[idx], pool_targets[idx]
        # G rollouts per prompt: tile the batch, one sampled seed per step
        tiled = jnp.repeat(prompts, g, axis=0)              # [P*G, L]
        tiled_targets = jnp.repeat(targets, g)
        lengths = jnp.full((p * g,), args.prompt_len, jnp.int32)
        generated, _ = decode.generate(
            state.params, tiled, lengths, cfg,
            max_new_tokens=args.max_new_tokens,
            temperature=args.temperature, rng=sample_rng)
        rewards = reward_fn(generated, tiled_targets)
        advantages = grpo_advantages(rewards, g)
        tokens = jnp.concatenate([tiled, generated], axis=1)
        gen_mask = jnp.concatenate(
            [jnp.zeros_like(tiled), jnp.ones_like(generated)], axis=1)
        state, metrics = grpo_step(state, tokens, gen_mask, advantages)
        if (step + 1) % args.log_every == 0 or step + 1 == args.steps:
            print(json.dumps({
                'step': step + 1,
                'mean_reward': round(float(rewards.mean()), 4),
                'loss': round(float(metrics['loss']), 4),
            }), flush=True)
        if (args.checkpoint_dir and
                ((step + 1) % args.checkpoint_every == 0 or
                 step + 1 == args.steps)):
            ckpt_lib.save(args.checkpoint_dir, step + 1, state)
    print(json.dumps({'done': True, 'final_step': args.steps}), flush=True)
    return 0


if __name__ == '__main__':
    sys.exit(main())
