"""Pretraining loop: loss, optimizer, sharded train step, checkpointing.

The in-tree MaxText-equivalent: BASELINE.md's north star workload
(Llama-3-8B pretraining on a v5p-64 slice) runs this module via a launched
task (`recipes/`).
"""
from skypilot_tpu.train.step import (TrainState, create_train_state,
                                     make_train_step, train_step_fn)
from skypilot_tpu.train.loss import cross_entropy_loss

__all__ = [
    'TrainState', 'create_train_state', 'make_train_step', 'train_step_fn',
    'cross_entropy_loss',
]
