"""Distributed request tracing: trace identity, propagation, sampling.

The platform's cross-process latency questions ("which hop made p99
regress?") need one request followed through every process it touches:
client SDK -> API server -> executor runner -> forked request child ->
backend/provision/data-transfer, and on the data plane serve-LB ->
inference engine. This module is the identity + propagation layer
(Dapper-style): spans carry ``trace_id``/``span_id``/``parent_span_id``,
contexts travel as W3C ``traceparent`` strings (HTTP header between
client/server/LB/replica, ``SKYT_TRACE_CONTEXT`` env into child
processes), and finished spans land in the durable per-trace store
(``utils/trace_store.py``) that ``GET /api/trace/<request_id>`` and
``skyt trace`` read back with the computed critical path.

Sampling (arm with ``SKYT_TRACE_SAMPLE``; unset = tracing fully off,
near-zero overhead on every instrumented path):

* **Head sampling** — the keep decision is a pure function of
  ``trace_id`` and the rate, so every process reaches the SAME verdict
  without coordination (Dapper's trick: sample traces, not spans).
* **Tail keep** — non-head-sampled spans are buffered in-process
  (bounded by ``SKYT_TRACE_BUFFER``); a span finishing with an error,
  or running past ``SKYT_TRACE_SLOW_MS``, promotes its whole buffered
  trace to the store. Errored/deadline-busting requests are therefore
  always inspectable even at sample rate 0.

Threading: the ambient context is a thread-local stack (``span(...)``
context managers push/pop); event-loop and scheduler code that cannot
use ambient nesting creates explicit spans via :func:`start_span` /
:func:`record_span`. Never raises into callers: a broken store degrades
to dropped spans, not failed requests.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, NamedTuple, Optional, Tuple

from skypilot_tpu.utils import env_registry, log

logger = log.init_logger(__name__)

SAMPLE_ENV = 'SKYT_TRACE_SAMPLE'
CONTEXT_ENV = 'SKYT_TRACE_CONTEXT'
TRACEPARENT_HEADER = 'traceparent'

_HEX = frozenset('0123456789abcdef')

_lock = threading.Lock()
_tls = threading.local()
# Non-head-sampled spans buffered per trace awaiting a tail trigger
# (error / slow). Bounded: oldest trace evicted past SKYT_TRACE_BUFFER
# total spans.
_buffers: 'Dict[str, List[dict]]' = {}
_buffered = 0
_dropped = 0
_service = 'python'
# Stable small per-thread lane ids (threading.get_ident() values are
# huge and reused; a modulo of them can collide two threads into one
# timeline lane — the bug class the timeline satellite fixes).
_tids: Dict[int, int] = {}


def set_service(name: str) -> None:
    """Process-wide service name stamped on spans (e.g. 'api-server',
    'executor', 'serve-lb', 'inference')."""
    global _service
    _service = name


def stable_tid() -> int:
    """Small, stable, per-process thread id (1, 2, 3, ...)."""
    ident = threading.get_ident()
    tid = _tids.get(ident)
    if tid is None:
        with _lock:
            tid = _tids.setdefault(ident, len(_tids) + 1)
    return tid


# -- arming + sampling --------------------------------------------------


def armed() -> bool:
    """Tracing records spans only when SKYT_TRACE_SAMPLE is set at all
    (even to 0 — rate 0 still buffers for tail-keep). Unset = the
    instrumentation reduces to one dict lookup per site."""
    return SAMPLE_ENV in os.environ


def sample_rate() -> float:
    rate = env_registry.get_float(SAMPLE_ENV, default=0.0)
    return 0.0 if rate is None else rate


def head_keep(trace_id: str, rate: Optional[float] = None) -> bool:
    """Deterministic head-sampling verdict: a pure function of the
    trace id and the rate, so client, server, runner, and child all
    agree without coordination."""
    if rate is None:
        rate = sample_rate()
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    try:
        return int(trace_id[:8], 16) / 0x100000000 < rate
    except (ValueError, IndexError):
        return False


def slow_ms() -> float:
    return env_registry.get_float('SKYT_TRACE_SLOW_MS')


def _buffer_cap() -> int:
    return env_registry.get_int('SKYT_TRACE_BUFFER', minimum=1)


# -- identity + propagation --------------------------------------------


def new_trace_id() -> str:
    return os.urandom(16).hex()


def new_span_id() -> str:
    return os.urandom(8).hex()


class SpanContext(NamedTuple):
    trace_id: str
    span_id: str

    @classmethod
    def new_root(cls) -> 'SpanContext':
        return cls(new_trace_id(), new_span_id())

    def child(self) -> 'SpanContext':
        return SpanContext(self.trace_id, new_span_id())

    def to_traceparent(self) -> str:
        flags = '01' if head_keep(self.trace_id) else '00'
        return f'00-{self.trace_id}-{self.span_id}-{flags}'


def parse_traceparent(value: Optional[str]) -> Optional[SpanContext]:
    """W3C traceparent -> context; anything malformed reads as absent
    (a bad header from a foreign client must not break the request)."""
    if not value:
        return None
    parts = value.strip().split('-')
    if len(parts) != 4:
        return None
    _version, trace_id, span_id, _flags = parts
    if (len(trace_id) != 32 or len(span_id) != 16 or
            not _HEX.issuperset(trace_id) or
            not _HEX.issuperset(span_id) or
            trace_id == '0' * 32 or span_id == '0' * 16):
        return None
    return SpanContext(trace_id, span_id)


def ambient() -> Optional[SpanContext]:
    """The current thread's active span context, falling back to the
    process-inherited SKYT_TRACE_CONTEXT (how an executor child joins
    its request's trace)."""
    stack = getattr(_tls, 'stack', None)
    if stack:
        return stack[-1]
    return parse_traceparent(os.environ.get(CONTEXT_ENV))


def current_ids() -> Optional[Tuple[str, str]]:
    """(trace_id, span_id) of the ambient context, or None. Cheap when
    disarmed — the events bus calls this on every publish."""
    if not armed():
        return None
    ctx = ambient()
    return (ctx.trace_id, ctx.span_id) if ctx is not None else None


def _push(ctx: SpanContext) -> None:
    stack = getattr(_tls, 'stack', None)
    if stack is None:
        stack = _tls.stack = []
    stack.append(ctx)


def _pop() -> None:
    stack = getattr(_tls, 'stack', None)
    if stack:
        stack.pop()


# -- spans --------------------------------------------------------------


class Span:
    """One in-flight span; ``finish()`` routes it to the store/buffer.
    'ts' is wall clock (viewers align processes on it); the duration is
    measured on the monotonic clock (SKYT009 discipline)."""

    __slots__ = ('name', 'context', 'parent_id', 'service', 'start_wall',
                 '_start_mono', 'annotations', '_finished')

    def __init__(self, name: str, context: SpanContext,
                 parent_id: Optional[str],
                 service: Optional[str] = None, **annotations) -> None:
        self.name = name
        self.context = context
        self.parent_id = parent_id
        self.service = service or _service
        self.start_wall = time.time()
        self._start_mono = time.monotonic()
        self.annotations = {k: v for k, v in annotations.items()
                            if v is not None}
        self._finished = False

    def annotate(self, **kv) -> None:
        self.annotations.update(
            {k: v for k, v in kv.items() if v is not None})

    def traceparent(self) -> str:
        return self.context.to_traceparent()

    def finish(self, error: Optional[BaseException] = None,
               **annotations) -> None:
        if self._finished:
            return
        self._finished = True
        self.annotate(**annotations)
        dur_ms = (time.monotonic() - self._start_mono) * 1000.0
        record = {
            'trace_id': self.context.trace_id,
            'span_id': self.context.span_id,
            'parent_span_id': self.parent_id,
            'name': self.name,
            'service': self.service,
            'pid': os.getpid(),
            'tid': stable_tid(),
            'start': self.start_wall,
            'dur_ms': round(dur_ms, 3),
            'status': 'error' if error is not None else 'ok',
        }
        if error is not None:
            record['error'] = f'{type(error).__name__}: {error}'
        if self.annotations:
            record['annotations'] = {
                k: (v if isinstance(v, (int, float, bool)) else str(v))
                for k, v in self.annotations.items()}
        _sink(record)


def start_span(name: str, parent: Optional[SpanContext] = None,
               service: Optional[str] = None,
               **annotations) -> Optional[Span]:
    """Explicit span for event-loop / scheduler code (no ambient push).
    Returns None when tracing is disarmed — callers guard on it."""
    if not armed():
        return None
    ctx = (parent.child() if parent is not None
           else SpanContext.new_root())
    return Span(name, ctx, parent.span_id if parent else None,
                service=service, **annotations)


def record_span(name: str, parent: Optional[SpanContext],
                start_wall: float, dur_s: float,
                service: Optional[str] = None,
                error: Optional[str] = None, **annotations) -> None:
    """Record an already-measured span retroactively (e.g. the
    inference engine's queue-wait, known only at admission)."""
    if not armed() or parent is None:
        return
    record = {
        'trace_id': parent.trace_id,
        'span_id': new_span_id(),
        'parent_span_id': parent.span_id,
        'name': name,
        'service': service or _service,
        'pid': os.getpid(),
        'tid': stable_tid(),
        'start': start_wall,
        'dur_ms': round(max(0.0, dur_s) * 1000.0, 3),
        'status': 'error' if error else 'ok',
    }
    if error:
        record['error'] = error
    if annotations:
        record['annotations'] = {
            k: (v if isinstance(v, (int, float, bool)) else str(v))
            for k, v in annotations.items() if v is not None}
    _sink(record)


class span:
    """``with tracing.span('server.submit', payload=name) as sp:`` —
    creates a child of the ambient context (or a new root), makes
    itself ambient for the body, records on exit (exceptions mark the
    span errored AND propagate). No-op when disarmed."""

    _AMBIENT = object()

    def __init__(self, name: str, parent=_AMBIENT,
                 service: Optional[str] = None, **annotations) -> None:
        self._name = name
        self._parent = parent
        self._service = service
        self._annotations = annotations
        self._span: Optional[Span] = None

    def __enter__(self) -> 'span':
        if not armed():
            return self
        parent = (ambient() if self._parent is span._AMBIENT
                  else self._parent)
        self._span = start_span(self._name, parent=parent,
                                service=self._service,
                                **self._annotations)
        if self._span is not None:
            _push(self._span.context)
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if self._span is None:
            return
        _pop()
        self._span.finish(error=exc)

    @property
    def context(self) -> Optional[SpanContext]:
        return self._span.context if self._span is not None else None

    def traceparent(self) -> Optional[str]:
        return self._span.traceparent() if self._span is not None \
            else None

    def annotate(self, **kv) -> None:
        if self._span is not None:
            self._span.annotate(**kv)


# -- collection ---------------------------------------------------------


def _sink(record: dict) -> None:
    """Route one finished span: head-sampled -> durable store now;
    otherwise buffer, promoting the whole trace on a tail trigger
    (error, or past the slow threshold)."""
    global _buffered, _dropped
    trace_id = record['trace_id']
    to_write: List[dict] = []
    with _lock:
        tail = (record['status'] == 'error' or
                record['dur_ms'] >= slow_ms())
        if head_keep(trace_id):
            to_write.append(record)
        elif tail:
            promoted = _buffers.pop(trace_id, [])
            _buffered -= len(promoted)
            to_write.extend(promoted)
            to_write.append(record)
        else:
            _buffers.setdefault(trace_id, []).append(record)
            _buffered += 1
            cap = _buffer_cap()
            while _buffered > cap and _buffers:
                oldest = next(iter(_buffers))
                evicted = _buffers.pop(oldest)
                _buffered -= len(evicted)
                _dropped += len(evicted)
    if to_write:
        _write(trace_id, to_write)


def flush(trace_id: str) -> None:
    """Force a trace's buffered spans into the store (used when another
    signal — e.g. a FAILED request row — says the trace matters)."""
    global _buffered
    with _lock:
        spans = _buffers.pop(trace_id, [])
        _buffered -= len(spans)
    if spans:
        _write(trace_id, spans)


def _write(trace_id: str, spans: List[dict]) -> None:
    try:
        from skypilot_tpu.utils import trace_store
        trace_store.append_spans(trace_id, spans)
    except Exception as e:  # pylint: disable=broad-except
        logger.debug('trace store append failed for %s: %s', trace_id, e)


def dropped_spans() -> int:
    return _dropped


def reset_for_tests() -> None:
    global _buffered, _dropped, _service
    with _lock:
        _buffers.clear()
        _buffered = 0
        _dropped = 0
        _service = 'python'
    if getattr(_tls, 'stack', None):
        _tls.stack = []
