"""Command runners: uniform exec/rsync over SSH or local subprocess.

Parity: ``sky/utils/command_runner.py`` (SSHCommandRunner :875,
LocalProcessCommandRunner :1834). The local runner gives every fake/local
"host" its own root directory, so multi-host TPU semantics (per-worker
workdirs, rank envs, gang start) are exercised for real on one machine.
"""
from __future__ import annotations

import os
import shlex
import subprocess
from typing import Dict, IO, List, Optional, Tuple

from skypilot_tpu import exceptions
from skypilot_tpu.provision.api import ClusterInfo, HostInfo
from skypilot_tpu.utils import log

logger = log.init_logger(__name__)

def _pycopy(src: str, dst: str, excludes=None) -> None:
    """Mirror src into dst without the rsync binary (dev images lack it)."""
    import shutil
    if not os.path.exists(src):
        raise exceptions.CommandError(1, f'copy {src}',
                                      error_msg=f'{src} does not exist')
    os.makedirs(os.path.dirname(dst.rstrip('/')) or '/', exist_ok=True)
    if os.path.isdir(src):
        ignore = (shutil.ignore_patterns(*excludes) if excludes else None)
        shutil.copytree(src, dst, dirs_exist_ok=True, ignore=ignore)
    else:
        shutil.copy2(src, dst)


# Shared by SSHCommandRunner and the head daemon's rank fan-out
# (runtime/daemon.py) -- one place to tune SSH behavior for every
# framework-issued connection.
SSH_OPTIONS = [
    '-o', 'StrictHostKeyChecking=no',
    '-o', 'UserKnownHostsFile=/dev/null',
    '-o', 'IdentitiesOnly=yes',
    '-o', 'ConnectTimeout=30',
    '-o', 'LogLevel=ERROR',
]
_SSH_OPTIONS = SSH_OPTIONS  # backward-compat alias


class CommandRunner:
    """Base: run a shell command on a host and rsync files to it."""

    def __init__(self, host: HostInfo) -> None:
        self.host = host

    def run(self,
            cmd: str,
            *,
            env: Optional[Dict[str, str]] = None,
            cwd: Optional[str] = None,
            stream_to: Optional[IO[str]] = None,
            log_path: Optional[str] = None,
            timeout: Optional[float] = None,
            check: bool = False) -> Tuple[int, str]:
        raise NotImplementedError

    def rsync(self, src: str, dst: str, *, up: bool = True,
              excludes: Optional[List[str]] = None) -> None:
        raise NotImplementedError

    def popen(self, cmd: str) -> subprocess.Popen:
        """Start `cmd` on the host with binary stdin/stdout pipes —
        the transport for long-lived framed-protocol connections
        (runtime/channel.py), which `run`'s one-shot exec can't carry."""
        raise NotImplementedError

    def _check(self, returncode: int, cmd: str, output: str,
               check: bool) -> None:
        if check and returncode != 0:
            raise exceptions.CommandError(returncode, cmd,
                                          error_msg=output[-2000:])


class LocalCommandRunner(CommandRunner):
    """Runs on this machine inside the host's private root directory."""

    def __init__(self, host: HostInfo, host_root: str) -> None:
        super().__init__(host)
        self.host_root = os.path.expanduser(host_root)
        os.makedirs(self.host_root, exist_ok=True)

    def _resolve(self, path: str) -> str:
        """Map a remote-style path (~/...) into the host root."""
        if path.startswith('~/'):
            return os.path.join(self.host_root, path[2:])
        if path == '~':
            return self.host_root
        return path

    def run(self, cmd, *, env=None, cwd=None, stream_to=None, log_path=None,
            timeout=None, check=False):
        full_env = {**os.environ, **(env or {})}
        full_env['HOME'] = self.host_root
        cwd = self._resolve(cwd) if cwd else self.host_root
        log_file = None
        if log_path:
            log_path = self._resolve(log_path)
            os.makedirs(os.path.dirname(log_path), exist_ok=True)
            log_file = open(log_path, 'a', encoding='utf-8')
        lines: List[str] = []
        try:
            proc = subprocess.Popen(['bash', '-c', cmd],
                                    cwd=cwd,
                                    env=full_env,
                                    stdout=subprocess.PIPE,
                                    stderr=subprocess.STDOUT,
                                    text=True,
                                    start_new_session=True)
            assert proc.stdout is not None
            for line in proc.stdout:
                lines.append(line)
                if stream_to is not None:
                    stream_to.write(line)
                    stream_to.flush()
                if log_file is not None:
                    log_file.write(line)
                    log_file.flush()
            returncode = proc.wait(timeout=timeout)
        finally:
            if log_file is not None:
                log_file.close()
        output = ''.join(lines)
        self._check(returncode, cmd, output, check)
        return returncode, output

    def rsync(self, src: str, dst: str, *, up: bool = True, excludes=None):
        src, dst = os.path.expanduser(src), self._resolve(dst)
        if not up:
            src, dst = dst, os.path.expanduser(src)
        _pycopy(src, dst, excludes)

    def popen(self, cmd: str) -> subprocess.Popen:
        full_env = {**os.environ, 'HOME': self.host_root}
        return subprocess.Popen(['bash', '-c', cmd], cwd=self.host_root,
                                env=full_env,
                                stdin=subprocess.PIPE,
                                stdout=subprocess.PIPE,
                                start_new_session=True)


class SSHCommandRunner(CommandRunner):
    """Runs over the `ssh` binary; files move with rsync-over-ssh."""

    def __init__(self, host: HostInfo, ssh_user: str,
                 ssh_key_path: Optional[str]) -> None:
        super().__init__(host)
        self.ssh_user = ssh_user
        self.ssh_key_path = ssh_key_path
        self.address = host.external_ip or host.internal_ip

    def _ssh_base(self) -> List[str]:
        cmd = ['ssh'] + _SSH_OPTIONS + ['-p', str(self.host.ssh_port)]
        if self.ssh_key_path:
            cmd += ['-i', os.path.expanduser(self.ssh_key_path)]
        cmd.append(f'{self.ssh_user}@{self.address}')
        return cmd

    def run(self, cmd, *, env=None, cwd=None, stream_to=None, log_path=None,
            timeout=None, check=False):
        remote = ''
        for key, value in (env or {}).items():
            remote += f'export {key}={shlex.quote(str(value))}; '
        if cwd:
            remote += f'cd {shlex.quote(cwd)}; '
        remote += cmd
        full = self._ssh_base() + [remote]
        log_file = None
        if log_path:
            os.makedirs(os.path.dirname(os.path.expanduser(log_path)),
                        exist_ok=True)
            log_file = open(os.path.expanduser(log_path), 'a',
                            encoding='utf-8')
        lines: List[str] = []
        try:
            proc = subprocess.Popen(full, stdout=subprocess.PIPE,
                                    stderr=subprocess.STDOUT, text=True)
            assert proc.stdout is not None
            for line in proc.stdout:
                lines.append(line)
                if stream_to is not None:
                    stream_to.write(line)
                    stream_to.flush()
                if log_file is not None:
                    log_file.write(line)
                    log_file.flush()
            returncode = proc.wait(timeout=timeout)
        finally:
            if log_file is not None:
                log_file.close()
        output = ''.join(lines)
        self._check(returncode, cmd, output, check)
        return returncode, output

    def popen(self, cmd: str) -> subprocess.Popen:
        return subprocess.Popen(self._ssh_base() + [cmd],
                                stdin=subprocess.PIPE,
                                stdout=subprocess.PIPE,
                                start_new_session=True)

    def rsync(self, src: str, dst: str, *, up: bool = True, excludes=None):
        ssh_cmd = ' '.join(['ssh'] + _SSH_OPTIONS +
                           ['-p', str(self.host.ssh_port)] +
                           (['-i', self.ssh_key_path] if self.ssh_key_path
                            else []))
        cmd = ['rsync', '-a', '--delete', '-e', ssh_cmd]
        for pattern in excludes or []:
            cmd += ['--exclude', pattern]
        remote = f'{self.ssh_user}@{self.address}:{dst}'
        src_arg = os.path.expanduser(src)
        if up:
            if os.path.isdir(src_arg):
                src_arg = src_arg.rstrip('/') + '/'
            cmd += [src_arg, remote]
        else:
            cmd += [remote, src_arg]
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              check=False)
        if proc.returncode != 0:
            raise exceptions.CommandError(proc.returncode, ' '.join(cmd),
                                          error_msg=proc.stderr[-500:])


class KubectlCommandRunner(CommandRunner):
    """Runs inside a pod via `kubectl exec`; files move with `kubectl cp`
    (parity: the reference's KubernetesCommandRunner,
    utils/command_runner.py:1410)."""

    def __init__(self, host: HostInfo, namespace: str) -> None:
        super().__init__(host)
        self.namespace = namespace
        self.pod = host.instance_id

    def _kubectl(self) -> List[str]:
        return ['kubectl', '-n', self.namespace]

    def run(self, cmd, *, env=None, cwd=None, stream_to=None, log_path=None,
            timeout=None, check=False):
        remote = ''
        for key, value in (env or {}).items():
            remote += f'export {key}={shlex.quote(str(value))}; '
        if cwd:
            remote += f'cd {shlex.quote(cwd)}; '
        remote += cmd
        full = self._kubectl() + ['exec', self.pod, '--', '/bin/sh', '-c',
                                  remote]
        log_file = None
        if log_path:
            os.makedirs(os.path.dirname(os.path.expanduser(log_path)),
                        exist_ok=True)
            log_file = open(os.path.expanduser(log_path), 'a',
                            encoding='utf-8')
        lines: List[str] = []
        try:
            proc = subprocess.Popen(full, stdout=subprocess.PIPE,
                                    stderr=subprocess.STDOUT, text=True)
            assert proc.stdout is not None
            for line in proc.stdout:
                lines.append(line)
                if stream_to is not None:
                    stream_to.write(line)
                    stream_to.flush()
                if log_file is not None:
                    log_file.write(line)
                    log_file.flush()
            returncode = proc.wait(timeout=timeout)
        finally:
            if log_file is not None:
                log_file.close()
        output = ''.join(lines)
        self._check(returncode, cmd, output, check)
        return returncode, output

    def popen(self, cmd: str) -> subprocess.Popen:
        full = self._kubectl() + ['exec', '-i', self.pod, '--',
                                  '/bin/sh', '-c', cmd]
        return subprocess.Popen(full, stdin=subprocess.PIPE,
                                stdout=subprocess.PIPE,
                                start_new_session=True)

    def rsync(self, src: str, dst: str, *, up: bool = True, excludes=None):
        # tar over `kubectl exec` rather than `kubectl cp`: honors
        # excludes, and `~` in dst expands inside the pod's shell
        # (kubectl cp would create a literal './~' directory).
        src_arg = os.path.expanduser(src)
        if up:
            tar_cmd = ['tar', '-C',
                       src_arg if os.path.isdir(src_arg)
                       else os.path.dirname(src_arg) or '.', '-czf', '-']
            for pattern in excludes or []:
                tar_cmd.append(f'--exclude={pattern}')
            tar_cmd.append('.' if os.path.isdir(src_arg)
                           else os.path.basename(src_arg))
            remote = (f'mkdir -p {dst} && tar -xzf - -C {dst}')
            kubectl = self._kubectl() + ['exec', '-i', self.pod, '--',
                                         '/bin/sh', '-c', remote]
            tar = subprocess.Popen(tar_cmd, stdout=subprocess.PIPE)
            proc = subprocess.run(kubectl, stdin=tar.stdout,
                                  capture_output=True, text=True,
                                  check=False)
            tar.wait()
            code = proc.returncode or tar.returncode
            if code != 0:
                raise exceptions.CommandError(
                    code, ' '.join(kubectl),
                    error_msg=(proc.stderr or '')[-500:])
        else:
            remote = f'tar -czf - -C {dst} .'
            kubectl = self._kubectl() + ['exec', self.pod, '--',
                                         '/bin/sh', '-c', remote]
            os.makedirs(src_arg, exist_ok=True)
            kproc = subprocess.Popen(kubectl, stdout=subprocess.PIPE)
            untar = subprocess.run(['tar', '-xzf', '-', '-C', src_arg],
                                   stdin=kproc.stdout,
                                   capture_output=True, text=True,
                                   check=False)
            kproc.wait()
            code = kproc.returncode or untar.returncode
            if code != 0:
                raise exceptions.CommandError(
                    code, ' '.join(kubectl),
                    error_msg=(untar.stderr or '')[-500:])


def runners_for_cluster(info: ClusterInfo) -> List[CommandRunner]:
    """One runner per host, ordered by (node_index, worker_index)."""
    local_style = info.custom.get('fake') or info.custom.get('local')
    runners: List[CommandRunner] = []
    state_dir = os.environ.get('SKYT_STATE_DIR',
                               os.path.expanduser('~/.skyt'))
    for host in info.hosts:
        if local_style:
            root = os.path.join(state_dir, 'hosts', info.cluster_name,
                                f'{host.node_index}-{host.worker_index}')
            runners.append(LocalCommandRunner(host, root))
        elif info.custom.get('kubernetes'):
            runners.append(KubectlCommandRunner(
                host, info.custom.get('namespace', 'default')))
        else:
            runners.append(SSHCommandRunner(host, info.ssh_user,
                                            info.ssh_key_path))
    return runners
