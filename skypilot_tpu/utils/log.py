"""Logger setup (parity: ``sky/sky_logging.py``)."""
from __future__ import annotations

import logging
import os
import sys

_FORMAT = '%(levelname).1s %(asctime)s %(name)s:%(lineno)d] %(message)s'
_DATE_FORMAT = '%m-%d %H:%M:%S'

_configured = False


def _configure_root() -> None:
    global _configured
    if _configured:
        return
    level_name = os.environ.get('SKYT_LOG_LEVEL', 'INFO').upper()
    level = getattr(logging, level_name, logging.INFO)
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(logging.Formatter(_FORMAT, datefmt=_DATE_FORMAT))
    root = logging.getLogger('skypilot_tpu')
    root.setLevel(level)
    if not root.handlers:
        root.addHandler(handler)
    root.propagate = False
    _configured = True


def init_logger(name: str) -> logging.Logger:
    _configure_root()
    return logging.getLogger(name)
