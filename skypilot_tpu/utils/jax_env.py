"""JAX environment shims for payload entrypoints.

The image's sitecustomize force-registers the remote-TPU backend and
IGNORES ``JAX_PLATFORMS`` — so a CPU-forced run (tests, the virtual
multi-chip dryrun, fake-cloud jobs) would still try to reach the
accelerator, hanging when the TPU tunnel is unreachable. Every
``python -m skypilot_tpu...`` payload entrypoint calls
``honor_jax_platforms()`` first thing in ``main`` to re-assert the
caller's platform choice before the backend initializes.
"""
from __future__ import annotations

import os


def honor_jax_platforms() -> None:
    platforms = os.environ.get('JAX_PLATFORMS')
    if platforms:
        import jax
        jax.config.update('jax_platforms', platforms)
