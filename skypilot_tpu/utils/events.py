"""Control-plane notification bus: event-driven wakeups over poll loops.

Every resident control-plane loop used to sleep a fixed cadence between
DB scans (executor spawner tick, pool-runner claim loop, ``/api/get``
long-poll, channel-server job-table watcher, serve controller, server
daemons) — so submit→running latency bottomed out at the poll interval
and an idle control plane burned DB round-trips doing nothing. This
module replaces the sleeps with a two-layer wakeup:

1. **In-process bus** — topic-keyed condition variables with a
   monotonic sequence cursor. Writers :func:`publish` after commit;
   same-process waiters in :func:`wait_for` wake within microseconds.
   The cursor makes delivery race-free: a publish landing between a
   reader's snapshot and its wait is seen as ``seq > cursor`` and
   returns immediately (no lost-wakeup window).

2. **Cross-process / cross-replica signal** — an
   :class:`ExternalSignal` the waiter checks on a short slice while it
   sleeps:

   * Postgres ``LISTEN/NOTIFY`` (:class:`PgNotifyListener`) when
     ``SKYT_DB_URL`` is set — writers ride a ``NOTIFY`` on their
     existing connection, listeners drain async NotificationResponse
     frames (utils/pg.py);
   * ``PRAGMA data_version`` (:class:`SqliteDataVersion`) for the
     local-sqlite backends — a single-page read that changes whenever
     ANOTHER connection commits to the file, i.e. a change *signal*,
     not a table scan.

The old poll cadence is kept as a **supervised fallback**: ``wait_for``
never blocks past ``fallback_interval``, so a lost/suppressed
notification degrades to (relaxed) polling instead of a hang. Sources
are counted per topic (``wakeup_counts``) so ``/api/metrics`` shows
notifications delivered vs fallback-poll wakeups.

Determinism / chaos: :func:`publish` runs under the
``SKYT_FAULT_SPEC`` site ``events.publish.<topic>`` (drop the notify,
keep the write) and external checks under ``events.external.<topic>``
— tests/test_events.py proves every converted loop still progresses
with both layers suppressed.

Env knobs::

    SKYT_EVENTS_DISABLED=1   # legacy behavior: wait_for = plain sleep
    SKYT_EVENTS_SLICE=0.02   # external-signal check cadence (seconds)
"""
from __future__ import annotations

import os
import threading
import time
from typing import Dict, Optional, Tuple

from skypilot_tpu.utils import env_registry, fault_injection, log

logger = log.init_logger(__name__)

# Topic names double as cross-process channel names (see pg_channel).
REQUESTS = 'requests'          # API request table (server/requests_db)
MANAGED_JOBS = 'managed-jobs'  # managed-jobs table (jobs/state)
SERVE = 'serve'                # serve services/replicas (serve/serve_state)
RUNTIME_JOBS = 'runtime-jobs'  # cluster-local job table (runtime/job_lib)
CLUSTERS = 'clusters'          # cluster records/events (state.py) — job
                               # controllers wake on preemption/health
                               # writes instead of their poll cadence
ALERTS = 'alerts'              # SLO burn-rate alert transitions
                               # (server/telemetry.py) — /api/alerts
                               # long-polls wake on pending/firing/
                               # resolved edges

DISABLE_ENV = 'SKYT_EVENTS_DISABLED'
SLICE_ENV = 'SKYT_EVENTS_SLICE'

# Wake sources (the label set of skyt_event_wakeups_total):
#   event    - in-process publish, delivered via the condition variable
#              (or found already-advanced when the wait began)
#   external - cross-process transport (LISTEN/NOTIFY or data_version)
#   catchup  - a timeout re-check found the cursor advanced (the notify
#              was lost/suppressed; the write was NOT lost)
#   fallback - fallback timeout, nothing changed (the degraded poll)
#   stop     - stop_event was set
SOURCES = ('event', 'external', 'catchup', 'fallback', 'stop')


def enabled() -> bool:
    return not env_registry.get_bool(DISABLE_ENV)


def _slice_interval() -> float:
    return max(0.005, env_registry.get_float(SLICE_ENV))


def pg_channel(topic: str) -> str:
    """NOTIFY/LISTEN channel for a topic ('-' is not identifier-safe)."""
    return 'skyt_evt_' + topic.replace('-', '_')


class _Topic:
    __slots__ = ('cond', 'seq', 'last_ctx')

    def __init__(self) -> None:
        self.cond = threading.Condition()
        self.seq = 0
        # (trace_id, span_id) of the most recent publisher's ambient
        # tracing span — wakeups become causal edges: a woken waiter
        # annotates its span with the publish that caused it.
        self.last_ctx: Optional[Tuple[str, str]] = None


_topics: Dict[str, _Topic] = {}
_topics_lock = threading.Lock()

# Process-local counters for /api/metrics (same in-memory stance as
# server/metrics.py — forked children's counts live in THEIR process).
_wakeups: Dict[Tuple[str, str], int] = {}
_published: Dict[str, int] = {}
_suppressed: Dict[str, int] = {}
_counts_lock = threading.Lock()


def _topic(name: str) -> _Topic:
    topic = _topics.get(name)
    if topic is None:
        with _topics_lock:
            topic = _topics.setdefault(name, _Topic())
    return topic


def cursor(name: str) -> int:
    """Current sequence for ``name`` — snapshot BEFORE reading the state
    you wait on, so a write landing in between reads as ``seq > cursor``
    and the next :func:`wait_for` returns immediately."""
    return _topic(name).seq


def last_context(name: str) -> Optional[Tuple[str, str]]:
    """(trace_id, span_id) of the most recent IN-PROCESS publisher on
    ``name``, for causal-edge annotations after an 'event' wake. Cross-
    process transports (LISTEN/NOTIFY, data_version) carry no payload,
    so external wakes read the last local publish — callers should only
    link when the wake source was 'event' (see docs/observability.md)."""
    return _topic(name).last_ctx


def _count_wakeup(name: str, source: str) -> None:
    with _counts_lock:
        key = (name, source)
        _wakeups[key] = _wakeups.get(key, 0) + 1


def publish(name: str, conn=None) -> int:
    """Signal a committed change on topic ``name``; returns the new
    sequence. Call AFTER the commit — waiters re-read the store on
    wake, so publishing an uncommitted write would hand them a stale
    snapshot and the fallback poll would be the only thing saving them.

    ``conn`` (optional) is the writer's DB connection: when it is a
    Postgres adapter (``SKYT_DB_URL`` deployments), a ``NOTIFY`` rides
    it so every OTHER replica's listeners wake too. Local sqlite needs
    no publisher-side action — the commit itself bumps the file's
    ``data_version``, which :class:`SqliteDataVersion` watches.

    Never raises: a failed/suppressed notify only degrades latency to
    the fallback poll (counted in ``suppressed``); the sequence still
    advances so late waiters catch up on their next wait.
    """
    topic = _topic(name)
    suppressed = False
    try:
        fault_injection.inject(f'events.publish.{name}')
    except Exception:  # pylint: disable=broad-except
        suppressed = True
    # Capture the publisher's tracing context (None when tracing is
    # disarmed — one env lookup) so in-process waiters can link their
    # wakeup back to the write that caused it.
    from skypilot_tpu.utils import tracing
    publish_ctx = tracing.current_ids()
    with topic.cond:
        topic.seq += 1
        seq = topic.seq
        if publish_ctx is not None:
            topic.last_ctx = publish_ctx
        if not suppressed:
            topic.cond.notify_all()
    with _counts_lock:
        bucket = _suppressed if suppressed else _published
        bucket[name] = bucket.get(name, 0) + 1
    if not suppressed and conn is not None and getattr(
            conn, 'is_postgres', False):
        try:
            conn.execute('NOTIFY ' + pg_channel(name))
        except Exception as e:  # pylint: disable=broad-except
            # Best-effort: an sqlite-backed PG stand-in (tests/fake_pg)
            # can't parse NOTIFY, and a flapping server may reject it —
            # peers then wake on their fallback poll instead.
            logger.debug('NOTIFY %s failed: %s', pg_channel(name), e)
    return seq


_UNSET = object()


def external_cursor(name: str, external: 'Optional[ExternalSignal]'
                    ) -> Optional[object]:
    """Snapshot the external transport's version BEFORE reading the
    state you wait on — symmetric with :func:`cursor`. Pass the result
    to :func:`wait_for` as ``external_base`` so a cross-process write
    landing DURING your read fires the next wait instead of being
    silently adopted as the baseline. ``None`` (transport unreadable)
    is a valid snapshot: the unreadable→readable transition fires."""
    return _external_version(name, external)


def wait_for(name: str,
             last_cursor: int,
             fallback_interval: float,
             external: 'Optional[ExternalSignal]' = None,
             stop_event: Optional[threading.Event] = None,
             external_base: object = _UNSET
             ) -> Tuple[int, str]:
    """Block until topic ``name`` advances past ``last_cursor``, the
    ``external`` transport signals a change, ``stop_event`` is set, or
    ``fallback_interval`` seconds pass — whichever first.

    Returns ``(new_cursor, source)`` with ``source`` in
    :data:`SOURCES`. The caller re-reads its store on ANY source — the
    bus carries "something changed", never payloads, so a spurious wake
    costs one read and a missed one costs at most the fallback
    interval. With ``SKYT_EVENTS_DISABLED=1`` this degenerates to the
    legacy bounded sleep (one ``stop_event.wait``), byte-for-byte the
    old loop behavior.
    """
    fallback_interval = max(0.0, fallback_interval)
    topic = _topic(name)
    if not enabled():
        if stop_event is not None:
            stop_event.wait(fallback_interval)
        else:
            time.sleep(fallback_interval)
        seq = topic.seq
        _count_wakeup(name, 'fallback')
        return seq, 'fallback'
    deadline = time.monotonic() + fallback_interval
    ext_base = (external_base if external_base is not _UNSET
                else _external_version(name, external))
    # Slice the sleep when anything must be checked out-of-band: the
    # external transport (no fd to select on for sqlite) or stop_event
    # (a Condition cannot be woken by an Event). Pure in-process waits
    # sleep the full interval in one cond.wait — zero idle cost.
    slice_needed = external is not None or stop_event is not None
    slice_interval = _slice_interval() if external is not None else 0.2
    while True:
        if stop_event is not None and stop_event.is_set():
            _count_wakeup(name, 'stop')
            return topic.seq, 'stop'
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            seq = topic.seq
            source = 'catchup' if seq > last_cursor else 'fallback'
            _count_wakeup(name, source)
            return seq, source
        wait_time = min(slice_interval, remaining) if slice_needed \
            else remaining
        with topic.cond:
            if topic.seq > last_cursor:
                _count_wakeup(name, 'event')
                return topic.seq, 'event'
            notified = topic.cond.wait(wait_time)
            if topic.seq > last_cursor:
                # 'catchup' = the advance was FOUND on a timeout
                # re-check, not delivered by a notify — that's how a
                # suppressed/lost notification shows up in metrics
                # while the loop still progresses.
                source = 'event' if notified else 'catchup'
                _count_wakeup(name, source)
                return topic.seq, source
        if external is not None:
            version = _external_version(name, external)
            if version is not None and version != ext_base:
                # Fires on the unreadable->readable transition too
                # (ext_base None): for SqliteDataVersion that
                # transition often IS the first write — the write
                # creates the DB file — and a spurious wake on
                # transport recovery costs one re-read, while a
                # swallowed first event costs a full poll interval.
                _count_wakeup(name, 'external')
                return topic.seq, 'external'


def _external_version(name: str, external) -> Optional[object]:
    """Never raises: a broken transport reads as 'no signal' and the
    fallback poll carries the loop (chaos site events.external.<topic>)."""
    if external is None:
        return None
    try:
        fault_injection.inject(f'events.external.{name}')
        return external.version()
    except Exception as e:  # pylint: disable=broad-except
        logger.debug('external signal for %s unreadable: %s', name, e)
        return None


# -- cross-process transports -------------------------------------------


class ExternalSignal:
    """A cheap cross-process change signal: ``version()`` returns an
    opaque value that differs after the watched store changed. May
    raise; :func:`wait_for` treats errors as 'no signal'."""

    def version(self) -> object:
        raise NotImplementedError

    def close(self) -> None:
        pass


class SqliteDataVersion(ExternalSignal):
    """``PRAGMA data_version`` watcher on one sqlite file.

    The pragma changes whenever a DIFFERENT connection commits to the
    file — one page read, no table scan, no locks taken. The value is
    only meaningful within one connection's lifetime, so reconnects
    bump a generation counter to keep versions comparable. Thread-safe
    (one shared signal serves every HTTP long-poll thread)."""

    def __init__(self, path: str) -> None:
        self._path = os.path.expanduser(path)
        self._conn = None
        self._generation = 0
        self._lock = threading.Lock()

    def version(self) -> object:
        import sqlite3
        with self._lock:
            if self._conn is None:
                if not os.path.exists(self._path):
                    # Not created yet (first write makes it): no signal
                    # rather than creating an empty DB as a side effect.
                    raise FileNotFoundError(self._path)
                self._generation += 1
                self._conn = sqlite3.connect(self._path, timeout=1,
                                             check_same_thread=False)
            try:
                row = self._conn.execute('PRAGMA data_version').fetchone()
            except sqlite3.Error:
                try:
                    self._conn.close()
                finally:
                    self._conn = None
                raise
            return (self._generation, row[0])

    def close(self) -> None:
        with self._lock:
            if self._conn is not None:
                try:
                    self._conn.close()
                finally:
                    self._conn = None


class PgNotifyListener(ExternalSignal):
    """``LISTEN``-ing Postgres connection; ``version()`` drains pending
    NotificationResponse frames non-blockingly and returns a count that
    grows with each delivery. Thread-safe; a dead connection is
    re-established lazily (a failed reconnect reads as 'no signal' and
    the fallback poll covers the gap)."""

    def __init__(self, url: str, channel: str) -> None:
        self._url = url
        self._channel = channel
        self._conn = None
        self._count = 0
        self._generation = 0
        self._lock = threading.Lock()
        self._connect_locked()

    def _connect_locked(self) -> None:
        from skypilot_tpu.utils import pg
        self._generation += 1
        self._conn = pg.PgConnection.from_url(self._url)
        self._conn.execute('LISTEN ' + self._channel)

    def version(self) -> object:
        with self._lock:
            if self._conn is None:
                self._connect_locked()
            try:
                self._count += self._conn.drain_notifications()
            except Exception:
                try:
                    self._conn.close()
                finally:
                    self._conn = None
                raise
            return (self._generation, self._count)

    def close(self) -> None:
        with self._lock:
            if self._conn is not None:
                try:
                    self._conn.close()
                finally:
                    self._conn = None


def external_signal(url: Optional[str], sqlite_path: str,
                    topic: str) -> Optional[ExternalSignal]:
    """Build the right transport for a dual-backend store: LISTEN on
    the shared Postgres when ``url`` is set (replica-wide wakeups),
    else a data_version watch on the local sqlite file. ``None`` when
    eventing is disabled or the transport can't be established (the
    caller's fallback poll then carries the loop alone)."""
    if not enabled():
        return None
    if url:
        try:
            return PgNotifyListener(url, pg_channel(topic))
        except Exception as e:  # pylint: disable=broad-except
            # e.g. an sqlite-backed PG stand-in that can't parse LISTEN
            # (tests/fake_pg), or the DB being briefly unreachable.
            logger.debug('LISTEN %s unavailable (%s); poll fallback only',
                         pg_channel(topic), e)
            return None
    return SqliteDataVersion(sqlite_path)


# -- metrics surface ----------------------------------------------------


def wakeup_counts() -> Dict[Tuple[str, str], int]:
    """(topic, source) -> wakeups, for skyt_event_wakeups_total."""
    with _counts_lock:
        return dict(_wakeups)


def publish_counts() -> Dict[str, int]:
    with _counts_lock:
        return dict(_published)


def suppressed_counts() -> Dict[str, int]:
    with _counts_lock:
        return dict(_suppressed)


def reset_for_tests() -> None:
    with _counts_lock:
        _wakeups.clear()
        _published.clear()
        _suppressed.clear()
    with _topics_lock:
        _topics.clear()
