"""Distributed locks guarding cluster state transitions.

Parity: ``sky/utils/locks.py:51`` (DistributedLock with FileLock /
PostgresLock backends). Default backend is filelock (one machine); when
the deployment runs against a shared Postgres (``SKYT_DB_URL``), the
backend switches to session advisory locks (``pg_advisory_lock`` —
exactly the reference's PostgresLock, :164) so API-server REPLICAS on
different machines serialize the same cluster's transitions.
"""
from __future__ import annotations

import hashlib
import os
import time
from typing import Optional

import filelock

from skypilot_tpu import exceptions

LOCK_DIR = os.path.expanduser('~/.skyt/locks')


class LockTimeout(exceptions.SkytError):
    pass


class _FileLockBackend:
    def __init__(self, name: str, timeout: Optional[float]) -> None:
        os.makedirs(LOCK_DIR, exist_ok=True)
        safe = name.replace('/', '_')
        self._path = os.path.join(LOCK_DIR, f'{safe}.lock')
        self._lock = filelock.FileLock(
            self._path, timeout=-1 if timeout is None else timeout)

    def acquire(self) -> None:
        try:
            self._lock.acquire()
        except filelock.Timeout as e:
            raise LockTimeout(str(e)) from None

    def release(self) -> None:
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.is_locked


class _PostgresLockBackend:
    """Session advisory lock on the shared DB (ref PostgresLock,
    sky/utils/locks.py:164): the lock key is a stable 64-bit hash of
    the name; held by THIS connection until released/closed, so a
    crashed holder's lock dies with its connection."""

    def __init__(self, name: str, url: str,
                 timeout: Optional[float]) -> None:
        self._name = name
        self._url = url
        self._timeout = timeout
        self._conn = None
        self._key = int.from_bytes(
            hashlib.sha256(name.encode()).digest()[:8], 'big',
            signed=True)
        self._held = False

    def acquire(self) -> None:
        from skypilot_tpu.utils import pg
        if self._conn is None:
            self._conn = pg.PgConnection.from_url(self._url)
        # ALWAYS poll with try-lock, even untimed: a blocking
        # pg_advisory_lock() can out-wait the client's socket timeout,
        # and the abandoned session would later be GRANTED the lock
        # server-side with nobody using it — a cross-replica deadlock.
        deadline = (None if self._timeout is None
                    else time.monotonic() + self._timeout)
        while True:
            row = self._conn.execute(
                f'SELECT pg_try_advisory_lock({self._key}) AS ok'
            ).fetchone()
            value = row['ok']
            if value is True or value == 't':
                self._held = True
                return
            if deadline is not None and time.monotonic() >= deadline:
                raise LockTimeout(
                    f'advisory lock {self._name!r} not acquired within '
                    f'{self._timeout}s')
            time.sleep(0.2 if self._timeout is None
                       else min(0.2, max(self._timeout / 20, 0.01)))

    def release(self) -> None:
        # Unlock AND drop the session: each lock object owns a dedicated
        # connection, and leaving it open until garbage collection
        # accumulates idle sessions against max_connections.
        if self._conn is not None:
            if self._held:
                try:
                    self._conn.execute(
                        f'SELECT pg_advisory_unlock({self._key})')
                except Exception:  # pylint: disable=broad-except
                    pass  # closing the session releases it anyway
                self._held = False
            self._conn.close()
            self._conn = None

    def locked(self) -> bool:
        return self._held


class DistributedLock:
    """A named inter-process lock (per-cluster, per-job-controller...)."""

    def __init__(self, name: str, timeout: Optional[float] = None) -> None:
        from skypilot_tpu import state
        url = state.db_url()
        if url is not None:
            self._backend = _PostgresLockBackend(name, url, timeout)
        else:
            self._backend = _FileLockBackend(name, timeout)

    def acquire(self) -> None:
        self._backend.acquire()

    def release(self) -> None:
        self._backend.release()

    def locked(self) -> bool:
        return self._backend.locked()

    def __enter__(self) -> 'DistributedLock':
        self.acquire()
        return self

    def __exit__(self, *args) -> None:
        self.release()


def cluster_lock(cluster_name: str,
                 timeout: Optional[float] = None) -> DistributedLock:
    """The per-cluster provision/teardown lock (parity:

    `_locked_provision`, sky/backends/cloud_vm_ray_backend.py:3342)."""
    return DistributedLock(f'cluster.{cluster_name}', timeout=timeout)
