"""Distributed locks guarding cluster state transitions.

Parity: ``sky/utils/locks.py:51`` (DistributedLock with FileLock/PostgresLock
backends). We ship the filelock backend; the interface leaves room for a DB
advisory-lock backend when the API server runs against Postgres.
"""
from __future__ import annotations

import os
from typing import Optional

import filelock

LOCK_DIR = os.path.expanduser('~/.skyt/locks')


class DistributedLock:
    """A named inter-process lock (per-cluster, per-job-controller...)."""

    def __init__(self, name: str, timeout: Optional[float] = None) -> None:
        os.makedirs(LOCK_DIR, exist_ok=True)
        safe = name.replace('/', '_')
        self._path = os.path.join(LOCK_DIR, f'{safe}.lock')
        self._timeout = -1 if timeout is None else timeout
        self._lock = filelock.FileLock(self._path, timeout=self._timeout)

    def acquire(self) -> None:
        self._lock.acquire()

    def release(self) -> None:
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.is_locked

    def __enter__(self) -> 'DistributedLock':
        self.acquire()
        return self

    def __exit__(self, *args) -> None:
        self.release()


def cluster_lock(cluster_name: str,
                 timeout: Optional[float] = None) -> DistributedLock:
    """The per-cluster provision/teardown lock (parity:

    `_locked_provision`, sky/backends/cloud_vm_ray_backend.py:3342)."""
    return DistributedLock(f'cluster.{cluster_name}', timeout=timeout)
