"""Control-plane resilience primitives: bounded retry + supervised threads.

Round-5 review (VERDICT weak #1) watched the executor's runner-spawner
thread die permanently on ONE transient ``sqlite3.OperationalError``
(`database is locked`): requests then queue forever while the replica's
heartbeat keeps beating from a different thread, so the HA requeue path
never notices anything wrong. The fix is structural, not a one-off
try/except — every resident control-plane loop (executor spawner, API
server daemons, pool runners, serve controller) runs under the two
primitives here:

* :func:`retry` — bounded exponential backoff with deterministic
  (injectable-RNG) jitter and a wall-clock deadline, for call sites
  where a transient DB/connection error should be absorbed in place.
* :class:`SupervisedThread` — a thread whose target is restarted with
  backoff if it ever escapes with an exception, with ``restarts`` /
  ``last_error`` surfaced so ``/api/health`` can show a limping loop
  instead of a silently missing one.

Backoff math lives in :func:`backoff_delays` so tests can assert the
exact sequence (seeded RNG) instead of sleeping.
"""
from __future__ import annotations

import random
import sqlite3
import threading
import time
from typing import Callable, Iterator, Optional, Tuple, Type

from skypilot_tpu.utils import log

logger = log.init_logger(__name__)


def transient_db_errors() -> Tuple[Type[BaseException], ...]:
    """Exception types every control-plane loop treats as retryable:
    sqlite lock/IO contention, Postgres wire errors, and socket-level
    connection failures. Lazy so importing this module never drags in
    the pg wire client."""
    from skypilot_tpu.utils import pg
    return (sqlite3.OperationalError, pg.PgError, ConnectionError,
            TimeoutError, OSError)


def backoff_delays(base: float = 0.05,
                   cap: float = 2.0,
                   multiplier: float = 2.0,
                   jitter: float = 0.25,
                   rng: Optional[random.Random] = None
                   ) -> Iterator[float]:
    """Infinite exponential-backoff delay sequence.

    Delay k is ``min(cap, base * multiplier**k)`` stretched by a random
    factor in ``[1, 1 + jitter]`` — jitter is strictly additive so the
    sequence never undershoots the deterministic floor (tests assert
    both bounds). Pass a seeded ``rng`` for a reproducible sequence.
    """
    if base <= 0:
        raise ValueError(f'backoff base must be > 0, got {base}')
    rng = rng or random
    delay = base
    while True:
        yield delay * (1.0 + rng.random() * jitter)
        delay = min(cap, delay * multiplier)


def retry(exceptions: Tuple[Type[BaseException], ...],
          *,
          base: float = 0.05,
          cap: float = 2.0,
          multiplier: float = 2.0,
          jitter: float = 0.25,
          deadline: Optional[float] = 10.0,
          max_attempts: Optional[int] = None,
          rng: Optional[random.Random] = None,
          sleep: Callable[[float], None] = time.sleep,
          what: Optional[str] = None):
    """Decorator: re-invoke the wrapped callable on ``exceptions`` with
    bounded backoff until it succeeds, the wall-clock ``deadline``
    (seconds, measured from the first attempt) passes, or
    ``max_attempts`` calls have failed — whichever comes first; then the
    last error is re-raised. ``deadline=None`` with
    ``max_attempts=None`` retries forever (supervised loops that must
    never die own their exit condition instead).

    The backoff/jitter math is :func:`backoff_delays`; ``sleep`` and
    ``rng`` are injectable so tests assert the schedule without waiting
    it out.
    """

    def decorate(fn: Callable):
        label = what or getattr(fn, '__qualname__', repr(fn))

        def wrapper(*args, **kwargs):
            delays = backoff_delays(base, cap, multiplier, jitter, rng)
            started = time.monotonic()
            attempt = 0
            while True:
                attempt += 1
                try:
                    return fn(*args, **kwargs)
                except exceptions as e:
                    if max_attempts is not None and attempt >= max_attempts:
                        raise
                    delay = next(delays)
                    if (deadline is not None and
                            time.monotonic() - started + delay > deadline):
                        raise
                    logger.debug(
                        '%s failed (%s: %s); retry %d in %.2fs',
                        label, type(e).__name__, e, attempt, delay)
                    sleep(delay)

        wrapper.__name__ = getattr(fn, '__name__', 'retried')
        wrapper.__doc__ = fn.__doc__
        return wrapper

    return decorate


def call_with_retry(fn: Callable, *args, **retry_kwargs):
    """Inline form of :func:`retry` for one call site:
    ``call_with_retry(lambda: db.write(x), deadline=5.0)``. Accepts the
    same keyword policy as :func:`retry`; ``exceptions`` defaults to
    :func:`transient_db_errors`."""
    exceptions = retry_kwargs.pop('exceptions', None) or \
        transient_db_errors()
    return retry(exceptions, **retry_kwargs)(fn)(*args)


class SupervisedThread:
    """A daemon thread whose target is restarted if it ever dies with an
    exception.

    The target owns its run-forever loop and its stop condition (it
    should return promptly once ``stop_event`` is set). The supervisor
    only handles the case the target was never supposed to reach:
    an exception escaping the loop. Each escape is logged, counted in
    ``restarts``, recorded in ``last_error``, and followed by an
    exponential restart backoff (``restart_backoff = (base, cap)``)
    that resets once a run survives ``stable_after`` seconds — so a
    crash-looping target is throttled, not hot-spun, and a
    recovered-long-ago one restarts fast again.

    ``health()`` is the observability surface ``/api/health`` exposes
    per loop.
    """

    def __init__(self,
                 target: Callable[[], None],
                 name: str,
                 restart_backoff: Tuple[float, float] = (0.2, 30.0),
                 stable_after: float = 5.0,
                 stop_event: Optional[threading.Event] = None) -> None:
        self._target = target
        self.name = name
        self._backoff_base, self._backoff_cap = restart_backoff
        self._stable_after = stable_after
        self.stop_event = stop_event or threading.Event()
        self.restarts = 0
        self.last_error: Optional[str] = None
        self.last_error_at: Optional[float] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._supervise,
                                        name=f'supervised-{self.name}',
                                        daemon=True)
        self._thread.start()

    def stop(self, join_timeout: float = 5.0) -> None:
        self.stop_event.set()
        if self._thread is not None and self._thread.is_alive():
            self._thread.join(timeout=join_timeout)

    def is_alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def health(self) -> dict:
        return {
            'name': self.name,
            'alive': self.is_alive(),
            'restarts': self.restarts,
            'last_error': self.last_error,
            'last_error_at': self.last_error_at,
        }

    def _supervise(self) -> None:
        backoff = self._backoff_base
        while not self.stop_event.is_set():
            started = time.monotonic()
            try:
                self._target()
                # A clean return means the target decided it is done
                # (stop requested, or a one-shot body) — don't resurrect.
                return
            except Exception as e:  # pylint: disable=broad-except
                self.restarts += 1
                self.last_error = f'{type(e).__name__}: {e}'
                self.last_error_at = time.time()
                if time.monotonic() - started > self._stable_after:
                    backoff = self._backoff_base
                logger.warning(
                    'supervised loop %s died (%s); restart %d in %.1fs',
                    self.name, self.last_error, self.restarts, backoff,
                    exc_info=True)
                self.stop_event.wait(backoff)
                backoff = min(backoff * 2, self._backoff_cap)


def supervised_thread(target: Callable[[], None],
                      name: str,
                      restart_backoff: Tuple[float, float] = (0.2, 30.0),
                      stop_event: Optional[threading.Event] = None,
                      stable_after: float = 5.0) -> SupervisedThread:
    """Build (without starting) a :class:`SupervisedThread` — the
    functional spelling most call sites use."""
    return SupervisedThread(target, name, restart_backoff=restart_backoff,
                            stable_after=stable_after,
                            stop_event=stop_event)
