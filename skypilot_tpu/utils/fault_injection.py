"""Deterministic fault injection for control-plane chaos tests.

``SKYT_FAULT_SPEC`` holds comma-separated clauses::

    <site>:<Exception>[:p=<float>][:seed=<int>][:times=<int>]

e.g. ``requests_db.claim:OperationalError:p=0.3:seed=7``. Named call
sites in the requests DB and the serve state store invoke
:func:`inject` with their site string; a matching clause raises its
exception with probability ``p`` (default 1.0) drawn from a
per-clause ``random.Random(seed)`` — the injection SEQUENCE is a pure
function of the seed, so a chaos test that passes once passes always.
``times`` caps total injections from that clause (default unlimited).
A site clause ending in ``*`` prefix-matches (``requests_db.*``).

The env var is inherited by every spawned process (runners, request
children, serve controllers), so one spec exercises the whole control
plane. When it is unset, :func:`inject` is a single dict lookup —
effectively free on production hot paths.

Tests drive this through ``tests/fault_injection.py``; the spec syntax
is documented for operators in ``docs/fault_tolerance.md``.
"""
from __future__ import annotations

import os
import random
import sqlite3
from typing import Callable, Dict, List, Optional, Tuple

SPEC_ENV = 'SKYT_FAULT_SPEC'


def _make_operational_error() -> BaseException:
    return sqlite3.OperationalError('injected: database is locked')


def _make_pg_error() -> BaseException:
    from skypilot_tpu.utils import pg
    return pg.PgError('injected: connection reset by peer')


_EXCEPTIONS: Dict[str, Callable[[], BaseException]] = {
    'OperationalError': _make_operational_error,
    'PgError': _make_pg_error,
    'OSError': lambda: OSError('injected: I/O fault'),
    'ConnectionError': lambda: ConnectionError(
        'injected: connection refused'),
    'TimeoutError': lambda: TimeoutError('injected: timed out'),
    'Exception': lambda: Exception('injected fault'),
}


class _Clause:
    def __init__(self, site: str, exc: str, p: float, seed: int,
                 times: Optional[int]) -> None:
        if exc not in _EXCEPTIONS:
            raise ValueError(
                f'unknown fault exception {exc!r}; one of '
                f'{sorted(_EXCEPTIONS)}')
        if not 0.0 <= p <= 1.0:
            raise ValueError(f'fault probability must be in [0,1], got {p}')
        self.site = site
        self.exc = exc
        self.p = p
        self.seed = seed
        self.times = times

    def matches(self, site: str) -> bool:
        if self.site.endswith('*'):
            return site.startswith(self.site[:-1])
        return site == self.site


def parse_spec(spec: str) -> List[_Clause]:
    """Parse a full SKYT_FAULT_SPEC value. Raises ``ValueError`` on any
    malformed clause — a typo that silently injected nothing would make
    a chaos test vacuously green."""
    clauses = []
    for raw in spec.split(','):
        raw = raw.strip()
        if not raw:
            continue
        parts = raw.split(':')
        if len(parts) < 2:
            raise ValueError(
                f'fault clause {raw!r} needs at least site:Exception')
        site, exc = parts[0], parts[1]
        p, seed, times = 1.0, 0, None
        for opt in parts[2:]:
            key, _, value = opt.partition('=')
            if key == 'p':
                p = float(value)
            elif key == 'seed':
                seed = int(value)
            elif key == 'times':
                times = int(value)
            else:
                raise ValueError(
                    f'unknown fault option {opt!r} in clause {raw!r}')
        clauses.append(_Clause(site, exc, p, seed, times))
    return clauses


# Parse cache keyed by the raw env value; per-clause runtime state
# (RNG + remaining-injection budget) keyed by (spec, clause index) so a
# spec change mid-process starts fresh.
_parsed: Dict[str, List[_Clause]] = {}
_runtime: Dict[Tuple[str, int], Dict] = {}


def active() -> bool:
    return bool(os.environ.get(SPEC_ENV))


def inject(site: str) -> None:
    """Raise the configured fault for ``site``, if any. No-op (one env
    lookup) when SKYT_FAULT_SPEC is unset."""
    spec = os.environ.get(SPEC_ENV)
    if not spec:
        return
    clauses = _parsed.get(spec)
    if clauses is None:
        clauses = parse_spec(spec)
        _parsed[spec] = clauses
    for index, clause in enumerate(clauses):
        if not clause.matches(site):
            continue
        state = _runtime.get((spec, index))
        if state is None:
            state = {'rng': random.Random(clause.seed),
                     'remaining': clause.times}
            _runtime[(spec, index)] = state
        if state['remaining'] is not None and state['remaining'] <= 0:
            continue
        # Always draw, even below p=1.0 thresholds that will fire: the
        # decision sequence must advance identically whether or not a
        # previous clause consumed the call.
        if state['rng'].random() < clause.p:
            if state['remaining'] is not None:
                state['remaining'] -= 1
            raise _EXCEPTIONS[clause.exc]()


def reset() -> None:
    """Forget parse + RNG/budget state (tests re-seed between cases)."""
    _parsed.clear()
    _runtime.clear()
