"""Chrome trace-event timeline for orchestration hot paths.

Parity: ``sky/utils/timeline.py:23`` -- opt-in tracing written as Chrome
``chrome://tracing`` / Perfetto JSON when ``SKYT_TIMELINE_FILE`` is set.
``@timeline.event('name')`` decorates hot functions (launch / provision /
sync / setup stages); ``with timeline.Event('name'):`` wraps ad-hoc
spans.

On-disk format: **JSONL, one complete-event per line**, flushed with an
flock'd append — multi-process runs (executor forks) accumulate by
appending, instead of the old read-merge-rewrite of the whole JSON
under flock (O(n^2) across flushes, and two children racing the rewrite
window could still drop spans). Conversion to the Chrome/Perfetto dict
happens at READ time: :func:`load` parses the JSONL (accepting legacy
whole-JSON files), and ``save(path, trace_id=...)`` exports a stored
distributed trace (utils/trace_store.py) in the same viewer format.

``Event`` is also the bridge into distributed tracing: when tracing is
armed (``SKYT_TRACE_SAMPLE``) and an ambient trace context exists (an
executor child running a traced request), every timeline event ALSO
records a child span — provision/sync/setup/transfer hops show up in
``skyt trace`` without a second instrumentation pass.
"""
from __future__ import annotations

import atexit
import functools
import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

_lock = threading.Lock()
_events: List[Dict[str, Any]] = []
_registered_atexit = False

ENV_VAR = 'SKYT_TIMELINE_FILE'


def enabled() -> bool:
    return bool(os.environ.get(ENV_VAR))


class Event:
    """Context manager recording one complete trace event (and, when a
    distributed trace is ambient, one tracing span)."""

    def __init__(self, name: str, **args: Any) -> None:
        self._name = name
        self._args = args
        self._begin: Optional[float] = None
        self._begin_mono: Optional[float] = None
        self._tspan = None

    def __enter__(self) -> 'Event':
        # Wall clock for the displayed 'ts' (trace viewers align
        # processes on it); monotonic for 'dur' so a wall-clock step
        # mid-span can't stretch or negate the measured duration.
        self._begin = time.time()
        self._begin_mono = time.monotonic()
        from skypilot_tpu.utils import tracing
        if tracing.armed() and tracing.ambient() is not None:
            self._tspan = tracing.span(self._name, **self._args)
            self._tspan.__enter__()
        return self

    def __exit__(self, *exc) -> None:
        if self._tspan is not None:
            self._tspan.__exit__(*exc)
            self._tspan = None
        if not enabled() or self._begin is None:
            return
        from skypilot_tpu.utils import tracing
        dur = time.monotonic() - (self._begin_mono
                                  if self._begin_mono is not None
                                  else 0.0)
        record = {
            'name': self._name,
            'ph': 'X',                          # complete event
            'ts': self._begin * 1e6,            # microseconds
            'dur': dur * 1e6,
            'pid': os.getpid(),
            # Stable small per-thread lane (get_ident() % 1e6 could
            # collide two threads into one lane).
            'tid': tracing.stable_tid(),
        }
        if self._args:
            record['args'] = {k: str(v) for k, v in self._args.items()}
        global _registered_atexit
        with _lock:
            _events.append(record)
            if not _registered_atexit:
                atexit.register(save)
                _registered_atexit = True


def event(name_or_fn=None, **event_args):
    """Decorator form: ``@timeline.event`` or ``@timeline.event('name')``."""

    def wrap(fn: Callable, name: str):
        @functools.wraps(fn)
        def inner(*args, **kwargs):
            from skypilot_tpu.utils import tracing
            if not enabled() and not tracing.armed():
                return fn(*args, **kwargs)
            with Event(name, **event_args):
                return fn(*args, **kwargs)
        return inner

    if callable(name_or_fn):
        return wrap(name_or_fn, name_or_fn.__qualname__)

    def deco(fn: Callable):
        return wrap(fn, name_or_fn or fn.__qualname__)
    return deco


def save(path: Optional[str] = None, *,
         trace_id: Optional[str] = None) -> Optional[str]:
    """Flush buffered events as flock'd JSONL appends; returns the path.

    With ``trace_id``, instead export that stored distributed trace
    (utils/trace_store.py) as a Chrome/Perfetto JSON file at ``path`` —
    the existing viewer path works on any collected trace.
    """
    if trace_id is not None:
        return _export_trace(trace_id, path)
    path = path or os.environ.get(ENV_VAR)
    if not path:
        return None
    with _lock:
        events, _events[:] = list(_events), []
    if not events:
        return path if os.path.exists(os.path.expanduser(path)) else None
    path = os.path.expanduser(path)
    os.makedirs(os.path.dirname(path) or '.', exist_ok=True)
    import fcntl
    payload = ''.join(json.dumps(e) + '\n' for e in events)
    with open(path, 'a', encoding='utf-8') as f:
        fcntl.flock(f, fcntl.LOCK_EX)
        f.write(payload)
        f.flush()
    return path


def load(path: Optional[str] = None) -> Optional[Dict[str, Any]]:
    """Read a timeline file into the Chrome trace dict
    (``{'traceEvents': [...], 'displayTimeUnit': 'ms'}``). Accepts both
    the JSONL format written by :func:`save` and legacy whole-JSON
    files from older versions."""
    path = path or os.environ.get(ENV_VAR)
    if not path:
        return None
    path = os.path.expanduser(path)
    if not os.path.exists(path):
        return None
    with open(path, encoding='utf-8') as f:
        text = f.read()
    events: List[Dict[str, Any]] = []
    stripped = text.lstrip()
    if stripped.startswith('{') and '\n{' not in text.strip():
        try:  # legacy single-dict file
            doc = json.loads(text)
            if isinstance(doc, dict) and 'traceEvents' in doc:
                return doc
        except json.JSONDecodeError:
            pass
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            record = json.loads(line)
        except json.JSONDecodeError:
            continue  # torn tail line from a crashed writer
        if isinstance(record, dict) and 'traceEvents' in record:
            events.extend(record['traceEvents'])  # legacy line
        elif isinstance(record, dict):
            events.append(record)
    return {'traceEvents': events, 'displayTimeUnit': 'ms'}


def export(path: str, out_path: str) -> str:
    """JSONL timeline -> Chrome JSON file (for viewers that want the
    classic single-document form)."""
    doc = load(path) or {'traceEvents': [], 'displayTimeUnit': 'ms'}
    out_path = os.path.expanduser(out_path)
    os.makedirs(os.path.dirname(out_path) or '.', exist_ok=True)
    tmp = f'{out_path}.{os.getpid()}.tmp'
    with open(tmp, 'w', encoding='utf-8') as f:
        json.dump(doc, f)
    os.replace(tmp, out_path)
    return out_path


def _export_trace(trace_id: str, path: Optional[str]) -> Optional[str]:
    """A stored distributed trace as Chrome/Perfetto JSON: one X event
    per span plus process_name metadata per (pid, service)."""
    from skypilot_tpu.utils import trace_store
    spans = trace_store.load_trace(trace_id)
    if not spans:
        return None
    path = path or os.environ.get(ENV_VAR)
    if not path:
        return None
    events: List[Dict[str, Any]] = []
    seen_procs = set()
    for s in spans:
        pid = s.get('pid', 0)
        service = s.get('service', '?')
        if pid not in seen_procs:
            seen_procs.add(pid)
            events.append({'ph': 'M', 'name': 'process_name',
                           'pid': pid, 'tid': 0,
                           'args': {'name': f'{service} ({pid})'}})
        args = dict(s.get('annotations') or {})
        args['span_id'] = s.get('span_id')
        if s.get('parent_span_id'):
            args['parent_span_id'] = s['parent_span_id']
        if s.get('status') == 'error':
            args['error'] = s.get('error', 'error')
        events.append({
            'name': s.get('name', '?'),
            'ph': 'X',
            'ts': s.get('start', 0.0) * 1e6,
            'dur': s.get('dur_ms', 0.0) * 1e3,
            'pid': pid,
            'tid': s.get('tid', 0),
            'args': {k: str(v) for k, v in args.items()},
        })
    path = os.path.expanduser(path)
    os.makedirs(os.path.dirname(path) or '.', exist_ok=True)
    tmp = f'{path}.{os.getpid()}.tmp'
    with open(tmp, 'w', encoding='utf-8') as f:
        json.dump({'traceEvents': events, 'displayTimeUnit': 'ms'}, f)
    os.replace(tmp, path)
    return path


def clear() -> None:
    with _lock:
        _events.clear()
