"""Chrome trace-event timeline for orchestration hot paths.

Parity: ``sky/utils/timeline.py:23`` -- opt-in tracing written as Chrome
``chrome://tracing`` / Perfetto JSON when ``SKYT_TIMELINE_FILE`` is set.
``@timeline.event('name')`` decorates hot functions (launch / provision /
sync / setup stages); ``with timeline.Event('name'):`` wraps ad-hoc
spans. Events are buffered in-process and flushed on exit (and on every
``save()``), one complete-event (ph='X') per span.
"""
from __future__ import annotations

import atexit
import functools
import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

_lock = threading.Lock()
_events: List[Dict[str, Any]] = []
_registered_atexit = False

ENV_VAR = 'SKYT_TIMELINE_FILE'


def enabled() -> bool:
    return bool(os.environ.get(ENV_VAR))


class Event:
    """Context manager recording one complete trace event."""

    def __init__(self, name: str, **args: Any) -> None:
        self._name = name
        self._args = args
        self._begin: Optional[float] = None
        self._begin_mono: Optional[float] = None

    def __enter__(self) -> 'Event':
        # Wall clock for the displayed 'ts' (trace viewers align
        # processes on it); monotonic for 'dur' so a wall-clock step
        # mid-span can't stretch or negate the measured duration.
        self._begin = time.time()
        self._begin_mono = time.monotonic()
        return self

    def __exit__(self, *exc) -> None:
        if not enabled() or self._begin is None:
            return
        dur = time.monotonic() - (self._begin_mono
                                  if self._begin_mono is not None
                                  else 0.0)
        record = {
            'name': self._name,
            'ph': 'X',                          # complete event
            'ts': self._begin * 1e6,            # microseconds
            'dur': dur * 1e6,
            'pid': os.getpid(),
            'tid': threading.get_ident() % 1_000_000,
        }
        if self._args:
            record['args'] = {k: str(v) for k, v in self._args.items()}
        global _registered_atexit
        with _lock:
            _events.append(record)
            if not _registered_atexit:
                atexit.register(save)
                _registered_atexit = True


def event(name_or_fn=None, **event_args):
    """Decorator form: ``@timeline.event`` or ``@timeline.event('name')``."""

    def wrap(fn: Callable, name: str):
        @functools.wraps(fn)
        def inner(*args, **kwargs):
            if not enabled():
                return fn(*args, **kwargs)
            with Event(name, **event_args):
                return fn(*args, **kwargs)
        return inner

    if callable(name_or_fn):
        return wrap(name_or_fn, name_or_fn.__qualname__)

    def deco(fn: Callable):
        return wrap(fn, name_or_fn or fn.__qualname__)
    return deco


def save(path: Optional[str] = None) -> Optional[str]:
    """Flush buffered events as a Chrome trace JSON; returns the path."""
    path = path or os.environ.get(ENV_VAR)
    if not path:
        return None
    with _lock:
        events = list(_events)
    if not events:
        return None
    path = os.path.expanduser(path)
    os.makedirs(os.path.dirname(path) or '.', exist_ok=True)
    # Merge with an existing file so multi-process runs (executor forks)
    # accumulate into one trace; the read-merge-replace is serialized
    # with flock or two children flushing together would drop spans.
    import fcntl
    lock_path = path + '.lock'
    with open(lock_path, 'w', encoding='utf-8') as lock_file:
        fcntl.flock(lock_file, fcntl.LOCK_EX)
        existing: List[Dict[str, Any]] = []
        if os.path.exists(path):
            try:
                with open(path, encoding='utf-8') as f:
                    existing = json.load(f).get('traceEvents', [])
            except (json.JSONDecodeError, OSError):
                existing = []
        seen = {(e['pid'], e['tid'], e['ts'], e['name'])
                for e in existing}
        merged = existing + [
            e for e in events
            if (e['pid'], e['tid'], e['ts'], e['name']) not in seen]
        tmp = f'{path}.{os.getpid()}.tmp'
        with open(tmp, 'w', encoding='utf-8') as f:
            json.dump({'traceEvents': merged, 'displayTimeUnit': 'ms'}, f)
        os.replace(tmp, path)
    return path


def clear() -> None:
    with _lock:
        _events.clear()
