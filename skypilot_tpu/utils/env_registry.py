"""Typed registry of every ``SKYT_*`` environment knob.

The platform grew ~100 env knobs by convention — each one parsed ad hoc
(`int(os.environ.get(...))`, `!= '0'`, `in ('1','true','yes')`) at its
read site, with no central list, no types, and no docs. This module is
the single source of truth:

* **Declarations** — :data:`REGISTRY` maps every knob to an
  :class:`EnvVar` (name, type, default, one-line doc). Dynamic families
  (``SKYT_JOBGROUP_HOSTS_<TASK>``) are declared as ``*`` patterns.
* **Typed accessors** — :func:`get_int` / :func:`get_float` /
  :func:`get_bool` / :func:`get_str` replace scattered raw parsing
  (semantics follow ``common_utils.env_int``: unset or unparsable
  reads as the declared default, never an exception on a hot path).
  Accessing an UNDECLARED name raises ``KeyError`` — a typo'd knob
  fails loudly in tests instead of silently reading its default.
* **Lint + docs** — the ``skylint`` SKYT002 pass cross-checks every
  env reference in the package against this table, and
  ``python -m skypilot_tpu.lint --dump-env-docs`` renders it as
  ``docs/env_vars.md`` (committed copy is verified in sync).

Keep declarations sorted by name within their group; a new knob MUST be
declared here before code reads it (skylint enforces this in tier-1).
"""
from __future__ import annotations

import os
from typing import Dict, Iterable, List, NamedTuple, Optional

# Valid declaration types. 'path' and 'url' parse as strings; the
# distinction is documentation (and lets docs/env_vars.md group them).
TYPES = ('str', 'int', 'float', 'bool', 'path', 'url')


class EnvVar(NamedTuple):
    name: str
    type: str
    default: object  # rendered into docs; None = unset/disabled
    doc: str
    # True for knobs consumed outside the package's own python sources
    # (recipe payloads, shell templates): the SKYT002
    # declared-but-unreferenced check exempts them.
    external: bool = False

    @property
    def is_pattern(self) -> bool:
        return self.name.endswith('*')


def _decl(entries: Iterable[tuple]) -> List[EnvVar]:
    out = []
    for entry in entries:
        var = EnvVar(*entry)
        assert var.type in TYPES, f'{var.name}: bad type {var.type!r}'
        out.append(var)
    return out


DECLARATIONS: List[EnvVar] = _decl([
    # -- core state / identity --------------------------------------
    ('SKYT_STATE_DIR', 'path', '~/.skyt',
     'Root directory for all local state (DBs, logs, catalogs, '
     'transfer manifests).'),
    ('SKYT_DB_URL', 'url', None,
     'Postgres URL for shared control-plane state; unset = local '
     'sqlite under SKYT_STATE_DIR.'),
    ('SKYT_CONFIG', 'path', None,
     'Explicit layered-config YAML path (overrides project/user '
     'config discovery).'),
    ('SKYT_WORKSPACE', 'str', None,
     'Active workspace; exported to request children and job '
     'controllers for multi-tenant scoping.'),
    ('SKYT_USER_HASH', 'str', None,
     'Override the stable 8-hex user/machine id.'),
    ('SKYT_LOG_LEVEL', 'str', 'INFO',
     'Root logger level (DEBUG/INFO/WARNING/ERROR).'),
    ('SKYT_TIMELINE_FILE', 'path', None,
     'Write opt-in Chrome-trace timeline JSONL to this path '
     '(timeline.load()/export() convert to viewer JSON).'),
    ('SKYT_TRACE_BUFFER', 'int', 512,
     'Distributed tracing: max non-head-sampled spans buffered '
     'per process awaiting a tail-keep trigger (oldest trace '
     'evicted past it).'),
    ('SKYT_TRACE_CONTEXT', 'str', None,
     'Distributed tracing: W3C traceparent inherited by child '
     'processes (exported by the executor runner so backend/'
     'provision spans parent under the request trace).'),
    ('SKYT_TRACE_DIR', 'path', None,
     'Distributed tracing: span store directory override (default: '
     '<server_dir>/traces).'),
    ('SKYT_TRACE_SAMPLE', 'float', None,
     'Distributed tracing: head-sampling rate in [0,1]. Unset '
     'disables tracing entirely; 0 still tail-keeps errored/slow '
     'requests (docs/observability.md).'),
    ('SKYT_TRACE_SLOW_MS', 'float', 10000.0,
     'Distributed tracing: spans at/over this duration promote '
     'their whole trace to the store even when not head-sampled '
     '(tail keep for deadline-busting requests).'),
    ('SKYT_CHECK_CACHE_TTL', 'float', 300.0,
     'Cloud-credential check cache TTL (seconds).'),
    ('SKYT_FAULT_SPEC', 'str', None,
     'Deterministic fault-injection spec '
     '(site:Exception[:p=..][:seed=..][:times=..], comma-separated; '
     'docs/fault_tolerance.md).'),
    ('SKYT_LINT_DYNAMIC', 'str', None,
     'Enable the dynamic lockset race detector + deadlock watchdog '
     '(skypilot_tpu/lint/dynamic.py) on chaos-marked tests; a '
     'path-like value also sets the JSON report destination '
     '(docs/static_analysis.md).'),

    # -- fleet telemetry plane --------------------------------------
    ('SKYT_SLO_FOR_SECONDS', 'float', 60.0,
     'SLO engine: default seconds a burn-rate breach must persist '
     'before a pending alert fires (spec for_seconds overrides).'),
    ('SKYT_SLO_RESOLVED_KEEP_S', 'float', 3600.0,
     'SLO engine: seconds a resolved alert stays on /api/alerts '
     'before it is dropped.'),
    ('SKYT_TELEMETRY_DIR', 'path', None,
     'Telemetry store directory override (default: '
     '<server_dir>/telemetry).'),
    ('SKYT_TELEMETRY_ENABLED', 'bool', True,
     'Run the scrape-federation telemetry daemon in the API server '
     '(0 disables the whole plane; /api/get stays untouched either '
     'way).'),
    ('SKYT_TELEMETRY_FLUSH_S', 'float', 60.0,
     'Telemetry store: cadence of forced head-chunk flushes (bounds '
     'how far cross-process readers lag the scraper).'),
    ('SKYT_TELEMETRY_INTERVAL', 'float', 15.0,
     'Scrape-federation cadence (seconds); each tick is jittered by '
     'SKYT_TELEMETRY_JITTER.'),
    ('SKYT_TELEMETRY_JITTER', 'float', 0.2,
     'Fractional jitter applied to every scrape interval (0.2 = '
     '+/-20%) so replica fleets do not scrape in lockstep.'),
    ('SKYT_TELEMETRY_RAW_RETENTION_S', 'float', 6 * 3600.0,
     'Telemetry store: raw-resolution retention (seconds); older '
     'segments are reclaimed, their history lives on in the '
     'rollups.'),
    ('SKYT_TELEMETRY_ROLLUP_BUCKET_S', 'float', 300.0,
     'Telemetry store: downsample bucket width (seconds; mean and '
     'max are kept per bucket).'),
    ('SKYT_TELEMETRY_ROLLUP_RETENTION_S', 'float', 14 * 86400.0,
     'Telemetry store: rollup retention (seconds).'),
    ('SKYT_TELEMETRY_SCRAPE_TIMEOUT', 'float', 2.0,
     'Per-target HTTP timeout for federation scrapes (seconds).'),

    # -- notification bus -------------------------------------------
    ('SKYT_EVENTS_DISABLED', 'bool', False,
     'Disable the notification bus; control-plane loops fall back to '
     'their legacy fixed-cadence polls.'),
    ('SKYT_EVENTS_SLICE', 'float', 0.02,
     'External-signal (LISTEN/NOTIFY, data_version) check cadence '
     'inside event waits (seconds).'),

    # -- API server + executor --------------------------------------
    ('SKYT_SERVER_DIR', 'path', None,
     'API-server state dir override (default: SKYT_STATE_DIR/server).'),
    ('SKYT_SERVER_ID', 'str', None,
     'Stable API-server replica identity (HA fencing / heartbeats).'),
    ('SKYT_API_SERVER_URL', 'url', None,
     'Client: remote API server base URL (unset = in-process local '
     'mode).'),
    ('SKYT_API_SERVER_TOKEN', 'str', None,
     'Server: static bearer token accepted for API auth.'),
    ('SKYT_API_TOKEN', 'str', None,
     'Client: bearer token sent with API requests.'),
    ('SKYT_CLIENT_RETRIES', 'int', 4,
     'Client HTTP retry attempts against the API server.'),
    ('SKYT_MAX_STREAMS', 'int', 64,
     'Concurrent log-stream responses before the server sheds with '
     '429.'),
    ('SKYT_LONG_WORKERS', 'int', 4,
     'Executor worker slots for the LONG request queue.'),
    ('SKYT_SHORT_WORKERS', 'int', 16,
     'Executor worker slots for the SHORT request queue.'),
    ('SKYT_EXECUTOR_IDLE_FALLBACK', 'float', None,
     'Executor idle fallback-poll seconds override (default 2.0 '
     'event-driven, 0.5 degraded).'),
    ('SKYT_REQUESTS_HA_INTERVAL', 'float', None,
     'HA requeue daemon tick override (seconds).'),
    ('SKYT_SERVER_STALE_S', 'float', 15.0,
     'Heartbeat age before a peer API server counts as dead and its '
     'requests are requeued.'),
    ('SKYT_FAIR_QUEUE', 'bool', True,
     'Workspace-sharded weighted fair (DRR) claim order in the '
     'request executor (0 = legacy global FIFO).'),
    ('SKYT_TENANT_WEIGHT_DEFAULT', 'float', 1.0,
     'Fair-share weight for workspaces with no explicit '
     'api_server.tenants.<ws>.weight config.'),
    ('SKYT_TENANT_MAX_PENDING', 'int', 1000,
     'Default per-workspace PENDING cap per queue; submits past it '
     'get 429 + Retry-After (0 = unbounded).'),
    ('SKYT_TENANT_MAX_INFLIGHT', 'int', 0,
     'Default per-workspace RUNNING cap per queue enforced at claim '
     '(0 = unbounded).'),
    ('SKYT_ADMIT_TARGET_MS', 'float', 0.0,
     'Overload gate: claimed-latency target in ms; EWMA above it '
     'sheds lowest-priority tenants first (0 = gate disabled).'),
    ('SKYT_ADMIT_HOLD_S', 'float', 5.0,
     'Overload gate hysteresis: continuous healthy seconds required '
     'before one shed level is restored.'),
    ('SKYT_ADMIT_EWMA_ALPHA', 'float', 0.3,
     'Overload gate EWMA smoothing factor for the claimed-latency '
     'signal.'),
    ('SKYT_REQUEST_RETENTION_S', 'float', 7 * 86400.0,
     'Terminal request rows older than this are archived+purged by '
     'the request-gc daemon (0 = keep forever).'),
    ('SKYT_REQUEST_GC_INTERVAL', 'float', 300.0,
     'request-gc daemon tick cadence (seconds).'),
    ('SKYT_CHANNEL_BROKER', 'bool', True,
     'Run the channel-broker socket in the API server (0 disables).'),
    ('SKYT_DAG_MAX_CONCURRENCY', 'int', 16,
     'DAG executor thread cap for pipeline fan-out.'),
    ('SKYT_PIPELINE_POLL_SECONDS', 'float', 5.0,
     'Pipeline stage-wait poll cadence (seconds).'),
    ('SKYT_PIPELINE_POLL_RETRIES', 'int', 10,
     'Transient status-poll error budget before a pipeline wait '
     'fails.'),
    ('SKYT_PIPELINE_DAEMON_GRACE_SECONDS', 'float', 60.0,
     'Pipeline daemon shutdown grace (seconds).'),

    # -- catalog ----------------------------------------------------
    ('SKYT_CATALOG_FEED', 'url', None,
     'Hardware catalog feed (https://, file://, or plain path to the '
     'fetcher JSON).'),
    ('SKYT_CATALOG_TTL_HOURS', 'float', 24.0,
     'Catalog refresh TTL (hours).'),

    # -- cluster runtime (on-node daemon, channels) -----------------
    ('SKYT_RUNTIME_CHANNEL', 'bool', True,
     'Use the persistent runtime channel for job-table ops (0 = SSH '
     'fallback).'),
    ('SKYT_RUNTIME_SKIP_IMPORT_CHECK', 'bool', False,
     'Skip the remote runtime import verification after setup.'),
    ('SKYT_RUNTIME_PKG_CACHE', 'path', None,
     'Runtime tarball cache dir (default: SKYT_STATE_DIR/'
     'runtime_pkg).'),
    ('SKYT_CHANNEL_TIMEOUT', 'float', 120.0,
     'Runtime channel RPC timeout (seconds).'),
    ('SKYT_CHANNEL_BROKER_SOCK', 'path', None,
     'Inherited channel-broker unix socket path (request children '
     'proxy job-table ops through it).'),
    ('SKYT_CHANNEL_WATCH_PERIOD', 'float', 0.3,
     'Channel server job-table watch cadence (seconds).'),
    ('SKYT_CHANNEL_WATCH_FALLBACK', 'float', None,
     'Channel watcher degraded-poll override (seconds).'),
    ('SKYT_DAEMON_PERIOD', 'float', 1.0,
     'On-node daemon event-loop cadence (seconds).'),
    ('SKYT_DAEMON_START_GRACE', 'float', 20.0,
     'Seconds to wait for the on-node daemon startup marker.'),
    ('SKYT_TAIL_DAEMON_GRACE', 'float', 45.0,
     'Log-tail daemon linger after the job finishes (seconds).'),
    ('SKYT_GANG_START_DEADLINE', 'float', 60.0,
     'Gang start barrier deadline across pod-slice hosts (seconds).'),
    ('SKYT_MAX_CONCURRENT_JOBS', 'int', 16,
     'Per-node concurrent job cap in the runtime daemon.'),

    # -- payload topology (exported to tasks by codegen) ------------
    ('SKYT_NODE_RANK', 'int', None,
     'Payload: this host\'s node index within its slice.', True),
    ('SKYT_NODE_IPS', 'str', None,
     'Payload: newline-separated internal IPs of the slice.', True),
    ('SKYT_NUM_NODES', 'int', None,
     'Payload: node count of the slice.', True),
    ('SKYT_COORDINATOR_ADDRESS', 'str', None,
     'Payload: jax.distributed coordinator host:port.', True),
    ('SKYT_CLUSTER_NAME', 'str', None,
     'Payload: owning cluster name.', True),
    ('SKYT_TPU_ACCELERATOR', 'str', None,
     'Payload: TPU accelerator name (e.g. v5p-128).', True),
    ('SKYT_TPU_TOPOLOGY', 'str', None,
     'Payload: TPU ICI topology string.', True),

    # -- managed jobs -----------------------------------------------
    ('SKYT_JOBS_CONTROLLER_POLL', 'float', 10.0,
     'Managed-jobs controller fallback poll (seconds); preemption '
     'reaction normally rides CLUSTERS events.'),
    ('SKYT_JOBS_EVENT_MIN_GAP', 'float', 0.5,
     'Coalescing window for CLUSTERS event bursts in the jobs '
     'controller (seconds).'),
    ('SKYT_JOBS_CONTROLLER_CLUSTER', 'str', None,
     'Run managed-job controllers on this cluster instead of '
     'locally.'),
    ('SKYT_JOBS_CONTROLLER_MAX_RESTARTS', 'int', None,
     'Supervision restart budget for job controllers.'),
    ('SKYT_JOBS_MAX_LAUNCHING', 'int', None,
     'Scheduler cap on concurrently-launching managed jobs.'),
    ('SKYT_JOBS_MAX_ALIVE', 'int', None,
     'Scheduler cap on alive managed jobs.'),
    ('SKYT_JOBS_MAX_LAUNCH_RETRIES', 'int', None,
     'Launch retry budget per recovery attempt.'),
    ('SKYT_JOBS_LAUNCH_RETRY_GAP', 'float', None,
     'Gap between managed-job launch retries (seconds).'),
    ('SKYT_JOBS_LOG_RETENTION_HOURS', 'float', 24.0,
     'Managed-job log GC retention (hours).'),
    ('SKYT_JOBGROUP', 'str', None,
     'Payload: gang-scheduled job-group name.', True),
    ('SKYT_JOBGROUP_HOSTS_*', 'str', None,
     'Payload: comma-separated host IPs per group member task '
     '(suffix = sanitized task name).', True),
    ('SKYT_JOBGROUP_BARRIER_TIMEOUT', 'float', 1800.0,
     'Job-group provision barrier timeout (seconds).'),
    ('SKYT_POOL', 'str', None,
     'Payload: pool name a batch worker should claim work from '
     '(recipes).', True),
    ('SKYT_ELASTIC', 'bool', False,
     'Payload: set when the gang runs under the elastic recovery '
     'strategy.', True),
    ('SKYT_ELASTIC_SLICES', 'int', None,
     'Payload: current elastic world size (slice count) to resolve '
     'the mesh for.', True),
    ('SKYT_RESIZE_SIGNAL', 'path', None,
     'Payload: path of the resize handshake file; the trainer exits '
     'at the next step boundary when it appears.', True),

    # -- serve ------------------------------------------------------
    ('SKYT_SERVE_CONTROLLER_POLL', 'float', 10.0,
     'Serve controller probe/reconcile cadence (seconds).'),
    ('SKYT_SERVE_CONTROLLER_CLUSTER', 'str', None,
     'Run serve controllers on this cluster instead of locally.'),
    ('SKYT_SERVE_CONTROLLER_MAX_RESTARTS', 'int', None,
     'Supervision restart budget for serve controllers.'),
    ('SKYT_SERVE_ON_CLUSTER', 'bool', False,
     'Set inside cluster-hosted serve controllers (changes state-dir '
     'resolution).'),
    ('SKYT_SERVE_LB_HOST', 'str', '127.0.0.1',
     'Bind host for service load balancers.'),
    ('SKYT_SERVE_ENDPOINT_HOST', 'str', None,
     'Advertised endpoint host override for serve services.'),
    ('SKYT_SERVE_NOT_READY_THRESHOLD', 'int', 3,
     'Consecutive failed probes before a replica is NOT_READY.'),
    ('SKYT_SERVE_REPLICA_PORT', 'int', None,
     'Payload: port a serve replica must listen on.', True),
    ('SKYT_SERVE_REPLICA_ID', 'int', None,
     'Payload: replica id within its service.', True),
    ('SKYT_FORECAST_HORIZON', 'float', 60.0,
     'SLO autoscaler: QPS forecast horizon (seconds) — should cover '
     'replica provision/resume time so capacity lands before the '
     'ramp (replica_policy.forecast_horizon_seconds overrides).'),
    ('SKYT_FORECAST_SEASONAL_PERIOD', 'float', 86400.0,
     'Seasonal forecaster: ring period (seconds; default one day for '
     'diurnal traffic).'),
    ('SKYT_FORECAST_SEASONAL_BUCKETS', 'int', 48,
     'Seasonal forecaster: phase buckets per period.'),
    ('SKYT_WARM_POOL_SIZE', 'int', 1,
     'Serve warm pool: max replicas parked stopped-not-torn-down for '
     'fast resume (0 disables; used by the SLO autoscaler mix '
     'policy).'),
    ('SKYT_WARM_POOL_TTL', 'float', 1800.0,
     'Serve warm pool: seconds a WARM replica is kept before a real '
     'teardown.'),
    ('SKYT_SCALE_TO_ZERO_IDLE_S', 'float', 300.0,
     'SLO autoscaler: observed+predicted-idle seconds before a '
     'min_replicas:0 service scales to zero '
     '(replica_policy.scale_to_zero_idle_seconds overrides).'),
    ('SKYT_MIX_EGRESS_GB_PER_HR', 'float', 1.0,
     'Mix policy: expected cross-region response traffic per replica '
     '(GB/hour) used to fold the egress hop into a domain\'s '
     'effective $/replica-hour.'),
    ('SKYT_LB_POOL_SIZE', 'int', 8,
     'LB: max idle keep-alive connections kept per replica (0 '
     'disables pooling).'),
    ('SKYT_LB_POOL_IDLE_SECONDS', 'float', 30.0,
     'LB: idle connection lifetime before reaping (seconds).'),
    ('SKYT_LB_MAX_INFLIGHT', 'int', 256,
     'LB: concurrent proxied requests before fast-fail 503.'),
    ('SKYT_LB_EJECT_THRESHOLD', 'int', 3,
     'LB: consecutive upstream failures before passive ejection.'),
    ('SKYT_LB_EJECT_SECONDS', 'float', 10.0,
     'LB: ejection duration before a half-open re-probe (seconds).'),
    ('SKYT_LB_EWMA_ALPHA', 'float', 0.3,
     'LB: TTFB EWMA smoothing factor for the p2c_ewma policy.'),
    ('SKYT_LB_UPSTREAM_TIMEOUT', 'float', 300.0,
     'LB: per-read upstream timeout (seconds).'),

    # -- simulation (simkit) ----------------------------------------
    ('SKYT_SIM_SEED', 'int', -1,
     'Simkit: RNG seed override for scenario runs (-1 uses the '
     'scenario file\'s seed).'),
    ('SKYT_SIM_SCALE', 'float', 1.0,
     'Simkit: proportional fleet/traffic scale factor applied by the '
     'CLI and bench_sim.py (0.1 shrinks a 10k-replica scenario to '
     '1k).'),
    ('SKYT_SIM_TELEMETRY_EXPORT', 'path', None,
     'Simkit: when set, every run exports its metric stream into '
     'this TSDB directory (point SKYT_TELEMETRY_DIR at it to query '
     'sim output via /api/metrics/query).'),

    # -- data plane -------------------------------------------------
    ('SKYT_TRANSFER_WORKERS', 'int', 16,
     'Transfer engine bounded worker-pool size.'),
    ('SKYT_TRANSFER_PART_SIZE', 'int', 8 * 1024 * 1024,
     'Transfer engine part size for multipart/ranged I/O (bytes).'),
    ('SKYT_TRANSFER_MULTIPART_THRESHOLD', 'int', None,
     'Object size that triggers multipart/ranged transfer (default '
     '2x part size).'),
    ('SKYT_TRANSFER_RETRIES', 'int', 4,
     'Transfer engine per-object attempt budget.'),
    ('SKYT_TRANSFER_DELTA', 'bool', True,
     'Manifest-based delta sync (0 forces full re-transfer).'),
    ('SKYT_TRANSFER_POOL_SIZE', 'int', 8,
     'Transfer engine: max idle keep-alive connections kept per '
     '(host, port) for ranged GETs (0 disables pooling — every part '
     'dials fresh).'),
    ('SKYT_S3_ENDPOINT_URL', 'url', None,
     'S3-compatible endpoint override (tests point it at fake_s3).'),
    ('SKYT_AZURE_BLOB_ENDPOINT', 'url', None,
     'Azure Blob endpoint override (tests point it at the fake).'),

    # -- weight fan-out (data/fanout.py) ----------------------------
    ('SKYT_FANOUT', 'bool', False,
     'Peer weight fan-out for serve replicas: new replicas pull '
     'checkpoint shards from READY peers over a binary tree instead '
     'of each hitting the bucket (docs/weight_distribution.md).'),
    ('SKYT_FANOUT_DEGREE', 'int', 2,
     'Fan-out tree arity: children a serving peer feeds '
     'concurrently.'),
    ('SKYT_FANOUT_BUCKET_LEASES', 'int', 0,
     'Concurrent bucket-read leases during fan-out (convoy '
     'control); 0 = auto ceil(log2(fleet+1)).'),
    ('SKYT_FANOUT_LEASE_TTL', 'float', 120.0,
     'Seconds before a bucket-read lease held by a dead puller '
     'expires and frees its slot.'),
    ('SKYT_FANOUT_PEER_TIMEOUT', 'float', 30.0,
     'Per-request timeout on peer shard fetches; a slow/hung peer '
     'is healed past after this long.'),
    ('SKYT_FANOUT_PEERS', 'str', None,
     'Payload: JSON peer plan (ancestor chain) the controller hands '
     'a launching replica.', True),
    ('SKYT_FANOUT_DIR', 'path', None,
     'Payload: directory a replica pulls weights into and serves '
     'peers from (/fanout endpoints).', True),

    # -- inference --------------------------------------------------
    ('SKYT_INFER_BLOCK_SIZE', 'int', 16,
     'Paged KV cache block size (tokens per block).'),
    ('SKYT_INFER_PREFILL_CHUNK', 'int', 64,
     'Chunked-prefill budget interleaved per decode step (tokens).'),
    ('SKYT_PAGED_BLOCK_K', 'int', 0,
     'Paged-attention kernel kv-block override: sub-divides a large '
     'KV pool block for VMEM shaping (must divide the block size; '
     '0 = one kernel block per pool block).'),
    ('SKYT_SPEC_DECODE', 'bool', False,
     'Speculative decoding in the continuous engine: draft + batched '
     'verify over the paged pool (greedy output stays identical to '
     'the plain engine).'),
    ('SKYT_SPEC_DRAFT_K', 'int', 4,
     'Draft tokens proposed per speculative verify step (the verify '
     'window is draft_k + 1).'),
    ('SKYT_SPEC_NGRAM_MAX', 'int', 3,
     'Longest trailing n-gram the prompt-lookup draft matches on '
     '(it backs off to shorter n-grams).'),
    ('SKYT_DISAGG_ROLE', 'str', '',
     'Disaggregated serving role for this replica: "prefill" (chunked '
     'prefill at full arithmetic intensity, exports finished KV '
     'blocks, never decodes), "decode" (imports KV blocks, batched '
     'decode, never prefill-interleaves except on re-prefill '
     'fallback); empty = colocated engine '
     '(docs/disaggregated_serving.md).'),
    ('SKYT_KV_MIGRATE_TIMEOUT', 'float', 30.0,
     'Per-request timeout on prefill->decode KV-block fetches; a '
     'hung prefill source fails the migration (the decode side falls '
     'back to a local re-prefill) after this long.'),
    ('SKYT_KV_MIGRATE_RETRIES', 'int', 3,
     'KV migration per-payload attempt budget: unavailable sources '
     'are retried with Retry-After-floored backoff, corrupt blocks '
     're-pulled from scratch, this many times before the decode side '
     'gives up and re-prefills.'),
    ('SKYT_LORA_PAGES', 'int', 0,
     'Device adapter page slots in the continuous engine (S-LoRA '
     'unified paging: each resident adapter charges KV blocks from '
     'the shared pool); 0 = multi-LoRA serving disabled '
     '(docs/multi_lora_serving.md).'),
    ('SKYT_LORA_MAX_RANK', 'int', 8,
     'Largest adapter rank the device page stack holds (lower ranks '
     'are zero-padded; registration rejects adapters above it).'),
    ('SKYT_LORA_MAX_ACTIVE', 'int', 0,
     'Per-adapter concurrent decode-slot quota; an adapter at its '
     'cap queues in its own DRR lane without blocking others '
     '(0 = unlimited).'),
    ('SKYT_LORA_DRR_QUANTUM', 'int', 4,
     'Deficit-round-robin admission quantum in KV blocks per adapter '
     'lane per round (mirrors SKYT_DB_DRR_QUANTUM one layer down: '
     'a hot adapter queues behind itself, not in front of the other '
     'tenants).'),
    ('SKYT_LORA_LB_STICKY', 'int', 1024,
     'LRU bound on the serve LB adapter-affinity sticky table '
     '(adapter -> last replica); overflow counts as '
     'skyt_lora_adapter_evictions_total.'),

    # -- RL post-training pipeline (jobs/rl_pipeline.py) ------------
    ('SKYT_RL_MAX_STALENESS', 'int', 4,
     'Off-policy staleness bound in learner steps: a rollout replica '
     'pauses generation (backpressure valve) whenever a batch it '
     'produced now could be consumed more than this many versions '
     'after the policy that generated it (docs/rl_pipeline.md).'),
    ('SKYT_RL_QUEUE_BATCHES', 'int', 2,
     'Rollout-batch buffer depth between the rollout fleet and the '
     'learner; every buffered batch adds one step of worst-case '
     'staleness, so the valve counts it.'),
    ('SKYT_RL_REFRESH_MODE', 'str', 'step',
     'How rollout replicas apply a published policy: "step" swaps '
     'live at a decode step boundary (in-flight KV kept), "drain" '
     'holds admission and waits out in-flight generation first (the '
     'stop-the-world per-replica baseline).'),
    ('SKYT_RL_REFRESH_CONCURRENCY', 'int', 1,
     'Rollout replicas allowed to refresh weights simultaneously; '
     'the rest keep generating, so a refresh wave never stops the '
     'fleet (staggered rollout of the new policy).'),
    ('SKYT_RL_ROLE', 'str', '',
     'Pipeline member role injected by the pipeline launcher: '
     '"learner" or "rollout"; empty = run the whole pipeline '
     'in-process.', True),
    ('SKYT_RL_RANK', 'int', 0,
     'Rollout replica rank within the pipeline fleet (stagger phase '
     'and metrics label).', True),
    ('SKYT_RL_FLEET', 'int', 1,
     'Rollout fleet size the pipeline was launched with.', True),
    ('SKYT_RL_STORE', 'path', None,
     'Policy store directory the learner commits delta manifests '
     'into and rollout replicas pull from (content-addressed shards '
     'via data/ckpt_manifest; rides the fan-out tree when remote).',
     True),
    ('SKYT_RL_EVAL_POLL_S', 'float', 10.0,
     'Poll cadence (seconds) for an inference server launched with '
     '--policy-store: the eval fleet checks the RL pipeline\'s store '
     'for a newer committed policy and live-refreshes the engine '
     'with the shard delta (recipe://rl-pipeline-evalserver).'),

    # -- provisioning -----------------------------------------------
    ('SKYT_K8S_FAKE', 'bool', False,
     'Use the in-repo fake kubernetes API (tests).'),
    ('SKYT_K8S_IMAGE', 'str', 'python:3.11-slim',
     'Pod image for kubernetes-provisioned nodes.'),
    ('SKYT_K8S_PROVISION_TIMEOUT', 'float', 600.0,
     'Kubernetes pod provision deadline (seconds).'),
    ('SKYT_SLURM_POLL_SECONDS', 'float', 2.0,
     'Slurm job state poll cadence (seconds).'),
    ('SKYT_SSH_NODE_POOLS', 'path', None,
     'SSH node-pool inventory YAML (default: SKYT_STATE_DIR/'
     'ssh_node_pools.yaml).'),
    ('SKYT_FAKE_SSH_MODE', 'bool', False,
     'Fake provider: expose nodes over fake SSH instead of '
     'local-style exec (tests).'),
    ('SKYT_FAKE_SSH_MAP', 'path', None,
     'Fake provider: host->workdir map file (default: '
     'SKYT_STATE_DIR/fake_ssh_map.json).'),
])

REGISTRY: Dict[str, EnvVar] = {
    v.name: v for v in DECLARATIONS if not v.is_pattern}
PATTERNS: List[EnvVar] = [v for v in DECLARATIONS if v.is_pattern]

assert len(REGISTRY) + len(PATTERNS) == len(DECLARATIONS), (
    'duplicate SKYT_* declaration')


def lookup(name: str) -> Optional[EnvVar]:
    """The declaration for ``name``, resolving dynamic families
    through their ``*`` patterns. ``None`` = undeclared."""
    var = REGISTRY.get(name)
    if var is not None:
        return var
    for pat in PATTERNS:
        if name.startswith(pat.name[:-1]):
            return pat
    return None


def _require(name: str) -> EnvVar:
    var = lookup(name)
    if var is None:
        raise KeyError(
            f'{name} is not a declared SKYT_* knob; add it to '
            'skypilot_tpu/utils/env_registry.py (skylint SKYT002 '
            'enforces this)')
    return var


def _warn(name: str, raw: str) -> None:
    from skypilot_tpu.utils import log
    log.init_logger(__name__).warning(
        'ignoring unparsable %s=%r (using declared default)', name, raw)


def get_str(name: str, default: object = REGISTRY) -> Optional[str]:
    """String/path/url knob; ``None`` when unset and no default.
    (The ``REGISTRY`` sentinel means "use the declared default".)"""
    var = _require(name)
    raw = os.environ.get(name)
    if raw:
        return raw
    return var.default if default is REGISTRY else default


def get_int(name: str, default: object = REGISTRY,
            minimum: Optional[int] = None) -> Optional[int]:
    """Integer knob: declared default when unset, unparsable, or below
    ``minimum`` (same semantics as ``common_utils.env_int``)."""
    var = _require(name)
    fallback = var.default if default is REGISTRY else default
    raw = os.environ.get(name, '').strip()
    if not raw:
        return fallback
    try:
        value = int(raw)
    except ValueError:
        _warn(name, raw)
        return fallback
    if minimum is not None and value < minimum:
        return fallback
    return value


def get_float(name: str, default: object = REGISTRY,
              minimum: Optional[float] = None) -> Optional[float]:
    var = _require(name)
    fallback = var.default if default is REGISTRY else default
    raw = os.environ.get(name, '').strip()
    if not raw:
        return fallback
    try:
        value = float(raw)
    except ValueError:
        _warn(name, raw)
        return fallback
    if minimum is not None and value < minimum:
        return fallback
    return value


_FALSE = frozenset(('', '0', 'false', 'no', 'off'))


def get_bool(name: str, default: object = REGISTRY) -> bool:
    """Boolean knob: unset -> declared default; '0'/'false'/'no'/'off'
    (case-insensitive) -> False; anything else set -> True. This
    subsumes both legacy idioms (``!= '0'`` default-on knobs and
    ``in ('1','true','yes')`` default-off knobs)."""
    var = _require(name)
    raw = os.environ.get(name)
    if raw is None:
        fallback = var.default if default is REGISTRY else default
        return bool(fallback)
    return raw.strip().lower() not in _FALSE


def is_set(name: str) -> bool:
    """Whether the (declared) knob is present in the environment at
    all — for call sites whose default depends on other state."""
    _require(name)
    return name in os.environ


def render_docs() -> str:
    """``docs/env_vars.md`` content, generated from the table (the
    committed copy is checked in-sync by the lint pass)."""
    lines = [
        '# SKYT_* environment knobs',
        '',
        '<!-- GENERATED FILE — do not edit by hand. -->',
        '<!-- Regenerate: python -m skypilot_tpu.lint --dump-env-docs '
        '> docs/env_vars.md -->',
        '',
        'Every `SKYT_*` knob the platform reads, generated from the '
        'typed declaration table in `skypilot_tpu/utils/'
        'env_registry.py`. The skylint SKYT002 pass fails if code '
        'references a knob missing from this table (or if this file '
        'drifts from the table).',
        '',
        '| Name | Type | Default | Description |',
        '| --- | --- | --- | --- |',
    ]
    for var in sorted(DECLARATIONS, key=lambda v: v.name):
        default = '(unset)' if var.default is None else f'`{var.default}`'
        name = var.name.replace('*', '\\*')
        lines.append(f'| `{name}` | {var.type} | {default} | '
                     f'{var.doc} |')
    lines.append('')
    lines.append(f'{len(DECLARATIONS)} declarations '
                 f'({len(PATTERNS)} dynamic patterns).')
    return '\n'.join(lines) + '\n'
