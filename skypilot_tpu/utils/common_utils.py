"""Small shared helpers: ids, user, retry/backoff, dict utils.

Parity targets: ``sky/utils/common_utils.py`` (cluster name/user helpers) and
the backoff helpers used by the provisioner retry loops.
"""
from __future__ import annotations

import getpass
import hashlib
import os
import random
import re
import sqlite3
import time
import uuid
from typing import Any, Callable, Dict, Optional, TypeVar

T = TypeVar('T')


def add_column_if_missing(conn: sqlite3.Connection, ddl: str) -> None:
    """Run an ``ALTER TABLE ... ADD COLUMN`` tolerating a concurrent winner.

    Schema migrations run lazily from every process that opens the DB;
    two processes can both observe the column missing before either
    commits, and sqlite raises ``duplicate column name`` for the loser.
    """
    try:
        conn.execute(ddl)
    except sqlite3.OperationalError as e:
        if 'duplicate column' not in str(e):
            raise
    except Exception as e:  # Postgres backend: same race, 42701
        from skypilot_tpu.utils.pg import PgError
        if not (isinstance(e, PgError)
                and (e.code == '42701' or 'already exists' in str(e))):
            raise

_USER_HASH_FILE = os.path.expanduser('~/.skyt/user_hash')
CLUSTER_NAME_VALID_REGEX = re.compile(r'^[a-zA-Z]([a-zA-Z0-9_-]*[a-zA-Z0-9])?$')


def get_user() -> str:
    try:
        return getpass.getuser()
    except Exception:  # pylint: disable=broad-except
        return 'unknown'


def get_user_hash() -> str:
    """Stable 8-hex id for this user/machine, cached on disk."""
    env = os.environ.get('SKYT_USER_HASH')
    if env:
        return env
    try:
        if os.path.exists(_USER_HASH_FILE):
            with open(_USER_HASH_FILE, encoding='utf-8') as f:
                cached = f.read().strip()
            if re.fullmatch(r'[0-9a-f]{8}', cached):
                return cached
    except OSError:
        pass
    user_hash = hashlib.md5(
        (get_user() + str(uuid.getnode())).encode()).hexdigest()[:8]
    try:
        os.makedirs(os.path.dirname(_USER_HASH_FILE), exist_ok=True)
        with open(_USER_HASH_FILE, 'w', encoding='utf-8') as f:
            f.write(user_hash)
    except OSError:
        pass
    return user_hash


def generate_cluster_name(prefix: str = 'skyt') -> str:
    return f'{prefix}-{uuid.uuid4().hex[:4]}-{get_user()[:8]}'


def validate_cluster_name(name: str) -> None:
    if not CLUSTER_NAME_VALID_REGEX.fullmatch(name):
        raise ValueError(
            f'Cluster name {name!r} is invalid: must start with a letter, '
            'contain only [a-zA-Z0-9_-], and not end with - or _.')


def new_request_id() -> str:
    return uuid.uuid4().hex


class Backoff:
    """Decorrelated-jitter exponential backoff (provisioner retry loops;

    the reference uses a similar helper for `_retry_zones`,
    sky/backends/cloud_vm_ray_backend.py:1003)."""

    def __init__(self,
                 initial: float = 1.0,
                 max_backoff: float = 30.0,
                 multiplier: float = 1.6,
                 rng: Optional[random.Random] = None) -> None:
        self._initial = initial
        self._max = max_backoff
        self._mult = multiplier
        self._current = initial
        # Injectable jitter source (seeded tests / simkit); defaults
        # to the module-level source.
        self._rng = rng if rng is not None else random

    def current_backoff(self) -> float:
        delay = min(self._current * self._rng.uniform(0.8, 1.2), self._max)
        self._current = min(self._current * self._mult, self._max)
        return delay

    def reset(self) -> None:
        self._current = self._initial


def retry(fn: Callable[[], T],
          *,
          max_attempts: int = 3,
          retryable: Callable[[Exception], bool] = lambda e: True,
          initial_backoff: float = 1.0) -> T:
    backoff = Backoff(initial=initial_backoff)
    last_exc: Optional[Exception] = None
    for attempt in range(max_attempts):
        try:
            return fn()
        except Exception as e:  # pylint: disable=broad-except
            if not retryable(e):
                raise
            last_exc = e
            if attempt < max_attempts - 1:
                time.sleep(backoff.current_backoff())
    assert last_exc is not None
    raise last_exc


def deep_update(base: Dict[str, Any], override: Dict[str, Any]) -> Dict[str, Any]:
    """Recursively merge `override` into `base` (returns a new dict)."""
    out = dict(base)
    for k, v in override.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = deep_update(out[k], v)
        else:
            out[k] = v
    return out


def format_float(x: Optional[float], precision: int = 2) -> str:
    if x is None:
        return '-'
    if x >= 100 or x == int(x):
        return str(int(round(x)))
    return f'{x:.{precision}f}'


def readable_duration(seconds: float) -> str:
    seconds = int(seconds)
    if seconds < 60:
        return f'{seconds}s'
    if seconds < 3600:
        return f'{seconds // 60}m {seconds % 60}s'
    return f'{seconds // 3600}h {(seconds % 3600) // 60}m'


def find_free_port(host: str = '127.0.0.1') -> int:
    """An OS-assigned free TCP port (racy by nature; callers bind soon
    after)."""
    import socket
    with socket.socket() as s:
        s.bind((host, 0))
        return s.getsockname()[1]
