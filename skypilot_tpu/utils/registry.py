"""Generic name->class registries.

Capability parity with the reference's ``sky/utils/registry.py:126-141``
(CLOUD/BACKEND/JOBS_RECOVERY_STRATEGY/... registries), redesigned as a small
typed helper rather than a metaclass dance.
"""
from __future__ import annotations

from typing import Callable, Dict, Generic, Iterator, List, Optional, TypeVar

T = TypeVar('T')


class Registry(Generic[T]):
    """A case-insensitive name -> object registry with aliases."""

    def __init__(self, registry_name: str) -> None:
        self._name = registry_name
        self._entries: Dict[str, T] = {}
        self._aliases: Dict[str, str] = {}
        self._default: Optional[str] = None

    def register(self,
                 name: str,
                 *,
                 aliases: Optional[List[str]] = None,
                 default: bool = False) -> Callable[[T], T]:
        """Decorator: register the decorated object under `name`."""

        def decorator(obj: T) -> T:
            key = name.lower()
            if key in self._entries:
                raise ValueError(
                    f'{self._name} registry: duplicate entry {name!r}')
            self._entries[key] = obj
            for alias in aliases or []:
                self._aliases[alias.lower()] = key
            if default:
                self._default = key
            return obj

        return decorator

    def get(self, name: Optional[str]) -> T:
        if name is None:
            if self._default is None:
                raise KeyError(f'{self._name} registry: no default entry')
            name = self._default
        key = name.lower()
        key = self._aliases.get(key, key)
        if key not in self._entries:
            raise KeyError(
                f'{self._name} registry: unknown entry {name!r}. '
                f'Available: {sorted(self._entries)}')
        return self._entries[key]

    def __contains__(self, name: str) -> bool:
        key = name.lower()
        return key in self._entries or key in self._aliases

    def keys(self) -> List[str]:
        return sorted(self._entries)

    def values(self) -> Iterator[T]:
        return iter(self._entries.values())


# Global registries (populated via decorators at import time of the
# respective subpackages).
CLOUD_REGISTRY: 'Registry' = Registry('cloud')
BACKEND_REGISTRY: 'Registry' = Registry('backend')
JOBS_RECOVERY_STRATEGY_REGISTRY: 'Registry' = Registry('jobs-recovery-strategy')
AUTOSCALER_REGISTRY: 'Registry' = Registry('autoscaler')
FORECASTER_REGISTRY: 'Registry' = Registry('forecaster')
LB_POLICY_REGISTRY: 'Registry' = Registry('load-balancing-policy')
MODEL_REGISTRY: 'Registry' = Registry('model')
