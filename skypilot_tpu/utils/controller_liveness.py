"""Liveness of offloaded controllers (shared by jobs and serve).

A controller that runs as a detached job on a controller cluster is
alive iff its job row on that cluster is non-terminal. Two subtleties
both consumers must share (a fix to one must not miss the other):

* one job-table fetch per cluster per reap pass (N offloaded
  controllers share a cluster; N identical SSH fetches scale queue
  inspection linearly for nothing);
* conclusively-gone clusters read as dead, but *unreachable* clusters
  (SSH blip, channel reconnect) read as ALIVE — declaring a healthy
  controller dead would spawn a duplicate and burn the restart budget.
"""
from __future__ import annotations

CLUSTER_GONE = object()
CLUSTER_UNREACHABLE = object()


def fetch_controller_queue(cluster: str, cache: dict):
    """The cluster's job table keyed by job_id, memoized in ``cache``;
    CLUSTER_GONE / CLUSTER_UNREACHABLE sentinels on failure."""
    if cluster not in cache:
        from skypilot_tpu import core, exceptions
        try:
            cache[cluster] = {j.get('job_id'): j
                              for j in core.queue(cluster)}
        except (exceptions.ClusterDoesNotExist,
                exceptions.ClusterNotUpError):
            cache[cluster] = CLUSTER_GONE
        except Exception:  # pylint: disable=broad-except
            cache[cluster] = CLUSTER_UNREACHABLE
    return cache[cluster]


def cluster_job_alive(cluster: str, job_id: int,
                      queue_cache: dict = None) -> bool:
    """Is the controller job non-terminal on its cluster? Inconclusive
    reads as alive (see module docstring)."""
    from skypilot_tpu.runtime import job_lib
    jobs = fetch_controller_queue(
        cluster, queue_cache if queue_cache is not None else {})
    if jobs is CLUSTER_GONE:
        return False
    if jobs is CLUSTER_UNREACHABLE:
        return True
    row = jobs.get(job_id)
    return (row is not None and
            not job_lib.JobStatus(row['status']).is_terminal())
