"""Orphan reaper: kill a process tree once its supervisor dies.

Parity: ``sky/skylet/subprocess_daemon.py:1-5`` — a tiny detached
process that waits for a parent pid to exit and then SIGTERM/SIGKILLs a
target process tree. Used by the request executor: every forked request
child gets a reaper watching its runner, so a hard-killed runner
(kill -9, OOM) cannot leak a half-finished launch running forever.

Run as: python -S -m-less bootstrap (see spawn_orphan_reaper) or
``python -m skypilot_tpu.utils.subprocess_daemon --parent-pid P
--proc-pid C``.
"""
from __future__ import annotations

import argparse
import os
import signal
import sys
import time


def _alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:
        return True


def main(argv=None) -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument('--parent-pid', type=int, required=True)
    parser.add_argument('--proc-pid', type=int, required=True)
    parser.add_argument('--poll-seconds', type=float, default=1.0)
    args = parser.parse_args(argv)

    while _alive(args.parent_pid):
        if not _alive(args.proc_pid):
            return 0  # target finished normally; nothing to reap
        time.sleep(args.poll_seconds)

    if not _alive(args.proc_pid):
        return 0
    # Parent died with the target still running: orphan. Kill the tree.
    # psutil may not be importable under -S bootstraps; walk /proc.
    victims = _descendants(args.proc_pid) + [args.proc_pid]
    for sig in (signal.SIGTERM, signal.SIGKILL):
        for pid in victims:
            try:
                os.kill(pid, sig)
            except (ProcessLookupError, PermissionError):
                pass
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and any(_alive(p) for p in victims):
            time.sleep(0.1)
        if not any(_alive(p) for p in victims):
            break
    return 0


def _descendants(root: int) -> list:
    """All transitive children of root, leaves first (via /proc)."""
    children: dict = {}
    try:
        for entry in os.listdir('/proc'):
            if not entry.isdigit():
                continue
            try:
                with open(f'/proc/{entry}/stat', encoding='utf-8',
                          errors='replace') as f:
                    fields = f.read().rsplit(')', 1)[-1].split()
                ppid = int(fields[1])
            except (OSError, IndexError, ValueError):
                continue
            children.setdefault(ppid, []).append(int(entry))
    except OSError:
        return []
    out = []
    stack = [root]
    while stack:
        pid = stack.pop()
        for child in children.get(pid, []):
            out.append(child)
            stack.append(child)
    return list(reversed(out))


if __name__ == '__main__':
    sys.exit(main())
