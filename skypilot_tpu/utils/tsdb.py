"""Durable on-disk time-series store for the fleet telemetry plane.

Every metrics surface this platform grew (``/api/metrics``, the serve
LB's ``/-/lb/metrics``, replica ``/metrics``) is a point-in-time
snapshot that dies with its process — history, the thing every "did
p99 regress" and "what did traffic look like yesterday" question needs,
lived nowhere. This module is the history: an append-only store of
compressed time-series chunks under ``<server_dir>/telemetry/``, fed by
the scrape-federation daemon (``server/telemetry.py``) and read by
range queries, the SLO burn-rate engine, and the serve forecaster's
restart hydration.

Layout follows Gorilla (Pelkonen et al., VLDB 2015), scaled to a
single-node control plane:

* **Chunk encoding** — per chunk, timestamps are delta-of-delta coded
  (a steady scrape cadence costs ~1 bit/sample) and values are
  XOR-coded against their predecessor (unchanged gauges cost 1 bit;
  slowly-moving floats store only their meaningful mantissa window).
* **Segments** — chunks append to ``raw/seg-<ts>.tsdb`` files rotated
  on a fixed cadence; a torn trailing record (crash mid-append) is
  ignored on read. Readers in OTHER processes (the serve controller
  hydrating its forecaster) scan the same files read-only.
* **Downsampling** — every raw sample also feeds a per-series rollup
  bucket (``SKYT_TELEMETRY_ROLLUP_BUCKET_S``, default 5 min); when the
  bucket rolls over, its mean and max land in the ``rollup/`` segment
  set. Retention is two-tier: raw segments are deleted after
  ``SKYT_TELEMETRY_RAW_RETENTION_S``, rollups after the (much longer)
  ``SKYT_TELEMETRY_ROLLUP_RETENTION_S`` — queries stitch rollup points
  in where raw has been reclaimed.
* **Counter-reset detection at ingest** — counters are stored as a
  monotone *adjusted* cumulative: when a scraped value drops below its
  predecessor (the exporting process restarted), the previous peak is
  folded into a per-series offset, so a restart reads as a rate
  discontinuity instead of a huge negative spike. The offset state
  itself survives store restarts by seeding from the persisted tail.

Timestamps are wall-clock seconds (persisted — the SKYT009 exemption
class); internally they are millisecond integers so delta-of-delta
stays exact.
"""
from __future__ import annotations

import json
import math
import os
import struct
import threading
import time
from typing import Callable, Dict, Iterable, List, NamedTuple, Optional, Tuple

from skypilot_tpu.utils import log

logger = log.init_logger(__name__)

# Resolutions a chunk can carry.
RES_RAW = 0
RES_ROLLUP_MEAN = 1
RES_ROLLUP_MAX = 2

KIND_GAUGE = 'gauge'
KIND_COUNTER = 'counter'

_MAGIC = b'SKTSDB1\n'
# Record header: marker, flags (bit0: counter; bits 1-2: resolution),
# key length, sample count, payload length, start/end ts (ms).
_REC = struct.Struct('<cBHHIqq')
_REC_MARK = b'C'


def series_key(name: str, labels: Dict[str, str]) -> str:
    """Canonical identity of one series (name + sorted label pairs)."""
    return json.dumps([name, sorted(labels.items())],
                      separators=(',', ':'))


def parse_key(key: str) -> Tuple[str, Dict[str, str]]:
    name, pairs = json.loads(key)
    return name, dict(pairs)


# -- bit-level codec ----------------------------------------------------


class _BitWriter:
    __slots__ = ('_buf', '_cur', '_nbits')

    def __init__(self) -> None:
        self._buf = bytearray()
        self._cur = 0
        self._nbits = 0

    def write(self, value: int, nbits: int) -> None:
        """Append the low ``nbits`` of ``value`` (MSB first)."""
        cur, filled = self._cur, self._nbits
        cur = (cur << nbits) | (value & ((1 << nbits) - 1))
        filled += nbits
        while filled >= 8:
            filled -= 8
            self._buf.append((cur >> filled) & 0xFF)
        self._cur = cur & ((1 << filled) - 1)
        self._nbits = filled

    def getvalue(self) -> bytes:
        out = bytes(self._buf)
        if self._nbits:
            out += bytes([(self._cur << (8 - self._nbits)) & 0xFF])
        return out


class _BitReader:
    __slots__ = ('_data', '_pos')

    def __init__(self, data: bytes) -> None:
        self._data = data
        self._pos = 0

    def read(self, nbits: int) -> int:
        out = 0
        pos = self._pos
        data = self._data
        for _ in range(nbits):
            byte = data[pos >> 3]
            out = (out << 1) | ((byte >> (7 - (pos & 7))) & 1)
            pos += 1
        self._pos = pos
        return out


def _zigzag(v: int) -> int:
    return (v << 1) ^ (v >> 63) if v < 0 else (v << 1)


def _unzigzag(z: int) -> int:
    return (z >> 1) ^ -(z & 1)


def _float_bits(v: float) -> int:
    return struct.unpack('<Q', struct.pack('<d', v))[0]


def _bits_float(b: int) -> float:
    return struct.unpack('<d', struct.pack('<Q', b))[0]


def encode_chunk(samples: List[Tuple[int, float]]) -> bytes:
    """Gorilla-encode ``[(ts_ms, value), ...]`` (ascending ts)."""
    w = _BitWriter()
    prev_ts = prev_delta = 0
    prev_bits = 0
    prev_lead = prev_mlen = -1
    for i, (ts, value) in enumerate(samples):
        bits = _float_bits(value)
        if i == 0:
            w.write(ts, 64)
            w.write(bits, 64)
        else:
            delta = ts - prev_ts
            dod = delta - prev_delta
            z = _zigzag(dod)
            if z == 0:
                w.write(0b0, 1)
            elif z < (1 << 7):
                w.write(0b10, 2)
                w.write(z, 7)
            elif z < (1 << 9):
                w.write(0b110, 3)
                w.write(z, 9)
            elif z < (1 << 12):
                w.write(0b1110, 4)
                w.write(z, 12)
            else:
                w.write(0b1111, 4)
                w.write(z, 64)
            prev_delta = delta
            xor = bits ^ prev_bits
            if xor == 0:
                w.write(0b0, 1)
            else:
                # Clamp the leading-zero count to the 5-bit field FIRST
                # and derive the meaningful length from the clamped
                # value — encoder and decoder must agree on the window.
                lead = min(64 - xor.bit_length(), 31)
                trail = (xor & -xor).bit_length() - 1
                mlen = 64 - lead - trail
                if (prev_lead >= 0 and lead >= prev_lead and
                        (64 - prev_lead - prev_mlen) <= trail):
                    # Fits the previous meaningful window: reuse it.
                    w.write(0b10, 2)
                    w.write(xor >> (64 - prev_lead - prev_mlen),
                            prev_mlen)
                else:
                    w.write(0b11, 2)
                    w.write(lead, 5)
                    w.write(mlen - 1, 6)
                    w.write(xor >> trail, mlen)
                    prev_lead, prev_mlen = lead, mlen
        if i == 0:
            prev_delta = 0
        prev_ts, prev_bits = ts, bits
    return w.getvalue()


def decode_chunk(payload: bytes, count: int) -> List[Tuple[int, float]]:
    """Inverse of :func:`encode_chunk`."""
    if count == 0:
        return []
    r = _BitReader(payload)
    out: List[Tuple[int, float]] = []
    ts = r.read(64)
    bits = r.read(64)
    out.append((ts, _bits_float(bits)))
    delta = 0
    lead = mlen = -1
    for _ in range(count - 1):
        if r.read(1) == 0:
            dod = 0
        elif r.read(1) == 0:
            dod = _unzigzag(r.read(7))
        elif r.read(1) == 0:
            dod = _unzigzag(r.read(9))
        elif r.read(1) == 0:
            dod = _unzigzag(r.read(12))
        else:
            dod = _unzigzag(r.read(64))
        delta += dod
        ts += delta
        if r.read(1) == 1:
            if r.read(1) == 0:
                xor = r.read(mlen) << (64 - lead - mlen)
            else:
                lead = r.read(5)
                mlen = r.read(6) + 1
                xor = r.read(mlen) << (64 - lead - mlen)
            bits ^= xor
        out.append((ts, _bits_float(bits)))
    return out


# -- chunk frames -------------------------------------------------------


class Chunk(NamedTuple):
    key: str
    kind: str                   # gauge | counter
    resolution: int             # RES_*
    start_ms: int
    end_ms: int
    count: int
    payload: bytes

    def samples(self) -> List[Tuple[int, float]]:
        return decode_chunk(self.payload, self.count)


def _frame(chunk: Chunk) -> bytes:
    key_bytes = chunk.key.encode('utf-8')
    flags = ((1 if chunk.kind == KIND_COUNTER else 0)
             | (chunk.resolution << 1))
    return _REC.pack(_REC_MARK, flags, len(key_bytes), chunk.count,
                     len(chunk.payload), chunk.start_ms,
                     chunk.end_ms) + key_bytes + chunk.payload


def _scan_segment(path: str) -> List[Chunk]:
    """Decode every complete record in a segment; a torn trailing
    record (crash mid-append) is silently dropped."""
    chunks: List[Chunk] = []
    try:
        with open(path, 'rb') as f:
            header = f.read(len(_MAGIC))
            if header != _MAGIC:
                return []
            while True:
                head = f.read(_REC.size)
                if len(head) < _REC.size:
                    break
                mark, flags, key_len, count, payload_len, start, end = \
                    _REC.unpack(head)
                if mark != _REC_MARK:
                    break
                body = f.read(key_len + payload_len)
                if len(body) < key_len + payload_len:
                    break
                chunks.append(Chunk(
                    body[:key_len].decode('utf-8'),
                    KIND_COUNTER if flags & 1 else KIND_GAUGE,
                    (flags >> 1) & 0x3, start, end, count,
                    body[key_len:]))
    except OSError:
        return []
    return chunks


class Series(NamedTuple):
    """One query result series."""
    name: str
    labels: Dict[str, str]
    points: List[Tuple[float, float]]    # (ts seconds, value)


class _Head:
    """The in-memory appending chunk of one series."""
    __slots__ = ('kind', 'samples')

    def __init__(self, kind: str) -> None:
        self.kind = kind
        self.samples: List[Tuple[int, float]] = []


class _RollupBucket:
    __slots__ = ('start', 'total', 'count', 'maximum')

    def __init__(self, start: float) -> None:
        self.start = start
        self.total = 0.0
        self.count = 0
        self.maximum = float('-inf')


class TSDB:
    """Append-only compressed time-series store (one writer process;
    any number of read-only openers)."""

    def __init__(self, root: str,
                 raw_retention_s: float = 6 * 3600.0,
                 rollup_retention_s: float = 14 * 86400.0,
                 rollup_bucket_s: float = 300.0,
                 chunk_samples: int = 240,
                 segment_seconds: float = 3600.0,
                 clock: Callable[[], float] = time.time) -> None:
        self.root = root
        self.raw_retention_s = float(raw_retention_s)
        self.rollup_retention_s = float(rollup_retention_s)
        self.rollup_bucket_s = max(1.0, float(rollup_bucket_s))
        self.chunk_samples = max(2, int(chunk_samples))
        self.segment_seconds = max(1.0, float(segment_seconds))
        self._clock = clock
        self._lock = threading.RLock()
        self._heads: Dict[Tuple[str, int], _Head] = {}
        self._sealed: List[Chunk] = []
        # counter key -> (last adjusted value, reset offset); the
        # adjusted series is what gets stored (monotone across resets).
        # Persisted to counters.json on forced flushes: the adjusted
        # tail alone cannot reconstruct the offset, and seeding a
        # restart with offset=0 would misread the exporter's (lower)
        # raw value as ANOTHER reset and double-count it.
        self._counter_state: Dict[str, Tuple[float, float]] = {}
        self._load_counter_state()
        self._rollups: Dict[str, _RollupBucket] = {}
        self._rollup_kind: Dict[str, str] = {}
        # (path, mtime, size) -> parsed chunks; segments are append-only
        # so a (size, mtime) match means the cache is current.
        self._segment_cache: Dict[str, Tuple[float, int, List[Chunk]]] = {}
        # heads-<pid>.json sidecar cache, same invalidation stance.
        self._heads_cache: Dict[str, Tuple[Tuple[float, int], list]] = {}
        self.dropped_out_of_order = 0

    # -- paths ---------------------------------------------------------

    def _dir(self, resolution: int) -> str:
        return os.path.join(
            self.root, 'raw' if resolution == RES_RAW else 'rollup')

    def _segments(self, resolution: int) -> List[str]:
        d = self._dir(resolution)
        try:
            names = sorted(n for n in os.listdir(d)
                           if n.startswith('seg-') and n.endswith('.tsdb'))
        except OSError:
            return []
        return [os.path.join(d, n) for n in names]

    def _current_segment(self, resolution: int, now: float) -> str:
        """The segment file new chunks append to: rotate on a fixed
        wall cadence so retention can reclaim whole files."""
        bucket = int(now // self.segment_seconds * self.segment_seconds)
        d = self._dir(resolution)
        os.makedirs(d, exist_ok=True)
        return os.path.join(d, f'seg-{bucket}.tsdb')

    # -- ingest --------------------------------------------------------

    def ingest(self, name: str, labels: Dict[str, str], value: float,
               ts: Optional[float] = None, kind: str = KIND_GAUGE) -> None:
        """Append one observation. Counter values are reset-adjusted
        (see module docstring); non-finite values are dropped."""
        if not isinstance(value, (int, float)) or value != value or \
                value in (float('inf'), float('-inf')):
            return
        if ts is None:
            ts = self._clock()
        key = series_key(name, labels)
        with self._lock:
            if kind == KIND_COUNTER:
                value = self._adjust_counter(key, float(value))
            self._append(key, kind, RES_RAW, ts, float(value))
            self._feed_rollup(key, kind, ts, float(value))

    def _adjust_counter(self, key: str, value: float) -> float:
        state = self._counter_state.get(key)
        if state is None:
            # First sight since (re)start: seed from the persisted tail
            # so a scraper restart doesn't itself read as a reset (and
            # a raw value BELOW the tail folds into an offset below).
            tail = self._tail_value(key)
            state = (tail if tail is not None else 0.0, 0.0)
        last, offset = state
        adjusted = value + offset
        if adjusted < last:
            # The exporter restarted (raw value fell): fold the
            # previous peak into the offset — the stored series stays
            # monotone and rate() reads a discontinuity, not a
            # negative spike.
            offset = last
            adjusted = value + offset
        self._counter_state[key] = (adjusted, offset)
        return adjusted

    def _tail_value(self, key: str) -> Optional[float]:
        best_ts = None
        best_val = None
        for chunk in self._iter_chunks(RES_RAW):
            if chunk.key != key:
                continue
            if best_ts is None or chunk.end_ms >= best_ts:
                samples = chunk.samples()
                if samples:
                    best_ts = samples[-1][0]
                    best_val = samples[-1][1]
        for entry_key, _kind, resolution, samples in \
                self._iter_head_entries():
            if resolution != RES_RAW or entry_key != key or not samples:
                continue
            ts, value = samples[-1]
            if best_ts is None or ts >= best_ts:
                best_ts, best_val = ts, value
        return best_val

    def _append(self, key: str, kind: str, resolution: int, ts: float,
                value: float) -> None:
        head = self._heads.get((key, resolution))
        if head is None:
            head = self._heads[(key, resolution)] = _Head(kind)
        ts_ms = int(round(ts * 1000.0))
        if head.samples and ts_ms <= head.samples[-1][0]:
            self.dropped_out_of_order += 1
            return
        head.samples.append((ts_ms, value))
        if len(head.samples) >= self.chunk_samples:
            self._seal(key, resolution, head)

    def _seal(self, key: str, resolution: int, head: _Head) -> None:
        if not head.samples:
            return
        self._sealed.append(Chunk(
            key, head.kind, resolution, head.samples[0][0],
            head.samples[-1][0], len(head.samples),
            encode_chunk(head.samples)))
        head.samples = []

    def _feed_rollup(self, key: str, kind: str, ts: float,
                     value: float) -> None:
        bucket_start = ts // self.rollup_bucket_s * self.rollup_bucket_s
        bucket = self._rollups.get(key)
        self._rollup_kind[key] = kind
        if bucket is not None and bucket_start > bucket.start:
            self._emit_rollup(key, bucket)
            bucket = None
        if bucket is None:
            bucket = self._rollups[key] = _RollupBucket(bucket_start)
        bucket.total += value
        bucket.count += 1
        bucket.maximum = max(bucket.maximum, value)

    def _emit_rollup(self, key: str, bucket: _RollupBucket) -> None:
        if bucket.count == 0:
            return
        kind = self._rollup_kind.get(key, KIND_GAUGE)
        # Rollup points are stamped at the bucket END (the moment the
        # aggregate became final).
        ts = bucket.start + self.rollup_bucket_s
        self._append(key, kind, RES_ROLLUP_MEAN, ts,
                     bucket.total / bucket.count)
        self._append(key, kind, RES_ROLLUP_MAX, ts, bucket.maximum)

    # -- durability ----------------------------------------------------

    def _counter_state_path(self) -> str:
        return os.path.join(self.root, 'counters.json')

    def _load_counter_state(self) -> None:
        try:
            with open(self._counter_state_path(),
                      encoding='utf-8') as f:
                raw = json.load(f)
            self._counter_state = {
                key: (float(pair[0]), float(pair[1]))
                for key, pair in raw.items()}
        except (OSError, ValueError, TypeError, IndexError):
            self._counter_state = {}

    def _save_counter_state(self) -> None:
        """Best-effort: a crash inside the flush window can lose up to
        one window of offset updates (a reset in that gap reads as a
        bounded dip on restart); a clean close() always saves."""
        if not self._counter_state:
            return
        try:
            os.makedirs(self.root, exist_ok=True)
            tmp = self._counter_state_path() + '.tmp'
            with open(tmp, 'w', encoding='utf-8') as f:
                json.dump({k: list(v)
                           for k, v in self._counter_state.items()}, f)
            os.replace(tmp, self._counter_state_path())
        except OSError as e:
            logger.debug('counter-state save failed: %s', e)

    def _heads_file(self) -> str:
        return os.path.join(self.root, f'heads-{os.getpid()}.json')

    def _write_heads_snapshot(self) -> None:
        """Durability for not-yet-sealed head samples WITHOUT sealing
        them: sealing on every forced flush would emit 1-4-sample
        chunks whose frame overhead defeats the Gorilla compression
        entirely. The snapshot is a small overwritable sidecar
        (atomic-replace) that readers merge with the segment chunks;
        close() seals for real and removes it. Duplicate samples
        (snapshot taken before a later seal) merge away on read — the
        (series, ts) dict keeps one value."""
        entries = [[key, head.kind, resolution, head.samples]
                   for (key, resolution), head in self._heads.items()
                   if head.samples]
        try:
            os.makedirs(self.root, exist_ok=True)
            tmp = self._heads_file() + '.tmp'
            with open(tmp, 'w', encoding='utf-8') as f:
                json.dump({'series': entries}, f)
            os.replace(tmp, self._heads_file())
        except OSError as e:
            logger.debug('heads snapshot failed: %s', e)

    def _iter_head_entries(self) -> list:
        """Entries ``[key, kind, resolution, [[ts_ms, v], ...]]`` from
        every heads sidecar in the root (all writers', own included —
        a fresh same-pid opener must see its predecessor's data)."""
        try:
            names = [n for n in os.listdir(self.root)
                     if n.startswith('heads-') and n.endswith('.json')]
        except OSError:
            return []
        out: list = []
        for name in names:
            path = os.path.join(self.root, name)
            try:
                stat = os.stat(path)
            except OSError:
                continue
            fingerprint = (stat.st_mtime, stat.st_size)
            cached = self._heads_cache.get(path)
            if cached is None or cached[0] != fingerprint:
                try:
                    with open(path, encoding='utf-8') as f:
                        entries = json.load(f).get('series', [])
                except (OSError, ValueError):
                    entries = []
                cached = (fingerprint, entries)
                self._heads_cache[path] = cached
            out.extend(cached[1])
        return out

    def flush(self, force: bool = False) -> int:
        """Persist sealed chunks; ``force=True`` additionally snapshots
        the open heads + counter state so other processes (and a
        restart) see data up to now. Returns chunks written."""
        with self._lock:
            if force:
                self._write_heads_snapshot()
                self._save_counter_state()
            sealed, self._sealed = self._sealed, []
            if not sealed:
                return 0
            now = self._clock()
            by_seg: Dict[int, List[Chunk]] = {}
            for chunk in sealed:
                # Mean and max rollups share the rollup segment set.
                seg_res = RES_RAW if chunk.resolution == RES_RAW else \
                    RES_ROLLUP_MEAN
                by_seg.setdefault(seg_res, []).append(chunk)
            for seg_res, chunks in by_seg.items():
                path = self._current_segment(seg_res, now)
                # flock'd append (same stance as trace_store): two
                # API-server replicas sharing a state dir must not
                # interleave buffered writes mid-frame or both write
                # the header — either would silently truncate every
                # read past the corruption point.
                import fcntl
                with open(path, 'ab') as f:
                    fcntl.flock(f.fileno(), fcntl.LOCK_EX)
                    try:
                        if os.fstat(f.fileno()).st_size == 0:
                            f.write(_MAGIC)
                        for chunk in chunks:
                            f.write(_frame(chunk))
                        f.flush()
                    finally:
                        fcntl.flock(f.fileno(), fcntl.LOCK_UN)
            return len(sealed)

    def enforce_retention(self, now: Optional[float] = None) -> int:
        """Delete whole segment files past their tier's retention
        (raw first — their data lives on in the rollups). Returns the
        number of files removed."""
        if now is None:
            now = self._clock()
        removed = 0
        for resolution, retention in ((RES_RAW, self.raw_retention_s),
                                      (RES_ROLLUP_MEAN,
                                       self.rollup_retention_s)):
            for path in self._segments(resolution):
                try:
                    if os.path.getmtime(path) < now - retention:
                        os.remove(path)
                        self._segment_cache.pop(path, None)
                        removed += 1
                except OSError:
                    continue
        # Dead writers' heads sidecars (a live writer rewrites its own
        # every forced flush) age out on the raw tier's clock.
        try:
            for name in os.listdir(self.root):
                if not (name.startswith('heads-') and
                        name.endswith('.json')):
                    continue
                path = os.path.join(self.root, name)
                try:
                    if os.path.getmtime(path) < now - \
                            self.raw_retention_s:
                        os.remove(path)
                        self._heads_cache.pop(path, None)
                        removed += 1
                except OSError:
                    continue
        except OSError:
            pass
        return removed

    def close(self) -> None:
        with self._lock:
            # Drain open rollup buckets: the final partial bucket of
            # every series would otherwise never reach the rollup tier
            # and leave a permanent gap once raw retention reclaims the
            # window. (Partial-at-close is approximate by design; a
            # restarted writer re-emitting the same bucket end is
            # dropped as out-of-order, keeping the first emission.)
            for key, bucket in list(self._rollups.items()):
                self._emit_rollup(key, bucket)
            self._rollups.clear()
            # The real seal: heads become proper compressed chunks and
            # the sidecar snapshot is retired.
            for (key, resolution), head in list(self._heads.items()):
                self._seal(key, resolution, head)
            self._save_counter_state()
        self.flush()
        try:
            os.remove(self._heads_file())
        except OSError:
            pass

    # -- read path -----------------------------------------------------

    def _iter_chunks(self, resolution: int) -> Iterable[Chunk]:
        """Persisted chunks of one resolution tier (mean and max rollup
        chunks are distinguished by their record flag)."""
        seg_res = RES_RAW if resolution == RES_RAW else RES_ROLLUP_MEAN
        for path in self._segments(seg_res):
            try:
                stat = os.stat(path)
            except OSError:
                continue
            cached = self._segment_cache.get(path)
            if cached is None or cached[0] != stat.st_mtime or \
                    cached[1] != stat.st_size:
                cached = (stat.st_mtime, stat.st_size,
                          _scan_segment(path))
                self._segment_cache[path] = cached
            for chunk in cached[2]:
                if chunk.resolution == resolution:
                    yield chunk

    def _match(self, chunk_key: str, name: str,
               labels: Optional[Dict[str, str]]) -> Optional[str]:
        try:
            chunk_name, chunk_labels = parse_key(chunk_key)
        except (ValueError, TypeError):
            return None
        if chunk_name != name:
            return None
        if labels:
            for k, v in labels.items():
                if chunk_labels.get(k) != v:
                    return None
        return chunk_key

    def query_range(self, name: str, start: float, end: float,
                    labels: Optional[Dict[str, str]] = None,
                    agg: str = 'mean') -> List[Series]:
        """Every series matching ``name`` (+ label subset) with its
        points in ``[start, end]``. Raw points are preferred; where raw
        has been reclaimed by retention, rollup points (``agg`` =
        ``mean`` or ``max``) fill the older part of the window."""
        # Floor/ceil the bounds: ingest ROUNDS to ms, so truncating the
        # end bound would (half the time) exclude a sample taken in the
        # same millisecond as the query — read-after-write must see it.
        start_ms = math.floor(start * 1000.0)
        end_ms = math.ceil(end * 1000.0)
        rollup_res = RES_ROLLUP_MAX if agg == 'max' else RES_ROLLUP_MEAN
        with self._lock:
            raw = self._collect_points(name, labels, RES_RAW,
                                       start_ms, end_ms)
            rollup = self._collect_points(name, labels, rollup_res,
                                          start_ms, end_ms)
        out: List[Series] = []
        for key in sorted(set(raw) | set(rollup)):
            raw_pts = raw.get(key, [])
            pts = list(raw_pts)
            if key in rollup:
                # Rollups only fill where raw is missing (older than
                # the oldest raw point) — never double-report a window.
                raw_floor = raw_pts[0][0] if raw_pts else float('inf')
                pts = [p for p in rollup[key] if p[0] < raw_floor] + pts
            series_name, series_labels = parse_key(key)
            out.append(Series(series_name, series_labels,
                              [(ts / 1000.0, v) for ts, v in pts]))
        return out

    def _collect_points(self, name: str,
                        labels: Optional[Dict[str, str]],
                        resolution: int, start_ms: int, end_ms: int
                        ) -> Dict[str, List[Tuple[int, float]]]:
        merged: Dict[str, Dict[int, float]] = {}
        for chunk in self._iter_chunks(resolution):
            if chunk.end_ms < start_ms or chunk.start_ms > end_ms:
                continue
            if self._match(chunk.key, name, labels) is None:
                continue
            bucket = merged.setdefault(chunk.key, {})
            for ts, v in chunk.samples():
                if start_ms <= ts <= end_ms:
                    bucket[ts] = v
        # In-memory (unflushed) data is part of the readable window for
        # the owning process...
        for (key, head_res), head in self._heads.items():
            if head_res != resolution:
                continue
            if self._match(key, name, labels) is None:
                continue
            bucket = merged.setdefault(key, {})
            for ts, v in head.samples:
                if start_ms <= ts <= end_ms:
                    bucket[ts] = v
        # ...and writers' snapshot sidecars cover it for everyone else
        # (duplicates against segments/own heads merge away by ts).
        for key, _kind, head_res, samples in self._iter_head_entries():
            if head_res != resolution:
                continue
            if self._match(key, name, labels) is None:
                continue
            bucket = merged.setdefault(key, {})
            for ts, v in samples:
                ts = int(ts)
                if start_ms <= ts <= end_ms:
                    bucket[ts] = v
        for chunk in self._sealed:
            if chunk.resolution != resolution:
                continue
            if self._match(chunk.key, name, labels) is None:
                continue
            bucket = merged.setdefault(chunk.key, {})
            for ts, v in chunk.samples():
                if start_ms <= ts <= end_ms:
                    bucket[ts] = v
        return {key: sorted(points.items())
                for key, points in merged.items()}

    def latest(self, name: str,
               labels: Optional[Dict[str, str]] = None,
               max_age_s: Optional[float] = None) -> List[Series]:
        """The most recent point of each matching series (hydration
        seeds). ``max_age_s`` drops series whose last sample is older
        (dead targets)."""
        now = self._clock()
        start = now - (max_age_s if max_age_s is not None
                       else self.raw_retention_s)
        out: List[Series] = []
        for series in self.query_range(name, start, now, labels):
            if series.points:
                out.append(Series(series.name, series.labels,
                                  [series.points[-1]]))
        return out

    def latest_all(self, max_age_s: float) -> List[Series]:
        """The most recent point of EVERY live series in one index walk
        (the federate surface — a per-name latest() loop would re-walk
        the whole chunk index once per metric name)."""
        now = self._clock()
        start_ms = math.floor((now - max_age_s) * 1000.0)
        end_ms = math.ceil(now * 1000.0)
        best: Dict[str, Tuple[int, float]] = {}

        def consider(key: str, ts: int, value: float) -> None:
            if start_ms <= ts <= end_ms:
                held = best.get(key)
                if held is None or ts >= held[0]:
                    best[key] = (ts, value)

        with self._lock:
            for chunk in self._iter_chunks(RES_RAW):
                if chunk.end_ms < start_ms:
                    continue
                for ts, value in chunk.samples():
                    consider(chunk.key, ts, value)
            for chunk in self._sealed:
                if chunk.resolution != RES_RAW:
                    continue
                for ts, value in chunk.samples():
                    consider(chunk.key, ts, value)
            for (key, resolution), head in self._heads.items():
                if resolution != RES_RAW:
                    continue
                for ts, value in head.samples:
                    consider(key, ts, value)
            for key, _kind, resolution, samples in \
                    self._iter_head_entries():
                if resolution != RES_RAW:
                    continue
                for ts, value in samples:
                    consider(key, int(ts), value)
        out: List[Series] = []
        for key in sorted(best):
            try:
                name, labels = parse_key(key)
            except (ValueError, TypeError):
                continue
            ts, value = best[key]
            out.append(Series(name, labels, [(ts / 1000.0, value)]))
        return out

    def series_names(self) -> List[str]:
        """Every distinct metric name with any stored data."""
        names = set()
        with self._lock:
            for chunk in self._iter_chunks(RES_RAW):
                try:
                    names.add(parse_key(chunk.key)[0])
                except (ValueError, TypeError):
                    continue
            for chunk in self._iter_chunks(RES_ROLLUP_MEAN):
                try:
                    names.add(parse_key(chunk.key)[0])
                except (ValueError, TypeError):
                    continue
            for (key, _), head in self._heads.items():
                if head.samples:
                    try:
                        names.add(parse_key(key)[0])
                    except (ValueError, TypeError):
                        continue
            for key, _kind, _res, samples in self._iter_head_entries():
                if samples:
                    try:
                        names.add(parse_key(key)[0])
                    except (ValueError, TypeError):
                        continue
            for chunk in self._sealed:
                try:
                    names.add(parse_key(chunk.key)[0])
                except (ValueError, TypeError):
                    continue
        return sorted(names)
