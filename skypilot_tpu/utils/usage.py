"""Usage telemetry: opt-in, local-first event log.

Parity: ``sky/usage/usage_lib.py`` + the heartbeat event (the reference
ships usage messages to a hosted Loki; design doc
sky/design_docs/usage_collection.md). Stance here: privacy-first —
events are ALWAYS recorded locally (a JSONL ring under the state dir,
useful for `skyt` debugging and the dashboard), and shipped to an HTTP
collector ONLY when the operator configures one::

    usage:
      endpoint: https://collector.corp/skyt   # POST, JSON body
      enabled: true

Payloads carry no cluster names, commands, or YAML contents — just the
verb, outcome, duration, and coarse environment facts.
"""
from __future__ import annotations

import json
import os
import platform
import time
import uuid
from typing import Any, Dict, Optional

import skypilot_tpu
from skypilot_tpu.utils import log

logger = log.init_logger(__name__)

_MAX_LOCAL_BYTES = 5 * 1024 * 1024


def _usage_dir() -> str:
    return os.path.join(
        os.environ.get('SKYT_STATE_DIR', os.path.expanduser('~/.skyt')),
        'usage')


def _run_id() -> str:
    """Stable anonymous installation id (random uuid, created once)."""
    path = os.path.join(_usage_dir(), 'installation_id')
    try:
        if os.path.exists(path):
            with open(path, encoding='utf-8') as f:
                return f.read().strip()
        os.makedirs(_usage_dir(), exist_ok=True)
        value = uuid.uuid4().hex
        with open(path, 'w', encoding='utf-8') as f:
            f.write(value)
        return value
    except OSError:
        return 'unknown'


def record(event: str, *, outcome: str = 'ok',
           duration_s: Optional[float] = None,
           detail: Optional[Dict[str, Any]] = None) -> None:
    """Append one event locally; ship it if a collector is configured.

    Never raises: telemetry must not break the actual work.
    """
    payload = {
        'ts': time.time(),
        'event': event,
        'outcome': outcome,
        'duration_s': (round(duration_s, 3)
                       if duration_s is not None else None),
        'version': skypilot_tpu.__version__,
        'python': platform.python_version(),
        'platform': platform.system().lower(),
        'installation': _run_id(),
        **(detail or {}),
    }
    try:
        os.makedirs(_usage_dir(), exist_ok=True)
        path = os.path.join(_usage_dir(), 'events.jsonl')
        # Bounded: rotate once instead of growing forever.
        if (os.path.exists(path) and
                os.path.getsize(path) > _MAX_LOCAL_BYTES):
            os.replace(path, path + '.1')
        with open(path, 'a', encoding='utf-8') as f:
            f.write(json.dumps(payload) + '\n')
    except OSError:
        pass
    _maybe_ship(payload)


def _maybe_ship(payload: Dict[str, Any]) -> None:
    """Fire-and-forget: a slow/blackholed collector must never stall
    the CLI exit path or an executor worker."""
    try:
        from skypilot_tpu import config
        if not config.get_nested(('usage', 'enabled'), False):
            return
        endpoint = config.get_nested(('usage', 'endpoint'), None)
        if not endpoint:
            return
    except Exception:  # pylint: disable=broad-except
        return

    def ship() -> None:
        try:
            import urllib.request
            req = urllib.request.Request(
                endpoint, data=json.dumps(payload).encode(),
                headers={'Content-Type': 'application/json'})
            urllib.request.urlopen(req, timeout=3).read()
        except Exception:  # pylint: disable=broad-except
            logger.debug('usage ship failed', exc_info=True)

    import threading
    thread = threading.Thread(target=ship, name='usage-ship',
                              daemon=True)
    thread.start()
    # Short bounded join: both production call sites (CLI exit path,
    # executor child about to os._exit) terminate right after record(),
    # which would kill an unjoined daemon thread before it ever
    # connects. 0.75s caps the stall a dead collector can add.
    thread.join(timeout=0.75)


def recent(limit: int = 100) -> list:
    path = os.path.join(_usage_dir(), 'events.jsonl')
    if not os.path.exists(path):
        return []
    with open(path, encoding='utf-8') as f:
        lines = f.readlines()[-limit:]
    out = []
    for line in lines:
        try:
            out.append(json.loads(line))
        except ValueError:
            continue
    return out
