"""Minimal PostgreSQL wire-protocol client on the stdlib socket.

Parity frame: the reference's Postgres support rides SQLAlchemy +
psycopg2 (``sky/global_user_state.py``, ``sky/utils/locks.py:164``);
neither is in this image, so — same stance as the GCP REST, S3 SigV4
and Azure SharedKey clients — the wire protocol (v3) is implemented
directly: SSLRequest TLS upgrade (``sslmode`` from the URL, like
libpq), startup, cleartext/md5/SCRAM-SHA-256 auth, the simple query
flow (Q → RowDescription/DataRow/CommandComplete) for parameterless
statements, and the EXTENDED protocol (Parse/Bind/Execute/Sync) for
everything with parameters — real server-side bind values, no
client-side literal substitution.

Deliberately small surface, shaped like sqlite3 so state.py can treat
either backend uniformly:

    conn = PgConnection.from_url(
        'postgres://user:pw@host:5432/db?sslmode=verify-full'
        '&sslrootcert=/etc/ssl/corp-ca.pem')
    rows = conn.execute('SELECT * FROM t WHERE name=?', ('x',)).fetchall()

``sslmode``: ``disable`` (default — matches the plaintext-only history
of this client), ``require`` (TLS, no cert validation — libpq's
require), ``verify-ca`` (validate chain), ``verify-full`` (chain +
hostname). Cloud-managed Postgres (the realistic HA deployment)
should use ``verify-full`` with the provider CA in ``sslrootcert``.
"""
from __future__ import annotations

import base64
import hashlib
import hmac
import math
import os
import socket
import ssl
import struct
import urllib.parse
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple


class PgError(Exception):
    """Server-reported error (message field M of ErrorResponse)."""

    def __init__(self, fields: Dict[str, str]) -> None:
        self.fields = fields
        self.code = fields.get('C', '')
        super().__init__(fields.get('M', 'postgres error'))


def to_dollar_params(sql: str) -> str:
    """``?`` placeholders → ``$1..$n`` (extended-protocol numbering),
    skipping string literals and ``--`` line comments."""
    out: List[str] = []
    n = 0
    i = 0
    in_string = False
    while i < len(sql):
        ch = sql[i]
        if in_string:
            out.append(ch)
            if ch == "'":
                in_string = False
            i += 1
            continue
        if ch == "'":
            in_string = True
            out.append(ch)
        elif ch == '-' and sql[i:i + 2] == '--':
            end = sql.find('\n', i)
            end = len(sql) if end < 0 else end
            out.append(sql[i:end])
            i = end
            continue
        elif ch == '?':
            n += 1
            out.append(f'${n}')
        else:
            out.append(ch)
        i += 1
    return ''.join(out)


# Parameter type OIDs declared at Parse time (explicit types keep the
# server from mis-inferring and give the fake server coercion info).
_PARAM_OID = {bool: 16, int: 20, float: 701, str: 25}


def _encode_param(value: Any) -> Tuple[int, Optional[bytes]]:
    """(type oid, text-format bytes or None for NULL)."""
    if value is None:
        return 0, None
    if isinstance(value, bool):
        return 16, b't' if value else b'f'
    if isinstance(value, float):
        if not math.isfinite(value):
            raise ValueError(
                f'non-finite float {value!r} has no SQL literal; '
                'store NULL explicitly instead')
        return 701, repr(value).encode()
    if isinstance(value, int):
        return 20, str(value).encode()
    return 25, str(value).encode('utf-8')


# Common type OIDs -> Python coercion (simple protocol is text-only).
_OID_CAST = {
    16: lambda v: v == 't',                      # bool
    20: int, 21: int, 23: int, 26: int,          # int8/2/4, oid
    700: float, 701: float, 1700: float,         # float4/8, numeric
}


class Row(dict):
    """A result row addressable by column name OR position (the two
    access styles sqlite3.Row callers use)."""

    def __init__(self, columns: List[str], values: List[Any]) -> None:
        super().__init__(zip(columns, values))
        self._values = values

    def __getitem__(self, key):
        if isinstance(key, int):
            return self._values[key]
        return super().__getitem__(key)


class _Result:
    """sqlite3-cursor-shaped result set (typed rows + rowcount)."""

    def __init__(self, columns: List[str], oids: List[int],
                 rows: List[List[Optional[str]]],
                 rowcount: int = -1) -> None:
        casts = [_OID_CAST.get(oid) for oid in oids]
        self._rows = [
            Row(columns,
                [value if value is None or cast is None else cast(value)
                 for cast, value in zip(casts, row)])
            for row in rows
        ]
        # DML statements report affected rows via the CommandComplete
        # tag; SELECTs report the row count (matching sqlite cursors
        # closely enough for the `rowcount == 1` guard idiom).
        self.rowcount = rowcount

    def fetchone(self) -> Optional[Row]:
        return self._rows[0] if self._rows else None

    def fetchall(self) -> List[Row]:
        return list(self._rows)

    def __iter__(self) -> Iterator[Row]:
        return iter(self._rows)


_SSL_REQUEST_CODE = 80877103


class PgConnection:
    def __init__(self, host: str, port: int, user: str,
                 password: str, database: str,
                 connect_timeout: float = 10.0,
                 sslmode: str = 'disable',
                 sslrootcert: Optional[str] = None) -> None:
        if sslmode not in ('disable', 'require', 'verify-ca',
                           'verify-full'):
            raise ValueError(f'unsupported sslmode {sslmode!r}')
        self.user = user
        self.password = password
        self._sock = socket.create_connection((host, port),
                                              timeout=connect_timeout)
        self._sock.settimeout(30.0)
        self._buf = b''
        # Async NotificationResponse frames ('A') collected from the
        # wire — LISTEN/NOTIFY support for the control-plane event bus
        # (utils/events.PgNotifyListener). (channel, payload) tuples.
        self.notifications: List[Tuple[str, str]] = []
        if sslmode != 'disable':
            self._tls_upgrade(host, sslmode, sslrootcert)
        self._startup(database)

    @classmethod
    def from_url(cls, url: str) -> 'PgConnection':
        parsed = urllib.parse.urlparse(url)
        if parsed.scheme not in ('postgres', 'postgresql'):
            raise ValueError(f'not a postgres url: {url!r}')
        query = urllib.parse.parse_qs(parsed.query)
        return cls(host=parsed.hostname or 'localhost',
                   port=parsed.port or 5432,
                   user=urllib.parse.unquote(parsed.username or 'postgres'),
                   password=urllib.parse.unquote(parsed.password or ''),
                   database=(parsed.path or '/postgres').lstrip('/')
                   or 'postgres',
                   sslmode=query.get('sslmode', ['disable'])[0],
                   sslrootcert=query.get('sslrootcert', [None])[0])

    # -- TLS -----------------------------------------------------------

    def _tls_upgrade(self, host: str, sslmode: str,
                     sslrootcert: Optional[str]) -> None:
        """SSLRequest then wrap (the protocol's STARTTLS: the 8-byte
        request goes out in clear, the server answers one byte)."""
        self._sock.sendall(struct.pack('>II', 8, _SSL_REQUEST_CODE))
        answer = self._sock.recv(1)
        if answer != b'S':
            raise PgError({'M': f'server refused TLS (sslmode={sslmode}'
                                f', got {answer!r})'})
        if sslmode == 'require':
            context = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
            context.check_hostname = False
            context.verify_mode = ssl.CERT_NONE
        else:
            context = ssl.create_default_context(cafile=sslrootcert)
            context.check_hostname = (sslmode == 'verify-full')
        try:
            self._sock = context.wrap_socket(self._sock,
                                             server_hostname=host)
        except ssl.SSLError as e:
            raise PgError({'M': f'TLS handshake failed: {e}'}) from e

    # -- framing -------------------------------------------------------

    def _send(self, type_byte: bytes, payload: bytes) -> None:
        self._sock.sendall(type_byte + struct.pack('>I', len(payload) + 4)
                           + payload)

    def _recv_exact(self, n: int) -> bytes:
        while len(self._buf) < n:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise PgError({'M': 'server closed the connection'})
            self._buf += chunk
        out, self._buf = self._buf[:n], self._buf[n:]
        return out

    def _recv_message(self) -> Tuple[bytes, bytes]:
        header = self._recv_exact(5)
        (length,) = struct.unpack('>I', header[1:])
        return header[:1], self._recv_exact(length - 4)

    # -- startup / auth ------------------------------------------------

    def _startup(self, database: str) -> None:
        params = (f'user\0{self.user}\0database\0{database}\0'
                  'application_name\0skypilot-tpu\0\0').encode()
        payload = struct.pack('>I', 196608) + params  # protocol 3.0
        self._sock.sendall(struct.pack('>I', len(payload) + 4) + payload)
        while True:
            mtype, body = self._recv_message()
            if mtype == b'R':
                self._handle_auth(body)
            elif mtype == b'Z':      # ReadyForQuery
                return
            elif mtype == b'E':
                raise PgError(_parse_error(body))
            # S (ParameterStatus) / K (BackendKeyData): ignored

    def _handle_auth(self, body: bytes) -> None:
        (code,) = struct.unpack('>I', body[:4])
        if code == 0:                # AuthenticationOk
            return
        if code == 3:                # cleartext
            self._send(b'p', self.password.encode() + b'\0')
            return
        if code == 5:                # md5
            salt = body[4:8]
            inner = hashlib.md5(
                self.password.encode() + self.user.encode()).hexdigest()
            digest = hashlib.md5(inner.encode() + salt).hexdigest()
            self._send(b'p', b'md5' + digest.encode() + b'\0')
            return
        if code == 10:               # SASL: mechanisms list
            mechanisms = body[4:].split(b'\0')
            if b'SCRAM-SHA-256' not in mechanisms:
                raise PgError({'M': f'unsupported SASL {mechanisms}'})
            self._scram()
            return
        raise PgError({'M': f'unsupported auth method {code}'})

    def _scram(self) -> None:
        """SCRAM-SHA-256 (RFC 5802/7677) over the SASL messages."""
        nonce = base64.b64encode(os.urandom(18)).decode()
        first_bare = f'n={self.user},r={nonce}'
        client_first = 'n,,' + first_bare
        payload = (b'SCRAM-SHA-256\0' +
                   struct.pack('>I', len(client_first)) +
                   client_first.encode())
        self._send(b'p', payload)
        mtype, body = self._recv_message()
        if mtype == b'E':
            raise PgError(_parse_error(body))
        (code,) = struct.unpack('>I', body[:4])
        assert code == 11, f'expected SASLContinue, got {code}'
        server_first = body[4:].decode()
        attrs = dict(p.split('=', 1) for p in server_first.split(','))
        server_nonce, salt_b64, iterations = (attrs['r'], attrs['s'],
                                              int(attrs['i']))
        if not server_nonce.startswith(nonce):
            raise PgError({'M': 'SCRAM server nonce mismatch'})
        salted = hashlib.pbkdf2_hmac('sha256', self.password.encode(),
                                     base64.b64decode(salt_b64),
                                     iterations)
        client_key = hmac.new(salted, b'Client Key',
                              hashlib.sha256).digest()
        stored_key = hashlib.sha256(client_key).digest()
        without_proof = f'c=biws,r={server_nonce}'
        auth_message = (f'{first_bare},{server_first},'
                        f'{without_proof}').encode()
        signature = hmac.new(stored_key, auth_message,
                             hashlib.sha256).digest()
        proof = bytes(a ^ b for a, b in zip(client_key, signature))
        final = (f'{without_proof},p='
                 f'{base64.b64encode(proof).decode()}')
        self._send(b'p', final.encode())
        mtype, body = self._recv_message()
        if mtype == b'E':
            raise PgError(_parse_error(body))
        (code,) = struct.unpack('>I', body[:4])
        assert code == 12, f'expected SASLFinal, got {code}'
        server_key = hmac.new(salted, b'Server Key',
                              hashlib.sha256).digest()
        expected = hmac.new(server_key, auth_message,
                            hashlib.sha256).digest()
        got = dict(p.split('=', 1)
                   for p in body[4:].decode().split(','))
        if base64.b64decode(got.get('v', '')) != expected:
            raise PgError({'M': 'SCRAM server signature mismatch '
                                '(not the server we authenticated?)'})

    # -- queries -------------------------------------------------------

    def execute(self, sql: str,
                params: Sequence[Any] = ()) -> _Result:
        """Parameterless statements ride the simple protocol (BEGIN,
        DDL, advisory locks); anything with parameters rides the
        extended protocol — values travel as bind parameters, never as
        spliced literals."""
        if params:
            self._send_extended(sql, params)
        else:
            self._send(b'Q', sql.encode() + b'\0')
        return self._collect()

    def _send_extended(self, sql: str, params: Sequence[Any]) -> None:
        encoded = [_encode_param(v) for v in params]
        query = to_dollar_params(sql).encode()
        parse = (b'\0' + query + b'\0' +
                 struct.pack('>H', len(encoded)) +
                 b''.join(struct.pack('>I', oid) for oid, _ in encoded))
        bind = bytearray(b'\0\0')             # unnamed portal + stmt
        bind += struct.pack('>H', 0)          # all params text format
        bind += struct.pack('>H', len(encoded))
        for _, value in encoded:
            if value is None:
                bind += struct.pack('>i', -1)
            else:
                bind += struct.pack('>i', len(value)) + value
        bind += struct.pack('>H', 0)          # result columns: text
        self._send(b'P', parse)
        self._send(b'B', bytes(bind))
        self._send(b'D', b'P\0')              # Describe the portal
        self._send(b'E', b'\0' + struct.pack('>I', 0))
        self._send(b'S', b'')

    def _collect(self) -> _Result:
        columns: List[str] = []
        oids: List[int] = []
        rows: List[List[Optional[str]]] = []
        rowcount = -1
        error: Optional[PgError] = None
        while True:
            mtype, body = self._recv_message()
            if mtype == b'T':        # RowDescription
                columns, oids = _parse_row_description(body)
            elif mtype == b'D':      # DataRow
                rows.append(_parse_data_row(body))
            elif mtype == b'C':      # CommandComplete: "UPDATE 3" etc.
                tag = body.rstrip(b'\0').decode('ascii', 'replace')
                parts = tag.split()
                if parts and parts[-1].isdigit():
                    rowcount = int(parts[-1])
            elif mtype == b'E':
                error = PgError(_parse_error(body))
            elif mtype == b'A':      # NotificationResponse (async)
                self.notifications.append(_parse_notification(body))
            elif mtype == b'Z':      # ReadyForQuery: statement done
                if error is not None:
                    raise error
                return _Result(columns, oids, rows, rowcount)
            # 1 (ParseComplete) / 2 (BindComplete) / n (NoData) /
            # s (PortalSuspended) / N (Notice) / I (EmptyQuery): skip

    def executescript(self, script: str) -> None:
        for statement in script.split(';'):
            if statement.strip():
                self.execute(statement)

    def drain_notifications(self) -> int:
        """Consume every async NotificationResponse currently pending
        (already-buffered frames plus whatever the socket holds) WITHOUT
        blocking; returns how many arrived. For dedicated LISTEN
        connections — on a connection with a query mid-flight the
        framing would interleave.

        Two-phase so a PARTIAL frame can never block: first pull all
        readable bytes into the buffer (select-gated recv, plus
        ``pending()`` for TLS sockets whose decrypted bytes don't show
        on the raw fd), then parse only frames the buffer holds in
        full — a split frame waits for the next drain instead of
        stalling this one on the 30s socket timeout."""
        import select
        count = len(self.notifications)
        self.notifications.clear()
        pending = getattr(self._sock, 'pending', None)
        # Short recv timeout: on TLS, select() reports the raw fd
        # readable as soon as the FIRST bytes of a record land, but a
        # blocking SSLSocket.recv waits for the complete record — cap
        # that wait so a split record can't stall every waiter behind
        # the listener lock for the 30s socket timeout.
        previous_timeout = self._sock.gettimeout()
        self._sock.settimeout(0.1)
        try:
            while True:
                if not (pending is not None and pending()):
                    readable, _, _ = select.select([self._sock], [], [],
                                                   0)
                    if not readable:
                        break
                try:
                    chunk = self._sock.recv(65536)
                except (socket.timeout, ssl.SSLWantReadError):
                    break        # partial TLS record: next drain's work
                if not chunk:
                    raise PgError({'M': 'server closed the connection'})
                self._buf += chunk
        finally:
            self._sock.settimeout(previous_timeout)
        while len(self._buf) >= 5:
            (length,) = struct.unpack('>I', self._buf[1:5])
            if len(self._buf) < 1 + length:
                break            # incomplete frame: next drain's work
            mtype, body = self._recv_message()
            if mtype == b'A':
                count += 1
            elif mtype == b'E':
                raise PgError(_parse_error(body))
            # S (ParameterStatus) / N (Notice) / Z: skip — idle-time
            # chatter on a LISTEN-only connection.
        return count

    def commit(self) -> None:
        """Simple-protocol statements autocommit; kept for sqlite-shaped
        call sites."""

    def close(self) -> None:
        try:
            self._send(b'X', b'')
            self._sock.close()
        except OSError:
            pass


def _parse_notification(body: bytes) -> Tuple[str, str]:
    """NotificationResponse: int32 sender pid, cstr channel, cstr
    payload."""
    end = body.index(b'\0', 4)
    channel = body[4:end].decode('utf-8', 'replace')
    payload = body[end + 1:].split(b'\0')[0].decode('utf-8', 'replace')
    return channel, payload


def _parse_error(body: bytes) -> Dict[str, str]:
    fields: Dict[str, str] = {}
    for part in body.split(b'\0'):
        if part:
            fields[chr(part[0])] = part[1:].decode('utf-8', 'replace')
    return fields


def _parse_row_description(body: bytes
                           ) -> Tuple[List[str], List[int]]:
    (count,) = struct.unpack('>H', body[:2])
    names: List[str] = []
    oids: List[int] = []
    offset = 2
    for _ in range(count):
        end = body.index(b'\0', offset)
        names.append(body[offset:end].decode())
        # fixed part: table oid(4) attnum(2) TYPE OID(4) len(2) mod(4)
        # fmt(2) = 18 bytes
        (oid,) = struct.unpack('>I', body[end + 7:end + 11])
        oids.append(oid)
        offset = end + 1 + 18
    return names, oids


def _parse_data_row(body: bytes) -> List[Optional[str]]:
    (count,) = struct.unpack('>H', body[:2])
    values: List[Optional[str]] = []
    offset = 2
    for _ in range(count):
        (length,) = struct.unpack('>i', body[offset:offset + 4])
        offset += 4
        if length < 0:
            values.append(None)
        else:
            values.append(body[offset:offset + length].decode('utf-8',
                                                              'replace'))
            offset += length
    return values


class PgSqliteAdapter:
    """sqlite3-connection-shaped facade over PgConnection, translating
    the state layers' sqlite-isms so one SQL body serves both backends
    (state.py, jobs/state.py)."""

    is_postgres = True

    def __init__(self, conn: 'PgConnection') -> None:
        self._conn = conn
        # Set when the underlying socket is conclusively gone (server
        # restart, idle-timeout drop): connect_dual_backend then evicts
        # this cached connection so the NEXT call reconnects — without
        # it, one transient DB blip wedges the thread until process
        # restart. SQL errors do NOT mark death (the connection
        # resyncs at ReadyForQuery).
        self.dead = False

    @staticmethod
    def _translate(sql: str) -> Optional[str]:
        stripped = sql.strip()
        if stripped.startswith('PRAGMA journal_mode'):
            return None                      # sqlite-only tuning
        if stripped.startswith('PRAGMA table_info'):
            table = stripped.split('(', 1)[1].rstrip(') ')
            return ("SELECT column_name AS name FROM "
                    "information_schema.columns WHERE table_name="
                    f"'{table}'")
        if stripped == 'BEGIN IMMEDIATE':
            return 'BEGIN'
        head = stripped[:6].upper()
        if head in ('CREATE', 'ALTER '):
            sql = sql.replace('INTEGER PRIMARY KEY AUTOINCREMENT',
                              'BIGSERIAL PRIMARY KEY')
            # sqlite REAL is 8-byte; Postgres REAL is float4, which
            # rounds epoch timestamps to ~2-minute granularity. DDL
            # statements only — a ' REAL' inside DML data must survive.
            sql = sql.replace(' REAL', ' DOUBLE PRECISION')
        return sql

    def execute(self, sql: str, params: Sequence[Any] = ()) -> _Result:
        translated = self._translate(sql)
        if translated is None:
            return _Result([], [], [])
        try:
            return self._conn.execute(translated, params)
        except (ConnectionError, OSError) as e:
            self.dead = True
            raise PgError({'M': f'connection lost: {e}'}) from e
        except PgError as e:
            if 'closed the connection' in str(e):
                self.dead = True
            raise

    def executescript(self, script: str) -> None:
        for statement in script.split(';'):
            if statement.strip():
                self.execute(statement)

    def insert_returning(self, sql: str, params: Sequence[Any],
                         id_column: str) -> int:
        """INSERT returning the new row id (sqlite callers use
        cursor.lastrowid, which the wire protocol has no analog for)."""
        try:
            row = self.execute(f'{sql} RETURNING {id_column}',
                               params).fetchone()
            return int(row[id_column])
        except PgError as e:
            if 'returning' not in str(e).lower():
                raise
            # The server under the wire protocol can't parse RETURNING
            # — an sqlite(<3.35)-backed Postgres stand-in (tests/
            # fake_pg.py). The syntax error aborted the whole INSERT,
            # so re-running it plainly is safe, and the stand-in
            # serializes every statement on ONE sqlite connection, so
            # last_insert_rowid() is its insert id. Real Postgres
            # parses RETURNING and never reaches this path.
            self.execute(sql, params)
            row = self.execute(
                'SELECT last_insert_rowid() AS rid').fetchone()
            return int(row['rid'])

    def commit(self) -> None:
        # Outside an explicit BEGIN, simple-protocol statements
        # autocommit and COMMIT is a harmless WARNING (not an error) —
        # so a raised PgError here is a REAL failed commit and must
        # propagate: swallowing it would let a claim 'succeed' that the
        # server rolled back.
        self.execute('COMMIT')

    def rollback(self) -> None:
        self.execute('ROLLBACK')

    def close(self) -> None:
        self._conn.close()


def enable_wal(conn) -> None:
    """Best-effort ``PRAGMA journal_mode=WAL`` for init_schema bodies.
    No-op through the PG adapter (the PRAGMA is translated away);
    on sqlite a concurrent writer makes the mode switch raise
    'database is locked' WITHOUT honoring the busy timeout — and WAL
    is persistent per-file, so a failed re-apply is harmless."""
    import sqlite3
    try:
        conn.execute('PRAGMA journal_mode=WAL')
    except sqlite3.OperationalError:
        pass


def connect_dual_backend(local, ready_set, *, url, sqlite_path,
                         init_schema):
    """Thread-cached connection for the dual-backend state DBs
    (state.py, jobs/state.py — one copy of the subtle logic):

    * per-thread, re-opened after fork (a parent's sqlite handle shared
      across processes corrupts the DB; the executor forks per request);
    * sqlite (default) or ``PgSqliteAdapter`` over the shared server
      when ``url`` is set;
    * ``init_schema(conn)`` (DDL + migrations, idempotent) runs on
      every sqlite connect (local file, ~free) but once per process for
      Postgres (``ready_set`` gates it — replaying DDL per HTTP request
      thread is round-trip waste against a remote DB).
    """
    import sqlite3
    cache_path = f'{url}#{sqlite_path}' if url else sqlite_path
    conn = getattr(local, 'conn', None)
    if (conn is not None and getattr(local, 'path', None) == cache_path
            and getattr(local, 'pid', None) == os.getpid()
            and not getattr(conn, 'dead', False)):
        return conn
    if url:
        conn = PgSqliteAdapter(PgConnection.from_url(url))
        if url not in ready_set:
            # Keyed by url alone: a forked request child INHERITS the
            # parent's ready set (the schema it ensured is just as
            # ensured), and replaying ~6 DDL round trips against the
            # remote DB on every forked request is pure hot-path waste.
            # Fresh processes start with an empty set and re-ensure.
            init_schema(conn)
            ready_set.add(url)
    else:
        os.makedirs(os.path.dirname(sqlite_path), exist_ok=True)
        conn = sqlite3.connect(sqlite_path, timeout=10)
        conn.row_factory = sqlite3.Row
        try:
            conn.execute('PRAGMA journal_mode=WAL')
        except sqlite3.OperationalError:
            # Switching journal modes needs a quiescent DB and does NOT
            # honor the busy timeout — a concurrent writer (another
            # thread's executor/daemon tick) makes this raise 'database
            # is locked' spuriously. WAL is persistent per-file: the
            # connection that created the file already set it, so a
            # failed re-apply is harmless.
            pass
        init_schema(conn)
        conn.commit()
    local.conn = conn
    local.path = cache_path
    local.pid = os.getpid()
    return conn
