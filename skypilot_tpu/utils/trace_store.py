"""Durable per-trace span store + the critical-path computation.

Spans are appended as flock'd JSONL, one file per trace, next to the
request logs (``<server_dir>/traces/<trace_id>.jsonl``) — the same
durability story as the request log itself: any process that shares the
state dir (server threads, executor runners, forked request children,
serve/service processes) appends; ``GET /api/trace/<request_id>``
re-reads and assembles.

The read side turns the flat span list into the artifact that matters
(Mystery Machine's lesson: the *critical path*, not the spans): a
synthetic root covering the trace's full extent, a parent/child tree,
and the longest blocking chain with per-hop self-time — walked over
*subtree* extents so asynchronous children (a request child finishing
long after the submit span that spawned it) stay on the path.
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

from skypilot_tpu.utils import env_registry

_EPS_MS = 0.001


def traces_dir() -> str:
    override = env_registry.get_str('SKYT_TRACE_DIR')
    if override:
        return os.path.expanduser(override)
    from skypilot_tpu.server import requests_db
    return os.path.join(requests_db.server_dir(), 'traces')


def _valid_trace_id(trace_id: str) -> bool:
    return (len(trace_id) == 32 and
            all(c in '0123456789abcdef' for c in trace_id))


def trace_path(trace_id: str) -> str:
    if not _valid_trace_id(trace_id):
        raise ValueError(f'malformed trace id {trace_id!r}')
    return os.path.join(traces_dir(), f'{trace_id}.jsonl')


def append_spans(trace_id: str, spans: List[dict]) -> str:
    """flock'd JSONL append — concurrent writers (runner + child +
    server threads) interleave whole lines, never torn ones."""
    import fcntl
    path = trace_path(trace_id)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    payload = ''.join(json.dumps(s) + '\n' for s in spans)
    with open(path, 'a', encoding='utf-8') as f:
        fcntl.flock(f, fcntl.LOCK_EX)
        f.write(payload)
        f.flush()
    return path


def load_trace(trace_id: str) -> List[dict]:
    """All spans of a trace, deduplicated by span_id (last write wins —
    a re-flushed buffer must not double spans)."""
    path = trace_path(trace_id)
    if not os.path.exists(path):
        return []
    by_id: Dict[str, dict] = {}
    with open(path, encoding='utf-8') as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                span = json.loads(line)
            except json.JSONDecodeError:
                continue  # torn tail line from a crashed writer
            if isinstance(span, dict) and span.get('span_id'):
                by_id[span['span_id']] = span
    return sorted(by_id.values(), key=lambda s: s.get('start', 0.0))


def list_traces(limit: int = 100) -> List[str]:
    d = traces_dir()
    if not os.path.isdir(d):
        return []
    names = [f[:-6] for f in os.listdir(d) if f.endswith('.jsonl')]
    names.sort(key=lambda n: os.path.getmtime(
        os.path.join(d, n + '.jsonl')), reverse=True)
    return names[:limit]


# -- tree + critical path ----------------------------------------------


def _end(span: dict) -> float:
    return span.get('start', 0.0) + span.get('dur_ms', 0.0) / 1000.0


def build_view(spans: List[dict]) -> Dict[str, Any]:
    """Assemble the /api/trace payload: the span list (with relative
    times), the parent/child tree, and the critical path."""
    if not spans:
        return {'spans': [], 'critical_path': [], 'total_ms': 0.0}
    t0 = min(s['start'] for s in spans)
    t_end = max(_end(s) for s in spans)
    # Observer spans (annotations.observer, e.g. the /api/get long-poll)
    # passively WAIT on the work; left in, the poll span would absorb
    # the whole wait as its own self-time and hide the executor chain
    # underneath. They stay in the span list but not in the path walk.
    path_spans = [s for s in spans
                  if not (s.get('annotations') or {}).get('observer')]
    by_id = {s['span_id']: s for s in path_spans}
    children: Dict[Optional[str], List[dict]] = {}
    for s in path_spans:
        parent = s.get('parent_span_id')
        if parent not in by_id:
            parent = None  # stored-orphan -> root
        children.setdefault(parent, []).append(s)

    # Subtree extent: an async child (executor work outliving the
    # submit span) extends its ancestors' effective window.
    eff_end: Dict[str, float] = {}

    def _eff(span: dict) -> float:
        sid = span['span_id']
        if sid not in eff_end:
            eff_end[sid] = max([_end(span)] + [
                _eff(c) for c in children.get(sid, [])])
        return eff_end[sid]

    roots = children.get(None, [])
    for root in roots:
        _eff(root)

    critical = []
    if roots:
        critical = _critical_path(
            roots, children, _eff,
            min(s['start'] for s in roots),
            max(_eff(r) for r in roots))
    out_spans = []
    for s in spans:
        entry = dict(s)
        entry['start_ms'] = round((s['start'] - t0) * 1000.0, 3)
        out_spans.append(entry)
    crit_ids = {c['span_id'] for c in critical if c['span_id']}
    return {
        'trace_id': spans[0].get('trace_id'),
        'span_count': len(spans),
        'total_ms': round((t_end - t0) * 1000.0, 3),
        'services': sorted({s.get('service', '?') for s in spans}),
        'processes': sorted({s.get('pid', 0) for s in spans}),
        'spans': out_spans,
        'critical_path': [
            {**c, 'start_ms': round((c['start'] - t0) * 1000.0, 3)}
            for c in critical],
        'critical_span_ids': sorted(crit_ids),
    }


def _critical_path(roots: List[dict],
                   children: Dict[Optional[str], List[dict]],
                   eff, window_start: float,
                   window_end: float) -> List[dict]:
    """Last-finishing-child walk (Mystery Machine shape): from the end
    of the window, repeatedly descend into the child whose subtree
    finished last before the cursor; the gaps between children are the
    parent's self-time on the path. Returns chronological segments
    ``{span_id, name, service, start, self_ms}``."""

    def walk(span: Optional[dict], kids: List[dict], start: float,
             cursor: float, depth: int) -> List[dict]:
        if depth > 200:  # defensive: cyclic/corrupt parent links
            return []
        segments: List[dict] = []
        for child in sorted(kids, key=eff, reverse=True):
            child_end = min(eff(child), cursor)
            if child_end <= start + _EPS_MS / 1000.0:
                continue
            if eff(child) > cursor + _EPS_MS / 1000.0:
                # Child extends past the cursor (overlaps a later
                # sibling already on the path): not the blocker here.
                continue
            gap_ms = (cursor - child_end) * 1000.0
            if span is not None and gap_ms > _EPS_MS:
                segments.append(_segment(span, child_end, gap_ms))
            segments.extend(
                walk(child, children.get(child['span_id'], []),
                     child['start'], child_end, depth + 1))
            cursor = min(cursor, child['start'])
        if span is not None and (cursor - start) * 1000.0 > _EPS_MS:
            segments.append(_segment(span, start, (cursor - start) * 1000.0))
        segments.sort(key=lambda seg: seg['start'])
        return segments

    return walk(None, roots, window_start, window_end, 0)


def _segment(span: dict, start: float, self_ms: float) -> dict:
    return {
        'span_id': span['span_id'],
        'name': span.get('name', '?'),
        'service': span.get('service', '?'),
        'start': start,
        'self_ms': round(self_ms, 3),
    }
