"""Subprocess helpers: run with streaming/capture, parallel map, kill trees.

Parity: ``sky/utils/subprocess_utils.py`` + the log-streaming bits of
``sky/skylet/log_lib.py``.
"""
from __future__ import annotations

import concurrent.futures
import os
import shlex
import signal
import subprocess
import sys
from typing import Callable, IO, Iterable, List, Optional, Tuple, TypeVar

import psutil

T = TypeVar('T')
R = TypeVar('R')


def run_command(cmd,
                *,
                shell: bool = False,
                cwd: Optional[str] = None,
                env: Optional[dict] = None,
                stream_to: Optional[IO[str]] = None,
                log_path: Optional[str] = None,
                timeout: Optional[float] = None) -> Tuple[int, str, str]:
    """Run a command; capture stdout/stderr; optionally tee stdout+stderr.

    Returns (returncode, stdout, stderr). When `stream_to`/`log_path` is
    given, stdout and stderr are merged and teed line-by-line.
    """
    if isinstance(cmd, str) and not shell:
        cmd = shlex.split(cmd)
    full_env = None
    if env is not None:
        full_env = {**os.environ, **env}
    if stream_to is None and log_path is None:
        proc = subprocess.run(cmd,
                              shell=shell,
                              cwd=cwd,
                              env=full_env,
                              capture_output=True,
                              text=True,
                              timeout=timeout,
                              check=False)
        return proc.returncode, proc.stdout, proc.stderr
    # Tee mode: merge stderr into stdout for ordered logs.
    log_file = open(log_path, 'a', encoding='utf-8') if log_path else None
    lines: List[str] = []
    try:
        proc = subprocess.Popen(cmd,
                                shell=shell,
                                cwd=cwd,
                                env=full_env,
                                stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT,
                                text=True,
                                start_new_session=True)
        assert proc.stdout is not None
        for line in proc.stdout:
            lines.append(line)
            if stream_to is not None:
                stream_to.write(line)
                stream_to.flush()
            if log_file is not None:
                log_file.write(line)
                log_file.flush()
        returncode = proc.wait(timeout=timeout)
    finally:
        if log_file is not None:
            log_file.close()
    return returncode, ''.join(lines), ''


def python_s_bootstrap(entry: str) -> List[str]:
    """argv prefix for a `python -S` child that can import skypilot_tpu.

    -S skips site startup — and with it the image's sitecustomize that
    force-imports jax (~4s + an accelerator handle no control-plane
    process wants) — so the child re-adds site-packages and the repo
    root itself, then runs ``entry`` (a python statement; argv is
    available as sys.argv[1:]).
    """
    import sysconfig
    site_dir = sysconfig.get_paths()['purelib']
    repo_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    bootstrap = (
        'import site, sys; '
        f'site.addsitedir({site_dir!r}); '
        f'sys.path.insert(0, {repo_root!r}); '
        f'{entry}')
    return [sys.executable, '-S', '-c', bootstrap]


def spawn_orphan_reaper(parent_pid: int, proc_pid: int) -> None:
    """Detached watchdog: when parent_pid dies, kill proc_pid's tree
    (parity: sky/skylet/subprocess_daemon.py). Fire-and-forget; the
    reaper exits on its own when the target finishes first."""
    cmd = python_s_bootstrap(
        'from skypilot_tpu.utils.subprocess_daemon import main; '
        'sys.exit(main(sys.argv[1:]))')
    subprocess.Popen(
        cmd + ['--parent-pid', str(parent_pid),
               '--proc-pid', str(proc_pid)],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        stdin=subprocess.DEVNULL, start_new_session=True)


def run_in_parallel(fn: Callable[[T], R],
                    args: Iterable[T],
                    max_workers: Optional[int] = None) -> List[R]:
    """Ordered parallel map over a thread pool (SSH fan-out to pod hosts)."""
    args = list(args)
    if not args:
        return []
    if len(args) == 1:
        return [fn(args[0])]
    max_workers = max_workers or min(32, len(args))
    with concurrent.futures.ThreadPoolExecutor(max_workers=max_workers) as ex:
        return list(ex.map(fn, args))


def kill_process_tree(pid: int, sig: int = signal.SIGTERM) -> None:
    """Signal a process and all of its descendants (gang teardown: a TPU

    program hangs rather than crashes on lost peers, so the whole rank tree
    must be killed -- see SURVEY.md section 7 'hard parts')."""
    try:
        root = psutil.Process(pid)
    except psutil.NoSuchProcess:
        return
    procs = [root]
    try:
        procs.extend(root.children(recursive=True))
    except psutil.NoSuchProcess:
        pass
    for proc in procs:
        try:
            proc.send_signal(sig)
        except (psutil.NoSuchProcess, ProcessLookupError):
            pass


def daemonize_and_run(cmd: List[str],
                      log_path: str,
                      env: Optional[dict] = None,
                      cwd: Optional[str] = None) -> int:
    """Start a fully detached background process; returns its pid."""
    full_env = {**os.environ, **(env or {})}
    os.makedirs(os.path.dirname(os.path.abspath(log_path)), exist_ok=True)
    with open(log_path, 'a', encoding='utf-8') as log_file:
        proc = subprocess.Popen(cmd,
                                stdout=log_file,
                                stderr=subprocess.STDOUT,
                                stdin=subprocess.DEVNULL,
                                env=full_env,
                                cwd=cwd,
                                start_new_session=True)
    return proc.pid
