"""SKYT004 — chaos coverage cross-check: fault-injection sites in code
vs the sites the chaos suites and docs actually target.

Two failure modes, both historically silent:

* a test (or doc example) targets a site string that no
  ``fault_injection.inject(...)`` call implements — the chaos test is
  vacuously green (the PR-2 design made malformed *specs* raise, but a
  well-formed spec naming a nonexistent site injects nothing);
* an instrumented site exists in code but nothing references it — the
  failure path has no chaos coverage and the operator docs don't know
  the site exists.

Site collection from code: literal ``inject('site')`` args; f-string
args (``inject(f'events.publish.{name}')``) become prefix patterns
(``events.publish.*``); variable args are resolved through
module-level string constants that look like sites (the transfer
engine's ``PUT_SITE = 'data.put_object'`` idiom).

Reference collection: spec-clause strings (``site:Exception[...]``) in
test sources and docs, the first argument of the ``clause(...)`` test
helper, plus any bare string/backtick token exactly equal to a known
code site.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Tuple

from skypilot_tpu.lint import astutil
from skypilot_tpu.lint.core import Context, Finding

CODE = 'SKYT004'

SITE_RE = re.compile(r'^[a-z_][a-z0-9_]*(\.[a-z0-9_]+)+$')
CLAUSE_RE = re.compile(
    r'([a-z_][a-z0-9_]*(?:\.[a-z0-9_*]+)+|[a-z0-9_.]*\*)'
    r':(?:OperationalError|PgError|OSError|ConnectionError|'
    r'TimeoutError|Exception)\b')
BACKTICK_RE = re.compile(r'`([a-z_][a-z0-9_]*(?:\.[a-z0-9_*]+)+)`')


class ChaosCoverageChecker:
    code = CODE
    name = 'SKYT_FAULT_SPEC site coverage'

    def run(self, ctx: Context) -> Iterator[Finding]:
        # site/pattern -> first (rel, line) where inject() implements it
        sites: Dict[str, Tuple[str, int]] = {}
        for mod in ctx.package_modules:
            for site, line in self._code_sites(mod):
                sites.setdefault(site, (mod.rel, line))

        def implemented(ref: str) -> bool:
            if ref in sites:
                return True
            if ref.endswith('*'):
                prefix = ref[:-1]
                return any(s.startswith(prefix) or
                           (s.endswith('*') and s[:-1].startswith(prefix))
                           for s in sites)
            return any(s.endswith('*') and ref.startswith(s[:-1])
                       for s in sites)

        covered: set = set()

        def cover(ref: str) -> None:
            for site in sites:
                if site == ref:
                    covered.add(site)
                elif site.endswith('*') and ref.startswith(site[:-1]):
                    covered.add(site)
                elif ref.endswith('*') and site.startswith(ref[:-1]):
                    covered.add(site)

        # Validated references: spec clauses + clause() helper args.
        for rel, refs in self._references(ctx):
            for ref, line, validated in refs:
                if validated and not implemented(ref):
                    yield Finding(
                        CODE, rel, line,
                        f'chaos reference targets nonexistent fault '
                        f'site {ref!r} (no fault_injection.inject() '
                        'implements it — the test injects nothing)',
                        slug=f'nonexistent:{ref}')
                cover(ref)

        for site in sorted(sites):
            if site not in covered:
                rel, line = sites[site]
                yield Finding(
                    CODE, rel, line,
                    f'fault site {site!r} has no chaos test or doc '
                    'reference (dead site: its failure path is never '
                    'exercised)', slug=f'dead:{site}')

    # -- collection -----------------------------------------------------

    def _code_sites(self, mod) -> Iterator[Tuple[str, int]]:
        module_strings = None
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, (ast.Attribute, ast.Name))
                    and (astutil.dotted(node.func) or ''
                         ).split('.')[-1] == 'inject'
                    and node.args):
                continue
            arg = node.args[0]
            literal = astutil.const_str(arg)
            if literal is not None:
                if SITE_RE.match(literal):
                    yield literal, node.lineno
                continue
            head = astutil.fstring_head(arg)
            if head is not None:
                if head.endswith('.'):
                    yield head + '*', node.lineno
                continue
            # Variable arg: fall back to module-level site constants.
            if module_strings is None:
                module_strings = self._module_site_constants(mod)
            for site, line in module_strings:
                yield site, line

    @staticmethod
    def _module_site_constants(mod) -> List[Tuple[str, int]]:
        out = []
        for node in mod.tree.body:
            if (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Constant)
                    and isinstance(node.value.value, str)
                    and SITE_RE.match(node.value.value)):
                out.append((node.value.value, node.lineno))
        return out

    def _references(self, ctx: Context):
        """Per source: [(ref, line, validated)] — validated refs must
        resolve to an implemented site; unvalidated ones (bare exact
        matches) only count as coverage."""
        for mod in ctx.test_modules:
            refs: List[Tuple[str, int, bool]] = []
            for node in ast.walk(mod.tree):
                if (isinstance(node, ast.Call)
                        and (astutil.dotted(node.func) or ''
                             ).split('.')[-1] == 'clause'
                        and node.args):
                    literal = astutil.const_str(node.args[0])
                    if literal is not None:
                        refs.append((literal, node.lineno, True))
            for text, line in astutil.walk_strings(mod.tree):
                for match in CLAUSE_RE.finditer(text):
                    refs.append((match.group(1), line, True))
                if SITE_RE.match(text) or (
                        text.endswith('*')
                        and SITE_RE.match(text[:-1] + 'x')):
                    refs.append((text, line, False))
            yield mod.rel, refs
        for rel, text in ctx.doc_texts.items():
            refs = []
            for match in CLAUSE_RE.finditer(text):
                refs.append((match.group(1), 0, True))
            for match in BACKTICK_RE.finditer(text):
                refs.append((match.group(1), 0, False))
            yield rel, refs
