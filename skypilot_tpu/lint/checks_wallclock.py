"""SKYT009 — wall-clock ``time.time()`` flowing into duration math.

The wall clock steps (NTP slew, suspend/resume, manual set); durations,
deadlines, cooldowns and rate windows measured with it silently stretch
or go negative. This exact bug was fixed by hand twice before this pass
existed (PR 4: the LB QPS ring; PR 9: the spot-placer cooldown and the
autoscaler hysteresis timer) while 131 other ``time.time()`` sites went
unreviewed. The pass automates the review with a taint analysis over
the shared CFG/reaching-definitions layer:

* a value is **wall-tainted** when every definition that reaches its
  use is derived from ``time.time()`` (possibly through ``+``/``-``
  with a plain number, ``int()``/``float()``, ``min``/``max`` of
  all-tainted args, or a module/class attribute or dict that is only
  ever assigned wall readings);
* a finding is a ``-`` or an ordering comparison where BOTH operands
  are wall-tainted — i.e. an elapsed-time or deadline computation done
  entirely on the local wall clock.

Requiring both sides tainted is what makes persisted/displayed
timestamps pass untouched: ``created_at=time.time()`` is never
arithmetic; ``time.time() - stale_after`` (a wall cutoff compared to
DB-persisted heartbeats) has an untainted operand; a DB row's
timestamp compared against ``time.time()`` is untainted on one side.
Every finding is a duration measured wall-to-wall in one process —
precisely the class where ``time.monotonic()`` is the fix.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from skypilot_tpu.lint import astutil, dataflow
from skypilot_tpu.lint.core import Context, Finding

CODE = 'SKYT009'

WALL_CALLS = frozenset({'time.time'})
# Positional/keyword wrappers through which taint flows unchanged.
_CAST_FNS = frozenset({'int', 'float', 'abs', 'round'})
_ALLTAINT_FNS = frozenset({'min', 'max'})
_ORDER_OPS = (ast.Lt, ast.LtE, ast.Gt, ast.GtE)

TAINTED, NEUTRAL, CLEAN = 'T', 'N', 'C'


def _is_neutral_const(expr: ast.AST) -> bool:
    """Numeric literals / None are sentinels (``last = 0.0``), not
    evidence about the clock a name is measured with."""
    if isinstance(expr, ast.Constant):
        return expr.value is None or isinstance(expr.value, (int, float))
    if isinstance(expr, ast.UnaryOp) and isinstance(expr.operand,
                                                    ast.Constant):
        return isinstance(expr.operand.value, (int, float))
    if isinstance(expr, (ast.Dict, ast.List, ast.Set)):
        return not expr.keys if isinstance(expr, ast.Dict) \
            else not expr.elts
    return False


class _FnInfo:
    """CFG + reaching defs + per-def taint states for one function."""

    def __init__(self, class_name: Optional[str],
                 fn: ast.AST) -> None:
        self.class_name = class_name
        self.fn = fn
        self.cfg = dataflow.CFG(fn)
        self.rd = dataflow.ReachingDefs(self.cfg)
        self.def_state: Dict[int, str] = {}
        self.globals_declared: Set[str] = {
            name for node in ast.walk(fn)
            if isinstance(node, ast.Global) for name in node.names}


class WallClockChecker:
    code = CODE
    name = 'wall clock in duration arithmetic'

    def run(self, ctx: Context) -> Iterator[Finding]:
        for mod in ctx.package_modules:
            yield from self._check_module(mod)

    # ------------------------------------------------------------------

    def _check_module(self, mod) -> Iterator[Finding]:
        imports = astutil.import_map(mod.tree)
        fns = [_FnInfo(cls, fn)
               for cls, fn in dataflow.functions_of(mod.tree)]
        module_names = {
            t.id for s in mod.tree.body
            if isinstance(s, (ast.Assign, ast.AnnAssign))
            for t in (s.targets if isinstance(s, ast.Assign)
                      else [s.target])
            if isinstance(t, ast.Name)}

        # Module/class locations only ever assigned wall readings.
        # Iterated: a location tainted via a name that is tainted via
        # another location needs a second round to settle.
        locations: Dict[Tuple, bool] = {}
        for _ in range(3):
            for info in fns:
                self._solve_fn(info, imports, locations)
            new_locations = self._collect_locations(
                mod, fns, imports, locations, module_names)
            if new_locations == locations:
                break
            locations = new_locations

        for info in fns:
            yield from self._find(mod, info, imports, locations)

    # -- location (module/class attr) taint -----------------------------

    def _collect_locations(self, mod, fns, imports, locations,
                           module_names) -> Dict[Tuple, bool]:
        votes: Dict[Tuple, List[str]] = {}

        def vote(key, state):
            votes.setdefault(key, []).append(state)

        # Module top level.
        for stmt in mod.tree.body:
            targets = []
            if isinstance(stmt, ast.Assign):
                targets = stmt.targets
                value = stmt.value
            elif isinstance(stmt, ast.AnnAssign) and stmt.value:
                targets = [stmt.target]
                value = stmt.value
            else:
                continue
            state = self._module_expr_state(value, imports, locations)
            for target in targets:
                if isinstance(target, ast.Name):
                    vote(('g', target.id), state)

        # Inside functions.
        for info in fns:
            for node in dataflow.statement_nodes(info.cfg):
                stmt = node.stmt
                if isinstance(stmt, ast.Assign):
                    value, targets = stmt.value, stmt.targets
                elif isinstance(stmt, ast.AnnAssign) and stmt.value:
                    value, targets = stmt.value, [stmt.target]
                else:
                    continue
                state = self._expr_state(value, info, node, imports,
                                         locations)
                for target in targets:
                    key = self._location_key(target, info, module_names)
                    if key is not None:
                        vote(key, state)

        out: Dict[Tuple, bool] = {}
        for key, states in votes.items():
            out[key] = (TAINTED in states) and (CLEAN not in states)
        return out

    def _location_key(self, target, info, module_names
                      ) -> Optional[Tuple]:
        if isinstance(target, ast.Name):
            if target.id in info.globals_declared:
                return ('g', target.id)
            return None
        if isinstance(target, ast.Attribute):
            name = astutil.dotted(target)
            if (name and name.startswith('self.')
                    and info.class_name and name.count('.') == 1):
                return ('c', info.class_name, target.attr)
            return None
        if isinstance(target, ast.Subscript):
            base = target.value
            if (isinstance(base, ast.Name)
                    and base.id in module_names
                    and base.id not in info.rd.local_names):
                return ('gd', base.id)
            base_name = astutil.dotted(base)
            if (base_name and base_name.startswith('self.')
                    and info.class_name and base_name.count('.') == 1):
                return ('cd', info.class_name, base.attr)
        return None

    def _module_expr_state(self, expr, imports, locations) -> str:
        """Taint state of a module-top-level expression (no locals)."""
        if _is_neutral_const(expr):
            return NEUTRAL
        dummy = _ModuleScope(imports, locations)
        return TAINTED if dummy.tainted(expr) else CLEAN

    # -- per-function def-state fixpoint --------------------------------

    def _solve_fn(self, info: _FnInfo, imports, locations) -> None:
        info.def_state = {id(d): CLEAN for d in info.rd.defs}
        for _ in range(len(info.rd.defs) + 2):
            changed = False
            for d in info.rd.defs:
                state = self._def_state(d, info, imports, locations)
                if state != info.def_state[id(d)]:
                    info.def_state[id(d)] = state
                    changed = True
            if not changed:
                break

    def _def_state(self, d, info, imports, locations) -> str:
        if d.value is dataflow.UNKNOWN:
            return CLEAN
        if isinstance(d.value, ast.AugAssign):
            stmt = d.value
            old = self._name_tainted(d.name, info, d.node, imports,
                                     locations, exclude=d)
            operand = self._expr_state(stmt.value, info, d.node,
                                       imports, locations)
            return TAINTED if (old or operand == TAINTED) else CLEAN
        return self._expr_state(d.value, info, d.node, imports,
                                locations)

    def _expr_state(self, expr, info, node, imports, locations) -> str:
        if _is_neutral_const(expr):
            return NEUTRAL
        return TAINTED if self._tainted(expr, info, node, imports,
                                        locations) else CLEAN

    # -- expression taint -----------------------------------------------

    def _name_tainted(self, name, info, node, imports, locations,
                      exclude=None) -> bool:
        defs = info.rd.at(node).get(name) if node is not None else None
        if name in info.rd.local_names:
            if not defs:
                return False
            states = [info.def_state.get(id(d), CLEAN)
                      for d in defs if d is not exclude]
            if not states:
                return False
            return TAINTED in states and CLEAN not in states
        return bool(locations.get(('g', name)))

    def _tainted(self, expr, info, node, imports, locations) -> bool:
        taint = lambda e: self._tainted(e, info, node, imports,  # noqa: E731
                                        locations)
        if isinstance(expr, ast.Call):
            resolved = astutil.resolve_call(expr.func, imports)
            if resolved in WALL_CALLS:
                return True
            if resolved in _CAST_FNS and expr.args:
                return taint(expr.args[0])
            if resolved in _ALLTAINT_FNS and expr.args:
                return all(taint(a) for a in expr.args)
            if (isinstance(expr.func, ast.Attribute)
                    and expr.func.attr in ('get', 'pop', 'setdefault')):
                if self._container_tainted(expr.func.value, info,
                                           locations):
                    return True
                if (expr.func.attr == 'setdefault'
                        and len(expr.args) >= 2):
                    return taint(expr.args[1])
            return False
        if isinstance(expr, ast.Name):
            return self._name_tainted(expr.id, info, node, imports,
                                      locations)
        if isinstance(expr, ast.Attribute):
            name = astutil.dotted(expr)
            if (name and name.startswith('self.')
                    and info.class_name and name.count('.') == 1):
                return bool(locations.get(
                    ('c', info.class_name, expr.attr)))
            return False
        if isinstance(expr, ast.Subscript):
            return self._container_tainted(expr.value, info, locations)
        if isinstance(expr, ast.BinOp) and isinstance(
                expr.op, (ast.Add, ast.Sub)):
            return taint(expr.left) or taint(expr.right)
        if isinstance(expr, ast.IfExp):
            return taint(expr.body) or taint(expr.orelse)
        if isinstance(expr, ast.BoolOp):
            return any(taint(v) for v in expr.values)
        if isinstance(expr, ast.NamedExpr):
            return taint(expr.value)
        return False

    def _container_tainted(self, base, info, locations) -> bool:
        if isinstance(base, ast.Name):
            if base.id in info.rd.local_names:
                return False
            return bool(locations.get(('gd', base.id)))
        name = astutil.dotted(base)
        if (name and name.startswith('self.') and info.class_name
                and name.count('.') == 1):
            return bool(locations.get(
                ('cd', info.class_name, base.attr)))
        return False

    # -- findings -------------------------------------------------------

    def _find(self, mod, info, imports, locations) -> Iterator[Finding]:
        fn_name = info.fn.name
        for node in dataflow.statement_nodes(info.cfg):
            for expr in dataflow.owned_exprs(node.stmt):
                for sub in ast.walk(expr):
                    hit = self._site(sub, info, node, imports,
                                     locations)
                    if hit is None:
                        continue
                    what, render = hit
                    yield Finding(
                        CODE, mod.rel, sub.lineno,
                        f'wall-clock {what} `{render}` — measure '
                        'durations/deadlines with time.monotonic() '
                        '(persisted or displayed timestamps stay on '
                        'time.time())',
                        slug=f'wall:{fn_name}:{render}')

    def _site(self, sub, info, node, imports, locations):
        if (isinstance(sub, ast.BinOp) and isinstance(sub.op, ast.Sub)
                and self._tainted(sub.left, info, node, imports,
                                  locations)
                and self._tainted(sub.right, info, node, imports,
                                  locations)):
            return 'elapsed/interval arithmetic', _render(sub)
        if isinstance(sub, ast.Compare):
            operands = [sub.left] + list(sub.comparators)
            for i, op in enumerate(sub.ops):
                if not isinstance(op, _ORDER_OPS):
                    continue
                left, right = operands[i], operands[i + 1]
                # Skip when either side already reports as a tainted
                # subtraction (one finding per root cause).
                if _has_tainted_sub(left, self, info, node, imports,
                                    locations) or _has_tainted_sub(
                                        right, self, info, node,
                                        imports, locations):
                    continue
                if (self._tainted(left, info, node, imports, locations)
                        and self._tainted(right, info, node, imports,
                                          locations)):
                    return 'deadline comparison', _render(sub)
        return None


def _has_tainted_sub(expr, checker, info, node, imports,
                     locations) -> bool:
    for sub in ast.walk(expr):
        if (isinstance(sub, ast.BinOp) and isinstance(sub.op, ast.Sub)
                and checker._tainted(sub.left, info, node, imports,
                                     locations)
                and checker._tainted(sub.right, info, node, imports,
                                     locations)):
            return True
    return False


def _render(expr) -> str:
    try:
        text = ast.unparse(expr)
    except Exception:  # pylint: disable=broad-except
        text = '<expr>'
    return ' '.join(text.split())[:80]


class _ModuleScope:
    """Minimal taint evaluator for module-top-level expressions."""

    def __init__(self, imports, locations) -> None:
        self.imports = imports
        self.locations = locations

    def tainted(self, expr) -> bool:
        if isinstance(expr, ast.Call):
            resolved = astutil.resolve_call(expr.func, self.imports)
            if resolved in WALL_CALLS:
                return True
            if resolved in _CAST_FNS and expr.args:
                return self.tainted(expr.args[0])
            return False
        if isinstance(expr, ast.Name):
            return bool(self.locations.get(('g', expr.id)))
        if isinstance(expr, ast.BinOp) and isinstance(
                expr.op, (ast.Add, ast.Sub)):
            return self.tainted(expr.left) or self.tainted(expr.right)
        return False
