"""SKYT005 — event-bus topic cross-check.

Topics are declared once in ``utils/events.py`` (module-level
UPPER_CASE string constants). Writers ``events.publish(topic)`` after
commit; consumers ``events.wait_for(topic, ...)`` / ``events.cursor``
/ ``events.external_signal(..., topic)``. The bus carries no payloads,
so a topic mismatch never errors — it just degrades that loop to its
fallback poll forever. This pass flags:

* publish/wait of a topic that is not declared in utils/events.py
  (string-literal topics included: a typo'd literal silently makes a
  private topic nobody else sees);
* a declared topic that is published but never referenced anywhere
  else (publish-without-subscriber — every write pays notify cost for
  a wakeup nobody gets);
* a topic waited on but never published (wait-on-never-published —
  that consumer lives on its fallback interval and the event layer is
  dead weight).

Consumer references are counted structurally (wait_for/cursor/
external_signal args) AND as any other ``events.TOPIC`` attribute use
(daemon constructors take ``topic=events.MANAGED_JOBS``), so indirect
subscriptions register.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, Set, Tuple

from skypilot_tpu.lint import astutil
from skypilot_tpu.lint.core import Context, Finding

CODE = 'SKYT005'

EVENTS_MODULE = 'utils/events.py'
PUBLISH_FNS = frozenset({'publish'})
WAIT_FNS = frozenset({'wait_for', 'cursor', 'external_cursor'})


def declared_topics(events_mod) -> Dict[str, str]:
    """CONST name -> topic string, from utils/events.py."""
    out: Dict[str, str] = {}
    for node in events_mod.tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id.isupper()
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)):
            name = node.targets[0].id
            # Skip non-topic string constants (env-var names etc.).
            if name.endswith('_ENV') or name == 'SOURCES':
                continue
            out[name] = node.value.value
    return out


class EventTopicChecker:
    code = CODE
    name = 'event-bus topic cross-check'

    def run(self, ctx: Context) -> Iterator[Finding]:
        events_mod = ctx.module(EVENTS_MODULE)
        if events_mod is None:
            return
        consts = declared_topics(events_mod)
        topics = set(consts.values())

        published: Dict[str, Tuple[str, int]] = {}
        waited: Dict[str, Tuple[str, int]] = {}
        referenced: Set[str] = set()

        for mod in ctx.package_modules:
            if mod is events_mod:
                continue
            imports = astutil.import_map(mod.tree)
            publish_args: Set[int] = set()
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call):
                    continue
                target = astutil.resolve_call(node.func, imports) or ''
                leaf = target.split('.')[-1]
                if not target.startswith('skypilot_tpu.utils.events.'):
                    continue
                if leaf in PUBLISH_FNS and node.args:
                    topic, ok = self._topic_of(node.args[0], consts)
                    publish_args.add(id(node.args[0]))
                    if topic is not None:
                        published.setdefault(topic,
                                             (mod.rel, node.lineno))
                        if not ok:
                            yield Finding(
                                CODE, mod.rel, node.lineno,
                                f'publish of undeclared topic '
                                f'{topic!r} — declare it as a constant '
                                'in utils/events.py',
                                slug=f'undeclared:{topic}')
                elif leaf in WAIT_FNS and node.args:
                    topic, ok = self._topic_of(node.args[0], consts)
                    publish_args.add(id(node.args[0]))
                    if topic is not None:
                        waited.setdefault(topic, (mod.rel, node.lineno))
                        referenced.add(topic)
                        if not ok:
                            yield Finding(
                                CODE, mod.rel, node.lineno,
                                f'wait on undeclared topic {topic!r} — '
                                'declare it as a constant in '
                                'utils/events.py',
                                slug=f'undeclared:{topic}')
                elif leaf == 'external_signal' and len(node.args) >= 3:
                    topic, _ = self._topic_of(node.args[2], consts)
                    publish_args.add(id(node.args[2]))
                    if topic is not None:
                        referenced.add(topic)
            # Any other events.TOPIC mention counts as a consumer-side
            # reference (constructor args, stored topics).
            for node in ast.walk(mod.tree):
                if (isinstance(node, ast.Attribute)
                        and id(node) not in publish_args
                        and (astutil.dotted(node.value) or ''
                             ).split('.')[-1] == 'events'
                        and node.attr in consts):
                    referenced.add(consts[node.attr])

        for topic in sorted(published):
            if topic in topics and topic not in referenced:
                rel, line = published[topic]
                yield Finding(
                    CODE, rel, line,
                    f'topic {topic!r} is published but nothing '
                    'subscribes (publish-without-subscriber: every '
                    'write pays notify cost for no wakeup)',
                    slug=f'nosub:{topic}')
        for topic in sorted(waited):
            if topic in topics and topic not in published:
                rel, line = waited[topic]
                yield Finding(
                    CODE, rel, line,
                    f'topic {topic!r} is waited on but never '
                    'published (that loop only ever wakes on its '
                    'fallback poll)', slug=f'nopub:{topic}')

    @staticmethod
    def _topic_of(node: ast.AST, consts: Dict[str, str]):
        """(topic, declared?) or (None, True) when dynamic."""
        literal = astutil.const_str(node)
        if literal is not None:
            return literal, literal in consts.values()
        name = astutil.dotted(node)
        if name is not None:
            leaf = name.split('.')[-1]
            if leaf in consts:
                return consts[leaf], True
            if leaf.isupper():
                return None, True      # unknown constant: dynamic
        return None, True
