"""SKYT012 — module-level mutables written from ≥2 threads, no lock.

RacerD-style ownership reasoning, scaled to this codebase's threading
idiom: threads are born at known sites (``threading.Thread(target=…)``,
``resilience.SupervisedThread``/``supervised_thread``), so a module's
thread entrypoints are statically enumerable. For every module-level
mutable (dict/list/set literal or constructor) the pass collects every
WRITE — rebinds under a ``global`` declaration, subscript stores,
mutator calls (``append``/``add``/``setdefault``/``pop``/…) — together
with the statically-held lockset at the write:

* the lexical ``with <lock>:`` nesting around the write, plus
* locks guaranteed held at every same-module call site on the path
  from the thread entrypoint to the writing function (meet over call
  chains — a helper only counts as locked if ALL its callers lock).

A mutable written from two different thread entrypoints (a writer
that is reachable from no entrypoint runs on the spawning thread and
counts as one more) whose write locksets share NO common lock is a
candidate race. Modules that spawn no threads are skipped entirely —
this pass only reasons where it can see the concurrency. Test-only
mutators (``reset_for_tests``-style helpers) are ignored: they race
with daemons by design and only in test teardown.

Static companion to the dynamic Eraser-style detector in
``skypilot_tpu/lint/dynamic.py`` — this pass sees code that never ran,
the dynamic one sees objects and locks the AST cannot name.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from skypilot_tpu.lint import astutil, dataflow
from skypilot_tpu.lint.core import Context, Finding

CODE = 'SKYT012'

_MUTABLE_CTORS = frozenset({'dict', 'list', 'set', 'collections.deque',
                            'collections.defaultdict',
                            'collections.OrderedDict'})
_MUTATORS = frozenset({'append', 'add', 'update', 'pop', 'setdefault',
                       'clear', 'extend', 'remove', 'insert',
                       'appendleft', 'popleft', 'discard',
                       '__setitem__'})
_THREAD_CTOR_TAILS = ('Thread', 'SupervisedThread')
_THREAD_FN_TAILS = ('supervised_thread',)
_MAIN = '<spawning-thread>'


class _Write:
    __slots__ = ('global_name', 'func', 'locks', 'line')

    def __init__(self, global_name: str, func: str,
                 locks: frozenset, line: int) -> None:
        self.global_name = global_name
        self.func = func
        self.locks = locks
        self.line = line


class SharedStateChecker:
    code = CODE
    name = 'unsynchronized shared state'

    def run(self, ctx: Context) -> Iterator[Finding]:
        for mod in ctx.package_modules:
            yield from self._check_module(mod)

    # ------------------------------------------------------------------

    def _check_module(self, mod) -> Iterator[Finding]:
        imports = astutil.import_map(mod.tree)
        fns = {self._qual(cls, fn.name): (cls, fn)
               for cls, fn in dataflow.functions_of(mod.tree)}

        entries = self._thread_entries(mod.tree, imports, fns)
        if not entries:
            return

        mutables = self._module_mutables(mod.tree, imports)
        if not mutables:
            return

        lock_names = self._lock_names(mod.tree, imports)

        # Per function: writes (with lexical locks) and same-module
        # call edges (with locks held at the call site).
        writes: Dict[str, List[_Write]] = {}
        edges: Dict[str, List[Tuple[str, frozenset]]] = {}
        for qual, (cls, fn) in fns.items():
            if self._test_only(fn.name):
                continue
            fn_writes, fn_edges = self._scan_fn(qual, cls, fn, fns,
                                                mutables, lock_names)
            if fn_writes:
                writes[qual] = fn_writes
            if fn_edges:
                edges[qual] = fn_edges

        if not writes:
            return

        # Guaranteed-held locks per (entry, function): meet over call
        # chains from the entrypoint.
        held = {entry: self._held_from(entry, edges, fns)
                for entry in entries}

        reported: Set[str] = set()
        for global_name in sorted(mutables):
            per_entry: Dict[str, List[Tuple[frozenset, _Write]]] = {}
            for qual, fn_writes in writes.items():
                for write in fn_writes:
                    if write.global_name != global_name:
                        continue
                    owners = [entry for entry in entries
                              if qual in held[entry]]
                    if not owners:
                        owners = [_MAIN]
                    for entry in owners:
                        base = (frozenset() if entry == _MAIN
                                else held[entry].get(qual, frozenset()))
                        per_entry.setdefault(entry, []).append(
                            (base | write.locks, write))
            real = [e for e in per_entry if e != _MAIN]
            if len(per_entry) < 2 or not real:
                continue
            all_locksets = [locks for entry_writes in per_entry.values()
                            for locks, _ in entry_writes]
            common = frozenset.intersection(*all_locksets) \
                if all_locksets else frozenset()
            if common:
                continue
            first = min((w for ws in per_entry.values() for _, w in ws),
                        key=lambda w: w.line)
            slug = f'race:{global_name}'
            if slug in reported:
                continue
            reported.add(slug)
            entries_desc = ', '.join(sorted(per_entry))
            yield Finding(
                CODE, mod.rel, first.line,
                f'module-level `{global_name}` is written from '
                f'multiple threads ({entries_desc}) with no common '
                'lock — guard every write with one lock (or confine '
                'the state to a single thread)',
                slug=slug)

    # -- discovery ------------------------------------------------------

    def _qual(self, cls: Optional[str], name: str) -> str:
        return f'{cls}.{name}' if cls else name

    def _test_only(self, name: str) -> bool:
        return name.endswith('_for_tests') or name.startswith('reset_')

    def _module_mutables(self, tree, imports) -> Set[str]:
        out: Set[str] = set()
        for stmt in tree.body:
            targets = []
            value = None
            if isinstance(stmt, ast.Assign):
                targets, value = stmt.targets, stmt.value
            elif isinstance(stmt, ast.AnnAssign) and stmt.value:
                targets, value = [stmt.target], stmt.value
            if value is None:
                continue
            is_mutable = isinstance(value, (ast.Dict, ast.List,
                                            ast.Set, ast.ListComp,
                                            ast.DictComp, ast.SetComp))
            if isinstance(value, ast.Call):
                resolved = astutil.resolve_call(value.func, imports)
                is_mutable = resolved in _MUTABLE_CTORS
            if not is_mutable:
                continue
            for target in targets:
                if isinstance(target, ast.Name):
                    out.add(target.id)
        return out

    def _lock_names(self, tree, imports) -> Set[str]:
        """Module-level and self-attribute lock identities (dotted
        receiver strings as they appear in ``with`` statements)."""
        out: Set[str] = set()
        for stmt in tree.body:
            if (isinstance(stmt, ast.Assign)
                    and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and isinstance(stmt.value, ast.Call)):
                resolved = astutil.resolve_call(stmt.value.func, imports)
                if resolved in ('threading.Lock', 'threading.RLock',
                                'threading.Condition'):
                    out.add(stmt.targets[0].id)
        for node in ast.walk(tree):
            if (isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Attribute)
                    and isinstance(node.targets[0].value, ast.Name)
                    and node.targets[0].value.id == 'self'
                    and isinstance(node.value, ast.Call)):
                resolved = astutil.resolve_call(node.value.func, imports)
                if resolved in ('threading.Lock', 'threading.RLock',
                                'threading.Condition'):
                    out.add(f'self.{node.targets[0].attr}')
        return out

    def _thread_entries(self, tree, imports, fns) -> Set[str]:
        """Qualified names of functions run on spawned threads."""
        out: Set[str] = set()

        def add_target(expr, cls_ctx: Optional[str]) -> None:
            if isinstance(expr, ast.Name) and expr.id in fns:
                out.add(expr.id)
                return
            name = astutil.dotted(expr)
            if name and name.startswith('self.') and cls_ctx:
                qual = f'{cls_ctx}.{name[len("self."):]}'
                if qual in fns:
                    out.add(qual)

        for cls, fn in dataflow.functions_of(tree):
            for call in ast.walk(fn):
                if not isinstance(call, ast.Call):
                    continue
                resolved = astutil.resolve_call(call.func, imports) or ''
                tail = resolved.rsplit('.', 1)[-1]
                if tail in _THREAD_CTOR_TAILS:
                    for kw in call.keywords:
                        if kw.arg == 'target':
                            add_target(kw.value, cls)
                elif tail in _THREAD_FN_TAILS and call.args:
                    add_target(call.args[0], cls)
        return out

    # -- per-function scan ----------------------------------------------

    def _scan_fn(self, qual, cls, fn, fns, mutables, lock_names):
        writes: List[_Write] = []
        edges: List[Tuple[str, frozenset]] = []
        globals_declared = {
            name for node in ast.walk(fn)
            if isinstance(node, ast.Global) for name in node.names}
        local_names = {
            n.id for n in ast.walk(fn)
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store)
            and n.id not in globals_declared}

        def is_global_mutable(name: str) -> bool:
            return (name in mutables
                    and (name in globals_declared
                         or name not in local_names))

        def walk(body, held: frozenset) -> None:
            for stmt in body:
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                    continue
                if isinstance(stmt, (ast.With, ast.AsyncWith)):
                    acquired = set()
                    for item in stmt.items:
                        name = astutil.dotted(item.context_expr)
                        if name and (name in lock_names
                                     or _lockish(name)):
                            acquired.add(name)
                    self._stmt_effects(stmt, held, is_global_mutable,
                                       qual, cls, fns, writes, edges)
                    walk(stmt.body, held | frozenset(acquired))
                    continue
                self._stmt_effects(stmt, held, is_global_mutable,
                                   qual, cls, fns, writes, edges)
                for field in ('body', 'orelse', 'finalbody'):
                    sub = getattr(stmt, field, None)
                    if sub:
                        walk(sub, held)
                for handler in getattr(stmt, 'handlers', ()) or ():
                    walk(handler.body, held)

        walk(fn.body, frozenset())
        return writes, edges

    def _stmt_effects(self, stmt, held, is_global_mutable, qual, cls,
                      fns, writes, edges) -> None:
        exprs = dataflow.owned_exprs(stmt)
        # Writes: subscript stores / del / augassign on the mutable.
        targets: List[ast.expr] = []
        if isinstance(stmt, ast.Assign):
            targets = stmt.targets
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            targets = [stmt.target]
        elif isinstance(stmt, ast.Delete):
            targets = stmt.targets
        for target in targets:
            base = target
            while isinstance(base, ast.Subscript):
                base = base.value
            if isinstance(base, ast.Name):
                if isinstance(target, ast.Subscript) \
                        and is_global_mutable(base.id):
                    writes.append(_Write(base.id, qual, held,
                                         stmt.lineno))
                elif (isinstance(target, ast.Name)
                      and is_global_mutable(target.id)):
                    writes.append(_Write(target.id, qual, held,
                                         stmt.lineno))
        for expr in exprs:
            for call in (n for n in ast.walk(expr)
                         if isinstance(n, ast.Call)):
                func = call.func
                if isinstance(func, ast.Attribute):
                    if (isinstance(func.value, ast.Name)
                            and func.attr in _MUTATORS
                            and is_global_mutable(func.value.id)):
                        writes.append(_Write(func.value.id, qual, held,
                                             call.lineno))
                        continue
                    # self.method() call edge.
                    name = astutil.dotted(func)
                    if name and name.startswith('self.') and cls:
                        callee = f'{cls}.{name[len("self."):]}'
                        if callee in fns:
                            edges.append((callee, held))
                elif isinstance(func, ast.Name) and func.id in fns:
                    edges.append((func.id, held))

    def _held_from(self, entry, edges, fns
                   ) -> Dict[str, frozenset]:
        """function -> locks guaranteed held when reached from
        ``entry`` (meet over call chains)."""
        if entry not in fns:
            return {}
        held: Dict[str, frozenset] = {entry: frozenset()}
        worklist = [entry]
        while worklist:
            func = worklist.pop()
            base = held[func]
            for callee, site_locks in edges.get(func, ()):
                candidate = base | site_locks
                prev = held.get(callee)
                new = candidate if prev is None else (prev & candidate)
                if new != prev:
                    held[callee] = new
                    worklist.append(callee)
        return held


def _lockish(name: str) -> bool:
    last = name.rsplit('.', 1)[-1].lower()
    return 'lock' in last or 'cond' in last
