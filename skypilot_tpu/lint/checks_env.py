"""SKYT002 — every ``SKYT_*`` env reference resolves against the typed
declaration table (``skypilot_tpu/utils/env_registry.py``).

The registry is the single source of truth for the platform's ~100
knobs: name, type, default, doc. This pass collects every place the
package touches a SKYT_* name — a string literal that IS exactly a
``SKYT_*`` token (or an f-string with a ``SKYT_..._`` literal head) in
any *structured* position:

* a call argument (``os.environ.get('X')``, ``os.getenv``, the typed
  ``env_registry.get_*`` accessors, helper calls like ``pick(...)``);
* a subscript key (``os.environ['X']`` reads AND ``envs['X'] = ...``
  child-environment construction — a typo here ships a knob nobody
  reads) or a dict-literal key;
* an ``'X' in os.environ`` membership test;
* a module-level name constant (``SPEC_ENV = 'SKYT_FAULT_SPEC'``);

prose (docstrings, embedded shell/JS text) never fullmatches, so it
never counts. Any collected name with no declaration is flagged. It also
flags declarations nothing references (dead knobs rot docs), except
those marked ``external=True`` (consumed by recipe payloads / shell
templates outside the package's python sources).

The committed ``docs/env_vars.md`` is generated from the same table;
the in-sync check lives in the runner (SKYT000) so CI fails when the
table changes without regenerating the doc.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Tuple

from skypilot_tpu.lint import astutil
from skypilot_tpu.lint.core import Context, Finding
from skypilot_tpu.utils import env_registry

CODE = 'SKYT002'

ENV_NAME_RE = re.compile(r'^SKYT_[A-Z0-9_]+$')
ENV_PREFIX_RE = re.compile(r'^SKYT_[A-Z0-9_]*_$')


class EnvRegistryChecker:
    code = CODE
    name = 'SKYT_* env registry'

    def run(self, ctx: Context) -> Iterator[Finding]:
        referenced: Dict[str, List[Tuple[str, int]]] = {}

        def note(name: str, mod, line: int) -> None:
            referenced.setdefault(name, []).append((mod.rel, line))

        for mod in ctx.package_modules:
            for node in ast.walk(mod.tree):
                for name, line in self._env_names(node):
                    note(name, mod, line)

        # Undeclared references.
        for name in sorted(referenced):
            if env_registry.lookup(name) is not None:
                continue
            # Prefix references (f-string heads) resolve through
            # patterns only; a concrete declared name that extends the
            # prefix is NOT enough — the suffix space is unbounded.
            rel, line = referenced[name][0]
            kind = 'dynamic prefix' if name.endswith('_') else 'knob'
            yield Finding(
                CODE, rel, line,
                f'undeclared SKYT_* {kind} {name!r}: declare it in '
                'skypilot_tpu/utils/env_registry.py (name, type, '
                'default, doc)',
                slug=f'undeclared:{name}')

        # Declarations nothing references.
        reg_mod = ctx.module('utils/env_registry.py')
        for var in env_registry.DECLARATIONS:
            if var.external:
                continue
            if var.is_pattern:
                prefix = var.name[:-1]
                hit = any(n.startswith(prefix) for n in referenced)
            else:
                hit = var.name in referenced
            if not hit:
                yield Finding(
                    CODE, reg_mod.rel if reg_mod else
                    'skypilot_tpu/utils/env_registry.py', 0,
                    f'declared knob {var.name} is never referenced in '
                    'the package (delete the declaration or mark it '
                    'external=True)',
                    slug=f'unreferenced:{var.name}')

    def _env_names(self, node: ast.AST) -> Iterator[Tuple[str, int]]:
        """SKYT_* names/prefixes referenced by this node."""
        if isinstance(node, ast.Call):
            for arg in list(node.args) + [
                    kw.value for kw in node.keywords]:
                yield from self._name_arg(arg)
        elif isinstance(node, ast.Subscript):
            # os.environ['X'] (read/write/del) and env-dict builds
            # (envs['SKYT_X'] = ...). Non-SKYT keys are ignored.
            yield from self._name_arg(node.slice, line=node.lineno)
        elif isinstance(node, ast.Dict):
            for key in node.keys:
                if key is not None:
                    yield from self._name_arg(key)
        elif isinstance(node, ast.Compare):
            # 'X' in os.environ
            if (len(node.ops) == 1 and isinstance(node.ops[0], ast.In)
                    and (astutil.dotted(node.comparators[0]) or ''
                         ).endswith('environ')):
                yield from self._name_arg(node.left)
        elif isinstance(node, ast.Assign):
            # Module/class-level env-name constants:
            # SPEC_ENV = 'SKYT_FAULT_SPEC'.
            if (len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                yield from self._name_arg(node.value)

    def _name_arg(self, node: ast.AST, line: int = 0
                  ) -> Iterator[Tuple[str, int]]:
        lineno = getattr(node, 'lineno', line)
        literal = astutil.const_str(node)
        if literal is not None:
            if ENV_NAME_RE.match(literal):
                yield literal, lineno
            return
        head = astutil.fstring_head(node)
        if head is not None and ENV_PREFIX_RE.match(head):
            yield head, lineno
