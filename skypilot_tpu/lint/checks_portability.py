"""SKYT007 (sqlite portability) and SKYT008 (JAX purity).

SKYT007: the PR-2 outage class — ``UPDATE .. RETURNING`` killed every
runner on sqlite < 3.35, and ``ON CONFLICT`` upserts need >= 3.24 —
must stay mechanically impossible. The only places allowed to emit
these dialect features are the adaptive helpers that probe backend
support and fall back (``server/requests_db.py``, ``utils/locks.py``,
``utils/pg.py``). Any other module embedding them in SQL text is a
portability regression.

SKYT008: host-side effects inside ``@jax.jit``/``pjit``-traced
functions (``time.time``, the stdlib ``random`` module, ``print``,
env reads, ``open``) execute ONCE at trace time and then bake their
value into the compiled program — a step function that "reads a knob
per step" actually reads it per *compile*. Flags impure calls inside
functions that are jit-decorated (including
``functools.partial(jax.jit, ...)``) or wrapped via ``jax.jit(fn)``
in the same module (the train/step.py idiom).
"""
from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Set

from skypilot_tpu.lint import astutil
from skypilot_tpu.lint.core import Context, Finding

SQL_CODE = 'SKYT007'
JAX_CODE = 'SKYT008'

# -- SKYT007 ------------------------------------------------------------

SQL_ALLOWED = ('server/requests_db.py', 'utils/locks.py', 'utils/pg.py')
SQL_DIALECT_RE = re.compile(r'\b(RETURNING|ON\s+CONFLICT)\b')
SQL_STMT_RE = re.compile(r'\b(INSERT|UPDATE|DELETE|SELECT)\b')


class SqlitePortabilityChecker:
    code = SQL_CODE
    name = 'sqlite dialect portability'

    def run(self, ctx: Context) -> Iterator[Finding]:
        for mod in ctx.package_modules:
            rel = mod.rel.replace('\\', '/')
            if rel.endswith(SQL_ALLOWED):
                continue
            docstrings = astutil.docstring_nodes(mod.tree)
            for node in ast.walk(mod.tree):
                if not (isinstance(node, ast.Constant)
                        and isinstance(node.value, str)
                        and id(node) not in docstrings):
                    continue
                text = node.value
                dialect = SQL_DIALECT_RE.search(text)
                if dialect and SQL_STMT_RE.search(text):
                    feature = ' '.join(dialect.group(1).split())
                    yield Finding(
                        SQL_CODE, mod.rel, node.lineno,
                        f'SQL uses {feature!r}: breaks sqlite < '
                        f'{"3.35" if feature == "RETURNING" else "3.24"}'
                        ' runners — route through the adaptive helpers '
                        'in requests_db.py/locks.py or write the '
                        'portable two-step form',
                        slug=f'{feature.lower().replace(" ", "-")}'
                             f':{node.lineno}')
        return

# -- SKYT008 ------------------------------------------------------------


IMPURE_EXACT = {
    'time.time': 'wall-clock is frozen at trace time',
    'time.monotonic': 'wall-clock is frozen at trace time',
    'time.perf_counter': 'wall-clock is frozen at trace time',
    'time.sleep': 'sleeps at trace time only, never per step',
    'os.getenv': 'env is read once at trace time',
    'os.environ.get': 'env is read once at trace time',
    'print': 'prints at trace time only (use jax.debug.print)',
    'input': 'blocks tracing',
    'open': 'file I/O does not belong in a traced function',
}
IMPURE_PREFIXES = {
    'random.': 'stdlib random is traced once (use jax.random with '
               'explicit keys)',
    'np.random.': 'numpy RNG is traced once (use jax.random)',
    'numpy.random.': 'numpy RNG is traced once (use jax.random)',
}
JIT_NAMES = ('jax.jit', 'jit', 'pjit', 'jax.pjit')


def _is_jit_expr(node: ast.AST) -> bool:
    """jax.jit / pjit / functools.partial(jax.jit, ...) expressions."""
    name = astutil.dotted(node)
    if name in JIT_NAMES:
        return True
    if isinstance(node, ast.Call):
        fn = astutil.dotted(node.func)
        if fn in JIT_NAMES:
            return True
        if fn in ('functools.partial', 'partial') and node.args:
            return _is_jit_expr(node.args[0])
    return False


class JaxPurityChecker:
    code = JAX_CODE
    name = 'JAX purity in jitted functions'

    def run(self, ctx: Context) -> Iterator[Finding]:
        for mod in ctx.package_modules:
            defs: Dict[str, List[ast.AST]] = {}
            for node in ast.walk(mod.tree):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    defs.setdefault(node.name, []).append(node)
            jitted: List[ast.AST] = []
            seen: Set[int] = set()

            def add(fn) -> None:
                if fn is not None and id(fn) not in seen:
                    seen.add(id(fn))
                    jitted.append(fn)

            for node in ast.walk(mod.tree):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    if any(_is_jit_expr(d) for d in node.decorator_list):
                        add(node)
                elif isinstance(node, ast.Call):
                    # jax.jit(fn, ...) wrapping a same-module def.
                    if astutil.dotted(node.func) in JIT_NAMES \
                            and node.args:
                        target = node.args[0]
                        if isinstance(target, ast.Name):
                            for fn in defs.get(target.id, ()):
                                add(fn)
            for fn in jitted:
                yield from self._check_fn(mod, fn)

    def _check_fn(self, mod, fn) -> Iterator[Finding]:
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            name = astutil.dotted(node.func)
            if name is None:
                continue
            why = IMPURE_EXACT.get(name)
            if why is None:
                for prefix, reason in IMPURE_PREFIXES.items():
                    if name.startswith(prefix):
                        why = reason
                        break
            if why:
                yield Finding(
                    JAX_CODE, mod.rel, node.lineno,
                    f'impure call {name}() inside jitted function '
                    f'{fn.name}(): {why}',
                    slug=f'{fn.name}:{name}')
