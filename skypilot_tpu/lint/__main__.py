"""skylint CLI.

Usage::

    python -m skypilot_tpu.lint                 # human output, exit 1
                                                # on active findings
    python -m skypilot_tpu.lint --json          # machine output for CI
    python -m skypilot_tpu.lint --write-baseline  # snapshot current
                                                # findings as
                                                # UNREVIEWED entries
    python -m skypilot_tpu.lint --dump-env-docs  # docs/env_vars.md to
                                                # stdout

The baseline path comes from ``[tool.skylint] baseline = "..."`` in
pyproject.toml (default ``lint_baseline.json`` at the repo root). A
default run also verifies the committed ``docs/env_vars.md`` matches
the env-registry table (SKYT000 finding when it drifts).
"""
from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys
from typing import List, Optional, Set

from skypilot_tpu.lint import core
from skypilot_tpu.utils import env_registry

ENV_DOCS_REL = os.path.join('docs', 'env_vars.md')

# --json report schema version. Bump when the report SHAPE changes;
# consumers gate on it instead of sniffing fields (docs/
# static_analysis.md "CI / JSON contract"). v2 added this field and
# the SKYT009..SKYT012 dataflow passes.
REPORT_SCHEMA = 2


def changed_files(repo_root: str) -> Optional[Set[str]]:
    """Repo-relative paths touched vs HEAD (staged + unstaged +
    untracked), or None when git is unavailable (fail open: a broken
    git must widen the run, never narrow it)."""
    try:
        out = subprocess.run(
            ['git', 'status', '--porcelain'], cwd=repo_root,
            capture_output=True, text=True, timeout=30, check=True)
    except (OSError, subprocess.SubprocessError):
        return None
    paths: Set[str] = set()
    for line in out.stdout.splitlines():
        if len(line) < 4:
            continue
        path = line[3:].strip()
        if ' -> ' in path:   # rename: take the new side
            path = path.split(' -> ', 1)[1]
        paths.add(path.strip('"'))
    return paths


def baseline_path_from_pyproject(repo_root: str) -> str:
    """``[tool.skylint] baseline`` (tomllib is 3.11+; a targeted regex
    keeps the linter runnable on the 3.10 runners)."""
    default = os.path.join(repo_root, 'lint_baseline.json')
    pyproject = os.path.join(repo_root, 'pyproject.toml')
    try:
        with open(pyproject, encoding='utf-8') as f:
            text = f.read()
    except OSError:
        return default
    section = re.search(r'^\[tool\.skylint\]\s*$(.*?)(?=^\[|\Z)', text,
                        re.M | re.S)
    if not section:
        return default
    match = re.search(r'^baseline\s*=\s*"([^"]+)"', section.group(1),
                      re.M)
    if not match:
        return default
    return os.path.join(repo_root, match.group(1))


def filter_changed(findings: List[core.Finding],
                   changed: Optional[Set[str]]) -> List[core.Finding]:
    """--changed-only scopes the REPORT, not the scan: cross-file
    passes (chaos coverage, event topics, lock graphs) need the whole
    repo to judge correctly; only the rendered findings narrow. Meta
    findings (baseline rot, docs drift) always show, and an unreadable
    git (``changed is None``) fails open to the full report."""
    if changed is None:
        return findings
    return [f for f in findings
            if f.path.replace(os.sep, '/') in changed
            or f.code == core.META_CODE]


def check_env_docs(repo_root: str) -> List[core.Finding]:
    """SKYT000 when the committed generated doc drifts from the
    registry table."""
    path = os.path.join(repo_root, ENV_DOCS_REL)
    expected = env_registry.render_docs()
    try:
        with open(path, encoding='utf-8') as f:
            actual = f.read()
    except OSError:
        return [core.Finding(
            core.META_CODE, ENV_DOCS_REL, 0,
            'generated env-var doc is missing — run `python -m '
            'skypilot_tpu.lint --dump-env-docs > docs/env_vars.md`',
            slug='env-docs-missing')]
    if actual != expected:
        return [core.Finding(
            core.META_CODE, ENV_DOCS_REL, 0,
            'generated env-var doc is out of sync with '
            'utils/env_registry.py — regenerate with `python -m '
            'skypilot_tpu.lint --dump-env-docs > docs/env_vars.md`',
            slug='env-docs-stale')]
    return []


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog='python -m skypilot_tpu.lint',
        description='AST-based invariant checker for the skypilot-tpu '
                    'control plane (SKYT001..SKYT012).')
    parser.add_argument('--json', action='store_true',
                        help='emit the JSON report (what CI consumes)')
    parser.add_argument('--baseline', default=None,
                        help='baseline file override (default: '
                             '[tool.skylint] in pyproject.toml)')
    parser.add_argument('--no-baseline', action='store_true',
                        help='ignore the baseline (show everything)')
    parser.add_argument('--write-baseline', action='store_true',
                        help='snapshot active findings as UNREVIEWED '
                             'suppressions (each must then be '
                             'justified or fixed)')
    parser.add_argument('--dump-env-docs', action='store_true',
                        help='print generated docs/env_vars.md and '
                             'exit')
    parser.add_argument('--changed-only', action='store_true',
                        help='report only findings in files the git '
                             'working tree changed vs HEAD (fast '
                             'iteration; the full scan still runs so '
                             'cross-file passes stay correct)')
    parser.add_argument('--root', default=None,
                        help='repo root override (tests)')
    args = parser.parse_args(argv)

    if args.dump_env_docs:
        sys.stdout.write(env_registry.render_docs())
        return 0

    repo_root = args.root or core.find_repo_root()
    package_files, test_files, doc_files = core.repo_paths(repo_root)
    ctx = core.Context(repo_root, package_files, test_files, doc_files)
    findings = core.run_checks(ctx)
    findings.extend(check_env_docs(repo_root))

    baseline_path = args.baseline or baseline_path_from_pyproject(
        repo_root)
    if args.write_baseline:
        count = core.write_baseline(findings, baseline_path)
        print(f'wrote {count} UNREVIEWED suppressions to '
              f'{baseline_path} — justify or fix each before '
              'committing')
        return 0
    if not args.no_baseline:
        try:
            entries = core.load_baseline(baseline_path)
        except (ValueError, json.JSONDecodeError) as e:
            print(f'error: bad baseline {baseline_path}: {e}',
                  file=sys.stderr)
            return 2
        findings = core.apply_baseline(findings, entries, baseline_path)
        findings.sort(key=lambda f: (f.path, f.line, f.code, f.slug))

    if args.changed_only:
        findings = filter_changed(findings, changed_files(repo_root))

    active = [f for f in findings if not f.baselined]
    if args.json:
        report = {
            'version': 1,
            'schema': REPORT_SCHEMA,
            'findings': [f.to_json() for f in findings],
            'summary': {
                'files_scanned': len(ctx.package_modules),
                'active': len(active),
                'baselined': len(findings) - len(active),
            },
        }
        json.dump(report, sys.stdout, indent=2)
        sys.stdout.write('\n')
    else:
        for finding in findings:
            print(finding.render())
        baselined = len(findings) - len(active)
        print(f'skylint: {len(ctx.package_modules)} files, '
              f'{len(active)} active finding(s), {baselined} '
              'baselined')
    return 1 if active else 0


if __name__ == '__main__':
    sys.exit(main())
