"""skylint: AST + dataflow invariant checker for the control plane.

``python -m skypilot_tpu.lint`` runs twelve passes over the package
(stdlib ``ast`` only) and exits non-zero on any non-baselined finding:

=======  ==========================================================
SKYT001  blocking call inside ``async def`` (event-loop stalls)
SKYT002  SKYT_* env knob not in the typed registry (+ dead knobs)
SKYT003  skyt_* metric family/type/label drift vs server/metrics.py
SKYT004  chaos-site cross-check (dead sites, tests on ghost sites)
SKYT005  event-bus topic cross-check (no-subscriber / no-publisher)
SKYT006  lock-acquisition-order cycles (potential deadlocks)
SKYT007  sqlite dialect portability (RETURNING / ON CONFLICT)
SKYT008  host-side effects inside jitted functions
SKYT009  wall-clock ``time.time()`` in duration/deadline arithmetic
SKYT010  blocking work / bare publish / abandonment in transactions
SKYT011  acquire/release pairing on every CFG path (locks, uploads,
         tempfiles, BlockPool refcounts)
SKYT012  module mutables written from ≥2 threads, no common lock
=======  ==========================================================

SKYT009..012 ride a shared CFG + reaching-definitions layer
(``lint/dataflow.py``); their runtime companion — an Eraser-style
lockset race detector and wait-for-graph deadlock watchdog behind
``SKYT_LINT_DYNAMIC`` — lives in ``lint/dynamic.py`` and rides the
``chaos`` pytest marker.

``SKYT000`` is the runner's own meta code (parse errors, stale or
unreviewed baseline entries, generated docs out of sync).

See ``docs/static_analysis.md`` for the checker catalogue and the
baseline workflow; ``tests/test_skylint.py`` gates tier-1 on a clean
run.
"""
from skypilot_tpu.lint.core import (Context, Finding, all_checkers,
                                    run_checks)

__all__ = ['Context', 'Finding', 'all_checkers', 'run_checks']
