"""SKYT006 — lock-acquisition-order graph (lockdep-lite).

The control plane holds 30+ ``threading.Lock``s across three
concurrency regimes; nothing enforces a consistent acquisition order,
and an inverted pair deadlocks only under the exact interleaving a
chaos run may never hit. This pass builds a directed
acquired-while-holding graph from lexical ``with`` nesting and reports
cycles.

Lock identity (conservative, per-module — two modules' ``_lock``s are
distinct):

* module-level ``X = threading.Lock()/RLock()``      -> ``mod:X``
* ``self._x = threading.Lock()`` in class ``C``      -> ``mod:C._x``
* function-local ``x = threading.Lock()``            -> ``mod:fn.x``

Edges come from ``with A: ... with B:`` nesting inside one function
body (including ``with A, B:`` multi-item forms, left to right).
Cross-function holds (call a lock-taking helper while holding a lock)
are out of scope — the graph under-approximates, so every reported
cycle is a real ordering inversion in the source.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from skypilot_tpu.lint import astutil
from skypilot_tpu.lint.core import Context, Finding

CODE = 'SKYT006'

LOCK_CTORS = frozenset({'threading.Lock', 'threading.RLock'})


class LockOrderChecker:
    code = CODE
    name = 'lock acquisition order'

    def run(self, ctx: Context) -> Iterator[Finding]:
        # edge (a, b): b acquired while holding a; value = first site.
        edges: Dict[Tuple[str, str], Tuple[str, int]] = {}
        for mod in ctx.package_modules:
            self._collect_module(mod, edges)
        yield from self._report_cycles(edges)

    # -- collection -----------------------------------------------------

    def _collect_module(self, mod, edges) -> None:
        imports = astutil.import_map(mod.tree)

        def is_lock_ctor(node: ast.AST) -> bool:
            if not isinstance(node, ast.Call):
                return False
            return astutil.resolve_call(node.func, imports) in LOCK_CTORS

        module_locks: Set[str] = set()
        class_locks: Dict[str, Set[str]] = {}
        for node in mod.tree.body:
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and is_lock_ctor(node.value)):
                module_locks.add(node.targets[0].id)
            elif isinstance(node, ast.ClassDef):
                attrs: Set[str] = set()
                for sub in ast.walk(node):
                    if (isinstance(sub, ast.Assign)
                            and len(sub.targets) == 1
                            and isinstance(sub.targets[0], ast.Attribute)
                            and isinstance(sub.targets[0].value, ast.Name)
                            and sub.targets[0].value.id == 'self'
                            and is_lock_ctor(sub.value)):
                        attrs.add(sub.targets[0].attr)
                if attrs:
                    class_locks[node.name] = attrs

        # Walk every function with (class, function) context.
        def visit_scope(body, class_name: Optional[str],
                        fn_name: Optional[str]) -> None:
            for node in body:
                if isinstance(node, ast.ClassDef):
                    visit_scope(node.body, node.name, None)
                elif isinstance(node, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    local_locks = {
                        t.targets[0].id
                        for t in ast.walk(node)
                        if isinstance(t, ast.Assign)
                        and len(t.targets) == 1
                        and isinstance(t.targets[0], ast.Name)
                        and is_lock_ctor(t.value)}

                    def resolve(expr: ast.AST) -> Optional[str]:
                        name = astutil.dotted(expr)
                        if name is None:
                            return None
                        if name.startswith('self.') and class_name:
                            attr = name[len('self.'):]
                            if attr in class_locks.get(class_name, ()):
                                return f'{mod.rel}:{class_name}.{attr}'
                            return None
                        if name in local_locks:
                            return f'{mod.rel}:{node.name}.{name}'
                        if name in module_locks:
                            return f'{mod.rel}:{name}'
                        return None

                    self._walk_withs(node.body, [], resolve, mod, edges)
                    visit_scope(node.body, class_name, node.name)

        visit_scope(mod.tree.body, None, None)

    def _walk_withs(self, body: List[ast.stmt], held: List[str],
                    resolve, mod, edges) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                acquired: List[str] = []
                for item in stmt.items:
                    lock = resolve(item.context_expr)
                    if lock is None:
                        continue
                    for holder in held + acquired:
                        if holder != lock:
                            edges.setdefault(
                                (holder, lock), (mod.rel, stmt.lineno))
                    acquired.append(lock)
                self._walk_withs(stmt.body, held + acquired, resolve,
                                 mod, edges)
            elif isinstance(stmt, (ast.FunctionDef,
                                   ast.AsyncFunctionDef, ast.ClassDef)):
                continue   # new scope: handled by visit_scope
            else:
                for field in ('body', 'orelse', 'finalbody'):
                    sub = getattr(stmt, field, None)
                    if sub:
                        self._walk_withs(sub, held, resolve, mod, edges)
                for handler in getattr(stmt, 'handlers', ()) or ():
                    self._walk_withs(handler.body, held, resolve, mod,
                                     edges)

    # -- cycle detection ------------------------------------------------

    def _report_cycles(self, edges) -> Iterator[Finding]:
        graph: Dict[str, Set[str]] = {}
        for a, b in edges:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
        seen_cycles: Set[Tuple[str, ...]] = set()
        # Tarjan SCC, iterative.
        index: Dict[str, int] = {}
        low: Dict[str, int] = {}
        on_stack: Set[str] = set()
        stack: List[str] = []
        counter = [0]

        def strongconnect(root: str):
            work = [(root, iter(sorted(graph[root])))]
            index[root] = low[root] = counter[0]
            counter[0] += 1
            stack.append(root)
            on_stack.add(root)
            while work:
                node, it = work[-1]
                advanced = False
                for succ in it:
                    if succ not in index:
                        index[succ] = low[succ] = counter[0]
                        counter[0] += 1
                        stack.append(succ)
                        on_stack.add(succ)
                        work.append((succ, iter(sorted(graph[succ]))))
                        advanced = True
                        break
                    if succ in on_stack:
                        low[node] = min(low[node], index[succ])
                if advanced:
                    continue
                work.pop()
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[node])
                if low[node] == index[node]:
                    scc = []
                    while True:
                        member = stack.pop()
                        on_stack.discard(member)
                        scc.append(member)
                        if member == node:
                            break
                    if len(scc) > 1 or (node, node) in edges:
                        yield tuple(sorted(scc))

        sccs = []
        for node in sorted(graph):
            if node not in index:
                sccs.extend(strongconnect(node))
        for scc in sccs:
            if scc in seen_cycles:
                continue
            seen_cycles.add(scc)
            rel, line = next(
                (edges[(a, b)] for a in scc for b in scc
                 if (a, b) in edges), ('?', 0))
            yield Finding(
                CODE, rel, line,
                'lock-order cycle (potential deadlock): '
                + ' <-> '.join(scc)
                + ' — pick one acquisition order and stick to it',
                slug='cycle:' + '|'.join(scc))
