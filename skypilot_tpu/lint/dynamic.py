"""Dynamic race detection: Eraser-style locksets + a deadlock watchdog.

The static passes (SKYT006 lock-order cycles, SKYT012 shared-state
locksets) reason about locks and state the AST can NAME. This module
covers the rest at runtime, behind the ``SKYT_LINT_DYNAMIC`` knob:

* **Lockset tracking** (Eraser, Savage et al. 1997): ``instrument()``
  patches ``threading.Lock``/``RLock`` factories so locks created in
  the instrumented window record, per thread, the set currently held.
  Objects registered with :func:`watch` get their attribute WRITES
  intercepted; each (object, attribute) keeps a candidate lockset
  ``C(v)`` — intersected with the writer's held set on every access.
  Once a second thread writes with ``C(v)`` empty, the pair is
  reported as a candidate race with both stacks.
* **Wait-for-graph deadlock watchdog**: instrumented locks also
  record who HOLDS and who WAITS; a daemon thread rebuilds the
  thread→lock→thread graph on a short cadence and reports any cycle
  that persists across two consecutive scans (one scan can witness a
  transient hand-off). This complements static SKYT006: the watchdog
  sees locks acquired through call chains and dynamic containers that
  lexical ``with``-nesting analysis cannot.

Reports accumulate in-process and are written as JSON at
:func:`write_report` (the pytest plugin in tests/conftest.py calls it
at session end; plain processes can ``atexit`` it). Schema::

    {"schema": "skylint-dynamic/v1",
     "races":     [{"object", "attribute", "threads", "stacks"}],
     "deadlocks": [{"cycle": [{"thread", "waiting_for", "holding"}]}]}

Enabling: ``SKYT_LINT_DYNAMIC=1`` turns instrumentation on;
a path-looking value (contains a separator or ends in ``.json``)
additionally chooses the report destination. The pytest plugin rides
the existing ``chaos`` marker, so tier-1 fault-injection runs double
as race hunts with zero new test surface — and a clean run must stay
silent: only locks created inside, and objects watched inside, the
instrumented window are observed.

Everything here is stdlib-only and off by default; production code
never imports this module (the knob is read by the test plugin).
"""
from __future__ import annotations

import atexit
import json
import os
import threading
import traceback
from typing import Any, Dict, List, Optional, Set, Tuple

KNOB = 'SKYT_LINT_DYNAMIC'
SCHEMA = 'skylint-dynamic/v1'

_DEFAULT_REPORT = 'skylint_dynamic_report.json'


def enabled() -> bool:
    value = os.environ.get(KNOB, '')
    return bool(value) and value.lower() not in ('0', 'false', 'no')


def report_path() -> str:
    value = os.environ.get(KNOB, '')
    if os.sep in value or value.endswith('.json'):
        return value
    state_dir = os.environ.get('SKYT_STATE_DIR',
                               os.path.expanduser('~/.skyt'))
    return os.path.join(state_dir, _DEFAULT_REPORT)


# -- registry -----------------------------------------------------------

_registry_lock = threading.Lock()
_held: Dict[int, List['TrackedLock']] = {}       # thread id -> locks
_waiting: Dict[int, 'TrackedLock'] = {}          # thread id -> lock
_races: List[Dict[str, Any]] = []
_deadlocks: List[Dict[str, Any]] = []
_race_keys: Set[Tuple[int, str]] = set()
_deadlock_keys: Set[frozenset] = set()


def _thread_held(ident: Optional[int] = None) -> List['TrackedLock']:
    ident = threading.get_ident() if ident is None else ident
    with _registry_lock:
        return list(_held.get(ident, ()))


class TrackedLock:
    """A Lock/RLock wrapper recording holders and waiters.

    Delegates the full lock protocol (including the private methods
    ``Condition`` probes for) to the real lock, so instrumented locks
    keep working inside Conditions/Events created in the window.
    """

    _seq = [0]

    def __init__(self, real) -> None:
        self._real = real
        with _registry_lock:
            TrackedLock._seq[0] += 1
            self.lock_id = TrackedLock._seq[0]
        self.name = f'lock#{self.lock_id}'
        self._owners: List[int] = []    # thread idents (RLock: dups)

    # -- protocol -------------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1):
        ident = threading.get_ident()
        if blocking:
            with _registry_lock:
                _waiting[ident] = self
        try:
            got = self._real.acquire(blocking, timeout)
        finally:
            if blocking:
                with _registry_lock:
                    _waiting.pop(ident, None)
        if got:
            with _registry_lock:
                self._owners.append(ident)
                _held.setdefault(ident, []).append(self)
        return got

    def release(self) -> None:
        ident = threading.get_ident()
        self._real.release()
        with _registry_lock:
            if ident in self._owners:
                self._owners.remove(ident)
            held = _held.get(ident)
            if held and self in held:
                held.remove(self)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False

    def locked(self) -> bool:
        return self._real.locked()

    def owners(self) -> List[int]:
        with _registry_lock:
            return list(self._owners)

    # Condition() adopts these when present on its lock; fall back to
    # plain acquire/release when the wrapped lock is not an RLock
    # (CPython's Condition does the same via AttributeError).
    def _acquire_restore(self, state):   # pragma: no cover - glue
        try:
            return self._real._acquire_restore(state)
        except AttributeError:
            self._real.acquire()
            return None

    def _release_save(self):             # pragma: no cover - glue
        try:
            return self._real._release_save()
        except AttributeError:
            self._real.release()
            return None

    def _is_owned(self):                 # pragma: no cover - RLock glue
        try:
            return self._real._is_owned()
        except AttributeError:
            if self._real.acquire(False):
                self._real.release()
                return False
            return True

    def __repr__(self) -> str:
        return f'<TrackedLock {self.name}>'


# -- instrumentation window ---------------------------------------------

_real_lock = None
_real_rlock = None
_instrumented = False


def instrument() -> None:
    """Patch threading.Lock/RLock factories; idempotent."""
    global _real_lock, _real_rlock, _instrumented
    if _instrumented:
        return
    _real_lock = threading.Lock
    _real_rlock = threading.RLock

    def make_lock():
        return TrackedLock(_real_lock())

    def make_rlock():
        return TrackedLock(_real_rlock())

    threading.Lock = make_lock          # type: ignore[assignment]
    threading.RLock = make_rlock        # type: ignore[assignment]
    _instrumented = True
    _watchdog_start()


def restore() -> None:
    """Undo instrument(); existing TrackedLocks keep functioning."""
    global _instrumented
    if not _instrumented:
        return
    threading.Lock = _real_lock         # type: ignore[assignment]
    threading.RLock = _real_rlock       # type: ignore[assignment]
    _instrumented = False
    _watchdog_stop()


class instrumented:
    """Context manager form: ``with dynamic.instrumented(): ...``."""

    def __enter__(self):
        instrument()
        return self

    def __exit__(self, *exc) -> bool:
        restore()
        return False


# -- Eraser lockset state machine ---------------------------------------

_VIRGIN, _EXCLUSIVE, _SHARED_MOD = 'virgin', 'exclusive', 'shared-mod'


class _AttrState:
    __slots__ = ('state', 'first_thread', 'lockset', 'threads')

    def __init__(self) -> None:
        self.state = _VIRGIN
        self.first_thread: Optional[int] = None
        self.lockset: Optional[Set[int]] = None   # candidate C(v)
        self.threads: Set[int] = set()


_watched: Dict[int, Dict[str, _AttrState]] = {}
_watched_names: Dict[int, str] = {}


def note_write(obj: Any, attr: str) -> None:
    """Record one write to ``obj.attr`` by the current thread; report
    a race when the candidate lockset empties under a second thread."""
    ident = threading.get_ident()
    held_ids = {lock.lock_id for lock in _thread_held(ident)}
    key = id(obj)
    with _registry_lock:
        attrs = _watched.get(key)
        if attrs is None:
            return
        st = attrs.setdefault(attr, _AttrState())
        st.threads.add(ident)
        if st.state == _VIRGIN:
            st.state = _EXCLUSIVE
            st.first_thread = ident
            st.lockset = set(held_ids)
            return
        if st.state == _EXCLUSIVE and ident == st.first_thread:
            st.lockset &= held_ids
            return
        st.state = _SHARED_MOD
        st.lockset = (set(held_ids) if st.lockset is None
                      else st.lockset & held_ids)
        if st.lockset:
            return
        race_key = (key, attr)
        if race_key in _race_keys:
            return
        _race_keys.add(race_key)
        _races.append({
            'object': _watched_names.get(key, f'obj@{key:#x}'),
            'attribute': attr,
            'threads': sorted(st.threads),
            'stacks': [''.join(traceback.format_stack(limit=8))],
        })


class _Watched:
    """Subclass template whose __setattr__ reports to note_write."""

    def __setattr__(self, name, value):
        note_write(self, name)
        super().__setattr__(name, value)


def watch(obj: Any, name: Optional[str] = None) -> Any:
    """Track attribute writes on ``obj`` (Eraser candidate locksets).

    Swaps the instance's class for a generated subclass overriding
    ``__setattr__`` — no proxy, so identity and isinstance stay
    intact. Returns ``obj``. Objects with ``__slots__``-only classes
    or C types are rejected (their class cannot be swapped)."""
    cls = type(obj)
    sub = type(f'Tracked{cls.__name__}', (_Watched, cls), {})
    with _registry_lock:
        _watched[id(obj)] = {}
        _watched_names[id(obj)] = name or f'{cls.__name__}@{id(obj):#x}'
    obj.__class__ = sub
    return obj


# -- wait-for-graph deadlock watchdog ------------------------------------

_watchdog_thread: Optional[threading.Thread] = None
_watchdog_stop_event: Optional[threading.Event] = None
WATCHDOG_INTERVAL = 0.05


def _wait_graph() -> Dict[int, Tuple['TrackedLock', List[int]]]:
    """thread -> (lock it waits for, that lock's owners)."""
    with _registry_lock:
        waiting = dict(_waiting)
    return {ident: (lock, lock.owners())
            for ident, lock in waiting.items()}


def _find_cycle() -> Optional[List[Tuple[int, 'TrackedLock']]]:
    graph = _wait_graph()
    for start in graph:
        path: List[Tuple[int, TrackedLock]] = []
        seen: Set[int] = set()
        node = start
        while node in graph and node not in seen:
            seen.add(node)
            lock, owners = graph[node]
            path.append((node, lock))
            # Follow any owner that is itself waiting.
            nxt = next((o for o in owners if o in graph), None)
            if nxt is None:
                break
            node = nxt
            if node == start:
                return path
    return None


def _watchdog_loop(stop: threading.Event) -> None:
    pending: Optional[frozenset] = None
    while not stop.wait(WATCHDOG_INTERVAL):
        cycle = _find_cycle()
        if not cycle:
            pending = None
            continue
        key = frozenset(ident for ident, _ in cycle)
        if pending != key:
            pending = key      # must persist across two scans
            continue
        with _registry_lock:
            if key in _deadlock_keys:
                continue
            _deadlock_keys.add(key)
            names = {t.ident: t.name for t in threading.enumerate()}
            _deadlocks.append({
                'cycle': [{
                    'thread': names.get(ident, str(ident)),
                    'waiting_for': lock.name,
                    'holding': [l.name for l in _held.get(ident, ())],
                } for ident, lock in cycle],
            })


def _watchdog_start() -> None:
    global _watchdog_thread, _watchdog_stop_event
    if _watchdog_thread is not None and _watchdog_thread.is_alive():
        return
    _watchdog_stop_event = threading.Event()
    _watchdog_thread = threading.Thread(
        target=_watchdog_loop, args=(_watchdog_stop_event,),
        name='skylint-deadlock-watchdog', daemon=True)
    _watchdog_thread.start()


def _watchdog_stop() -> None:
    global _watchdog_thread
    if _watchdog_stop_event is not None:
        _watchdog_stop_event.set()
    if _watchdog_thread is not None:
        _watchdog_thread.join(timeout=1.0)
    _watchdog_thread = None


# -- reporting -----------------------------------------------------------


def report() -> Dict[str, Any]:
    with _registry_lock:
        return {
            'schema': SCHEMA,
            'races': list(_races),
            'deadlocks': list(_deadlocks),
        }


def write_report(path: Optional[str] = None) -> Optional[str]:
    """Write the JSON report; returns the path, or None when there is
    nothing to report (no file is created for a clean run)."""
    data = report()
    if not data['races'] and not data['deadlocks']:
        return None
    path = path or report_path()
    os.makedirs(os.path.dirname(path) or '.', exist_ok=True)
    with open(path, 'w', encoding='utf-8') as f:
        json.dump(data, f, indent=2)
        f.write('\n')
    return path


def register_atexit() -> None:
    atexit.register(write_report)


def snapshot() -> Dict[str, Any]:
    """Capture the full detector state so a test can isolate itself
    WITHOUT erasing findings (or live lock bookkeeping of still-running
    threads) accumulated earlier in the session — the session-end
    report must survive the detector's own test suite running."""
    with _registry_lock:
        return {
            'races': list(_races),
            'deadlocks': list(_deadlocks),
            'race_keys': set(_race_keys),
            'deadlock_keys': set(_deadlock_keys),
            'watched': dict(_watched),
            'watched_names': dict(_watched_names),
            'held': {k: list(v) for k, v in _held.items()},
            'waiting': dict(_waiting),
        }


def restore_snapshot(snap: Dict[str, Any]) -> None:
    with _registry_lock:
        _races[:] = snap['races']
        _deadlocks[:] = snap['deadlocks']
        _race_keys.clear()
        _race_keys.update(snap['race_keys'])
        _deadlock_keys.clear()
        _deadlock_keys.update(snap['deadlock_keys'])
        _watched.clear()
        _watched.update(snap['watched'])
        _watched_names.clear()
        _watched_names.update(snap['watched_names'])
        _held.clear()
        _held.update({k: list(v) for k, v in snap['held'].items()})
        _waiting.clear()
        _waiting.update(snap['waiting'])


def reset_for_tests() -> None:
    restore_snapshot({
        'races': [], 'deadlocks': [], 'race_keys': set(),
        'deadlock_keys': set(), 'watched': {}, 'watched_names': {},
        'held': {}, 'waiting': {},
    })
