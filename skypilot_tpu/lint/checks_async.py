"""SKYT001 — blocking call inside ``async def``.

The serve data plane runs ONE event loop per service process
(serve/load_balancer.py): a single synchronous call — ``time.sleep``,
an sqlite query through the state stores, ``subprocess.run`` — freezes
every in-flight stream through the proxy at once. PR-4's review caught
one of these by hand; this pass makes the catch permanent for every
current and future async module.

Flagged inside any ``async def`` (including sync helpers lexically
nested in one — they execute on the loop when called):

* ``time.sleep`` (use ``asyncio.sleep``);
* subprocess entry points (``run``/``call``/``check_call``/
  ``check_output``/``getoutput``/``Popen``, ``os.system``);
* ``sqlite3.connect`` and ANY call into the synchronous DB/state
  layers (requests_db, serve_state, jobs/runtime/users state stores,
  the pg adapter, distributed locks) — these block on I/O and file
  locks (route through ``loop.run_in_executor`` instead);
* blocking socket/HTTP constructors (``socket.create_connection``,
  ``urllib.request.urlopen``).
"""
from __future__ import annotations

import ast
from typing import Iterator

from skypilot_tpu.lint import astutil
from skypilot_tpu.lint.core import Context, Finding

CODE = 'SKYT001'

# Exact fully-qualified call targets that block the loop.
BLOCKING_CALLS = frozenset({
    'time.sleep',
    'os.system',
    'os.popen',
    'subprocess.run',
    'subprocess.call',
    'subprocess.check_call',
    'subprocess.check_output',
    'subprocess.getoutput',
    'subprocess.Popen',
    'sqlite3.connect',
    'socket.create_connection',
    'urllib.request.urlopen',
})

# Any call into these modules is synchronous DB/lock I/O.
BLOCKING_MODULES = (
    'skypilot_tpu.server.requests_db',
    'skypilot_tpu.serve.serve_state',
    'skypilot_tpu.jobs.state',
    'skypilot_tpu.runtime.job_lib',
    'skypilot_tpu.users.users_db',
    'skypilot_tpu.utils.pg',
    'skypilot_tpu.utils.locks',
    'skypilot_tpu.state',
)


class AsyncBlockingChecker:
    code = CODE
    name = 'blocking call in async def'

    def run(self, ctx: Context) -> Iterator[Finding]:
        for mod in ctx.package_modules:
            imports = astutil.import_map(mod.tree)
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.AsyncFunctionDef):
                    yield from self._check_async_fn(mod, node, imports)

    def _check_async_fn(self, mod, fn: ast.AsyncFunctionDef,
                        imports) -> Iterator[Finding]:
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            target = astutil.resolve_call(node.func, imports)
            if target is None:
                continue
            reason = self._blocking_reason(target)
            if reason:
                yield Finding(
                    CODE, mod.rel, node.lineno,
                    f'blocking call {target}() inside async def '
                    f'{fn.name}() {reason}',
                    slug=f'{fn.name}:{target}')

    @staticmethod
    def _blocking_reason(target: str) -> str:
        if target in BLOCKING_CALLS:
            if target == 'time.sleep':
                return '(use asyncio.sleep)'
            return '(stalls the event loop; run it in an executor)'
        for module in BLOCKING_MODULES:
            if target.startswith(module + '.'):
                return ('(synchronous DB/lock I/O; use '
                        'loop.run_in_executor)')
        return ''
