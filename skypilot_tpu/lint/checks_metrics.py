"""SKYT003 — metrics registry: every ``skyt_*`` family declared once,
with the right instrument type and a fixed label set.

Declarations are the module-level ``NAME = Counter/Gauge/Histogram(
'skyt_family', help, labels=(...))`` constructors in
``server/metrics.py`` (parsed from AST — the checker never imports the
server). This pass enforces:

* family names are unique, ``skyt_``-prefixed, and follow Prometheus
  conventions (counters end ``_total``; gauges/histograms don't);
* every declaration carries an explicit ``labels=(...)`` tuple — the
  label schema is part of the contract, not the help string;
* every emitter call (``X.inc`` / ``X.set`` / ``X.observe`` on a
  declared metric, however imported) uses the method matching the
  instrument (``rate()`` over a gauge is silently wrong on scrape) and
  passes EXACTLY the declared label keys — a missing label forks a
  second timeseries; an extra one explodes cardinality;
* dynamically named families (the inference server's
  ``skyt_inference_<stat>`` exposition) may only use prefixes listed
  in ``DYNAMIC_FAMILY_PREFIXES`` in server/metrics.py — their
  counter-vs-gauge split lives there too (``INFERENCE_COUNTER_STATS``)
  so the emitting module cannot drift from the declared typing.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, NamedTuple, Optional

from skypilot_tpu.lint import astutil
from skypilot_tpu.lint.core import Context, Finding

CODE = 'SKYT003'

METRICS_MODULE = 'server/metrics.py'
KINDS = {'Counter': 'inc', 'Gauge': 'set', 'Histogram': 'observe'}
EMIT_METHODS = frozenset(KINDS.values())
# Emitter keywords that are NOT labels: 'amount' is Counter.inc's
# increment, 'exemplar' is Histogram.observe's OpenMetrics trace_id
# attachment — neither forks a timeseries.
NON_LABEL_KWARGS = frozenset({'amount', 'exemplar'})


class MetricDecl(NamedTuple):
    var: str
    family: str
    kind: str                  # Counter | Gauge | Histogram
    labels: Optional[tuple]    # None = labels= missing (a finding)
    line: int


def parse_declarations(metrics_mod) -> Dict[str, MetricDecl]:
    """var name -> declaration, from module-level assignments."""
    decls: Dict[str, MetricDecl] = {}
    for node in metrics_mod.tree.body:
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        target = node.targets[0]
        call = node.value
        if not (isinstance(target, ast.Name)
                and isinstance(call, ast.Call)):
            continue
        ctor = astutil.dotted(call.func)
        if ctor not in KINDS:
            continue
        family = astutil.const_str(call.args[0]) if call.args else None
        labels: Optional[tuple] = None
        for kw in call.keywords:
            if kw.arg == 'labels' and isinstance(
                    kw.value, (ast.Tuple, ast.List)):
                labels = tuple(
                    astutil.const_str(e) for e in kw.value.elts)
        decls[target.id] = MetricDecl(
            target.id, family or '?', ctor, labels, node.lineno)
    return decls


def parse_dynamic_prefixes(metrics_mod) -> tuple:
    """The ``DYNAMIC_FAMILY_PREFIXES`` tuple from server/metrics.py —
    allowed prefixes for families whose full name is computed at
    runtime (e.g. the inference server's per-stat exposition)."""
    for node in metrics_mod.tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == 'DYNAMIC_FAMILY_PREFIXES'
                and isinstance(node.value, (ast.Tuple, ast.List))):
            return tuple(astutil.const_str(e) for e in node.value.elts)
    return ()


class MetricsRegistryChecker:
    code = CODE
    name = 'skyt_* metrics registry'

    def run(self, ctx: Context) -> Iterator[Finding]:
        metrics_mod = ctx.module(METRICS_MODULE)
        if metrics_mod is None:
            return
        decls = parse_declarations(metrics_mod)
        dynamic_prefixes = parse_dynamic_prefixes(metrics_mod)
        yield from self._check_declarations(metrics_mod, decls)
        for mod in ctx.package_modules:
            yield from self._check_emitters(mod, decls)
            if mod is not metrics_mod:
                yield from self._check_dynamic(mod, dynamic_prefixes)

    def _check_declarations(self, mod, decls) -> Iterator[Finding]:
        seen: Dict[str, str] = {}
        for decl in decls.values():
            if not decl.family.startswith('skyt_'):
                yield Finding(
                    CODE, mod.rel, decl.line,
                    f'metric family {decl.family!r} must be '
                    "skyt_-prefixed", slug=f'prefix:{decl.var}')
            if decl.family in seen:
                yield Finding(
                    CODE, mod.rel, decl.line,
                    f'metric family {decl.family!r} declared twice '
                    f'({seen[decl.family]} and {decl.var})',
                    slug=f'dup:{decl.family}')
            seen[decl.family] = decl.var
            is_total = decl.family.endswith('_total')
            if decl.kind == 'Counter' and not is_total:
                yield Finding(
                    CODE, mod.rel, decl.line,
                    f'counter {decl.family!r} must end in _total '
                    '(Prometheus naming convention)',
                    slug=f'total:{decl.var}')
            if decl.kind != 'Counter' and is_total:
                yield Finding(
                    CODE, mod.rel, decl.line,
                    f'{decl.kind.lower()} {decl.family!r} must not end '
                    'in _total (scrapers treat _total as a counter)',
                    slug=f'total:{decl.var}')
            if decl.labels is None:
                yield Finding(
                    CODE, mod.rel, decl.line,
                    f'{decl.var} ({decl.family}) has no labels=(...) '
                    'declaration — the label schema is part of the '
                    'metric contract', slug=f'nolabels:{decl.var}')

    def _check_emitters(self, mod, decls) -> Iterator[Finding]:
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in EMIT_METHODS):
                continue
            base = node.func.value
            var = None
            if isinstance(base, ast.Name):
                var = base.id
            elif isinstance(base, ast.Attribute):
                var = base.attr           # metrics.LB_REQUESTS.inc
            decl = decls.get(var or '')
            if decl is None:
                continue
            method = node.func.attr
            expected = KINDS[decl.kind]
            if method != expected:
                yield Finding(
                    CODE, mod.rel, node.lineno,
                    f'{decl.var} is a {decl.kind} ({decl.family}); '
                    f'.{method}() is the '
                    f'{self._kind_of_method(method)} API — use '
                    f'.{expected}() or fix the declaration',
                    slug=f'kind:{decl.var}:{method}')
                continue
            if decl.labels is None:
                continue
            if any(kw.arg is None for kw in node.keywords):
                continue                   # **labels: not checkable
            passed = tuple(sorted(kw.arg for kw in node.keywords
                                  if kw.arg not in NON_LABEL_KWARGS))
            declared = tuple(sorted(l for l in decl.labels if l))
            if passed != declared:
                yield Finding(
                    CODE, mod.rel, node.lineno,
                    f'{decl.var} ({decl.family}) emitted with labels '
                    f'{list(passed)} but declared {list(declared)} — '
                    'label drift forks/explodes the timeseries',
                    slug=f'labels:{decl.var}:{",".join(passed)}')

    def _check_dynamic(self, mod, prefixes) -> Iterator[Finding]:
        """Computed family names (f'skyt_...{x}') outside metrics.py
        must use a declared dynamic prefix."""
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.JoinedStr):
                continue
            head = astutil.fstring_head(node)
            if head is None or not head.startswith('skyt_'):
                continue
            if not any(p and head.startswith(p) for p in prefixes):
                yield Finding(
                    CODE, mod.rel, node.lineno,
                    f'computed metric family prefix {head!r} is not in '
                    'DYNAMIC_FAMILY_PREFIXES (server/metrics.py) — '
                    'declare the dynamic family there',
                    slug=f'dynamic:{head}')

    @staticmethod
    def _kind_of_method(method: str) -> str:
        return {v: k for k, v in KINDS.items()}[method]
