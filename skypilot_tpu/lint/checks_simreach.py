"""SKYT013 — ambient clock/RNG calls in sim-reachable modules.

simkit (``skypilot_tpu/sim``) replays the real serve decision stack —
autoscalers, mix policy, spot placer, LB policies — on a virtual clock
and seeded RNG streams so a simulated day is bit-reproducible. That
contract holds only while every module on the sim-reachable path draws
time and randomness through an injectable parameter (``clock=``,
``rng=``, ``self._clock``): one stray ``time.monotonic()`` or
``random.random()`` re-couples the run to the host and silently breaks
replay determinism (this is FoundationDB's simulation discipline — the
whole fleet shares one logical clock and one seed).

The pass flags direct ``time.time()`` / ``time.monotonic()`` (and the
``_ns``/``perf_counter`` variants) and module-level ``random.*()``
calls in the modules listed in :data:`SIM_REACHABLE` — the in-tree
registry of what the simulator can reach. Sanctioned idioms pass:

* the injectable-fallback ``if x is None: x = time.time()`` (the
  parameter IS the injection point; the sim always supplies it);
* ``random.Random(seed)`` — constructing a seeded instance is itself
  deterministic (it is how ``SimRng`` mints child streams);
* bare references without a call (``self._clock = time.monotonic`` as
  an injectable default) — only *calls* couple to the host.

Modules outside the registry can opt in with a ``# skylint:
sim-reachable`` pragma anywhere in the file (the fixture tests use
this; so should any new module the sim grows to reach).
"""
from __future__ import annotations

import ast
import os
from typing import Dict, Iterator, List, Set

from skypilot_tpu.lint import astutil
from skypilot_tpu.lint.core import Context, Finding

CODE = 'SKYT013'

# Repo-relative path suffixes of everything a scenario run can reach.
# Grow this list when the sim grows a new dependency; the module then
# has to keep its clock/RNG injectable to stay lint-clean.
SIM_REACHABLE = (
    'serve/autoscalers.py',
    'serve/slo_autoscaler.py',
    'serve/mix_policy.py',
    'serve/forecast.py',
    'serve/spot_placer.py',
    'serve/load_balancing_policies.py',
    'serve/controller.py',
    'utils/fault_injection.py',
    'data/fanout.py',
    'sim/kernel.py',
    'sim/traffic.py',
    'sim/scenario.py',
    'sim/fleet.py',
    'sim/faults.py',
    'sim/report.py',
    'sim/runner.py',
)

PRAGMA = 'skylint: sim-reachable'

_CLOCK_CALLS = frozenset({
    'time.time', 'time.monotonic', 'time.time_ns', 'time.monotonic_ns',
    'time.perf_counter', 'time.perf_counter_ns',
})
# random.Random(seed) mints a deterministic child stream; everything
# else on the module (`random.random`, `random.uniform`, ...) draws
# from the shared ambient state. SystemRandom is never reproducible.
_SEEDED_CTOR = 'random.Random'


class SimReachDeterminismChecker:
    code = CODE
    name = 'ambient clock/RNG on a sim-reachable path'

    def run(self, ctx: Context) -> Iterator[Finding]:
        for mod in ctx.package_modules:
            rel = mod.rel.replace(os.sep, '/')
            if not (rel.endswith(SIM_REACHABLE) or PRAGMA in mod.source):
                continue
            imports = astutil.import_map(mod.tree)
            sanctioned = _fallback_calls(mod.tree)
            counts: Dict[str, int] = {}
            for qual, call in _calls_with_scope(mod.tree):
                name = astutil.resolve_call(call.func, imports)
                if name is None:
                    continue
                if name in _CLOCK_CALLS:
                    kind = 'clock'
                elif (name.startswith('random.') and
                      name != _SEEDED_CTOR and name.count('.') == 1):
                    kind = 'rng'
                else:
                    continue
                if id(call) in sanctioned:
                    continue
                slot = f'{qual}:{name}'
                ordinal = counts.get(slot, 0)
                counts[slot] = ordinal + 1
                yield Finding(
                    CODE, mod.rel, call.lineno,
                    f'{name}() in sim-reachable scope {qual}: ambient '
                    f'{"clock" if kind == "clock" else "RNG"} breaks '
                    f'simulation replay — take an injectable '
                    f'clock/rng parameter instead',
                    slug=f'ambient-{kind}:{slot}:{ordinal}')


def _fallback_calls(tree: ast.Module) -> Set[int]:
    """ids of Call nodes inside the injectable-fallback idiom
    ``if x is None: x = <call>()`` (x a name or self attribute)."""
    out: Set[int] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.If):
            continue
        test = node.test
        if not (isinstance(test, ast.Compare) and len(test.ops) == 1 and
                isinstance(test.ops[0], ast.Is) and
                isinstance(test.comparators[0], ast.Constant) and
                test.comparators[0].value is None):
            continue
        guard = astutil.dotted(test.left)
        if guard is None:
            continue
        for stmt in node.body:
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
                target, value = stmt.targets[0], stmt.value
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                target, value = stmt.target, stmt.value
            else:
                continue
            if astutil.dotted(target) == guard and \
                    isinstance(value, ast.Call):
                out.add(id(value))
    return out


def _calls_with_scope(tree: ast.Module):
    """Yield ``(enclosing_qualname, Call)`` pairs, qualname like
    ``Class.method`` / ``fn`` / ``<module>`` — stable slug material."""
    results: List = []

    def walk(node: ast.AST, stack: List[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                walk(child, stack + [child.name])
            else:
                if isinstance(child, ast.Call):
                    results.append(('.'.join(stack) or '<module>', child))
                walk(child, stack)

    walk(tree, [])
    return results
