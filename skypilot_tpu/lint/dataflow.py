"""Intraprocedural CFG + reaching definitions for the dataflow passes.

PR 8's passes were syntactic (one AST walk, no flow). The SKYT009..012
passes need to answer flow questions — "which definitions of ``now``
reach this subtraction", "is a transaction still open when this call
runs", "does every path out of this function (including exception
edges) balance this acquire" — so this module builds, per function:

* a statement-granularity **control-flow graph** with labelled edges
  (``normal`` / ``exc``). Exception edges are emitted from every
  statement that contains a call (the conservative "any call may
  raise") to the innermost enclosing handler/finally, or to the exit
  node when nothing encloses it. ``break``/``continue``/``return``/
  ``raise`` are wired exactly.
* **reaching definitions** over that CFG: for each node and local
  name, the set of definition sites (with their value expressions
  where syntactically recoverable) that may flow there.
* a tiny generic **forward engine** (:func:`forward`) the passes
  instantiate with their own lattices (transaction state, outstanding
  resource sets).

Everything is stdlib ``ast`` only, same as the rest of the linter.
The graph deliberately UNDER-approximates interprocedural effects
(calls are opaque); passes built on it must choose gen/kill rules so
that imprecision degrades to silence, not noise.
"""
from __future__ import annotations

import ast
from typing import Callable, Dict, Iterable, List, Optional, Set, Tuple

NORMAL = 'normal'
EXC = 'exc'

# Sentinel value-expression for definitions whose value is not a plain
# expression (loop targets, except aliases, parameters, with-as names).
UNKNOWN = object()


class Node:
    """One CFG node: a statement, or a synthetic entry/exit/join."""

    __slots__ = ('stmt', 'label', 'succs', 'preds')

    def __init__(self, stmt: Optional[ast.stmt], label: str) -> None:
        self.stmt = stmt
        self.label = label                      # 'stmt'|'entry'|'exit'|'join'
        self.succs: List[Tuple['Node', str]] = []
        self.preds: List[Tuple['Node', str]] = []

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        line = getattr(self.stmt, 'lineno', '?')
        return f'<Node {self.label}@{line}>'


def _link(a: Node, b: Node, kind: str = NORMAL) -> None:
    for succ, k in a.succs:
        if succ is b and k == kind:
            return
    a.succs.append((b, kind))
    b.preds.append((a, kind))


def stmt_may_raise(stmt: ast.stmt) -> bool:
    """Conservative: a statement that evaluates any call may raise.
    Compound statements only evaluate their HEADER expressions at
    their own CFG node (bodies are separate nodes with their own
    edges); nested function/class bodies are nobody's calls."""
    if isinstance(stmt, (ast.Raise, ast.Assert)):
        return True
    for expr in owned_exprs(stmt):
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                return True
    return False


class CFG:
    """Control-flow graph of one function body.

    Compound statements contribute a node for their header (test/iter/
    context managers) with the body wired structurally; simple
    statements are one node each.
    """

    def __init__(self, fn: ast.AST) -> None:
        self.fn = fn
        self.entry = Node(None, 'entry')
        self.exit = Node(None, 'exit')
        self.nodes: List[Node] = [self.entry, self.exit]
        # (loop-head, break-sinks) stack and exception-target stack are
        # builder-local; kept on self for the recursive helpers.
        self._loops: List[Tuple[Node, List[Node]]] = []
        self._exc_targets: List[List[Node]] = [[self.exit]]
        # Innermost-first stack of (finally-entry join, loop depth at
        # push): return/break/continue inside a try..finally run the
        # finally first. The loop depth decides whether a break/
        # continue crosses the finally (finally inside the loop) or
        # not (loop inside the finally).
        self._finallys: List[Tuple[Node, int]] = []
        frontier = self._stmts(list(getattr(fn, 'body', [])),
                               [self.entry])
        for node in frontier:
            _link(node, self.exit)

    # -- construction ---------------------------------------------------

    def _new(self, stmt: Optional[ast.stmt], label: str = 'stmt') -> Node:
        node = Node(stmt, label)
        self.nodes.append(node)
        return node

    def _exc_edges(self, node: Node) -> None:
        """Wire ``node`` to the innermost exception targets."""
        if node.stmt is None or not stmt_may_raise(node.stmt):
            return
        for target in self._exc_targets[-1]:
            _link(node, target, EXC)

    def _stmts(self, body: List[ast.stmt],
               frontier: List[Node]) -> List[Node]:
        for stmt in body:
            if not frontier:
                break   # unreachable code after return/raise/break
            frontier = self._stmt(stmt, frontier)
        return frontier

    def _stmt(self, stmt: ast.stmt, frontier: List[Node]) -> List[Node]:
        if isinstance(stmt, ast.If):
            test = self._new(stmt)
            for node in frontier:
                _link(node, test)
            self._exc_edges(test)
            then_exits = self._stmts(stmt.body, [test])
            else_exits = (self._stmts(stmt.orelse, [test])
                          if stmt.orelse else [test])
            return then_exits + else_exits
        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            head = self._new(stmt)
            for node in frontier:
                _link(node, head)
            self._exc_edges(head)
            breaks: List[Node] = []
            self._loops.append((head, breaks))
            body_exits = self._stmts(stmt.body, [head])
            self._loops.pop()
            for node in body_exits:
                _link(node, head)
            orelse_exits = (self._stmts(stmt.orelse, [head])
                            if stmt.orelse else [])
            return [head] + breaks + orelse_exits
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            head = self._new(stmt)
            for node in frontier:
                _link(node, head)
            self._exc_edges(head)
            return self._stmts(stmt.body, [head])
        if isinstance(stmt, ast.Try):
            return self._try(stmt, frontier)
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            node = self._new(stmt)   # definition only; body not walked
            for pred in frontier:
                _link(pred, node)
            return [node]
        # -- simple statements -----------------------------------------
        node = self._new(stmt)
        for pred in frontier:
            _link(pred, node)
        if isinstance(stmt, ast.Return):
            self._exc_edges(node)
            # A return inside try..finally runs the finally first; the
            # finally's continuation edges carry the path to the exit.
            _link(node, self._finallys[-1][0] if self._finallys
                  else self.exit)
            return []
        if isinstance(stmt, ast.Raise):
            for target in self._exc_targets[-1]:
                _link(node, target, EXC)
            return []
        if isinstance(stmt, (ast.Break, ast.Continue)):
            crosses_finally = (
                self._finallys
                and self._finallys[-1][1] >= len(self._loops))
            if crosses_finally:
                _link(node, self._finallys[-1][0])
            elif self._loops:
                if isinstance(stmt, ast.Break):
                    self._loops[-1][1].append(node)
                else:
                    _link(node, self._loops[-1][0])
            return []
        self._exc_edges(node)
        return [node]

    def _try(self, stmt: ast.Try, frontier: List[Node]) -> List[Node]:
        has_final = bool(stmt.finalbody)
        finally_join = self._new(None, 'join') if has_final else None

        handler_nodes: List[Node] = []
        for handler in stmt.handlers:
            handler_nodes.append(self._new(None, 'join'))

        # Exceptions inside the body go to the handlers (approximation:
        # all of them), else to finally, else to the outer targets.
        if handler_nodes:
            inner_targets: List[Node] = list(handler_nodes)
            if has_final:
                # A raise matching no handler still runs finally.
                inner_targets.append(finally_join)
        elif has_final:
            inner_targets = [finally_join]
        else:
            inner_targets = self._exc_targets[-1]
        if has_final:
            self._finallys.append((finally_join, len(self._loops)))
        self._exc_targets.append(inner_targets)
        body_exits = self._stmts(stmt.body, frontier)
        self._exc_targets.pop()

        # `else:` bodies and handler bodies share exception targets:
        # their raises are NOT caught by this try's handlers, but they
        # DO run the finally before propagating outward.
        outer_targets = ([finally_join] if has_final
                         else self._exc_targets[-1])
        self._exc_targets.append(outer_targets)
        orelse_exits = (self._stmts(stmt.orelse, body_exits)
                        if stmt.orelse else body_exits)
        handler_exits: List[Node] = []
        for handler, hnode in zip(stmt.handlers, handler_nodes):
            handler_exits.extend(self._stmts(handler.body, [hnode]))
        self._exc_targets.pop()

        if has_final:
            self._finallys.pop()
            for node in orelse_exits + handler_exits:
                _link(node, finally_join)
            final_exits = self._stmts(stmt.finalbody, [finally_join])
            # The finally block also sits on the exceptional path: after
            # it runs, an in-flight exception continues outward.
            for node in final_exits:
                for target in self._exc_targets[-1]:
                    _link(node, target, EXC)
            return final_exits
        return orelse_exits + handler_exits


# -- reaching definitions ----------------------------------------------


class Def:
    """One definition site of a local name."""

    __slots__ = ('name', 'node', 'value', 'index')

    def __init__(self, name: str, node: Optional[Node], value,
                 index: int) -> None:
        self.name = name
        self.node = node          # None for parameter defs (entry)
        self.value = value        # ast.expr | UNKNOWN
        self.index = index        # stable id within the function

    def __repr__(self) -> str:  # pragma: no cover
        return f'<Def {self.name}#{self.index}>'


def _assign_pairs(target: ast.expr, value) -> Iterable[Tuple[str, object]]:
    """(name, value_expr|UNKNOWN) pairs defined by one assign target."""
    if isinstance(target, ast.Name):
        yield target.id, value
    elif isinstance(target, (ast.Tuple, ast.List)):
        elts = target.elts
        velts = (value.elts if isinstance(value, (ast.Tuple, ast.List))
                 and len(value.elts) == len(elts) else None)
        for i, elt in enumerate(elts):
            if isinstance(elt, ast.Starred):
                elt = elt.value
            sub = velts[i] if velts is not None else UNKNOWN
            yield from _assign_pairs(elt, sub)
    # Attribute/Subscript targets are not local defs.


def node_defs(node: Node) -> List[Tuple[str, object]]:
    """Local (name, value) definitions a CFG node generates. AugAssign
    is reported with the whole statement as value so taint evaluators
    can treat it as a pass-through of the old value and the operand."""
    stmt = node.stmt
    out: List[Tuple[str, object]] = []
    if stmt is None:
        return out
    if isinstance(stmt, ast.Assign):
        for target in stmt.targets:
            out.extend(_assign_pairs(target, stmt.value))
    elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
        out.extend(_assign_pairs(stmt.target, stmt.value))
    elif isinstance(stmt, ast.AugAssign):
        if isinstance(stmt.target, ast.Name):
            out.append((stmt.target.id, stmt))
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        out.extend(_assign_pairs(stmt.target, UNKNOWN))
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        for item in stmt.items:
            if item.optional_vars is not None:
                out.extend(_assign_pairs(item.optional_vars, UNKNOWN))
    elif isinstance(stmt, (ast.Import, ast.ImportFrom)):
        for alias in stmt.names:
            out.append(((alias.asname or alias.name.split('.')[0]),
                        UNKNOWN))
    # Walrus targets anywhere in the statement's expressions.
    if not isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
        for sub in ast.walk(stmt):
            if isinstance(sub, ast.NamedExpr) and isinstance(
                    sub.target, ast.Name):
                out.append((sub.target.id, sub.value))
    return out


class ReachingDefs:
    """Reaching definitions over one CFG.

    ``at(node)`` returns the IN map ``{name: {Def, ...}}`` for the
    node; names never defined locally (true globals) are absent.
    """

    def __init__(self, cfg: CFG) -> None:
        self.cfg = cfg
        self.defs: List[Def] = []
        self._gen: Dict[int, List[Def]] = {}
        counter = 0
        param_defs: List[Def] = []
        args = getattr(cfg.fn, 'args', None)
        if args is not None:
            names = [a.arg for a in (args.posonlyargs + args.args
                                     + args.kwonlyargs)]
            if args.vararg:
                names.append(args.vararg.arg)
            if args.kwarg:
                names.append(args.kwarg.arg)
            for name in names:
                d = Def(name, None, UNKNOWN, counter)
                counter += 1
                param_defs.append(d)
                self.defs.append(d)
        self._gen[id(cfg.entry)] = param_defs
        for node in cfg.nodes:
            if node.stmt is None:
                continue
            gen = []
            for name, value in node_defs(node):
                d = Def(name, node, value, counter)
                counter += 1
                gen.append(d)
                self.defs.append(d)
            if gen:
                self._gen[id(node)] = gen
        self.local_names: Set[str] = {d.name for d in self.defs}
        self._in: Dict[int, Dict[str, Set[Def]]] = {}
        self._solve()

    def _solve(self) -> None:
        out: Dict[int, Dict[str, Set[Def]]] = {}
        worklist = list(self.cfg.nodes)
        while worklist:
            node = worklist.pop()
            in_map: Dict[str, Set[Def]] = {}
            for pred, _ in node.preds:
                for name, defs in out.get(id(pred), {}).items():
                    in_map.setdefault(name, set()).update(defs)
            self._in[id(node)] = in_map
            new_out = {name: set(defs) for name, defs in in_map.items()}
            for d in self._gen.get(id(node), []):
                new_out[d.name] = {d}
            # AugAssign / multi-def nodes: later defs of the same name
            # in one node overwrite earlier ones (handled by dict).
            if new_out != out.get(id(node)):
                out[id(node)] = new_out
                for succ, _ in node.succs:
                    worklist.append(succ)

    def at(self, node: Node) -> Dict[str, Set[Def]]:
        return self._in.get(id(node), {})


# -- generic forward engine --------------------------------------------


def forward(cfg: CFG,
            init,
            transfer: Callable[[Node, object], Tuple[object, object]],
            merge: Callable[[object, object], object]
            ) -> Dict[int, object]:
    """Forward dataflow to fixpoint.

    ``transfer(node, in_state) -> (out_normal, out_exc)`` — the second
    element flows along ``exc`` edges (letting passes send the
    PRE-state of a partially-executed statement down its exception
    edge when that is the right semantics). States must support ``==``.
    Returns ``{id(node): in_state}``.
    """
    in_states: Dict[int, object] = {id(cfg.entry): init}
    out_states: Dict[int, Tuple[object, object]] = {}
    worklist: List[Node] = [cfg.entry]
    iterations = 0
    limit = 50 * max(1, len(cfg.nodes)) * max(1, len(cfg.nodes))
    while worklist and iterations < limit:
        iterations += 1
        node = worklist.pop()
        state = in_states.get(id(node))
        if state is None:
            continue
        outs = transfer(node, state)
        if outs == out_states.get(id(node)):
            continue
        out_states[id(node)] = outs
        out_normal, out_exc = outs
        for succ, kind in node.succs:
            flowing = out_exc if kind == EXC else out_normal
            prev = in_states.get(id(succ))
            merged = flowing if prev is None else merge(prev, flowing)
            if merged != prev:
                in_states[id(succ)] = merged
                worklist.append(succ)
    return in_states


def statement_nodes(cfg: CFG) -> List[Node]:
    return [n for n in cfg.nodes if n.stmt is not None]


def owned_exprs(stmt: ast.stmt) -> List[ast.expr]:
    """Expressions evaluated AT a CFG node. Compound statements own
    only their header expressions — their bodies are separate nodes —
    so passes that attribute expression facts to nodes must walk these
    instead of ``ast.walk(stmt)``."""
    if isinstance(stmt, (ast.If, ast.While)):
        return [stmt.test]
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        return [stmt.iter]
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        return [item.context_expr for item in stmt.items]
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef, ast.Try)):
        return []
    return [child for child in ast.iter_child_nodes(stmt)
            if isinstance(child, ast.expr)]


def owned_calls(stmt: ast.stmt) -> List[ast.Call]:
    """Call expressions evaluated at a CFG node (see owned_exprs)."""
    out: List[ast.Call] = []
    for expr in owned_exprs(stmt):
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                out.append(node)
    return out


def functions_of(tree: ast.Module):
    """Yield (class_name_or_None, function_node) for every def in the
    module, including methods and nested defs."""
    def visit(body, class_name):
        for node in body:
            if isinstance(node, ast.ClassDef):
                yield from visit(node.body, node.name)
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                yield class_name, node
                yield from visit(node.body, class_name)
    yield from visit(tree.body, None)
