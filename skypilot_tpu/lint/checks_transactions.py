"""SKYT010 — transaction hygiene in the control-plane DB modules.

Two invariants over the ``conn = _db()`` idiom the state stores share
(requests_db, jobs/state, serve_state, users_db, state.py):

1. **No blocking work and no bare event publish inside an open
   transaction.** sqlite serializes writers on ONE file lock and
   Postgres holds row locks until commit — a ``time.sleep``, a network
   call, a subprocess, or a deterministic-chaos ``inject()`` inside an
   open write transaction stalls every other writer in the deployment
   for its duration. ``events.publish(topic)`` without ``conn=`` is the
   subtler bug: in-process waiters wake IMMEDIATELY, re-read the store,
   and see the pre-commit snapshot — the publish must ride the
   writer's connection (``conn=conn``, requests_db.create's form) so
   cross-replica NOTIFY delivery is transactional, or simply move
   after the commit.

2. **No path abandons an open transaction.** An execute that raised
   (or a guard that ``raise``s after a write) leaves the implicit
   transaction open on the per-thread connection — the write lock is
   then held for the THREAD's lifetime, starving every claimant (the
   exact outage requests_db.create's rollback comment documents).
   Every explicit ``raise`` reachable with an open transaction, and
   every normal exit without commit/rollback, is flagged — for
   functions that obtained the connection themselves (helpers taking
   ``conn`` as a parameter hand commit responsibility to the caller).

The pass is CFG-based (dataflow.forward): "open" is tracked through
branches, loops and exception edges — a failed INSERT's exception edge
carries the open state into the handler, so a handler that re-raises
without ``rollback()`` is a finding while requests_db.create (which
rolls back first) is not.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from skypilot_tpu.lint import astutil, dataflow
from skypilot_tpu.lint.core import Context, Finding

CODE = 'SKYT010'

_WRITE_KEYWORDS = frozenset({'INSERT', 'UPDATE', 'DELETE', 'REPLACE'})
_EXEC_METHODS = frozenset({'execute', 'executemany', 'executescript'})
_CLOSE_METHODS = frozenset({'commit', 'rollback', 'close'})
# Adapter methods that commit internally.
_SELF_COMMITTING = frozenset({'insert_returning'})
_CONN_FACTORY_TAILS = ('_db', 'connect_dual_backend', 'connect',
                       'from_url')
_BLOCKING_HEADS = ('requests', 'urllib', 'socket', 'http',
                   'subprocess')


def _sql_keyword(arg: ast.AST, rd_vals) -> Optional[str]:
    """First SQL keyword of an execute() argument: literal, f-string
    head, or a local name with a single constant reaching definition."""
    text = astutil.const_str(arg) or astutil.fstring_head(arg)
    if text is None and isinstance(arg, ast.Name) and rd_vals:
        defs = rd_vals.get(arg.id, set())
        consts = {astutil.const_str(d.value)
                  for d in defs
                  if d.value is not dataflow.UNKNOWN
                  and isinstance(d.value, ast.AST)}
        if len(consts) == 1 and None not in consts:
            text = next(iter(consts))
    if text is None:
        return None
    stripped = text.lstrip().lstrip('(')
    return stripped.split(None, 1)[0].upper() if stripped.split() else None


class _FnScan:
    """Per-statement facts for one function."""

    def __init__(self, fn, imports) -> None:
        self.fn = fn
        self.imports = imports
        self.cfg = dataflow.CFG(fn)
        self.rd = dataflow.ReachingDefs(self.cfg)
        self.conns: Set[str] = set()       # locally-obtained connections
        self.param_conns: Set[str] = set()  # caller-owned connections
        args = getattr(fn, 'args', None)
        if args is not None:
            for a in args.posonlyargs + args.args + args.kwonlyargs:
                if a.arg in ('conn', 'db'):
                    self.param_conns.add(a.arg)
        for node in dataflow.statement_nodes(self.cfg):
            stmt = node.stmt
            if not isinstance(stmt, ast.Assign):
                continue
            if not (len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)):
                continue
            if isinstance(stmt.value, ast.Call):
                resolved = astutil.resolve_call(stmt.value.func,
                                                imports) or ''
                tail = resolved.rsplit('.', 1)[-1]
                if tail in _CONN_FACTORY_TAILS:
                    self.conns.add(stmt.targets[0].id)

    def all_conns(self) -> Set[str]:
        return self.conns | self.param_conns


class TransactionHygieneChecker:
    code = CODE
    name = 'transaction hygiene'

    def run(self, ctx: Context) -> Iterator[Finding]:
        for mod in ctx.package_modules:
            imports = astutil.import_map(mod.tree)
            for class_name, fn in dataflow.functions_of(mod.tree):
                del class_name
                scan = _FnScan(fn, imports)
                if not scan.all_conns():
                    continue
                yield from self._check_fn(mod, scan)

    # ------------------------------------------------------------------

    def _check_fn(self, mod, scan: _FnScan) -> Iterator[Finding]:
        conns = scan.all_conns()
        lexical_open = self._with_conn_statements(scan.fn, conns)

        def effects(node) -> Tuple[Set[str], Set[str]]:
            """(opens, closes) conn names for one statement node."""
            opens: Set[str] = set()
            closes: Set[str] = set()
            stmt = node.stmt
            if id(stmt) in lexical_open:
                # Writes inside `with conn:` are closed by the context
                # manager at block exit (commit/rollback both ways);
                # only the blocking-work rule applies there.
                return opens, closes
            for call in _calls_of(stmt):
                recv = _conn_receiver(call, conns)
                if recv is None:
                    continue
                attr = call.func.attr
                if attr in _EXEC_METHODS:
                    keyword = None
                    if call.args:
                        keyword = _sql_keyword(call.args[0],
                                               scan.rd.at(node))
                    if (keyword in _WRITE_KEYWORDS
                            or attr == 'executescript'):
                        opens.add(recv)
                elif attr in _CLOSE_METHODS or attr in _SELF_COMMITTING:
                    closes.add(recv)
            return opens, closes

        def transfer(node, state):
            if node.stmt is None:
                return state, state
            opens, closes = effects(node)
            out = frozenset((state - closes) | opens)
            # A failed write statement ALSO leaves its transaction
            # open (BEGIN ran before the statement errored) — the
            # exception edge carries the open state.
            return out, out

        init = frozenset()
        in_states = dataflow.forward(
            scan.cfg, init, transfer,
            merge=lambda a, b: frozenset(a | b))

        fn_name = scan.fn.name
        reported: Set[str] = set()
        for node in dataflow.statement_nodes(scan.cfg):
            state = in_states.get(id(node), frozenset())
            stmt = node.stmt
            in_txn = bool(state) or id(stmt) in lexical_open
            if not in_txn:
                continue
            for call in _calls_of(stmt):
                label = self._blocking_label(call, scan.imports)
                if label is None:
                    continue
                slug = f'txn-blocking:{fn_name}:{label}'
                if slug in reported:
                    continue
                reported.add(slug)
                yield Finding(
                    CODE, mod.rel, call.lineno,
                    f'`{label}` inside an open transaction in '
                    f'{fn_name}() — blocking work and bare publishes '
                    'must move past the commit (publish may ride '
                    '`conn=` instead)',
                    slug=slug)
            # Explicit raise while a transaction this function owns is
            # open: the write lock outlives the call.
            if (isinstance(stmt, ast.Raise)
                    and (state & scan.conns)
                    and id(stmt) not in lexical_open):
                conn = sorted(state & scan.conns)[0]
                slug = f'txn-raise:{fn_name}:{conn}'
                if slug not in reported:
                    reported.add(slug)
                    yield Finding(
                        CODE, mod.rel, stmt.lineno,
                        f'raise with transaction on `{conn}` still '
                        f'open in {fn_name}() — rollback before '
                        'raising or the per-thread connection holds '
                        'the write lock forever',
                        slug=slug)

        # Normal exit with an owned transaction open: some return/
        # fallthrough path (including returns from an except handler
        # that never rolled back) ends the function holding the write
        # lock. Only NORMAL edges into the exit node count — an
        # uncaught exception propagating out of a DB call is the
        # caller's cleanup problem and flagging every such call would
        # be noise.
        exit_open: Set[str] = set()
        for pred, kind in scan.cfg.exit.preds:
            if kind != dataflow.NORMAL:
                continue
            pred_state = in_states.get(id(pred))
            if pred_state is None:
                continue
            out_normal, _ = transfer(pred, pred_state)
            exit_open |= out_normal
        for conn in sorted(exit_open & scan.conns):
            slug = f'txn-open-exit:{fn_name}:{conn}'
            if slug not in reported:
                reported.add(slug)
                yield Finding(
                    CODE, mod.rel, scan.fn.lineno,
                    f'{fn_name}() can return with the transaction on '
                    f'`{conn}` still open — commit/rollback on every '
                    'path',
                    slug=slug)

    def _with_conn_statements(self, fn, conns) -> Set[int]:
        """ids of statements lexically inside a ``with conn:`` body
        (the context manager commits/rolls back at exit, so only rule
        1 applies there)."""
        out: Set[int] = set()
        for node in ast.walk(fn):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            if not any(isinstance(item.context_expr, ast.Name)
                       and item.context_expr.id in conns
                       for item in node.items):
                continue
            for stmt in node.body:
                for sub in ast.walk(stmt):
                    out.add(id(sub))
        return out

    def _blocking_label(self, call: ast.Call, imports) -> Optional[str]:
        resolved = astutil.resolve_call(call.func, imports)
        if resolved is None:
            return None
        if resolved == 'time.sleep':
            return 'time.sleep'
        tail = resolved.rsplit('.', 1)[-1]
        if tail == 'inject' and 'fault_injection' in resolved:
            return 'fault_injection.inject'
        if resolved.endswith('events.publish') or resolved == 'publish':
            has_conn = any(kw.arg == 'conn' for kw in call.keywords)
            return None if has_conn else 'events.publish'
        head = resolved.split('.', 1)[0]
        if head in _BLOCKING_HEADS:
            return resolved
        return None


def _calls_of(stmt: ast.stmt) -> List[ast.Call]:
    return dataflow.owned_calls(stmt)


def _conn_receiver(call: ast.Call, conns: Set[str]) -> Optional[str]:
    if not isinstance(call.func, ast.Attribute):
        return None
    base = call.func.value
    if isinstance(base, ast.Name) and base.id in conns:
        return base.id
    return None
